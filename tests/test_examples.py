"""Smoke-run the example drivers (reference ``tests/test_examples.py`` runs
``examples/qm9``, ``examples/md17``, ``examples/LennardJones`` through
subprocess)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS=os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
    PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
)


def run_example(args, timeout=420):
    proc = subprocess.run(
        [sys.executable] + args,
        cwd=REPO,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, f"example failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    return proc.stdout


def test_example_qm9():
    out = run_example(
        ["examples/qm9/qm9.py", "--epochs", "2", "--samples", "60"]
    )
    assert "RMSE" in out


@pytest.mark.slow  # r12 tier-1 budget: LJ dataset + training covered by
#   test_md/test_forces/test_fused_cell_list; qm9/mptrj/oc20 keep the
#   dataset-driver canary role
def test_example_lennard_jones():
    out = run_example(
        ["examples/LennardJones/LennardJones.py", "--epochs", "3", "--configs", "30"]
    )
    assert "force RMSE" in out


@pytest.mark.slow  # r12 tier-1 budget: forces pipeline covered by
#   test_forces + mlip suites; md17 loader exercised in the slow tier
def test_example_md17():
    out = run_example(
        ["examples/md17/md17.py", "--epochs", "2", "--frames", "40", "--arch", "PAINN"]
    )
    assert "energy RMSE" in out


@pytest.mark.slow  # r12 tier-1 budget: generator+training path shares
#   every stage with the remaining non-slow drivers
def test_example_ising():
    out = run_example(
        ["examples/ising_model/ising.py", "--epochs", "3", "--configs", "40"]
    )
    assert "energy RMSE" in out


def test_example_qm9_hpo():
    out = run_example(
        ["examples/qm9_hpo/qm9_hpo.py", "--trials", "2", "--samples", "40",
         "--epochs", "1"],
        timeout=600,
    )
    assert "best: mpnn_type=" in out


def test_example_multibranch():
    out = run_example(
        ["examples/multibranch/train.py", "--epochs", "2", "--configs", "16"]
    )
    assert "mesh: (2 branch x 4 data)" in out
    assert "epoch 1" in out


@pytest.mark.slow  # r12 tier-1 budget: packed store + multidataset
#   covered by test_datasets/test_multibranch; the multi-format driver
#   role stays with qm9/mptrj/oc20
def test_example_multidataset_packed(tmp_path):
    """GFM-style driver: synthesize per-branch packed stores, then train
    from them with --multi (the open_*/mptrj driver pattern)."""
    d = str(tmp_path / "gfm")
    out = run_example(
        ["examples/multidataset/train.py", "--make-synthetic", d, "--branches", "2",
         "--configs", "16", "--epochs", "2"]
    )
    assert "synthesized 2 packed stores" in out
    assert "epoch 1" in out

    out2 = run_example(
        ["examples/multidataset/train.py", "--multi", f"{d}/branch0.gpk,{d}/branch1.gpk",
         "--epochs", "1"]
    )
    assert "mesh: (2 branch x 4 data)" in out2
    assert "epoch 0" in out2


# slow (PR 6 tier-1 budget): ~16 s, runs the SAME train/predict stack as
# the faster example drivers above — niche-workload coverage, not unique
# code paths. Runs under `pytest -m slow`.
@pytest.mark.slow
def test_example_uv_spectrum_smooth_and_discrete():
    """DFTB UV-spectrum driver: wide spectrum head + two-head discrete mode."""
    out = run_example(
        ["examples/dftb_uv_spectrum/train.py", "--mode", "smooth", "--bins", "48",
         "--molecules", "48", "--epochs", "2", "--batch", "8"]
    )
    assert "spectrum RMSE (48 bins)" in out
    out2 = run_example(
        ["examples/dftb_uv_spectrum/train.py", "--mode", "discrete", "--lines", "6",
         "--molecules", "48", "--epochs", "2", "--batch", "8"]
    )
    assert "energies RMSE" in out2 and "strengths RMSE" in out2


# slow (PR 6 tier-1 budget): ~20 s subprocess-fleet HPO; the HPO engine
# keeps non-slow coverage via test_example_qm9_hpo + the run_hpo tests in
# test_population.py, and the packed multidataset driver via
# test_example_multidataset_packed.
@pytest.mark.slow
def test_example_multidataset_hpo(tmp_path):
    """GFM HPO driver: concurrent subprocess trials over packed stores."""
    d = str(tmp_path / "gfmhpo")
    out = run_example(
        ["examples/multidataset_hpo/gfm_hpo.py", "--make-synthetic", d,
         "--trials", "2", "--workers", "2", "--epochs", "1", "--configs", "16"],
        timeout=600,
    )
    assert "best: mpnn_type=" in out
    assert "val_loss=" in out


def test_example_mptrj(tmp_path):
    """MPTrj-style driver: E/atom training with force-outlier filtering,
    (charge, spin) FiLM conditioning and linreg baseline subtraction."""
    d = str(tmp_path / "mptrj")
    out = run_example(
        ["examples/mptrj/train.py", "--make-synthetic", d, "--configs", "20",
         "--epochs", "2", "--batch", "4", "--linreg"]
    )
    assert "synthesized MPTrj store" in out
    assert "linear-regression baseline" in out
    assert "eV/atom" in out


def test_example_oc20_s2ef(tmp_path):
    """OC20-style S2EF driver: packed store -> MLIP energy+force training."""
    d = str(tmp_path / "oc20")
    out = run_example(
        ["examples/oc20/train.py", "--make-synthetic", d, "--configs", "24",
         "--epochs", "2", "--batch", "4"]
    )
    assert "synthesized S2EF store" in out
    assert "S2EF metrics" in out
    out2 = run_example(
        ["examples/oc20/train.py", "--data", f"{d}/s2ef.gpk", "--epochs", "1",
         "--batch", "4"]
    )
    assert "24 structures" in out2


# slow (PR 6 tier-1 budget): ~31 s, the most expensive example test; the
# sequential qm9_hpo driver stays non-slow, and trial CONCURRENCY is what
# this one uniquely proves.
@pytest.mark.slow
def test_example_qm9_hpo_parallel_trials(tmp_path):
    """Concurrent subprocess HPO (round-3 verdict missing #4 / next-round #8):
    >=2 trials must demonstrably run AT THE SAME TIME — proven from the
    per-trial wall-clock spans the evaluator records."""
    import json

    log = tmp_path / "hpo" / "result.json"
    out = run_example(
        ["examples/qm9_hpo/qm9_hpo.py", "--trials", "3", "--samples", "40",
         "--epochs", "1", "--workers", "2", "--log", str(log)],
        timeout=900,
    )
    assert "best: mpnn_type=" in out
    assert "overlapping trial pairs" in out
    spans = []
    for p in sorted((tmp_path / "hpo" / "trials").glob("trial_*.json")):
        rec = json.loads(p.read_text())
        spans.append((rec["t_start"], rec["t_end"]))
    assert len(spans) == 3
    overlap = any(
        s1 < e0
        for i, (s0, e0) in enumerate(spans)
        for s1, _ in spans[i + 1 :]
    )
    assert overlap, f"no two trials overlapped: {spans}"


@pytest.mark.slow  # r12 tier-1 budget: MD rollout covered by test_md +
#   test_fused_cell_list; the big-lattice variant was already slow
def test_example_md_rollout():
    """Train an MLIP, then roll on-device MD with it (beyond the reference:
    graph rebuild + forward + grad forces + Verlet in one compiled step)."""
    out = run_example(
        ["examples/md_rollout/md_rollout.py", "--epochs", "3", "--configs",
         "24", "--steps", "60"],
        timeout=600,
    )
    assert "MD rollout: 60 steps on-device" in out
    assert "total-energy drift" in out


# slow (PR 6 tier-1 budget): ~8 s; the binned cell-list path it exercises
# is also covered by test_md.py, and the rollout driver by
# test_example_md_rollout.
@pytest.mark.slow
def test_example_md_rollout_big_lattice():
    """The --big mode: analytic-LJ lattice on the binned cell list (CI-sized
    here; same code path as the 10k-atom demo)."""
    out = run_example(
        ["examples/md_rollout/md_rollout.py", "--big", "600", "--steps",
         "30", "--record-every", "10"],
        timeout=600,
    )
    assert "cell list" in out
    assert "total-energy drift" in out
