"""Spatial partitioning helpers (graphs/partition): Morton-ordered cell
assignment, count-balanced contiguous splits, and boundary-set extraction —
the host-side machinery the halo-exchange route builds its static plans from."""

import numpy as np
import pytest

from hydragnn_tpu.graphs.partition import (
    bounding_cell,
    boundary_sets,
    cell_assignment,
    morton_codes,
    partition_nodes,
)


def test_morton_known_values():
    # code = interleave(x, y, z) with x highest: (x, y, z) on a 2^3 grid
    # reduces to 4x + 2y + z
    idx = np.array(
        [[0, 0, 0], [0, 0, 1], [0, 1, 0], [1, 0, 0], [1, 1, 1]], np.int64
    )
    np.testing.assert_array_equal(morton_codes(idx), [0, 1, 2, 4, 7])
    # bit interleaving beyond one bit per axis: x=2 -> bit 1 spreads to bit 3,
    # shifted left 2 for the x lane
    assert int(morton_codes(np.array([[2, 0, 0]]))[0]) == 32
    assert int(morton_codes(np.array([[3, 3, 3]]))[0]) == 63


def test_morton_locality_order():
    # walking a 2x2x2 grid in code order visits each octant before jumping —
    # consecutive codes differ in at most the low bits (compact bricks)
    g = np.array([[x, y, z] for x in range(2) for y in range(2) for z in range(2)])
    codes = morton_codes(g)
    order = np.argsort(codes)
    walked = g[order]
    # first four visited cells all share x=0 (one spatial half), last four x=1
    assert set(map(tuple, walked[:4])) == {(0, 0, 0), (0, 0, 1), (0, 1, 0), (0, 1, 1)}
    assert (walked[4:, 0] == 1).all()


def test_morton_range_errors():
    with pytest.raises(ValueError):
        morton_codes(np.array([[-1, 0, 0]]))
    with pytest.raises(ValueError):
        morton_codes(np.array([[1 << 21, 0, 0]]))


def test_cell_assignment_formula():
    cell = np.diag([10.0, 10.0, 10.0])
    grid = (5, 5, 5)
    pos = np.array(
        [
            [0.0, 0.0, 0.0],  # first cell
            [1.9, 2.1, 9.9],  # floors of frac * grid
            [10.5, 0.0, 0.0],  # periodic: wraps to 0.5 -> cell 0
            [-0.5, 0.0, 0.0],  # periodic: wraps to 9.5 -> cell 4
        ]
    )
    idx3, cid = cell_assignment(pos, grid, cell)
    np.testing.assert_array_equal(
        idx3, [[0, 0, 0], [0, 1, 4], [0, 0, 0], [4, 0, 0]]
    )
    # flat id matches (ix * gy + iy) * gz + iz
    np.testing.assert_array_equal(cid, [0, 9, 0, 100])


def test_cell_assignment_open_axes_clamp():
    cell = np.diag([10.0, 10.0, 10.0])
    pos = np.array([[-3.0, 10.0, 11.0]])
    idx3, _ = cell_assignment(pos, (5, 5, 5), cell, pbc=[False] * 3)
    # below the box clamps into the first cell; at/above the max corner into
    # the LAST cell, never one past it
    np.testing.assert_array_equal(idx3, [[0, 4, 4]])


def test_cell_assignment_grid_error():
    with pytest.raises(ValueError):
        cell_assignment(np.zeros((1, 3)), (0, 1, 1), np.eye(3))


def test_cell_assignment_origin_shift():
    cell = np.diag([4.0, 4.0, 4.0])
    pos = np.array([[102.0, 101.0, 103.9]])
    idx3, _ = cell_assignment(
        pos, (4, 4, 4), cell, pbc=[False] * 3, origin=np.array([100.0] * 3)
    )
    np.testing.assert_array_equal(idx3, [[2, 1, 3]])


def test_bounding_cell_covers_all_atoms():
    rng = np.random.default_rng(3)
    pos = rng.uniform(-5, 17, size=(64, 3))
    cell = bounding_cell(pos)
    # binning against the bounding cell keeps every atom inside the grid
    idx3, _ = cell_assignment(
        pos, (4, 4, 4), cell, pbc=[False] * 3, origin=pos.min(axis=0)
    )
    assert (idx3 >= 0).all() and (idx3 <= 3).all()


def test_partition_nodes_balance_and_determinism():
    rng = np.random.default_rng(11)
    pos = rng.uniform(0, 12.0, size=(403, 3))
    p1 = partition_nodes(pos, 8, cutoff=2.5)
    p2 = partition_nodes(pos, 8, cutoff=2.5)
    np.testing.assert_array_equal(p1.order, p2.order)
    np.testing.assert_array_equal(p1.owner, p2.owner)
    np.testing.assert_array_equal(p1.start, p2.start)
    assert p1.n_parts == 8
    sizes = np.diff(p1.start)
    assert sizes.sum() == 403 and sizes.max() - sizes.min() <= 1
    # order / owner / start agree: part(p) is exactly owner == p
    for p in range(8):
        ids = p1.part(p)
        assert len(ids) == sizes[p]
        assert (p1.owner[ids] == p).all()
    # order is a permutation of all nodes
    assert len(np.unique(p1.order)) == 403


def test_partition_nodes_morton_contiguity():
    """Owned ranges are contiguous in the Morton walk: each partition's cells
    form a compact rank range, not an interleaved scatter."""
    rng = np.random.default_rng(5)
    pos = rng.uniform(0, 8.0, size=(256, 3))
    plan = partition_nodes(pos, 4, cutoff=2.0)
    idx3, _ = cell_assignment(
        pos, plan.grid, bounding_cell(pos), pbc=[False] * 3, origin=pos.min(axis=0)
    )
    codes = morton_codes(idx3)
    walked = codes[plan.order]
    assert (np.diff(walked.astype(np.float64)) >= 0).all()


def test_partition_nodes_errors():
    pos = np.zeros((3, 3))
    with pytest.raises(ValueError):
        partition_nodes(pos, 0)
    with pytest.raises(ValueError):
        partition_nodes(pos, 4)  # more partitions than nodes


def test_boundary_sets_match_bruteforce():
    rng = np.random.default_rng(17)
    pos = rng.uniform(0, 10.0, size=(200, 3))
    plan = partition_nodes(pos, 4, cutoff=3.0)
    # random directed edges
    senders = rng.integers(0, 200, 600)
    receivers = rng.integers(0, 200, 600)
    got = boundary_sets(senders, receivers, plan.owner, 4)

    want: dict = {}
    for s, r in zip(senders, receivers):
        ps, pr = int(plan.owner[s]), int(plan.owner[r])
        if ps != pr:
            want.setdefault((ps, pr), set()).add(int(s))
    assert set(got) == set(want)
    for pair, ids in got.items():
        np.testing.assert_array_equal(ids, sorted(want[pair]))
        assert ids.dtype == np.int32


def test_boundary_sets_no_crossings():
    owner = np.zeros(10, np.int64)  # everything on one partition
    assert boundary_sets(np.arange(9), np.arange(1, 10), owner, 4) == {}
