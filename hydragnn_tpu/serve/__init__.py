"""Always-hot serving tier (ISSUE 9 / ROADMAP "[serving]").

A persistent prediction server over the training stack's own primitives:
``graphs.batching`` pad buckets for dynamic micro-batching,
``utils.compile_cache`` AOT compilation for boot-time warm-up,
``analysis.sentinel`` for the zero-recompile steady-state guarantee, and the
shared :class:`~hydragnn_tpu.serve.predictor.Predictor` core so served
answers bit-match ``run_prediction`` on identical fp32 inputs.
"""

from .admission import (  # noqa: F401
    AdmissionError,
    DeadlineExceededError,
    IncompatibleSampleError,
    OversizeError,
    QueueFullError,
    Request,
    RequestQueue,
    ServerClosedError,
    UnknownModelError,
)
from .batcher import MicroBatcher, canonical_meta, serving_collate  # noqa: F401
from .predictor import Predictor  # noqa: F401
from .quant import QuantizationError  # noqa: F401
from .server import (  # noqa: F401
    ModelEndpoint,
    PredictionServer,
    ServingConfig,
    serving_config_defaults,
)
from .traffic import TrafficReport, run_traffic  # noqa: F401

__all__ = [
    "AdmissionError",
    "DeadlineExceededError",
    "IncompatibleSampleError",
    "MicroBatcher",
    "ModelEndpoint",
    "OversizeError",
    "PredictionServer",
    "Predictor",
    "QuantizationError",
    "QueueFullError",
    "Request",
    "RequestQueue",
    "ServerClosedError",
    "ServingConfig",
    "TrafficReport",
    "UnknownModelError",
    "canonical_meta",
    "run_traffic",
    "serving_collate",
    "serving_config_defaults",
]
