"""Always-hot serving tier (ISSUE 9 / ROADMAP "[serving]").

A persistent prediction server over the training stack's own primitives:
``graphs.batching`` pad buckets for dynamic micro-batching,
``utils.compile_cache`` AOT compilation for boot-time warm-up,
``analysis.sentinel`` for the zero-recompile steady-state guarantee, and the
shared :class:`~hydragnn_tpu.serve.predictor.Predictor` core so served
answers bit-match ``run_prediction`` on identical fp32 inputs.
"""

from .admission import (  # noqa: F401
    AdmissionError,
    DeadlineExceededError,
    IncompatibleSampleError,
    OversizeError,
    QueueFullError,
    Request,
    RequestQueue,
    ServerClosedError,
    UnknownModelError,
)
from .batcher import MicroBatcher, canonical_meta, serving_collate  # noqa: F401
from .fleet import (  # noqa: F401
    AnswerCache,
    Autoscaler,
    AutoscalerConfig,
    CanaryMismatchError,
    FleetConfig,
    FleetRouter,
    ReplicaBootError,
    ReplicaHost,
    RolloutConfig,
    answer_key,
    blue_green_rollout,
    fleet_config_defaults,
    spawn_replica,
)
from .predictor import Predictor  # noqa: F401
from .quant import QuantizationError  # noqa: F401
from .server import (  # noqa: F401
    ModelEndpoint,
    PredictionServer,
    ServingConfig,
    serving_config_defaults,
)
from .traffic import (  # noqa: F401
    TrafficReport,
    mixed_priority_plan,
    run_traffic,
    zipf_duplicate_order,
)

__all__ = [
    "AdmissionError",
    "AnswerCache",
    "Autoscaler",
    "AutoscalerConfig",
    "CanaryMismatchError",
    "DeadlineExceededError",
    "FleetConfig",
    "FleetRouter",
    "IncompatibleSampleError",
    "MicroBatcher",
    "ModelEndpoint",
    "OversizeError",
    "PredictionServer",
    "Predictor",
    "QuantizationError",
    "QueueFullError",
    "ReplicaBootError",
    "ReplicaHost",
    "Request",
    "RequestQueue",
    "RolloutConfig",
    "ServerClosedError",
    "ServingConfig",
    "TrafficReport",
    "UnknownModelError",
    "answer_key",
    "blue_green_rollout",
    "canonical_meta",
    "fleet_config_defaults",
    "mixed_priority_plan",
    "run_traffic",
    "serving_collate",
    "serving_config_defaults",
    "spawn_replica",
    "zipf_duplicate_order",
]
