"""Always-hot prediction server: persistent process, warm executables.

``run_prediction`` is a batch evaluator — every invocation pays data loading
plus the first-compile cost (20-40 s on TPU) before the first answer.
:class:`PredictionServer` inverts the lifecycle for online traffic:

- **boot**: register models (architecture + trained state + augmented
  config), derive each endpoint's pad-bucket table (the SAME
  ``compute_pad_buckets`` table training uses), AOT-lower and compile every
  (model, bucket) predict program (``utils.compile_cache.aot_compile``, disk
  cache warm across restarts), and verify with the strict recompile sentinel
  that a dummy pass through every executable triggers ZERO lowerings;
- **steady state**: a bounded request queue with typed load-shedding feeds a
  per-endpoint micro-batcher (``serve.batcher``) whose batches run through
  the pre-compiled executables only — no jit cache in the hot path, nothing
  left to compile, donated batch buffers on accelerators;
- **routing**: several architectures/checkpoints serve concurrently from one
  process, each endpoint with its own queue, bucket table, executor table,
  and dispatcher thread — one slow model cannot head-of-line-block another.

Config: the validated top-level ``Serving`` block (``config/schema.py``),
overridden by ``HYDRAGNN_SERVE_*`` env flags (``utils.flags``).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from concurrent.futures import Future
from typing import Sequence

import numpy as np

from ..graphs.batching import PadSpec, compute_pad_buckets, pick_bucket
from ..graphs.graph import GraphSample

_EMPTY = np.zeros((0,), np.int32)  # triplet default for extras-less samples
from ..train.step import TrainState
from ..utils import flags
from .. import telemetry as tel
from .admission import (
    DeadlineExceededError,
    IncompatibleSampleError,
    Request,
    RequestQueue,
    ServerClosedError,
    UnknownModelError,
)
from .batcher import MicroBatcher, serving_collate
from .fleet.config import FleetConfig, fleet_config_defaults
from .predictor import Predictor


# top-level sections of the repo's JSON config schema — lets from_config
# tell "full config without a Serving block" (defaults) apart from "typo'd
# serving block" (raise); single-sourced from config/schema.py
from ..config.schema import CONFIG_SECTIONS as _CONFIG_SECTIONS


@dataclasses.dataclass
class ServingConfig:
    """The ``Serving`` config block — these field defaults ARE the schema
    defaults (single-source, same pattern as ``StoreConfig`` /
    ``Training.resilience``). Env flags ``HYDRAGNN_SERVE_QUEUE_DEPTH`` /
    ``_FLUSH_MS`` / ``_WARMUP`` override at server construction."""

    queue_depth: int = 256   # bounded admission; beyond it requests shed
    flush_ms: float = 5.0    # max micro-batch coalescing latency
    warmup: bool = True      # AOT-compile every bucket executable at boot
    max_batch_graphs: int = 0  # per-batch request cap (0 = bucket capacity)
    deadline_ms: float = 0.0   # default per-request deadline (0 = none)
    # int8 inference (serve.quant): calibrate per-(model, bucket) activation
    # scales at warm-up, AOT-compile an int8 predict variant alongside fp32,
    # and serve it — REFUSING to boot if any head's calibrated error vs the
    # fp32 answer exceeds quant_tol (QuantizationError)
    quantize: bool = False
    quant_tol: float = 0.1       # per-head max abs error ceiling vs fp32
    quant_calib_batches: int = 4  # calibration batches per (model, bucket)
    # fleet front end (serve/fleet): the nested Serving.fleet block —
    # replicas / per-class budgets / cache_bytes / auth — single-sourced
    # from the FleetConfig dataclass (fleet/config.py) and validated
    # through it below. The in-process PredictionServer ignores it; the
    # FleetRouter reads it via FleetConfig.from_config(full config).
    fleet: dict = dataclasses.field(
        default_factory=lambda: fleet_config_defaults()
    )

    @staticmethod
    def from_config(config: dict | None) -> "ServingConfig":
        """Accepts a FULL config dict (reads its ``Serving`` block, absent =
        defaults) or the serving block itself ({"queue_depth": 8, ...} —
        recognized by its field names; unknown fields then raise instead of
        silently falling back to defaults)."""
        config = config or {}
        block = config.get("Serving")
        if block is None and config:
            if any(k in serving_config_defaults() for k in config):
                block = config  # the caller passed the block directly
            elif not any(k in _CONFIG_SECTIONS for k in config):
                # neither serving fields nor config sections: a typo'd
                # block must raise, not silently boot with defaults
                raise ValueError(
                    f"unrecognized serving config keys {sorted(config)}; "
                    f"expected a full config (sections "
                    f"{sorted(_CONFIG_SECTIONS)}) or a Serving block "
                    f"(fields {sorted(serving_config_defaults())})"
                )
        return ServingConfig(**(block or {})).apply_env()

    def apply_env(self) -> "ServingConfig":
        """Fold ``HYDRAGNN_SERVE_*`` overrides in (idempotent). The server
        applies this on EVERY construction path — a directly-built
        ``ServingConfig`` honors the flag table the same as a config dict."""
        depth = flags.get(flags.SERVE_QUEUE_DEPTH)
        if depth is not None:
            self.queue_depth = int(depth)
        flush = flags.get(flags.SERVE_FLUSH_MS)
        if flush is not None:
            self.flush_ms = float(flush)
        warm = flags.get(flags.SERVE_WARMUP)
        if warm is not None:
            self.warmup = bool(warm)
        quant = flags.get(flags.SERVE_QUANT)
        if quant is not None:
            self.quantize = bool(quant)
        return self

    def validate(self) -> "ServingConfig":
        """Range-check every field; the ONE implementation behind both the
        schema's ``Serving`` block validation and direct server
        construction (which bypasses ``update_config``)."""
        if int(self.queue_depth) < 1:
            raise ValueError(
                f"Serving.queue_depth must be >= 1, got {self.queue_depth}"
            )
        for fkey in ("flush_ms", "deadline_ms"):
            if float(getattr(self, fkey)) < 0:
                raise ValueError(
                    f"Serving.{fkey} must be >= 0, got {getattr(self, fkey)}"
                )
        if int(self.max_batch_graphs) < 0:
            raise ValueError(
                "Serving.max_batch_graphs must be >= 0 (0 = bucket "
                f"capacity), got {self.max_batch_graphs}"
            )
        if float(self.quant_tol) <= 0:
            raise ValueError(
                f"Serving.quant_tol must be > 0, got {self.quant_tol}"
            )
        if int(self.quant_calib_batches) < 1:
            raise ValueError(
                "Serving.quant_calib_batches must be >= 1, got "
                f"{self.quant_calib_batches}"
            )
        if self.quantize and not self.warmup:
            raise ValueError(
                "Serving.quantize requires Serving.warmup: calibration and "
                "the error-bound gate run at warm-up — without it the "
                "server would silently serve fp32 despite quantize=true"
            )
        if not isinstance(self.fleet, dict):
            raise ValueError(
                f"Serving.fleet must be a dict, got {type(self.fleet).__name__}"
            )
        unknown = set(self.fleet) - set(fleet_config_defaults())
        if unknown:
            raise ValueError(
                f"Unknown Serving.fleet key(s) {sorted(unknown)}; known: "
                f"{sorted(fleet_config_defaults())}"
            )
        FleetConfig(**self.fleet).validate()  # one range-check impl
        return self


def serving_config_defaults() -> dict:
    return dataclasses.asdict(ServingConfig())


def _dummy_sample(example: GraphSample) -> GraphSample:
    """A minimal 1-node, 0-edge sample with ``example``'s feature widths —
    collated alone it exercises every array field of a bucket, so one AOT
    lowering per bucket covers every real batch shape of that bucket."""
    n_y = example.node_y.shape[1]
    extras = {}
    if "pe" in example.extras:
        k = example.extras["pe"].shape[1]
        extras["pe"] = np.zeros((1, k), np.float32)
        extras["rel_pe"] = np.zeros((0, k), np.float32)
    if "idx_kj" in example.extras:
        extras["idx_kj"] = np.zeros((0,), np.int32)
        extras["idx_ji"] = np.zeros((0,), np.int32)
    return GraphSample(
        x=np.zeros((1, example.x.shape[1]), np.float32),
        edge_attr=np.zeros((0, example.edge_attr.shape[1]), np.float32),
        graph_attr=np.zeros_like(example.graph_attr),
        graph_y=np.zeros_like(example.graph_y),
        node_y=np.zeros((1, n_y), np.float32),
        extras=extras,
    )


class ModelEndpoint:
    """One served model: predictor + bucket table + queue + executor table."""

    def __init__(self, name: str, predictor: Predictor,
                 buckets: Sequence[PadSpec], example: GraphSample,
                 cfg: ServingConfig, denormalize: bool = False,
                 calib_samples: Sequence[GraphSample] | None = None,
                 artifact_dir: str | None = None):
        self.name = name
        self.predictor = predictor
        self.buckets = sorted(buckets, key=lambda p: p.as_tuple())
        self.example = example
        self.cfg = cfg
        self.denormalize = denormalize
        # serialized-AOT artifact store (serve/fleet serialized boot): warm()
        # loads per-bucket executables from here when fingerprints match and
        # persists fresh ones when they don't; None = always compile
        self.artifact_dir = artifact_dir
        self.executables: dict[tuple, object] = {}
        # int8 variants (cfg.quantize): one quantized executable per bucket,
        # compiled ALONGSIDE the fp32 table — never instead of it
        self.executables_quant: dict[tuple, object] = {}
        self.quant_bounds: list[float] | None = None  # per-head, calibrated
        self.calib_samples = list(calib_samples) if calib_samples else [example]
        self.thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self.counters = {  # guarded-by: _lock
            "submitted": 0, "served": 0, "shed": 0, "shed_deadline": 0,
            "shed_oversize": 0, "failed": 0, "cancelled": 0,
            "batches": 0, "real_graph_slots": 0, "graph_slots": 0,
        }
        # invariant for the endpoint's lifetime — computed once, compared
        # against every request on the admission hot path
        self._want_signature = self._signature(example)
        self.reset_queue()

    def reset_queue(self) -> None:
        """Fresh queue + batcher (boot, and re-arm after ``stop()`` — a
        closed queue cannot be reopened, but a restarted server keeps its
        warm executables, which is the expensive part)."""
        self.queue = RequestQueue(self.cfg.queue_depth)
        self.batcher = MicroBatcher(
            self.queue, self.buckets, flush_s=self.cfg.flush_ms / 1e3,
            max_graphs=self.cfg.max_batch_graphs,
            on_shed=self._on_shed,
        )

    def _on_shed(self, kind: str) -> None:
        # "cancelled" = the client cancelled before the batcher could shed;
        # still a terminal outcome the submitted-total must account for
        self._count("cancelled" if kind == "cancelled" else f"shed_{kind}")
        if kind != "cancelled":
            tel.emit("shed", model=self.name, reason=kind)

    def _count(self, key: str, by: int = 1) -> None:
        with self._lock:
            self.counters[key] += by
        # dual-write into the unified registry: the dict above stays the
        # test-pinned stats() surface; the labeled counter is what the
        # fleet `metrics` wire op and the CLI read
        tel.counter("serve_requests", model=self.name, event=key).inc(by)

    @staticmethod
    def _signature(s: GraphSample) -> dict:
        return {
            "x_width": s.x.shape[1],
            "edge_attr_width": s.edge_attr.shape[1],
            "graph_attr_width": s.graph_attr.shape[0],
            "graph_y_width": s.graph_y.shape[0],
            "node_y_width": s.node_y.shape[1],
            "pe_width": s.extras["pe"].shape[1] if "pe" in s.extras else 0,
            # collate reads rel_pe whenever pe is present — a pe-with-no-
            # rel_pe request would KeyError the whole micro-batch
            "rel_pe_width": (
                s.extras["rel_pe"].shape[1] if "rel_pe" in s.extras else 0
            ),
            # DimeNet endpoints: a triplet-less request would collate fine
            # (zero triplets) but serve angle-blind predictions silently
            "has_triplets": "idx_kj" in s.extras,
        }

    def check_sample(self, s: GraphSample) -> None:
        """Admission-time schema check: every request must match the
        feature-width signature the endpoint's executables were compiled
        for. Without this, ``collate``'s first-sample pe-width rule would
        let one pe-less request collapse a whole micro-batch's pe arrays
        (failing the warm executable's shape check, or silently zeroing
        co-batched requests' PEs on an unwarmed endpoint)."""
        got = self._signature(s)
        want = self._want_signature
        if got != want:
            mismatch = {k: (got[k], want[k]) for k in got if got[k] != want[k]}
            raise IncompatibleSampleError(
                f"sample does not match endpoint {self.name!r}'s signature: "
                f"(got, want) per field: {mismatch}"
            )

    def warm(self, verify: bool = True) -> dict:
        """AOT-lower + compile this endpoint's predict program once per
        bucket; optionally verify a dummy pass through every executable is
        lowering-free (the strict-sentinel gate CI runs).

        With an ``artifact_dir``, each bucket first tries the serialized-AOT
        artifact store: a fingerprint-matched artifact deserializes in
        seconds (the fast replica boot path); a missing/stale one logs a
        LOUD per-bucket note, compiles from the exported StableHLO, and
        persists a fresh artifact for the next boot. Both paths produce the
        same program, so serialized boots answer bit-identically."""
        from ..analysis.sentinel import no_recompile
        from ..utils.compile_cache import (
            ArtifactError,
            aot_compile,
            enable_compile_cache,
            load_artifact,
            save_artifact,
            shape_structs,
        )

        # here, not only in PredictionServer.warmup(): the lazy start()
        # warm path must hit the same persistent disk cache across restarts
        enable_compile_cache()
        report = {}
        dummy = _dummy_sample(self.example)
        if self.artifact_dir:
            report["serialized"] = {}
        for pad in self.buckets:
            batch = serving_collate([dummy], pad)
            t0 = time.perf_counter()
            ledger_entry = {
                "model": self.name, "bucket": pad.as_tuple(),
                "kind": "predict",
                "precision": str(self.predictor.compute_dtype),
            }
            if self.artifact_dir:
                key = dict(
                    model=self.name, bucket=pad.as_tuple(), kind="predict",
                    precision=str(self.predictor.compute_dtype),
                )
                try:
                    self.executables[pad.as_tuple()] = load_artifact(
                        self.artifact_dir, self.predictor.state,
                        shape_structs(batch), ledger_entry=ledger_entry,
                        **key,
                    )
                    report["serialized"][repr(pad)] = "loaded"
                except ArtifactError as e:
                    # loud, per-bucket: a fleet operator watching a slow
                    # boot must see WHY the fast path was skipped
                    print(
                        f"[serve] endpoint {self.name!r} bucket {pad!r}: "
                        f"serialized-AOT fallback to compile-from-source: "
                        f"{e}",
                        file=sys.stderr, flush=True,
                    )
                    self.executables[pad.as_tuple()], _ = save_artifact(
                        self.artifact_dir, self.predictor.predict_step,
                        self.predictor.state, shape_structs(batch),
                        ledger_entry=ledger_entry, **key,
                    )
                    report["serialized"][repr(pad)] = "saved"
            else:
                self.executables[pad.as_tuple()] = aot_compile(
                    self.predictor.predict_step,
                    self.predictor.state,
                    shape_structs(batch),
                    ledger_entry=ledger_entry,
                )
            report[repr(pad)] = round(time.perf_counter() - t0, 4)
        if self.cfg.quantize:
            report["quant"] = self.warm_quant()
        if verify:
            with no_recompile(0, what=f"serving warm-up verify [{self.name}]"):
                for pad in self.buckets:
                    self.executables[pad.as_tuple()](
                        self.predictor.state, serving_collate([dummy], pad)
                    )
                for pad in self.buckets:
                    exe = self.executables_quant.get(pad.as_tuple())
                    if exe is not None:
                        exe(self.predictor.state, serving_collate([dummy], pad))
        return report

    def warm_quant(self) -> dict:
        """The int8 half of warm-up (``serve.quant``): per-bucket activation
        calibration over this endpoint's calibration samples, one quantized
        executable per bucket AOT-compiled next to the fp32 one, and per-head
        error bounds certified against the fp32 answers — above
        ``Serving.quant_tol`` this RAISES instead of serving degraded
        answers. Returns the warm-up report (scales/bounds/compile s)."""
        from ..utils.compile_cache import aot_compile, shape_structs
        from .quant import (
            QuantizationError,
            certify_quant_error,
            collect_activation_scales,
            make_quantized_predict_step,
            quantize_dense_weights,
        )

        pred = self.predictor
        report: dict = {"buckets": {}}
        bounds = [0.0] * len(pred.cols)
        k = max(int(self.cfg.quant_calib_batches), 1)
        for pad in self.buckets:
            # calibration traffic for THIS bucket: the largest calibration
            # samples the bucket admits, collated exactly as serving would
            fitting = [
                s for s in self.calib_samples
                if pick_bucket([pad], s.num_nodes, s.num_edges,
                               s.extras.get("idx_kj", _EMPTY).shape[0], 1)
            ]
            if not fitting:
                # certifying on a synthetic dummy would produce ~0 "bounds"
                # that say nothing about real traffic — the whole contract
                # is "bounded and certified, never assumed", so refuse
                raise QuantizationError(
                    f"endpoint {self.name!r}: no calibration sample fits "
                    f"bucket {pad!r} — pass `samples` covering every "
                    "bucket to add_model (or drop the bucket) before "
                    "enabling Serving.quantize"
                )
            batches = [
                serving_collate([s], pad)
                for s in sorted(fitting, key=lambda s: -s.num_nodes)[:k]
            ]
            scales = collect_activation_scales(
                pred.model, pred.state, batches, pred.compute_dtype
            )
            weights = quantize_dense_weights(pred.state.params, scales)
            q_step = make_quantized_predict_step(
                pred.model, scales, weights, pred.compute_dtype
            )
            t0 = time.perf_counter()
            exe = aot_compile(
                q_step, pred.state, shape_structs(batches[0]),
                ledger_entry={
                    "model": self.name, "bucket": pad.as_tuple(),
                    "kind": "quant_predict", "precision": "int8",
                },
            )
            pad_bounds = certify_quant_error(pred, exe, batches)
            bounds = [max(a, b) for a, b in zip(bounds, pad_bounds)]
            self.executables_quant[pad.as_tuple()] = exe
            report["buckets"][repr(pad)] = {
                "compile_s": round(time.perf_counter() - t0, 4),
                "n_dense_layers": len(weights),
                "error_bounds": [round(b, 6) for b in pad_bounds],
            }
        report["error_bounds"] = [round(b, 6) for b in bounds]
        report["quant_tol"] = self.cfg.quant_tol
        self.quant_bounds = bounds
        over = [
            (i, b) for i, b in enumerate(bounds) if b > self.cfg.quant_tol
        ]
        if over:
            self.executables_quant.clear()
            self.quant_bounds = None
            raise QuantizationError(
                f"endpoint {self.name!r}: calibrated int8 error exceeds "
                f"Serving.quant_tol={self.cfg.quant_tol} for head(s) "
                f"{[(i, round(b, 6)) for i, b in over]} — serve fp32 "
                "(quantize=false) or raise quant_tol if the error is "
                "acceptable for this model"
            )
        tel.emit(
            "quant_cert", model=self.name,
            bounds=[round(b, 6) for b in bounds],
            quant_tol=self.cfg.quant_tol, buckets=len(self.buckets),
        )
        return report

    def _step_for(self, pad: PadSpec):
        if self.cfg.quantize:
            exe = self.executables_quant.get(pad.as_tuple())
            if exe is not None:
                return exe
        exe = self.executables.get(pad.as_tuple())
        # warmup=False endpoints lazily fall back to the jitted step: first
        # use of a (bucket) treedef compiles, steady state then hits the jit
        # cache — the strict sentinel only certifies warmed endpoints
        return exe if exe is not None else self.predictor.predict_step

    def serve_batch(self, members: list[Request], pad: PadSpec) -> None:
        # dispatch-time gate: re-check deadlines (a request can expire while
        # the flush window coalesces joiners — serving it anyway would
        # return a "success" past its contract) and CLAIM every future so a
        # client-side cancel can never InvalidStateError the dispatcher
        live = []
        for req in members:
            if req.expired():
                if req.reject(DeadlineExceededError(
                    "deadline passed while the batch coalesced"
                )):
                    self._count("shed_deadline")
                else:
                    self._count("cancelled")  # client's cancel won the race
            elif req.claim():
                live.append(req)
            else:
                self._count("cancelled")  # client cancelled while queued
        members = live
        if not members:
            return
        try:
            batch = serving_collate([r.sample for r in members], pad)
            out = self.predictor.outputs(batch, step=self._step_for(pad))
            counts = [r.sample.num_nodes for r in members]
            per_graph = self.predictor.split_graphs(out, counts)
            if self.denormalize:
                per_graph = [
                    self.predictor.denormalize_preds(heads)
                    for heads in per_graph
                ]
            now = time.monotonic()
            self._count("batches")
            self._count("real_graph_slots", len(members))
            self._count("graph_slots", pad.n_graph - 1)
            self._count("served", len(members))
            for req, heads in zip(members, per_graph):
                req.future.set_result({
                    "heads": heads,
                    "latency_s": now - req.enqueued_at,
                    "bucket": pad.as_tuple(),
                    "batch_graphs": len(members),
                })
        except Exception as exc:  # fail THIS batch's futures, keep serving
            self._count("failed", len(members))
            for req in members:
                if not req.future.done():  # claimed above: cancel impossible
                    req.future.set_exception(exc)


class PredictionServer:
    """The persistent multi-model prediction process. Lifecycle:

        server = PredictionServer(config)          # or ServingConfig()
        server.add_model("mace_v2", model, state, aug_config, samples=train)
        server.warmup()                            # AOT, strict-verified
        server.start()
        fut = server.submit("mace_v2", sample, deadline_ms=50)
        result = fut.result()["heads"]             # per-head arrays
        server.stop()
    """

    def __init__(self, config: ServingConfig | dict | None = None):
        if isinstance(config, ServingConfig):
            # copy before folding env in — the caller's object stays as built
            self.cfg = dataclasses.replace(config).apply_env()
        else:
            self.cfg = ServingConfig.from_config(config)
        # ServingConfig / raw-dict construction bypasses update_config
        self.cfg.validate()
        self._models: dict[str, ModelEndpoint] = {}
        self._running = False
        self._stopping = False

    # -- registration / warm-up ---------------------------------------------

    def add_model(
        self,
        name: str,
        model,
        state: TrainState,
        config: dict,
        samples: Sequence[GraphSample] | None = None,
        buckets: Sequence[PadSpec] | None = None,
        example: GraphSample | None = None,
        batch_size: int | None = None,
        max_buckets: int = 4,
        denormalize: bool = False,
        flush_ms: float | None = None,
        max_batch_graphs: int | None = None,
        artifact_dir: str | None = None,
    ) -> ModelEndpoint:
        """Register one servable model. ``config`` is its AUGMENTED config;
        the bucket table comes from ``buckets`` (explicit) or is derived from
        ``samples`` with the training-side ``compute_pad_buckets``. One
        ``example`` sample (default ``samples[0]``) fixes the endpoint's
        feature-width signature — warm-up shapes AND the admission-time
        schema check every request is validated against. Endpoint kwargs
        override the server-wide batching policy per model."""
        if self._running:
            raise RuntimeError("add_model before start(): registration is a boot-time operation")
        if name in self._models:
            raise ValueError(f"model {name!r} already registered")
        if buckets is None:
            if not samples:
                raise ValueError(
                    "add_model needs `samples` to derive the bucket table "
                    "(or pass `buckets` plus an `example` sample)"
                )
            bs = int(batch_size or config["NeuralNetwork"]["Training"].get("batch_size", 32))
            buckets = compute_pad_buckets(samples, bs, max_buckets=max_buckets)
        if example is None and samples:
            example = samples[0]
        if example is None:
            raise ValueError(
                "add_model needs an `example` sample (or `samples`) to fix "
                "the endpoint's feature-width signature"
            )
        cfg = dataclasses.replace(
            self.cfg,
            flush_ms=self.cfg.flush_ms if flush_ms is None else float(flush_ms),
            max_batch_graphs=(
                self.cfg.max_batch_graphs if max_batch_graphs is None
                else int(max_batch_graphs)
            ),
        )
        predictor = Predictor(model, state, config, donate_batch=True)
        ep = ModelEndpoint(name, predictor, buckets, example, cfg,
                           denormalize=denormalize, calib_samples=samples,
                           artifact_dir=artifact_dir)
        self._models[name] = ep
        return ep

    def add_model_from_checkpoint(
        self,
        name: str,
        log_name: str,
        path: str = "./logs/",
        config: dict | None = None,
        samples: Sequence[GraphSample] | None = None,
        epoch: int | None = None,
        **add_model_kwargs,
    ) -> ModelEndpoint:
        """Register a servable model straight from a training run's
        checkpoint directory — the PR 6 follow-up (callers previously had
        to reconstruct model+state themselves). ``config`` defaults to the
        AUGMENTED ``config.json`` ``save_config`` wrote next to the run's
        logs; the model/optimizer/state template are rebuilt from it and
        the newest (or ``epoch``-pinned) checkpoint is restored into it.
        ``samples`` provide the bucket table + feature signature, exactly
        as in :meth:`add_model`."""
        import jax as _jax
        import jax.numpy as _jnp

        from ..config.schema import load_config
        from ..graphs.batching import collate, compute_pad_spec
        from ..models.create import create_model_config
        from ..train.checkpoint import load_checkpoint
        from ..train.optimizer import select_optimizer
        from ..train.step import create_train_state

        if config is None:
            config = load_config(os.path.join(path, log_name, "config.json"))
        if not samples:
            raise ValueError(
                "add_model_from_checkpoint needs `samples` to derive the "
                "bucket table and the state template's batch shapes"
            )
        model = create_model_config(config)
        opt = select_optimizer(
            config["NeuralNetwork"]["Training"]["Optimizer"]
        )
        bs = int(
            add_model_kwargs.get("batch_size")
            or config["NeuralNetwork"]["Training"].get("batch_size", 32)
        )
        probe = list(samples[: max(1, min(len(samples), bs))])
        pad = compute_pad_spec(probe, len(probe))
        template = create_train_state(
            model, opt, _jax.tree.map(_jnp.asarray, collate(probe, pad))
        )
        state, _meta = load_checkpoint(
            template, log_name, path=path, epoch=epoch
        )
        return self.add_model(
            name, model, state, config, samples=samples, **add_model_kwargs
        )

    def warmup(self, verify: bool = True) -> dict:
        """Boot-time compile of every (model, bucket) executable. The
        persistent XLA disk cache is enabled (inside ``ModelEndpoint.warm``),
        so a restarted server re-lowers but skips the backend compile.
        Returns per-model per-bucket compile seconds — the README's warm-up
        cost table is this dict."""
        t0 = time.perf_counter()
        report = {
            name: ep.warm(verify=verify) for name, ep in self._models.items()
        }
        report["total_s"] = round(time.perf_counter() - t0, 4)
        tel.emit(
            "serve_warmup", models=sorted(self._models),
            total_s=report["total_s"],
        )
        # every (model, bucket) executable above fed the cost ledger; a
        # path-valued HYDRAGNN_LEDGER persists the document here so serve
        # warm-ups leave the same ledger.json evidence trains/screens do
        tel.ledger.maybe_save()
        return report

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "PredictionServer":
        if self._running:
            return self
        if not self._models:
            raise RuntimeError("no models registered")
        if self.cfg.warmup:
            for ep in self._models.values():
                if not ep.executables:
                    ep.warm(verify=False)
                elif ep.cfg.quantize and not ep.executables_quant:
                    # fp32 table warm but the quant half missing (e.g. a
                    # caught QuantizationError from an earlier warmup()):
                    # re-run the quant warm so start() either serves REAL
                    # int8 or fails loudly — never quantize=true-but-fp32
                    ep.warm_quant()
        self._stopping = False
        for ep in self._models.values():
            if ep.queue.closed:  # restart after stop(): re-arm the queue
                ep.reset_queue()
        for ep in self._models.values():
            ep.thread = threading.Thread(
                target=self._dispatch_loop, args=(ep,),
                name=f"serve-{ep.name}", daemon=True,
            )
            ep.thread.start()
        self._running = True
        return self

    def stop(self) -> None:
        if not self._running:
            return
        self._stopping = True
        for ep in self._models.values():
            for req in ep.queue.close():
                # drained futures are PENDING or client-CANCELLED (never
                # dispatched); reject() is safe for both, and either way the
                # request terminated unserved — count it
                req.reject(
                    ServerClosedError("server stopped with the request queued")
                )
                ep._count("cancelled")  # keeps submitted == resolved sum
        for ep in self._models.values():
            if ep.thread is not None:
                ep.thread.join(timeout=10.0)
        self._running = False

    def __enter__(self) -> "PredictionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _dispatch_loop(self, ep: ModelEndpoint) -> None:
        batcher = ep.batcher  # this run's batcher: a restart makes a new one
        while True:
            got = batcher.next_batch(block=False)
            if got is None:
                # timeout poll (shutdown responsiveness) — or the queue was
                # closed, which must END the thread, not spin it hot
                if self._stopping or batcher.queue.closed:
                    return
                continue
            ep.serve_batch(*got)

    # -- request plane ------------------------------------------------------

    def submit(self, model: str, sample: GraphSample,
               deadline_ms: float | None = None) -> Future:
        """Admit one request; returns its Future. Sheds with a typed
        exception RAISED here when admission fails (queue full / unknown
        model / stopped server) — the future path is only for requests that
        were actually admitted."""
        ep = self._models.get(model)
        if ep is None:
            raise UnknownModelError(
                f"no model {model!r}; serving: {sorted(self._models)}"
            )
        if not self._running:
            raise ServerClosedError("server not started")
        if deadline_ms is None and self.cfg.deadline_ms:
            deadline_ms = self.cfg.deadline_ms
        deadline = (
            time.monotonic() + deadline_ms / 1e3 if deadline_ms else None
        )
        req = Request(sample=sample, deadline=deadline)
        ep._count("submitted")
        try:
            # admission-layer sheds (schema mismatch, queue full, closing
            # race) all land in the 'shed' counter — an operator watching
            # stats() sees misrouted traffic, not just backpressure
            ep.check_sample(sample)
            ep.queue.put(req)
        except Exception as exc:
            ep._count("shed")
            tel.emit("shed", model=model, reason=type(exc).__name__)
            raise
        return req.future

    def predict(self, model: str, samples: Sequence[GraphSample],
                deadline_ms: float | None = None, timeout: float = 60.0):
        """Synchronous convenience: submit every sample, wait, return the
        per-request ``heads`` lists in order."""
        futures = [self.submit(model, s, deadline_ms=deadline_ms) for s in samples]
        return [f.result(timeout=timeout)["heads"] for f in futures]

    def stats(self) -> dict:
        """Per-model serving counters, plus batch occupancy (real graphs per
        padded graph slot — the micro-batcher's packing efficiency)."""
        out = {}
        for name, ep in self._models.items():
            with ep._lock:
                c = dict(ep.counters)
            c["queue_depth"] = len(ep.queue)
            c["buckets"] = [b.as_tuple() for b in ep.buckets]
            c["warm_executables"] = len(ep.executables)
            c["quantized"] = bool(
                ep.cfg.quantize and ep.executables_quant
            )
            c["quant_executables"] = len(ep.executables_quant)
            if ep.quant_bounds is not None:
                c["quant_bounds"] = [round(b, 6) for b in ep.quant_bounds]
            c["occupancy"] = round(
                c["real_graph_slots"] / c["graph_slots"], 4
            ) if c["graph_slots"] else None
            # registry mirror of the derived values (counters dual-write at
            # their increment sites); the dict itself stays byte-compatible
            tel.publish("serve", c, model=name)
            out[name] = c
        return out


__all__ = [
    "ModelEndpoint",
    "PredictionServer",
    "ServingConfig",
    "serving_config_defaults",
]
