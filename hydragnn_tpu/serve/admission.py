"""Request queue with admission control for the serving tier.

Load-shedding is TYPED: every rejection is a distinct exception class so
clients (and the traffic generator's shed accounting) can tell "queue full —
back off" from "your request can never be served — fix it" without string
matching. The queue itself is a small deque + condition variable rather than
``queue.Queue`` because the micro-batcher needs two operations Queue lacks:
push-back (a request that would overflow the current bucket returns to the
HEAD so arrival order — and therefore deadline order — is preserved) and
drain-on-shutdown (pending futures must fail loudly, not hang forever).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

from ..graphs.graph import GraphSample


class AdmissionError(RuntimeError):
    """Base class of every typed serving rejection."""


class QueueFullError(AdmissionError):
    """Bounded queue at capacity — load shed at admission; retry later."""


class DeadlineExceededError(AdmissionError):
    """The request's deadline passed before its batch dispatched."""


class OversizeError(AdmissionError):
    """The sample does not fit the largest padding bucket of the endpoint —
    or exceeds the per-graph node bound its warm programs were certified
    for — so no amount of waiting can serve it."""


class IncompatibleSampleError(AdmissionError):
    """The sample's feature widths do not match the endpoint's signature
    (the shapes its executables were AOT-compiled for) — e.g. a pe-less
    graph routed to a GPS endpoint, or the wrong input feature count."""


class UnknownModelError(AdmissionError):
    """Request routed to a model name the server does not host."""


class ServerClosedError(AdmissionError):
    """The server was stopped while the request waited in queue."""


@dataclass
class Request:
    """One in-flight prediction request: a single graph + its result slot."""

    sample: GraphSample
    future: Future = field(default_factory=Future)
    deadline: Optional[float] = None  # absolute time.monotonic() instant
    enqueued_at: float = field(default_factory=time.monotonic)

    def expired(self, now: float | None = None) -> bool:
        return self.deadline is not None and (now or time.monotonic()) >= self.deadline

    def claim(self) -> bool:
        """Transition the future to RUNNING; False if the client already
        cancelled it. MUST be called before resolving from server threads —
        an unguarded ``set_result``/``set_exception`` on a cancelled future
        raises ``InvalidStateError`` and would kill the dispatcher."""
        return self.future.set_running_or_notify_cancel()

    def reject(self, exc: BaseException) -> bool:
        """Claim-then-fail; returns False (and does nothing) if the client
        cancelled first. Safe from any server thread."""
        if not self.claim():
            return False
        self.future.set_exception(exc)
        return True


class RequestQueue:
    """Bounded FIFO of :class:`Request` with blocking get and head push-back."""

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self._q: deque[Request] = deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False  # guarded-by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def put(self, req: Request) -> None:
        """Admit or shed: a full queue raises :class:`QueueFullError`
        immediately (bounded depth IS the backpressure signal — blocking
        producers would just move the unbounded buffer into their threads)."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("server is stopped")
            if len(self._q) >= self.depth:
                raise QueueFullError(
                    f"queue at capacity ({self.depth}); request shed"
                )
            self._q.append(req)
            self._nonempty.notify()

    def get(self, timeout: float | None = None) -> Request | None:
        """Pop the oldest request, blocking up to ``timeout`` seconds.
        Returns ``None`` on timeout or when the queue is closed and empty."""
        with self._lock:
            if timeout is None:
                while not self._q and not self._closed:
                    self._nonempty.wait()
            else:
                end = time.monotonic() + timeout
                while not self._q and not self._closed:
                    remaining = end - time.monotonic()
                    if remaining <= 0 or not self._nonempty.wait(remaining):
                        break
            return self._q.popleft() if self._q else None

    def push_back(self, req: Request) -> None:
        """Return a request to the HEAD (it was popped but does not fit the
        batch being formed) — keeps FIFO order for the next batch."""
        with self._lock:
            self._q.appendleft(req)
            self._nonempty.notify()

    def close(self) -> list[Request]:
        """Stop admitting, wake every waiter, return the drained backlog so
        the caller can fail its futures."""
        with self._lock:
            self._closed = True
            drained = list(self._q)
            self._q.clear()
            self._nonempty.notify_all()
        return drained


__all__ = [
    "AdmissionError",
    "QueueFullError",
    "DeadlineExceededError",
    "IncompatibleSampleError",
    "OversizeError",
    "UnknownModelError",
    "ServerClosedError",
    "Request",
    "RequestQueue",
]
