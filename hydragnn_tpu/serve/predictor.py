"""Shared prediction core: step construction, per-head gather, denormalize.

This is the ONE implementation of "turn a trained state + a padded batch into
per-head physical-unit predictions" — the batch evaluator (``run_prediction``)
and the always-hot serving tier (``serve.server``) both execute it, so a
served answer is bit-identical to what the offline evaluator would report for
the same fp32 inputs on the same backend. Before this module the predict path
lived inline in ``run_prediction`` and a server would have had to fork it.
"""

from __future__ import annotations

import numpy as np

from ..models.base import head_columns
from ..train.step import TrainState, make_predict_step, resolve_precision


class Predictor:
    """Model + state + config bound into a reusable predict core.

    ``config`` is the AUGMENTED config dict (post ``update_config``): the
    precision policy and the denormalization minmax tables are read from it.

    - :meth:`outputs` — run the jitted predict step (var_output squeezed).
    - :meth:`gather` — per-head (true, pred) arrays for the REAL rows of a
      batch, exactly the collection loop ``run_prediction`` historically ran.
    - :meth:`split_graphs` — per-graph views of a batch's outputs, the unit
      the serving tier hands back to individual requests.
    - :meth:`denormalize` — min-max denormalization per the config's
      ``Variables_of_interest`` (no-op unless ``denormalize_output``).
    """

    def __init__(self, model, state: TrainState, config: dict,
                 donate_batch: bool = False):
        self.model = model
        self.state = state
        self.spec = model.spec
        self.voi = config["NeuralNetwork"]["Variables_of_interest"]
        self.compute_dtype = resolve_precision(
            config["NeuralNetwork"]["Training"].get("precision", "fp32")
        )
        self.predict_step = make_predict_step(
            model, compute_dtype=self.compute_dtype, donate_batch=donate_batch
        )
        self.cols = head_columns(model.spec)
        self._scales = None

    def outputs(self, batch, step=None):
        """Per-head prediction arrays for one padded batch (still padded;
        callers mask). ``step`` overrides the jitted predict step — the
        serving tier passes its per-bucket AOT executable here."""
        out = (step or self.predict_step)(self.state, batch)
        if self.spec.var_output:
            out = out[0]
        return out

    def gather(self, batch, out=None):
        """(trues, preds): per-head arrays holding only the REAL rows of
        ``batch`` — graph heads masked by ``graph_mask``, node heads by
        ``node_mask`` (the reference ``test()`` collection,
        train_validate_test.py:989-1080)."""
        if out is None:
            out = self.outputs(batch)
        trues, preds = [], []
        for ihead, (kind, col, dim) in enumerate(self.cols):
            if kind == "graph":
                mask = np.asarray(batch.graph_mask) > 0
                trues.append(np.asarray(batch.graph_y[:, col : col + dim])[mask])
                preds.append(np.asarray(out[ihead])[mask])
            else:
                mask = np.asarray(batch.node_mask) > 0
                trues.append(np.asarray(batch.node_y[:, col : col + dim])[mask])
                preds.append(np.asarray(out[ihead])[mask])
        return trues, preds

    def split_graphs(self, out, node_counts):
        """Split padded per-head outputs into per-graph results.

        ``node_counts``: real node count of each graph, in collate order.
        Returns a list (one entry per graph) of per-head np arrays: graph
        heads give the ``[dim]`` row for that graph, node heads the
        ``[n_i, dim]`` rows of that graph's nodes."""
        results = [[] for _ in node_counts]
        offsets = np.concatenate([[0], np.cumsum(node_counts)])
        for ihead, (kind, _col, _dim) in enumerate(self.cols):
            arr = np.asarray(out[ihead])
            for g in range(len(node_counts)):
                if kind == "graph":
                    results[g].append(arr[g])
                else:
                    results[g].append(arr[offsets[g] : offsets[g + 1]])
        return results

    def denormalize(self, trues, preds):
        """Map min-max-normalized values back to physical units when the
        config asks for it (reference ``postprocess.py:13``)."""
        if not self.voi.get("denormalize_output"):
            return trues, preds
        from ..postprocess.postprocess import output_denormalize

        return output_denormalize(self.voi, trues, preds, self.spec)

    def denormalize_preds(self, preds):
        """Preds-only denormalize for the serving hot path (no targets exist
        for a live request; running the paired API on duplicated inputs
        would double the per-request work). Scales are extracted once and
        cached — they are a property of the training dataset, not the batch."""
        if not self.voi.get("denormalize_output"):
            return preds
        if self._scales is None:
            from ..postprocess.postprocess import head_scales

            self._scales = head_scales(self.voi, self.spec)
        return [p * rng + lo for p, (lo, rng) in zip(preds, self._scales)]


__all__ = ["Predictor"]
