"""The ``Serving.fleet`` config block, single-sourced from one dataclass.

Same pattern as ``StoreConfig`` (``Dataset.store``) and ``ServingConfig``
(``Serving``): the :class:`FleetConfig` field defaults ARE the schema
defaults (``config.update_config`` fills the nested block from
``fleet_config_defaults`` and validates it through ``validate()``), and
the ``HYDRAGNN_FLEET_*`` env flags override at router construction.

Deliberately import-light (stdlib + the flag registry only): the config
schema validates this block at config-load time, long before any model —
or even jax — is imported.
"""

from __future__ import annotations

import dataclasses

PRIORITY_CLASSES = ("interactive", "batch", "best_effort")


@dataclasses.dataclass
class FleetConfig:
    """Fleet-router knobs.

    * ``replicas`` — how many replica processes a fleet deployment boots
      (``HYDRAGNN_FLEET_REPLICAS`` overrides; the router itself serves
      however many replicas are attached — this knob sizes deployments
      and the bench/test topologies).
    * ``budget_interactive`` / ``budget_batch`` / ``budget_best_effort`` —
      per-priority-class admission queue budgets. A class at budget sheds
      NEW arrivals of that class with a typed ``QueueFullError`` while the
      other classes keep admitting — under overload best-effort saturates
      and sheds first, interactive keeps flowing.
    * ``cache_bytes`` — byte budget of the router's content-addressed
      answer cache (0 disables; ``HYDRAGNN_FLEET_CACHE_BYTES`` overrides).
      Keyed on canonicalized graph bytes + model + quant flag, so
      duplicate molecules under heavy traffic cost zero replica compute.
    * ``auth`` — shared-secret token stamped on every replica round-trip
      (same misconfiguration-guard trust model as ``ShardServer``; an
      auth mismatch is LOUD, never failed over).
    * ``peer_timeout`` — connect/read deadline per replica socket; the
      watchdog severs round-trips at ~1.25x this, so even a
      byte-dribbling replica cannot park a request.
    * ``probe_interval`` / ``quarantine_base_s`` / ``quarantine_cap_s`` —
      the PR 4 quarantine + doubling re-probe clock, applied to inference
      replicas instead of shard owners.
    * ``inflight_per_replica`` — concurrent round-trips the router keeps
      open per replica (the replica's own micro-batcher coalesces them);
      also bounds the dispatch window that least-loaded routing balances.
    """

    replicas: int = 2
    budget_interactive: int = 256
    budget_batch: int = 128
    budget_best_effort: int = 64
    cache_bytes: int = 33_554_432  # 32 MiB
    auth: str | None = None
    peer_timeout: float = 30.0
    probe_interval: float = 0.5
    quarantine_base_s: float = 0.5
    quarantine_cap_s: float = 8.0
    inflight_per_replica: int = 2

    @staticmethod
    def from_config(config: "dict | FleetConfig | None") -> "FleetConfig":
        """Accepts a FleetConfig (copied), a full config dict (reads
        ``Serving.fleet``, absent = defaults), the ``Serving`` block, or
        the fleet block itself — recognized by its field names; unknown
        fields raise instead of silently falling back to defaults."""
        if isinstance(config, FleetConfig):
            return dataclasses.replace(config).apply_env()
        config = config or {}
        if "Serving" in config:
            # full config: its Serving.fleet block, absent = defaults
            serving = config["Serving"]
            if not isinstance(serving, dict):
                raise ValueError(
                    f"Serving must be a dict, got {type(serving).__name__}"
                )
            block = serving.get("fleet") or {}
        elif "fleet" in config:
            block = config["fleet"]  # the Serving block itself
        else:
            # the fleet block directly — recognized by its field names, so
            # a typo'd block raises instead of silently using defaults
            known = fleet_config_defaults()
            if config and not any(k in known for k in config):
                raise ValueError(
                    f"unrecognized fleet config keys {sorted(config)}; "
                    f"expected Serving.fleet fields {sorted(known)}"
                )
            block = config
        if not isinstance(block, dict):
            raise ValueError(
                f"Serving.fleet must be a dict, got {type(block).__name__}"
            )
        return FleetConfig(**block).apply_env()

    def apply_env(self) -> "FleetConfig":
        """Fold ``HYDRAGNN_FLEET_*`` overrides in (idempotent)."""
        from ...utils import flags

        n = flags.get(flags.FLEET_REPLICAS)
        if n is not None:
            self.replicas = int(n)
        b = flags.get(flags.FLEET_CACHE_BYTES)
        if b is not None:
            self.cache_bytes = int(b)
        return self

    def validate(self) -> "FleetConfig":
        """Range-check every field; the ONE implementation behind both the
        schema's nested ``Serving.fleet`` validation and direct router
        construction."""
        if int(self.replicas) < 1:
            raise ValueError(
                f"Serving.fleet.replicas must be >= 1, got {self.replicas}"
            )
        for cls in PRIORITY_CLASSES:
            key = f"budget_{cls}"
            if int(getattr(self, key)) < 1:
                raise ValueError(
                    f"Serving.fleet.{key} must be >= 1, got "
                    f"{getattr(self, key)}"
                )
        if int(self.cache_bytes) < 0:
            raise ValueError(
                "Serving.fleet.cache_bytes must be >= 0 (0 disables the "
                f"answer cache), got {self.cache_bytes}"
            )
        if self.auth is not None and not isinstance(self.auth, str):
            raise ValueError(
                f"Serving.fleet.auth must be a string token or null, got "
                f"{type(self.auth).__name__}"
            )
        for key in ("peer_timeout", "probe_interval", "quarantine_base_s",
                    "quarantine_cap_s"):
            if float(getattr(self, key)) <= 0:
                raise ValueError(
                    f"Serving.fleet.{key} must be > 0, got "
                    f"{getattr(self, key)}"
                )
        if int(self.inflight_per_replica) < 1:
            raise ValueError(
                "Serving.fleet.inflight_per_replica must be >= 1, got "
                f"{self.inflight_per_replica}"
            )
        return self

    def budget(self, priority: str) -> int:
        return int(getattr(self, f"budget_{priority}"))


def fleet_config_defaults() -> dict:
    """``{config key: default}`` for the ``Serving.fleet`` block — derived
    from ``dataclasses.fields`` so a future field cannot silently drop out
    of the schema/validation plumbing."""
    return {f.name: f.default for f in dataclasses.fields(FleetConfig)}


__all__ = ["FleetConfig", "PRIORITY_CLASSES", "fleet_config_defaults"]
