"""The ``Serving.fleet`` config block, single-sourced from one dataclass.

Same pattern as ``StoreConfig`` (``Dataset.store``) and ``ServingConfig``
(``Serving``): the :class:`FleetConfig` field defaults ARE the schema
defaults (``config.update_config`` fills the nested block from
``fleet_config_defaults`` and validates it through ``validate()``), and
the ``HYDRAGNN_FLEET_*`` env flags override at router construction.

The self-driving control planes nest here too: ``Serving.fleet.autoscale``
(:class:`AutoscalerConfig` — the SLO autoscaler's targets/hysteresis) and
``Serving.fleet.rollout`` (:class:`RolloutConfig` — the blue/green canary
knobs), each single-sourced from its own dataclass with the same
unknown-key-rejecting validation.

Deliberately import-light (stdlib + the flag registry only): the config
schema validates this block at config-load time, long before any model —
or even jax — is imported.
"""

from __future__ import annotations

import dataclasses

PRIORITY_CLASSES = ("interactive", "batch", "best_effort")


def _dataclass_defaults(cls) -> dict:
    """``{field: default}`` for a config dataclass, honoring
    ``default_factory`` fields (plain ``f.default`` is MISSING for those,
    which would silently drop a nested block out of the schema)."""
    out = {}
    for f in dataclasses.fields(cls):
        if f.default is not dataclasses.MISSING:
            out[f.name] = f.default
        else:
            out[f.name] = f.default_factory()
    return out


def _nested_block(config, key: str, known: dict, what: str) -> dict:
    """Resolve the ``Serving.fleet.<key>`` block from a full config, the
    ``Serving`` block, the ``fleet`` block, or the block itself
    (recognized by its field names — a typo'd block raises instead of
    silently falling back to defaults)."""
    config = config or {}
    if not isinstance(config, dict):
        raise ValueError(f"{what} must be a dict, got {type(config).__name__}")
    for outer in ("Serving", "fleet"):
        if outer in config:
            config = config[outer] or {}
            if not isinstance(config, dict):
                raise ValueError(
                    f"{outer} must be a dict, got {type(config).__name__}"
                )
    if key in config:
        block = config[key]
    elif config and not any(k in known for k in config):
        raise ValueError(
            f"unrecognized {what} config keys {sorted(config)}; "
            f"expected Serving.fleet.{key} fields {sorted(known)}"
        )
    else:
        block = config
    if block is None:
        return {}
    if not isinstance(block, dict):
        raise ValueError(
            f"Serving.fleet.{key} must be a dict, got {type(block).__name__}"
        )
    return block


@dataclasses.dataclass
class AutoscalerConfig:
    """The ``Serving.fleet.autoscale`` block: SLO targets + control-loop
    discipline for :class:`~hydragnn_tpu.serve.fleet.autoscaler.Autoscaler`.

    * ``enabled`` — arm the control loop (``HYDRAGNN_FLEET_AUTOSCALE``
      overrides). Off, the fleet survives faults but never repairs them.
    * ``interval_s`` — metrics poll period of the control loop.
    * ``min_replicas`` / ``max_replicas`` — the replica budget the loop
      may move within; it never retires below min nor spawns past max.
    * ``target_p99_ms`` — interactive-class p99 SLO; a recent p99 above
      it is a scale-up breach.
    * ``max_queue_per_replica`` — admission backlog per ACTIVE replica
      tolerated before queue depth counts as a breach.
    * ``shed_tolerance`` — sheds per poll interval tolerated before the
      shed rate counts as a breach.
    * ``up_consecutive`` / ``down_consecutive`` — hysteresis: that many
      CONSECUTIVE breach (calm) polls before a spawn (retire). Calm needs
      a longer streak than breach — capacity mistakes are asymmetric.
    * ``cooldown_s`` — dead time after any action (a fresh replica needs
      a poll or two of traffic before its effect is measurable; acting
      again inside the window double-corrects).
    * ``down_fraction`` — scale-down requires p99 under
      ``down_fraction * target_p99_ms`` (not merely under target), so the
      loop never oscillates around the SLO boundary.
    * ``drain_timeout_s`` — bound on draining a retiring replica's
      in-flight work before its rank is detached.
    """

    enabled: bool = False
    interval_s: float = 1.0
    min_replicas: int = 1
    max_replicas: int = 4
    target_p99_ms: float = 500.0
    max_queue_per_replica: int = 8
    shed_tolerance: int = 0
    up_consecutive: int = 2
    down_consecutive: int = 5
    cooldown_s: float = 10.0
    down_fraction: float = 0.3
    drain_timeout_s: float = 30.0

    @staticmethod
    def from_config(config: "dict | AutoscalerConfig | None") -> "AutoscalerConfig":
        if isinstance(config, AutoscalerConfig):
            return dataclasses.replace(config).apply_env()
        block = _nested_block(
            config, "autoscale", autoscaler_config_defaults(), "autoscale"
        )
        unknown = set(block) - set(autoscaler_config_defaults())
        if unknown:
            raise ValueError(
                f"Unknown Serving.fleet.autoscale key(s) {sorted(unknown)}; "
                f"known: {sorted(autoscaler_config_defaults())}"
            )
        return AutoscalerConfig(**block).apply_env()

    def apply_env(self) -> "AutoscalerConfig":
        from ...utils import flags

        v = flags.get(flags.FLEET_AUTOSCALE)
        if v is not None:
            self.enabled = bool(v)
        return self

    def validate(self) -> "AutoscalerConfig":
        if int(self.min_replicas) < 1:
            raise ValueError(
                "Serving.fleet.autoscale.min_replicas must be >= 1, got "
                f"{self.min_replicas}"
            )
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                "Serving.fleet.autoscale.max_replicas must be >= "
                f"min_replicas ({self.min_replicas}), got {self.max_replicas}"
            )
        for key in ("interval_s", "target_p99_ms", "drain_timeout_s",
                    "down_fraction"):
            if float(getattr(self, key)) <= 0:
                raise ValueError(
                    f"Serving.fleet.autoscale.{key} must be > 0, got "
                    f"{getattr(self, key)}"
                )
        if float(self.down_fraction) >= 1.0:
            raise ValueError(
                "Serving.fleet.autoscale.down_fraction must be < 1 (scale "
                "down only well clear of the SLO boundary), got "
                f"{self.down_fraction}"
            )
        for key in ("up_consecutive", "down_consecutive",
                    "max_queue_per_replica"):
            if int(getattr(self, key)) < 1:
                raise ValueError(
                    f"Serving.fleet.autoscale.{key} must be >= 1, got "
                    f"{getattr(self, key)}"
                )
        if int(self.shed_tolerance) < 0:
            raise ValueError(
                "Serving.fleet.autoscale.shed_tolerance must be >= 0, got "
                f"{self.shed_tolerance}"
            )
        if float(self.cooldown_s) < 0:
            raise ValueError(
                "Serving.fleet.autoscale.cooldown_s must be >= 0, got "
                f"{self.cooldown_s}"
            )
        return self


@dataclasses.dataclass
class RolloutConfig:
    """The ``Serving.fleet.rollout`` block: blue/green cutover knobs for
    :func:`~hydragnn_tpu.serve.fleet.rollout.blue_green_rollout`.

    * ``canary`` — require the bit-identity canary before cutover
      (``HYDRAGNN_ROLLOUT_CANARY`` overrides). Disabling it trades the
      served-answer parity proof for rollout speed — never do that for a
      checkpoint whose architecture changed.
    * ``canary_probes`` — pinned probe requests compared bit-for-bit
      between the live set and every green replica.
    * ``probe_timeout_s`` — per canary round-trip deadline.
    * ``drain_timeout_s`` — bound on draining each blue replica's
      in-flight work after cutover before its rank is detached.
    """

    canary: bool = True
    canary_probes: int = 4
    probe_timeout_s: float = 30.0
    drain_timeout_s: float = 30.0

    @staticmethod
    def from_config(config: "dict | RolloutConfig | None") -> "RolloutConfig":
        if isinstance(config, RolloutConfig):
            return dataclasses.replace(config).apply_env()
        block = _nested_block(
            config, "rollout", rollout_config_defaults(), "rollout"
        )
        unknown = set(block) - set(rollout_config_defaults())
        if unknown:
            raise ValueError(
                f"Unknown Serving.fleet.rollout key(s) {sorted(unknown)}; "
                f"known: {sorted(rollout_config_defaults())}"
            )
        return RolloutConfig(**block).apply_env()

    def apply_env(self) -> "RolloutConfig":
        from ...utils import flags

        v = flags.get(flags.ROLLOUT_CANARY)
        if v is not None:
            self.canary = bool(v)
        return self

    def validate(self) -> "RolloutConfig":
        if int(self.canary_probes) < 1:
            raise ValueError(
                "Serving.fleet.rollout.canary_probes must be >= 1, got "
                f"{self.canary_probes}"
            )
        for key in ("probe_timeout_s", "drain_timeout_s"):
            if float(getattr(self, key)) <= 0:
                raise ValueError(
                    f"Serving.fleet.rollout.{key} must be > 0, got "
                    f"{getattr(self, key)}"
                )
        return self


def autoscaler_config_defaults() -> dict:
    """``{key: default}`` for ``Serving.fleet.autoscale`` (derived from the
    dataclass fields — same single-sourcing as the parent block)."""
    return _dataclass_defaults(AutoscalerConfig)


def rollout_config_defaults() -> dict:
    """``{key: default}`` for ``Serving.fleet.rollout``."""
    return _dataclass_defaults(RolloutConfig)


@dataclasses.dataclass
class FleetConfig:
    """Fleet-router knobs.

    * ``replicas`` — how many replica processes a fleet deployment boots
      (``HYDRAGNN_FLEET_REPLICAS`` overrides; the router itself serves
      however many replicas are attached — this knob sizes deployments
      and the bench/test topologies).
    * ``budget_interactive`` / ``budget_batch`` / ``budget_best_effort`` —
      per-priority-class admission queue budgets. A class at budget sheds
      NEW arrivals of that class with a typed ``QueueFullError`` while the
      other classes keep admitting — under overload best-effort saturates
      and sheds first, interactive keeps flowing.
    * ``cache_bytes`` — byte budget of the router's content-addressed
      answer cache (0 disables; ``HYDRAGNN_FLEET_CACHE_BYTES`` overrides).
      Keyed on canonicalized graph bytes + model + quant flag, so
      duplicate molecules under heavy traffic cost zero replica compute.
    * ``auth`` — shared-secret token stamped on every replica round-trip
      (same misconfiguration-guard trust model as ``ShardServer``; an
      auth mismatch is LOUD, never failed over).
    * ``peer_timeout`` — connect/read deadline per replica socket; the
      watchdog severs round-trips at ~1.25x this, so even a
      byte-dribbling replica cannot park a request.
    * ``probe_interval`` / ``quarantine_base_s`` / ``quarantine_cap_s`` —
      the PR 4 quarantine + doubling re-probe clock, applied to inference
      replicas instead of shard owners.
    * ``inflight_per_replica`` — concurrent round-trips the router keeps
      open per replica (the replica's own micro-batcher coalesces them);
      also bounds the dispatch window that least-loaded routing balances.
    * ``quarantine_jitter`` — random spread (fraction of the backoff) added
      to each quarantine re-probe deadline so multiple clients don't
      re-probe a recovering replica in the same instant (0 = the old
      synchronized doubling clock).
    * ``boot_timeout_s`` — how long ``spawn_replica`` waits for a worker's
      ready file before declaring the boot dead (serialized-AOT boots
      finish in seconds; compile-from-source can take minutes).
    * ``serialized_boot`` — let workers boot from persisted ``jax.export``
      artifacts instead of recompiling when a matching artifact exists
      (``HYDRAGNN_SERIALIZED_BOOT`` overrides); mismatched fingerprints
      fall back to compile-from-source LOUDLY.
    * ``autoscale`` / ``rollout`` — nested control-plane blocks; see
      :class:`AutoscalerConfig` and :class:`RolloutConfig`.
    """

    replicas: int = 2
    budget_interactive: int = 256
    budget_batch: int = 128
    budget_best_effort: int = 64
    cache_bytes: int = 33_554_432  # 32 MiB
    auth: str | None = None
    peer_timeout: float = 30.0
    probe_interval: float = 0.5
    quarantine_base_s: float = 0.5
    quarantine_cap_s: float = 8.0
    inflight_per_replica: int = 2
    quarantine_jitter: float = 0.25
    boot_timeout_s: float = 300.0
    serialized_boot: bool = True
    autoscale: dict = dataclasses.field(default_factory=autoscaler_config_defaults)
    rollout: dict = dataclasses.field(default_factory=rollout_config_defaults)

    @staticmethod
    def from_config(config: "dict | FleetConfig | None") -> "FleetConfig":
        """Accepts a FleetConfig (copied), a full config dict (reads
        ``Serving.fleet``, absent = defaults), the ``Serving`` block, or
        the fleet block itself — recognized by its field names; unknown
        fields raise instead of silently falling back to defaults."""
        if isinstance(config, FleetConfig):
            return dataclasses.replace(config).apply_env()
        config = config or {}
        if "Serving" in config:
            # full config: its Serving.fleet block, absent = defaults
            serving = config["Serving"]
            if not isinstance(serving, dict):
                raise ValueError(
                    f"Serving must be a dict, got {type(serving).__name__}"
                )
            block = serving.get("fleet") or {}
        elif "fleet" in config:
            block = config["fleet"]  # the Serving block itself
        else:
            # the fleet block directly — recognized by its field names, so
            # a typo'd block raises instead of silently using defaults
            known = fleet_config_defaults()
            if config and not any(k in known for k in config):
                raise ValueError(
                    f"unrecognized fleet config keys {sorted(config)}; "
                    f"expected Serving.fleet fields {sorted(known)}"
                )
            block = config
        if not isinstance(block, dict):
            raise ValueError(
                f"Serving.fleet must be a dict, got {type(block).__name__}"
            )
        return FleetConfig(**block).apply_env()

    def apply_env(self) -> "FleetConfig":
        """Fold ``HYDRAGNN_FLEET_*`` overrides in (idempotent)."""
        from ...utils import flags

        n = flags.get(flags.FLEET_REPLICAS)
        if n is not None:
            self.replicas = int(n)
        b = flags.get(flags.FLEET_CACHE_BYTES)
        if b is not None:
            self.cache_bytes = int(b)
        s = flags.get(flags.SERIALIZED_BOOT)
        if s is not None:
            self.serialized_boot = bool(s)
        return self

    def validate(self) -> "FleetConfig":
        """Range-check every field; the ONE implementation behind both the
        schema's nested ``Serving.fleet`` validation and direct router
        construction."""
        if int(self.replicas) < 1:
            raise ValueError(
                f"Serving.fleet.replicas must be >= 1, got {self.replicas}"
            )
        for cls in PRIORITY_CLASSES:
            key = f"budget_{cls}"
            if int(getattr(self, key)) < 1:
                raise ValueError(
                    f"Serving.fleet.{key} must be >= 1, got "
                    f"{getattr(self, key)}"
                )
        if int(self.cache_bytes) < 0:
            raise ValueError(
                "Serving.fleet.cache_bytes must be >= 0 (0 disables the "
                f"answer cache), got {self.cache_bytes}"
            )
        if self.auth is not None and not isinstance(self.auth, str):
            raise ValueError(
                f"Serving.fleet.auth must be a string token or null, got "
                f"{type(self.auth).__name__}"
            )
        for key in ("peer_timeout", "probe_interval", "quarantine_base_s",
                    "quarantine_cap_s"):
            if float(getattr(self, key)) <= 0:
                raise ValueError(
                    f"Serving.fleet.{key} must be > 0, got "
                    f"{getattr(self, key)}"
                )
        if int(self.inflight_per_replica) < 1:
            raise ValueError(
                "Serving.fleet.inflight_per_replica must be >= 1, got "
                f"{self.inflight_per_replica}"
            )
        if float(self.quarantine_jitter) < 0:
            raise ValueError(
                "Serving.fleet.quarantine_jitter must be >= 0 (0 disables "
                f"re-probe jitter), got {self.quarantine_jitter}"
            )
        if float(self.boot_timeout_s) <= 0:
            raise ValueError(
                "Serving.fleet.boot_timeout_s must be > 0, got "
                f"{self.boot_timeout_s}"
            )
        # The nested control-plane blocks validate through their own
        # dataclasses; unknown keys inside them are rejected HERE so a
        # typo'd autoscale knob fails at config load, not mid-incident.
        for key, defaults_fn, cls in (
            ("autoscale", autoscaler_config_defaults, AutoscalerConfig),
            ("rollout", rollout_config_defaults, RolloutConfig),
        ):
            block = getattr(self, key) or {}
            if not isinstance(block, dict):
                raise ValueError(
                    f"Serving.fleet.{key} must be a dict, got "
                    f"{type(block).__name__}"
                )
            unknown = set(block) - set(defaults_fn())
            if unknown:
                raise ValueError(
                    f"Unknown Serving.fleet.{key} key(s) {sorted(unknown)}; "
                    f"known: {sorted(defaults_fn())}"
                )
            cls(**block).validate()
        return self

    def budget(self, priority: str) -> int:
        return int(getattr(self, f"budget_{priority}"))

    def autoscaler_config(self) -> AutoscalerConfig:
        """The nested ``autoscale`` block as a typed config (env applied)."""
        return AutoscalerConfig.from_config({"autoscale": dict(self.autoscale or {})})

    def rollout_config(self) -> RolloutConfig:
        """The nested ``rollout`` block as a typed config (env applied)."""
        return RolloutConfig.from_config({"rollout": dict(self.rollout or {})})


def fleet_config_defaults() -> dict:
    """``{config key: default}`` for the ``Serving.fleet`` block — derived
    from ``dataclasses.fields`` so a future field cannot silently drop out
    of the schema/validation plumbing (nested blocks come from their own
    ``default_factory``)."""
    return _dataclass_defaults(FleetConfig)


__all__ = [
    "AutoscalerConfig",
    "FleetConfig",
    "PRIORITY_CLASSES",
    "RolloutConfig",
    "autoscaler_config_defaults",
    "fleet_config_defaults",
    "rollout_config_defaults",
]
