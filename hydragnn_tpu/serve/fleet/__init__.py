"""Fleet serving: a multi-process RPC front end over ``PredictionServer``.

N replica processes (``replica.py`` — each a ``PredictionServer`` booted
from checkpoint paths alone, AOT-warmed before it advertises ready) behind
one :class:`~hydragnn_tpu.serve.fleet.router.FleetRouter` speaking the
shared ``utils.wire`` transport (the SAME framing/auth/watchdog machinery
as the elastic data plane — one transport, not two). The router adds
request-priority classes with per-class queue budgets and deadline-aware
shedding, least-loaded dispatch, health-checked failover (the PR 4
quarantine + doubling re-probe pattern, applied to inference replicas),
and a content-addressed answer cache so duplicate graphs under heavy
traffic cost zero replica compute.

Attribute access is lazy (PEP 562): ``serve.server`` imports this
package's ``config`` submodule at module level, and an eager router
import here would close an import cycle back into ``serve.server``.
"""

from .config import (  # noqa: F401
    AutoscalerConfig,
    FleetConfig,
    PRIORITY_CLASSES,
    RolloutConfig,
    autoscaler_config_defaults,
    fleet_config_defaults,
    rollout_config_defaults,
)

_LAZY = {
    "AnswerCache": ".cache",
    "answer_key": ".cache",
    "canonical_sample_bytes": ".cache",
    "FleetRouter": ".router",
    "Autoscaler": ".autoscaler",
    "CanaryMismatchError": ".rollout",
    "blue_green_rollout": ".rollout",
    "run_canary": ".rollout",
    "ReplicaBootError": ".replica",
    "ReplicaHost": ".replica",
    "ReplicaProcess": ".replica",
    "spawn_replica": ".replica",
    "worker_main": ".replica",
    "write_samples_file": ".replica",
}

__all__ = [
    "AnswerCache",
    "Autoscaler",
    "AutoscalerConfig",
    "CanaryMismatchError",
    "FleetConfig",
    "FleetRouter",
    "PRIORITY_CLASSES",
    "ReplicaBootError",
    "ReplicaHost",
    "ReplicaProcess",
    "RolloutConfig",
    "answer_key",
    "autoscaler_config_defaults",
    "blue_green_rollout",
    "canonical_sample_bytes",
    "fleet_config_defaults",
    "rollout_config_defaults",
    "run_canary",
    "spawn_replica",
    "worker_main",
    "write_samples_file",
]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod, __name__), name)
