"""The fleet router: one front door over N prediction replicas.

Admission (priority classes), dispatch (least-loaded), and health
(quarantine + re-probe failover) in one place, on the shared
``utils.wire`` transport:

* **priority classes** — every request is ``interactive`` / ``batch`` /
  ``best_effort``; each class has its own bounded admission queue
  (``Serving.fleet.budget_*``). A class at budget sheds NEW arrivals of
  that class with the SAME typed ``QueueFullError`` the in-process
  admission layer raises — under overload best-effort saturates and
  sheds first while interactive keeps admitting. Dispatch drains strict
  priority order, and expired requests shed typed
  (``DeadlineExceededError``) at dequeue — deadline-aware shedding, so a
  dead request never burns a replica slot.
* **least-loaded dispatch** — the dispatcher assigns each request to the
  healthy replica (advertising the model) with the fewest in-flight
  round-trips, rotating ties; ``inflight_per_replica`` bounds the window
  so the replica's own micro-batcher sees a steady trickle to coalesce.
* **failover** — a transport fault (connect refused, timeout, watchdog-
  severed dribble) quarantines the replica on the PR 4 doubling re-probe
  clock (``wire.HealthTable``), evicts its pooled sockets, and REQUEUES
  the in-flight request at the head of its class — a replica dying
  mid-request costs a retry on a sibling, never a lost request. Protocol
  errors stay loud: an auth-token mismatch or a replica-side exception
  rejects the future with the cause — a *reachable but wrong* replica is
  a configuration bug failover must not paper over.
* **answer cache** — a content-addressed byte-budgeted LRU
  (``fleet.cache``) keyed on canonicalized graph bytes + model + quant
  flag; a hit resolves the future at admission with arrays byte-identical
  to replica compute, at zero replica cost.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from ... import telemetry as tel
from ...utils import wire
from ...utils.retry import RetryPolicy
from .. import admission
from ..admission import (
    DeadlineExceededError,
    QueueFullError,
    Request,
    ServerClosedError,
    UnknownModelError,
)
from .cache import AnswerCache, answer_key
from .config import FleetConfig, PRIORITY_CLASSES

# the failover path retries ACROSS replicas; a per-replica backoff loop
# would multiply an outage by the replica count (same policy as the store)
_ONE_ATTEMPT = RetryPolicy(attempts=1)


@dataclasses.dataclass
class RoutedRequest(Request):
    """A :class:`~hydragnn_tpu.serve.admission.Request` plus routing state."""

    model: str = ""
    priority: str = "interactive"
    digest: str | None = None  # answer-cache key (None = cache disabled)
    attempts: int = 0          # replica round-trips consumed (failover cap)
    # fleet-unique trace id minted at admission (None = propagation off);
    # scoped around every downstream stage so the journal records of
    # admission -> dispatch -> replica execute -> reply -> cache fill share
    # it across processes
    request_id: str | None = None


@dataclasses.dataclass
class _Replica:
    rank: int
    host: str
    port: int
    models: tuple
    quantized: dict
    inflight: int = 0
    served: int = 0
    failures: int = 0
    # drain/retire lifecycle (guarded-by: _work, like the mutable counters
    # above): ``draining`` stops NEW dispatch while in-flight round-trips
    # finish; ``retired`` removes the replica from every routing/metrics
    # surface. Ranks stay stable — a retired replica keeps its list slot
    # (callers hold ranks across scale events), it is just never picked.
    draining: bool = False
    retired: bool = False


class FleetRouter:
    """Front door over attached replicas. Lifecycle::

        router = FleetRouter({"cache_bytes": 1 << 24, "peer_timeout": 5.0})
        router.attach("127.0.0.1", replica_a.port)
        router.attach("127.0.0.1", replica_b.port)
        router.start()
        fut = router.submit("mace_v2", sample, priority="interactive",
                            deadline_ms=50)
        heads = fut.result()["heads"]
        router.stop()

    ``attach`` pings the replica over the wire and trusts only what the
    validated pong advertises (ready bit, model list, quant flags) — a
    replica that has not finished AOT warm-up is not routable because it
    does not LISTEN until warm-up completes (the worker boot contract).
    """

    def __init__(self, config: "FleetConfig | dict | None" = None):
        self.cfg = FleetConfig.from_config(config).validate()
        self._rt = wire.RoundTripper(
            self.cfg.peer_timeout, auth_token=self.cfg.auth
        )
        self._health = wire.HealthTable(
            self.cfg.quarantine_base_s, self.cfg.quarantine_cap_s,
            jitter=self.cfg.quarantine_jitter,
        )
        self.cache = AnswerCache(self.cfg.cache_bytes)
        self._replicas: list[_Replica] = []  # guarded-by: _work
        # _work guards queues + inflight + counters; future resolution and
        # network round-trips happen OUTSIDE it (client done-callbacks run
        # inline on set_result — resolving under the lock could re-enter)
        self._work = threading.Condition(threading.Lock())
        self._queues: dict[str, deque] = {c: deque() for c in PRIORITY_CLASSES}  # guarded-by: _work
        self.counters = {  # guarded-by: _work
            "submitted": 0, "served": 0, "cache_hits": 0, "failed": 0,
            "cancelled": 0, "shed": 0, "shed_deadline": 0,
            "failovers": 0, "requeues": 0,
            **{f"shed_{c}": 0 for c in PRIORITY_CLASSES},
        }
        # per-class sliding latency windows (replica-served requests only —
        # cache hits would flatter the tail the autoscaler watches);
        # bounded deques, so stats() percentiles cost O(window) not O(traffic)
        self._latency: dict[str, deque] = {  # guarded-by: _work
            c: deque(maxlen=256) for c in PRIORITY_CLASSES
        }
        self._running = False
        self._stopping = False
        self._rot = 0  # guarded-by: _work
        self._dispatcher: threading.Thread | None = None
        self._exec: ThreadPoolExecutor | None = None
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None

    # -- topology -----------------------------------------------------------

    def attach(self, host: str, port: int) -> int:
        """Register one replica by address; returns its rank. Validates
        the ping pong (ready bit) through the shared ``wire.check_pong``
        and records the advertised model list + quant flags (the quant
        flag is part of the answer-cache key). Auth mismatch is LOUD."""
        z = self._rt.round_trip(
            (host, port), host, port, policy=_ONE_ATTEMPT,
            what=f"fleet attach ping to {host}:{port}",
            ping=np.asarray(1, np.int64),
        )
        self._check_protocol(z, host, port)
        wire.check_pong(z, f"attach of replica {host}:{port}", ready=1)
        names = tuple(
            n for n in wire.field_text(z.get("models")).split(",") if n
        )
        if not names:
            raise RuntimeError(
                f"replica {host}:{port} advertises no models; refusing to "
                "route to it"
            )
        qflags = np.asarray(z.get("quantized", np.zeros(len(names))), np.int64)
        quantized = {n: bool(qflags[i]) for i, n in enumerate(names)}
        with self._work:
            # quant flags must agree across replicas of one model: answers
            # differ between modes, so both least-loaded dispatch and the
            # (quant-flag-keyed) answer cache would mix them — a precision-
            # heterogeneous fleet is a configuration error, refused here
            # (retired generations don't constrain the new one)
            for r in self._replicas:
                if r.retired:
                    continue
                for m in set(r.models) & set(names):
                    if r.quantized.get(m) != quantized.get(m):
                        raise RuntimeError(
                            f"replica {host}:{port} serves {m!r} "
                            f"{'int8' if quantized[m] else 'fp32'} but "
                            f"replica {r.rank} serves it "
                            f"{'int8' if r.quantized.get(m) else 'fp32'} — "
                            "a fleet must serve one model in one precision"
                        )
            rank = len(self._replicas)
            self._replicas.append(_Replica(
                rank=rank, host=host, port=port, models=names,
                quantized=quantized,
            ))
            self._work.notify_all()
        return rank

    def _models_union(self) -> set:
        # draining replicas still count: their in-flight work finishes and,
        # during a cutover, the green generation is attached BEFORE blue
        # drains — so the served-model set never blinks empty
        return {m for r in self._replicas if not r.retired for m in r.models}

    def begin_drain(self, rank: int) -> None:
        """Stop dispatching NEW work to ``rank``; in-flight round-trips
        finish and resolve normally. Queued requests simply route to the
        other replicas — nothing is dropped or re-ordered."""
        with self._work:
            self._replicas[rank].draining = True
            self._work.notify_all()
        tel.emit("fleet_drain_begin", replica=rank)

    def retire(self, rank: int, timeout_s: float = 30.0) -> bool:
        """Drain ``rank`` and remove it from every routing surface. Blocks
        until its in-flight count hits zero (each decrement notifies
        ``_work``) or ``timeout_s`` passes; either way the replica is
        retired — on timeout its still-in-flight requests fail over through
        the normal transport-fault path when the process dies, so the
        zero-lost-requests property holds regardless. Returns True when the
        drain completed cleanly inside the timeout."""
        self.begin_drain(rank)
        deadline = time.monotonic() + float(timeout_s)
        with self._work:
            r = self._replicas[rank]
            while r.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._work.wait(min(remaining, 0.1))
            left = r.inflight
            drained = left == 0
            r.retired = True
            self._work.notify_all()
        self._health.lift(rank)  # no point probing a retired replica
        self._rt.evict((r.host, r.port))
        tel.emit("fleet_retire", replica=rank, drained=bool(drained))
        if not drained:
            warnings.warn(
                f"fleet replica {rank} retired with {left} round-trips "
                f"still in flight after {timeout_s}s drain; they resolve or "
                "fail over on their own"
            )
        return drained

    def active_ranks(self) -> list:
        """Ranks currently eligible for new dispatch (not draining, not
        retired) — the live set a rollout cuts over from and the replica
        count the autoscaler budgets against."""
        with self._work:
            return [
                r.rank for r in self._replicas
                if not r.draining and not r.retired
            ]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRouter":
        if self._running:
            return self
        if not self._replicas:
            raise RuntimeError("no replicas attached")
        self._stopping = False
        # fresh stop signal + transport: a restart after stop() must be
        # able to probe quarantined replicas again (the old event stays
        # set) and to pool sockets again (the old pool is closed)
        self._probe_stop = threading.Event()
        if self._rt.pool._closed:
            self._rt = wire.RoundTripper(
                self.cfg.peer_timeout, auth_token=self.cfg.auth
            )
        # headroom over the boot-time replica count: the autoscaler and
        # blue/green rollouts ATTACH replicas while the router is live, and
        # an executor sized exactly to the boot topology would serialize
        # the new capacity's round-trips behind the old pool
        self._exec = ThreadPoolExecutor(
            max_workers=max(
                16,
                max(1, len(self._replicas))
                * int(self.cfg.inflight_per_replica),
            ),
            thread_name_prefix="fleet-send",
        )
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="fleet-dispatch", daemon=True
        )
        self._dispatcher.start()
        self._running = True
        return self

    def stop(self) -> None:
        if not self._running:
            return
        with self._work:
            self._stopping = True
            self._work.notify_all()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=10.0)
        if self._exec is not None:
            # in-flight round-trips finish and resolve their futures; a
            # failed one requeues and is drained below
            self._exec.shutdown(wait=True)
        drained: list[RoutedRequest] = []
        with self._work:
            for q in self._queues.values():
                drained.extend(q)
                q.clear()
        for req in drained:
            if req.reject(ServerClosedError(
                "router stopped with the request queued"
            )):
                self._count("cancelled")
        self._probe_stop.set()
        self._rt.close()  # pooled sockets don't outlive the router
        self._running = False

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request plane ------------------------------------------------------

    def submit(self, model: str, sample, priority: str = "interactive",
               deadline_ms: float | None = None) -> Future:
        """Admit one request into its priority class; returns its Future.
        Sheds with a typed exception RAISED here when admission fails
        (class budget full / unknown model / stopped router); a cache hit
        resolves the future immediately — byte-identical to compute — and
        never touches a replica."""
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {priority!r}; classes: {PRIORITY_CLASSES}"
            )
        if not self._running:
            raise ServerClosedError("router not started")
        if model not in self._models_union():
            raise UnknownModelError(
                f"no attached replica serves {model!r}; serving: "
                f"{sorted(self._models_union())}"
            )
        self._count("submitted")
        deadline = (
            time.monotonic() + deadline_ms / 1e3 if deadline_ms else None
        )
        req = RoutedRequest(
            sample=sample, deadline=deadline, model=model, priority=priority
        )
        if tel.propagate_enabled():
            # adopt the caller's ambient request_id (an upstream tier may
            # have minted one) or mint the fleet-unique id every stage of
            # this request's timeline will share
            req.request_id = (
                tel.get_context().get("request_id") or tel.new_request_id()
            )
            tel.emit(
                "fleet_admit", request_id=req.request_id, model=model,
                **{"class": priority},
            )
        if self.cfg.cache_bytes > 0:
            quant = any(
                r.quantized.get(model, False) for r in self._replicas
            )
            req.digest = answer_key(sample, model, quantized=quant)
            hit = self.cache.get(req.digest)
            if hit is not None:
                self._count("cache_hits")
                self._count("served")
                if req.request_id is not None:
                    tel.emit(
                        "fleet_cache_hit", request_id=req.request_id,
                        model=model,
                    )
                if req.claim():
                    req.future.set_result({
                        "heads": hit,
                        "latency_s": time.monotonic() - req.enqueued_at,
                        "cached": True,
                    })
                return req.future
        shed_full = False
        with self._work:
            q = self._queues[priority]
            if len(q) >= self.cfg.budget(priority):
                self.counters[f"shed_{priority}"] += 1
                self.counters["shed"] += 1
                shed_full = True
            else:
                q.append(req)
                self._work.notify_all()
        if shed_full:
            tel.counter("fleet_requests", event=f"shed_{priority}").inc()
            tel.counter("fleet_requests", event="shed").inc()
            tel.emit("shed", **{"class": priority, "reason": "queue_full"})
            raise QueueFullError(
                f"{priority} class at budget "
                f"({self.cfg.budget(priority)}); request shed"
            )
        return req.future

    def predict(self, model: str, samples, priority: str = "interactive",
                deadline_ms: float | None = None, timeout: float = 60.0):
        """Synchronous convenience mirroring ``PredictionServer.predict``."""
        futures = [
            self.submit(model, s, priority=priority, deadline_ms=deadline_ms)
            for s in samples
        ]
        return [f.result(timeout=timeout)["heads"] for f in futures]

    def _count(self, key: str, by: int = 1) -> None:
        with self._work:
            self.counters[key] += by
        # dual-write into the unified registry (the dict stays the
        # test-pinned stats() surface; the labeled series feed metrics())
        tel.counter("fleet_requests", event=key).inc(by)

    # -- dispatch -----------------------------------------------------------

    def _pop_dispatchable_locked(
        self,
    ) -> "tuple[RoutedRequest | None, _Replica | None, list]":
        """Strict-priority pop of the oldest request whose model has a free
        replica slot — the slot is RESERVED (inflight++) under the same
        lock hold — plus the expired requests swept past on the way
        (rejected OUTSIDE the lock by the caller).

        A request whose model has no free slot STAYS QUEUED. The previous
        dispatcher popped first and parked on the slot wait holding the
        request, which (a) made class-budget accounting lie by one — a
        popped-but-undispatched request no longer counted against its
        class, so the class over-admitted past its budget — and (b)
        inverted priority: a popped best_effort parked on the slot wait
        beat any interactive request that arrived while it waited. Popping
        and reserving atomically makes both properties hold by
        construction instead of by timing luck."""
        expired: list = []
        # models probed slotless THIS scan: nothing can free a slot while
        # we hold _work, so N queued requests of one saturated model cost
        # one _pick_locked probe, not N (and the deque is walked by
        # iteration + one rebuild, never by O(n) index/delete)
        no_slot: set[str] = set()
        for cls in PRIORITY_CLASSES:
            q = self._queues[cls]
            if not q:
                continue
            chosen: "tuple[RoutedRequest, _Replica] | None" = None
            kept: list = []
            for req in q:
                if chosen is not None:
                    kept.append(req)
                    continue
                if req.expired():
                    expired.append(req)
                    continue
                if req.model in no_slot:
                    # no slot for THIS model: later requests of another
                    # model may still dispatch (strict priority, no
                    # cross-model head-of-line blocking); FIFO within
                    # (class, model) holds
                    kept.append(req)
                    continue
                target = self._pick_locked(req.model)
                if target is None:
                    no_slot.add(req.model)
                    kept.append(req)
                    continue
                target.inflight += 1
                chosen = (req, target)
            if len(kept) != len(q):
                q.clear()
                q.extend(kept)
            if chosen is not None:
                return chosen[0], chosen[1], expired
        return None, None, expired

    def _shed_expired(self, expired: list) -> None:
        for req in expired:
            if req.reject(DeadlineExceededError(
                "deadline passed while queued at the router"
            )):
                self._count("shed_deadline")
                self._count("shed")
                tel.emit(
                    "shed", **{"class": req.priority}, model=req.model,
                    reason="deadline",
                )
            else:
                self._count("cancelled")

    def _pick_locked(self, model: str) -> "_Replica | None":
        """Least-loaded HEALTHY replica advertising ``model`` with a free
        in-flight slot; ties rotate. Quarantined replicas are a last
        resort only when the model has NO healthy replica at all — a
        healthy sibling that is merely slot-saturated means WAIT for its
        slot (return None), not "burn one of the request's bounded
        failover attempts on a peer we already know is down": under a
        replica kill the survivor's window saturates instantly, and the
        old free-slots-beat-health order hammered every queued request
        into the dead peer until its attempt cap killed it."""
        avail = [
            r for r in self._replicas
            if model in r.models and not r.draining and not r.retired
            and r.inflight < self.cfg.inflight_per_replica
        ]
        if not avail:
            return None
        order = self._health.order([r.rank for r in avail], rot=self._rot)
        self._rot += 1
        by_rank = {r.rank: r for r in avail}
        pool = [by_rank[k] for k in order if not self._health.quarantined(k)]
        if not pool:
            if any(
                model in r.models and not r.draining and not r.retired
                and not self._health.quarantined(r.rank)
                for r in self._replicas
            ):
                return None  # healthy-but-saturated exists: wait for it
            pool = [by_rank[order[0]]]  # all quarantined: a request is
            # the cheapest live probe — try the soonest-due peer
        best = pool[0]
        for r in pool[1:]:
            if r.inflight < best.inflight:
                best = r
        return best

    def _dispatch_loop(self) -> None:
        while True:
            with self._work:
                if self._stopping:
                    return  # stop() drains whatever is still queued
                req, target, expired = self._pop_dispatchable_locked()
                if req is None and not expired:
                    # every state change notifies (submit, slot free in
                    # _serve_one's finally, requeue, attach, stop); the
                    # timeout is NOT the wakeup mechanism — it only bounds
                    # the deadline-expiry sweep on an otherwise idle router
                    self._work.wait(0.1)
                    continue
            self._shed_expired(expired)
            if req is None:
                continue
            # the pop already re-checked expiry at dequeue and reserved the
            # slot under the same lock hold — nothing can age between here
            # and the executor handoff but microseconds
            self._exec.submit(self._serve_one, req, target)

    # -- replica round-trip -------------------------------------------------

    def _serve_one(self, req: RoutedRequest, replica: _Replica) -> None:
        # the request's trace id becomes this dispatcher THREAD's journal
        # scope: every record below carries it, and RoundTripper.request
        # ships it to the replica inside the frame (propagation armed)
        with tel.scoped_context(request_id=req.request_id):
            self._serve_one_scoped(req, replica)

    def _serve_one_scoped(self, req: RoutedRequest, replica: _Replica) -> None:
        try:
            fields = {
                "predict": np.asarray(1, np.int64),
                "model": wire.text_field(req.model),
                **wire.sample_fields([req.sample]),
            }
            if req.request_id is not None:
                tel.emit(
                    "fleet_dispatch", model=req.model, replica=replica.rank,
                    attempt=req.attempts,
                )
            try:
                z = self._rt.round_trip(
                    (replica.host, replica.port), replica.host, replica.port,
                    policy=_ONE_ATTEMPT,
                    what=f"fleet predict on replica {replica.rank} "
                         f"({replica.host}:{replica.port})",
                    **fields,
                )
            except (ConnectionError, OSError) as e:
                # transport fault: quarantine + requeue — the request is
                # idempotent, a sibling replica serves it (zero lost)
                self._mark_replica_down(replica, e)
                self._requeue(req, e)
                return
            try:
                self._resolve(req, replica, z)
            except Exception as e:
                # a malformed reply (missing fields, bad shapes) must fail
                # THIS request loudly, never leave its claimed future
                # unresolved — an unhandled raise here would hang the
                # client until its own timeout with zero diagnostics
                exc = RuntimeError(
                    f"replica {replica.rank} answered an undecodable "
                    f"predict reply ({type(e).__name__}: {e})"
                )
                try:
                    claimed = req.claim()
                except RuntimeError:
                    claimed = True  # _resolve claimed it before raising
                if claimed:
                    if not req.future.done():
                        req.future.set_exception(exc)
                    self._count("failed")
                else:
                    self._count("cancelled")
        finally:
            with self._work:
                replica.inflight -= 1
                self._work.notify_all()

    def _resolve(self, req: RoutedRequest, replica: _Replica, z: dict) -> None:
        n = int(z["n"])
        if n == -4:
            # typed admission shed from the replica, re-raised as the SAME
            # serve.admission class. A transiently full replica queue
            # requeues (least-loaded may have raced a burst); every other
            # shed is an answer about the REQUEST, not the replica.
            etype = wire.field_text(z.get("etype"), "AdmissionError")
            detail = wire.field_text(z.get("detail"))
            exc_cls = getattr(admission, etype, admission.AdmissionError)
            if exc_cls is QueueFullError:
                # transient backpressure: retry at the TAIL after a beat
                # (head-requeue with no backoff would hammer the same full
                # replica queue in a hot loop)
                time.sleep(0.002)
                self._requeue(req, exc_cls(detail), head=False)
                return
            if req.reject(exc_cls(f"replica {replica.rank}: {detail}")):
                self._count("shed")
            else:
                self._count("cancelled")
            return
        if n < 0:
            # protocol errors stay LOUD (never failover): auth mismatch and
            # replica-side exceptions are configuration/server bugs a
            # sibling replica would just repeat — or worse, mask
            if n == -2:
                exc = RuntimeError(
                    f"fleet predict rejected by replica {replica.rank} "
                    f"({replica.host}:{replica.port}): auth token mismatch "
                    "(pass the same Serving.fleet.auth to router and "
                    "replicas)"
                )
            else:
                exc = RuntimeError(
                    f"replica {replica.rank} failed serving the request: "
                    f"{wire.frame_detail(z) or 'unknown error'}"
                )
            if req.reject(exc):
                self._count("failed")
            else:
                self._count("cancelled")
            return
        heads = [np.array(z[f"h{i}"]) for i in range(int(z["nheads"]))]
        self._health.lift(replica.rank)  # it answered: clear any suspicion
        latency_s = time.monotonic() - req.enqueued_at
        with self._work:
            replica.served += 1
            # the autoscaler's SLO signal: queue wait + round-trip, per
            # class, recorded for every replica-served answer (even ones a
            # racing cancel makes unclaimable — the latency was real)
            self._latency[req.priority].append(latency_s)
        if req.digest is not None:
            # insert BEFORE resolving the future: a client that resubmits
            # the same graph the instant its result lands must find the
            # cache populated, not race the insert
            self.cache.put(req.digest, heads)
            if req.request_id is not None:
                tel.emit("fleet_cache_fill", model=req.model)
        if not req.claim():
            self._count("cancelled")
            return
        if req.request_id is not None:
            tel.emit(
                "fleet_reply", model=req.model, replica=replica.rank,
                latency_s=round(latency_s, 6),
            )
        req.future.set_result({
            "heads": heads,
            "latency_s": latency_s,
            "replica": replica.rank,
            "cached": False,
        })
        self._count("served")

    def _requeue(self, req: RoutedRequest, err: BaseException,
                 head: bool = True) -> None:
        req.attempts += 1
        cap = max(4, 2 * len(self._replicas))
        if req.attempts >= cap:
            # keep the failure TYPED: a replica-side admission shed that
            # exhausted its retries is still an AdmissionError (callers
            # handle those); only transport faults become ConnectionError
            exc = err if isinstance(err, admission.AdmissionError) else (
                ConnectionError(
                    f"request failed on {req.attempts} replica "
                    f"round-trip(s); last error: "
                    f"{type(err).__name__}: {err}"
                )
            )
            if req.reject(exc):
                self._count("failed")
            else:
                self._count("cancelled")
            return
        requeued = False
        with self._work:
            if self._stopping:
                # stop() already drained (or is draining) the queues: fail
                # the future now instead of parking it forever
                pass
            else:
                self._count_locked("requeues")
                q = self._queues[req.priority]
                q.appendleft(req) if head else q.append(req)
                self._work.notify_all()
                requeued = True
        if requeued:
            tel.counter("fleet_requests", event="requeues").inc()
            return
        if req.reject(ServerClosedError(
            "router stopped while the request was failing over"
        )):
            self._count("cancelled")

    def _count_locked(self, key: str, by: int = 1) -> None:
        # caller holds _work — the registry dual-write happens at the
        # caller AFTER release (nesting the telemetry locks under _work
        # would add the exact lock-order edge cache.py documents avoiding)
        self.counters[key] += by

    def _mark_replica_down(self, replica: _Replica, err: BaseException) -> None:
        fresh = self._health.bump(replica.rank)
        self._rt.evict((replica.host, replica.port))
        with self._work:
            replica.failures += 1
            self.counters["failovers"] += 1
        tel.counter("fleet_requests", event="failovers").inc()
        tel.emit(
            "failover", replica=replica.rank, host=replica.host,
            port=replica.port, error=type(err).__name__,
            fresh_quarantine=bool(fresh),
        )
        if fresh:
            warnings.warn(
                f"fleet replica {replica.rank} ({replica.host}:"
                f"{replica.port}) is down ({type(err).__name__}: {err}): "
                "quarantined, in-flight requests fail over to siblings"
            )
        self._ensure_prober()

    # -- health probing ------------------------------------------------------

    def _ensure_prober(self) -> None:
        with self._health.lock:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                return
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="fleet-prober", daemon=True
            )
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        """Mirror of the ShardedStore prober on the shared machinery:
        ping due quarantined replicas (watchdog-guarded — a replica reborn
        as a dribbler must not wedge the singleton prober) and lift the
        quarantine only when the validated pong advertises the SAME
        identity it was attached under (ready + model list) — a replica
        restarted with different models must stay quarantined rather than
        silently serve the wrong endpoint set."""
        while not self._probe_stop.wait(self.cfg.probe_interval):
            with self._health.lock:
                if not self._health.entries:
                    self._probe_thread = None
                    return
            for rank in self._health.due_probes():
                replica = self._replicas[rank]
                try:
                    z = self._rt.round_trip(
                        (replica.host, replica.port),
                        replica.host, replica.port, policy=_ONE_ATTEMPT,
                        what=f"fleet probe of replica {rank}",
                        ping=np.asarray(1, np.int64),
                    )
                    self._check_protocol(z, replica.host, replica.port)
                    wire.check_pong(
                        z, f"probe of fleet replica {rank}", ready=1
                    )
                    advertised = wire.field_text(z.get("models"))
                    if advertised != ",".join(replica.models):
                        raise ConnectionError(
                            f"replica {rank} reborn with models "
                            f"[{advertised}], attached as "
                            f"[{','.join(replica.models)}]"
                        )
                except (ConnectionError, OSError):
                    self._health.bump(rank)
                    continue
                except RuntimeError:
                    # protocol rejection (e.g. auth flip): stays suspect,
                    # but keep probing — the operator may fix the config
                    self._health.bump(rank)
                    continue
                if self._health.lift(rank) is not None:
                    tel.emit("quarantine_lifted", replica=rank)
                    warnings.warn(
                        f"fleet replica {rank} ({replica.host}:"
                        f"{replica.port}) answers again: quarantine lifted"
                    )

    # -- protocol / stats ----------------------------------------------------

    @staticmethod
    def _check_protocol(z: dict, host: str, port: int) -> None:
        n = int(np.asarray(z.get("n", 0)).reshape(-1)[0]) if "n" in z else 0
        if n == -2:
            raise RuntimeError(
                f"replica {host}:{port} rejected the request: auth token "
                "mismatch (pass the same Serving.fleet.auth everywhere)"
            )
        if n == -3:
            raise RuntimeError(
                f"replica {host}:{port} failed: "
                f"{wire.frame_detail(z) or 'unknown error'}"
            )

    def replica_stats(self, rank: int) -> dict:
        """The replica's ``stats`` wire op, decoded — per-endpoint queue
        depth, shed counters, and its steady-lowering count (0 = the
        zero-recompile guarantee holding across the process boundary)."""
        r = self._replicas[rank]
        z = self._rt.round_trip(
            (r.host, r.port), r.host, r.port, policy=_ONE_ATTEMPT,
            what=f"fleet stats of replica {rank}",
            stats=np.asarray(1, np.int64),
        )
        self._check_protocol(z, r.host, r.port)
        return json.loads(wire.field_text(z["stats"]))

    def stats(self) -> dict:
        with self._work:
            c = dict(self.counters)
            depths = {cls: len(q) for cls, q in self._queues.items()}
            latency = {
                cls: (
                    round(
                        float(np.percentile(np.asarray(win), 99)) * 1e3, 3
                    )
                    if win else None
                )
                for cls, win in self._latency.items()
            }
            replicas = [
                {
                    "rank": r.rank, "host": r.host, "port": r.port,
                    "models": list(r.models), "inflight": r.inflight,
                    "served": r.served, "failures": r.failures,
                    "quarantined": self._health.quarantined(r.rank),
                    "draining": r.draining, "retired": r.retired,
                }
                for r in self._replicas
            ]
            active = sum(
                1 for r in self._replicas if not r.draining and not r.retired
            )
        c["queue_depths"] = depths
        # p99 over the per-class sliding windows (replica-served requests;
        # None = no traffic in the window yet) — the autoscaler's SLO input
        c["latency_p99_ms"] = latency
        c["replicas"] = replicas
        c["active_replicas"] = active
        c["cache"] = self.cache.stats()
        # registry mirror (counters dual-write at their increment sites)
        tel.publish("fleet", c)
        for cls, depth in depths.items():
            tel.gauge("fleet_queue_depth", **{"class": cls}).set(depth)
        return c

    def replica_metrics(self, rank: int) -> dict:
        """One replica's ``metrics`` wire op, decoded: its full telemetry
        registry snapshot plus its stats dict — the per-process view the
        fleet-wide aggregation below folds together."""
        r = self._replicas[rank]
        z = self._rt.round_trip(
            (r.host, r.port), r.host, r.port, policy=_ONE_ATTEMPT,
            what=f"fleet metrics of replica {rank}",
            metrics=np.asarray(1, np.int64),
        )
        self._check_protocol(z, r.host, r.port)
        return json.loads(wire.field_text(z["metrics"]))

    def metrics(self) -> dict:
        """The fleet-wide telemetry view: the router's own stats + registry
        snapshot, every reachable replica's ``metrics`` wire-op answer, and
        an aggregate row (total queue depth, shed/served counts, steady
        lowerings, cache hit-rate) — the one dict an operator (or the bench
        harness) reads to answer "how is the fleet doing". Quarantined or
        unreachable replicas report an ``error`` entry instead of hanging
        the aggregation."""
        out: dict = {
            "router": self.stats(),
            "registry": tel.snapshot(),
            "replicas": {},
        }
        live = [r for r in list(self._replicas) if not r.retired]
        agg = {
            "replicas_total": len(live),
            "replicas_reporting": 0,
            "queue_depth": 0,
            "shed": 0,
            "served": 0,
            "steady_lowerings": 0,
        }
        for r in live:
            if self._health.quarantined(r.rank):
                out["replicas"][str(r.rank)] = {"error": "quarantined"}
                continue
            try:
                m = self.replica_metrics(r.rank)
            except (ConnectionError, OSError, RuntimeError) as e:
                out["replicas"][str(r.rank)] = {
                    "error": f"{type(e).__name__}: {e}"
                }
                continue
            out["replicas"][str(r.rank)] = m
            stats = m.get("stats", {})
            agg["replicas_reporting"] += 1
            for key in ("queue_depth", "shed", "served", "steady_lowerings"):
                agg[key] += int(stats.get(key, 0) or 0)
        agg["cache_hit_rate"] = out["router"]["cache"].get("hit_rate")
        out["aggregate"] = agg
        tel.publish("fleet_aggregate", agg)
        return out


__all__ = ["FleetRouter", "RoutedRequest"]
