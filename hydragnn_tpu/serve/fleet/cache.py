"""Content-addressed answer cache for the fleet router.

Key = SHA-256 over the CANONICALIZED graph bytes + model name + quant
flag. Canonicalization reuses the wire codec (``utils.wire``): the
sample's arrays, key-sorted, packed with their dtype/shape specs — two
requests carrying the same molecule produce the same bytes regardless of
dict insertion order or array contiguity, and two molecules differing in
any feature bit produce different bytes (the codec frames raw array
bytes, so the digest covers every value exactly; no float rounding, no
summary hashing).

The cache is a byte-budgeted LRU: entries are charged the sum of their
per-head array bytes (plus key overhead), and inserts evict from the
cold end until the budget holds. Both ``put`` and ``get`` deep-copy —
the cache's instance stays pristine no matter what callers do to theirs
(the ADVICE r5 aliasing lesson), which is what lets the hit-path answers
stay BYTE-IDENTICAL to replica compute forever.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from ...graphs.graph import GraphSample
from ...utils import wire


def canonical_sample_bytes(sample: GraphSample) -> bytes:
    """The content-address preimage of one graph: its wire arrays in
    sorted key order (``pack_arrays`` covers name + dtype + shape + raw
    bytes per array, so any difference in any field changes the bytes)."""
    return wire.pack_arrays(dict(sorted(wire.sample_to_arrays(sample).items())))


def answer_key(sample: GraphSample, model: str, quantized: bool = False) -> str:
    """Digest of (canonical graph bytes, model name, quant flag). The
    quant flag is part of the address: an int8 answer and an fp32 answer
    for the same graph are DIFFERENT answers, and a fleet that flips
    quantization must never serve stale cross-mode hits."""
    h = hashlib.sha256()
    h.update(canonical_sample_bytes(sample))
    h.update(b"\x00model:")
    h.update(model.encode())
    h.update(b"\x00quant:1" if quantized else b"\x00quant:0")
    return h.hexdigest()


class AnswerCache:
    """Byte-budgeted LRU of per-request head answers, keyed by
    :func:`answer_key`. Thread-safe; array copies happen OUTSIDE the lock
    (the lock serializes bookkeeping only, so dispatcher threads don't
    stall each other on memcpy)."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, tuple[list[np.ndarray], int]]" = (  # guarded-by: _lock
            OrderedDict()
        )
        self.bytes = 0  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.insertions = 0  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        self.oversize_skips = 0  # guarded-by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @staticmethod
    def _cost(key: str, heads: list[np.ndarray]) -> int:
        return sum(int(a.nbytes) for a in heads) + len(key)

    def get(self, key: str) -> "list[np.ndarray] | None":
        """The cached heads (fresh writable copies) or None. A hit
        promotes the entry to the hot end."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            heads = entry[0]  # reference only under the lock
        return [np.array(a) for a in heads]

    def put(self, key: str, heads: "list[np.ndarray]") -> bool:
        """Insert (a pristine copy of) one answer; False when the cache is
        disabled (budget 0) or the single answer exceeds the whole budget
        (caching it would just evict everything else for one entry)."""
        if self.budget_bytes <= 0:
            return False
        copies = [np.array(a) for a in heads]
        cost = self._cost(key, copies)
        if cost > self.budget_bytes:
            with self._lock:
                self.oversize_skips += 1
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old[1]
            self._entries[key] = (copies, cost)
            self.bytes += cost
            self.insertions += 1
            while self.bytes > self.budget_bytes and self._entries:
                _, (_, evicted_cost) = self._entries.popitem(last=False)
                self.bytes -= evicted_cost
                self.evictions += 1
        return True

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            out = {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "insertions": self.insertions,
                "evictions": self.evictions,
                "oversize_skips": self.oversize_skips,
            }
        # registry mirror OUTSIDE the lock (telemetry has its own locks;
        # nesting them under ours would add a needless lock-order edge);
        # the returned dict stays the test-pinned byte-compatible surface
        from ... import telemetry as tel

        tel.publish("fleet_cache", out)
        return out


__all__ = ["AnswerCache", "answer_key", "canonical_sample_bytes"]
