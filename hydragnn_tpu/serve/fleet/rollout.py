"""Zero-downtime blue/green rollout for the serving fleet.

A model update used to mean a cold restart: stop the router, kill the
replicas, boot new ones, re-warm, reattach. :func:`blue_green_rollout`
replaces that with the production shape:

1. the caller boots the GREEN generation off to the side (new checkpoint,
   AOT-warm — serialized-AOT boot makes this seconds, not minutes); the
   router keeps serving from BLUE the whole time;
2. the **bit-identity canary**: every green replica is probed DIRECTLY
   over the wire (never through the router — canary traffic must not
   touch the answer cache or the SLO windows) on a pinned probe batch,
   and its served answers are compared bit-for-bit against the live
   set's answers on the same samples. Any mismatch REFUSES the rollout
   with a typed :class:`CanaryMismatchError` and leaves the live set
   untouched — green was never attached, nothing to unwind;
3. **cutover**: green attaches (new ranks, dispatchable immediately),
   then every blue rank drains — new dispatch stops, in-flight
   round-trips finish — and retires. A request admitted DURING the swap
   is served exactly once, by whichever generation dispatch hands it to;
   that is safe precisely because the canary proved the generations
   answer bit-identically, and the claim()-exactly-once future protocol
   already guarantees single resolution.

The router never stops, no queue is drained, no future is dropped: zero
dropped and zero double-served requests across the cutover, by
construction. Every stage lands in the telemetry journal as a
``rollout`` record (stage, ranks, canary verdicts), so the fleet CLI's
timeline shows the upgrade the same way it shows faults.
"""

from __future__ import annotations

import numpy as np

from ... import telemetry as tel
from ...utils import wire
from ...utils.retry import RetryPolicy
from .config import RolloutConfig

_ONE_ATTEMPT = RetryPolicy(attempts=1)


class CanaryMismatchError(RuntimeError):
    """A green replica's served answer differed from the live set's on a
    pinned probe — the rollout is refused and the live set untouched.
    Bit-identity is the contract that makes mid-cutover dual-serving safe;
    a generation that cannot meet it must not join the fleet."""


def _probe_predict(rt, host: str, port: int, model: str, sample,
                   what: str) -> list:
    """One direct-wire predict round-trip (the replica's normal serving
    path — micro-batcher, warm executable — just not via the router)."""
    z = rt.round_trip(
        (host, port), host, port, policy=_ONE_ATTEMPT, what=what,
        predict=np.asarray(1, np.int64),
        model=wire.text_field(model),
        **wire.sample_fields([sample]),
    )
    n = int(z["n"])
    if n != 1:
        raise CanaryMismatchError(
            f"{what}: replica {host}:{port} answered n={n} "
            f"({wire.field_text(z.get('etype')) or wire.frame_detail(z) or 'no detail'}) "
            "instead of serving the probe"
        )
    return [np.array(z[f"h{i}"]) for i in range(int(z["nheads"]))]


def _reference_answers(router, rt, probes: list) -> list:
    """The live set's answers on the probe batch: each probe goes to the
    first active, unquarantined blue replica advertising its model."""
    answers = []
    stats = {r["rank"]: r for r in router.stats()["replicas"]}
    for model, sample in probes:
        target = None
        for rank in router.active_ranks():
            row = stats[rank]
            if model in row["models"] and not row["quarantined"]:
                target = row
                break
        if target is None:
            raise RuntimeError(
                f"rollout canary: no active live replica serves {model!r} "
                "to answer the reference probe"
            )
        answers.append(_probe_predict(
            rt, target["host"], target["port"], model, sample,
            what=f"rollout reference probe ({model}) on live replica "
                 f"{target['rank']}",
        ))
    return answers


def run_canary(router, green: list, probes: list,
               cfg: RolloutConfig, rt=None) -> dict:
    """The bit-identity gate, callable on its own: probe every green
    replica on the pinned batch and compare bit-for-bit against the live
    set. Returns ``{green_index: "ok"}`` per replica; raises
    :class:`CanaryMismatchError` on the first divergence."""
    if not probes:
        raise ValueError(
            "rollout canary requires probe samples (rollout.canary_probes "
            "of them); pass canary=False only for a known "
            "answer-compatible generation"
        )
    probes = list(probes)[: int(cfg.canary_probes)]
    own_rt = rt is None
    if own_rt:
        rt = wire.RoundTripper(
            cfg.probe_timeout_s, auth_token=router.cfg.auth
        )
    verdicts: dict = {}
    try:
        reference = _reference_answers(router, rt, probes)
        for g_i, (host, port) in enumerate(green):
            for (model, sample), ref in zip(probes, reference):
                got = _probe_predict(
                    rt, host, port, model, sample,
                    what=f"rollout canary probe ({model}) on green "
                         f"{host}:{port}",
                )
                if len(got) != len(ref):
                    raise CanaryMismatchError(
                        f"green {host}:{port} answered {len(got)} heads for "
                        f"{model!r}, live set answered {len(ref)}"
                    )
                for h_i, (a, b) in enumerate(zip(ref, got)):
                    if a.shape != b.shape or not np.array_equal(a, b):
                        diff = (
                            float(np.max(np.abs(
                                a.astype(np.float64) - b.astype(np.float64)
                            )))
                            if a.shape == b.shape else None
                        )
                        raise CanaryMismatchError(
                            f"green {host}:{port} diverges from the live "
                            f"set on {model!r} head {h_i}: shapes "
                            f"{b.shape} vs {a.shape}, max|diff| {diff} — "
                            "rollout refused, live set untouched"
                        )
            verdicts[g_i] = "ok"
            tel.emit(
                "rollout", stage="canary", green=f"{host}:{port}",
                verdict="ok", probes=len(probes),
            )
    finally:
        if own_rt:
            rt.close()
    return verdicts


def _addresses(green) -> list:
    out = []
    for g in green:
        if isinstance(g, tuple):
            out.append((g[0], int(g[1])))
        else:
            out.append((getattr(g, "host", "127.0.0.1"), int(g.port)))
    return out


def blue_green_rollout(router, green, probes=None,
                       config: "RolloutConfig | dict | None" = None) -> dict:
    """Cut the fleet over from its current (blue) generation to ``green``.

    ``green`` — already-booted new-generation replicas: ``(host, port)``
    tuples or handles with ``.port`` (``ReplicaProcess``/``ReplicaHost``).
    ``probes`` — pinned ``(model, sample)`` pairs for the canary (required
    unless ``rollout.canary`` is off). Returns a report dict
    (``green_ranks``, ``blue_ranks``, per-rank drain verdicts, canary
    outcome). The caller owns the blue processes — terminate them after
    this returns (their ranks are retired, nothing routes to them)."""
    cfg = RolloutConfig.from_config(config).validate()
    addrs = _addresses(green)
    if not addrs:
        raise ValueError("rollout needs at least one green replica")
    blue = router.active_ranks()
    if not blue:
        raise RuntimeError("rollout: no active replicas to cut over from")
    tel.emit(
        "rollout", stage="begin", blue=list(blue),
        green=[f"{h}:{p}" for h, p in addrs], canary=bool(cfg.canary),
    )
    if cfg.canary:
        canary = run_canary(router, addrs, probes or [], cfg)
    else:
        canary = "skipped"
        tel.emit("rollout", stage="canary", verdict="skipped")
    # attach green FIRST: from this instant both generations are
    # dispatchable (bit-identical by the canary's proof), so the served-
    # model set never blinks and no queued request waits on the drain
    green_ranks = [router.attach(h, p) for h, p in addrs]
    tel.emit("rollout", stage="cutover", green_ranks=list(green_ranks))
    drained = {}
    for rank in blue:
        drained[rank] = router.retire(rank, timeout_s=cfg.drain_timeout_s)
    report = {
        "green_ranks": green_ranks,
        "blue_ranks": list(blue),
        "drained": drained,
        "canary": canary,
    }
    tel.emit(
        "rollout", stage="complete", green_ranks=list(green_ranks),
        blue_ranks=list(blue), drained_clean=all(drained.values()),
    )
    return report


__all__ = ["CanaryMismatchError", "blue_green_rollout", "run_canary"]
