"""One fleet replica: a ``PredictionServer`` behind the shared wire.

:class:`ReplicaHost` is the wire front end — a ``utils.wire.WireServer``
exposing three ops over the SAME transport the elastic data plane speaks:

* ``predict`` — one graph in (wire sample codec), per-head arrays out;
  typed admission errors (queue full, oversize, deadline, incompatible
  sample, unknown model) travel as ``n=-4`` records carrying the
  exception class name, so the router re-raises the SAME types
  ``serve.admission`` defines;
* ``ping`` — readiness + identity (model list, per-model quant flags);
  the router's health prober validates these through ``wire.check_pong``
  before lifting a quarantine, exactly like the ShardedStore prober
  validates a shard's advertised range;
* ``stats`` — per-endpoint queue depth, shed counters, and the
  STEADY-LOWERING COUNT (jit lowerings since the replica advertised
  ready — 0 is the AOT zero-recompile guarantee, now provable per
  replica across a process boundary) for routing/ops decisions;
* ``metrics`` — the replica's full telemetry-registry snapshot
  (``hydragnn_tpu.telemetry``) plus its stats dict, JSON over the wire;
  ``FleetRouter.metrics()`` folds every replica's answer into the
  fleet-wide aggregate view.

``worker_main`` is the subprocess entry (``python -m
hydragnn_tpu.serve.fleet.replica spec.json``): it boots a
``PredictionServer`` from CHECKPOINT PATHS ALONE
(``add_model_from_checkpoint``), completes AOT warm-up, and only then
binds the wire port and writes the ready file — a replica is never
routable before its executables are warm. ``spawn_replica`` is the
parent-side helper the router/bench/tests use.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

from ...utils import wire
from ..admission import AdmissionError
from .config import FleetConfig

_PREDICT_TIMEOUT_S = 120.0


class ReplicaBootError(RuntimeError):
    """A worker's ready file existed but could not be trusted: torn or
    garbage contents (writer killed mid-write, foreign file) or a payload
    missing the boot contract's fields. Carries the path and the partial
    contents so the operator sees WHAT was on disk, not an opaque
    ``JSONDecodeError`` from deep inside the poll loop."""


class ReplicaHost(wire.WireServer):
    """Wire front end of one (already registered + warmed) ``PredictionServer``.

    In-process it gives tests/bench a real-RPC replica without a
    subprocess boot; ``worker_main`` wraps the identical class around a
    checkpoint-booted server — one serving path, two deployment shapes."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 auth_token: str | None = None,
                 predict_timeout_s: float = _PREDICT_TIMEOUT_S,
                 journal=None):
        from ...analysis.sentinel import compile_counts

        self.server = server
        self._predict_timeout_s = float(predict_timeout_s)
        # lowering counter snapshot AT READY: stats() reports the delta,
        # which a warmed replica must keep at zero (the strict-sentinel
        # property, observable over the wire)
        self._ready_lowerings = int(compile_counts()["lowerings"])
        # journal= routes this replica's trace-scoped records into its own
        # EventJournal (subprocess workers: their log dir; in-process
        # tests: a distinct dir per replica) instead of the process-global
        # journal the router writes
        super().__init__(host=host, port=port, auth_token=auth_token,
                         name="ReplicaHost", journal=journal)

    # -- wire ops -----------------------------------------------------------

    def pong_fields(self) -> dict:
        names = sorted(self.server._models)
        quant = np.asarray(
            [
                1 if self.server._models[n].cfg.quantize
                and self.server._models[n].executables_quant else 0
                for n in names
            ],
            np.int64,
        )
        return {
            "ready": np.asarray(1, np.int64),
            "models": wire.text_field(",".join(names)),
            "quantized": quant,
        }

    def handle_frame(self, z: dict) -> bytes | dict:
        if "stats" in z:
            return {
                "n": np.asarray(0, np.int64),
                "stats": wire.text_field(json.dumps(self.stats())),
            }
        if "metrics" in z:
            return {
                "n": np.asarray(0, np.int64),
                "metrics": wire.text_field(json.dumps(self.metrics())),
            }
        if "predict" in z:
            return self._handle_predict(z)
        raise ValueError(f"unknown fleet op in frame keys {sorted(z)}")

    def _handle_predict(self, z: dict) -> dict:
        from ... import telemetry as tel

        model = wire.field_text(z.get("model"))
        sample = wire.samples_from_frame(z)[0]
        # the handler thread's scope (set by WireServer from the frame's
        # trace context) decides whether this predict is part of a traced
        # request — only then does it journal, so untraced traffic adds
        # zero records
        traced = bool(tel.get_context().get("request_id"))
        try:
            fut = self.server.submit(model, sample)
            result = fut.result(timeout=self._predict_timeout_s)
        except AdmissionError as e:
            # typed shed: the router re-raises the same admission class on
            # its side of the wire (never laundered into a transport fault
            # — a shed is an ANSWER, failover would re-ask the question)
            if traced:
                self.emit_event(
                    "replica_execute", model=model, shed=type(e).__name__,
                )
            return {
                "n": np.asarray(-4, np.int64),
                "etype": wire.text_field(type(e).__name__),
                "detail": wire.text_field(str(e)[:512]),
            }
        if traced:
            self.emit_event(
                "replica_execute", model=model,
                latency_s=round(float(result["latency_s"]), 6),
            )
        out = {
            "n": np.asarray(1, np.int64),
            "nheads": np.asarray(len(result["heads"]), np.int64),
            "latency_s": np.asarray(result["latency_s"], np.float64),
        }
        for i, head in enumerate(result["heads"]):
            out[f"h{i}"] = np.asarray(head)
        return out

    def stats(self) -> dict:
        from ...analysis.sentinel import compile_counts

        per_model = self.server.stats()
        return {
            "models": per_model,
            "queue_depth": sum(m["queue_depth"] for m in per_model.values()),
            "shed": sum(m["shed"] for m in per_model.values()),
            "served": sum(m["served"] for m in per_model.values()),
            # jit lowerings since this replica advertised ready: 0 is the
            # per-replica zero-recompile guarantee
            "steady_lowerings": int(compile_counts()["lowerings"])
            - self._ready_lowerings,
        }

    def metrics(self) -> dict:
        """The ``metrics`` wire op's payload: the replica process's whole
        telemetry registry (``stats()`` first, so derived gauges are
        fresh) plus the stats dict the aggregate row sums."""
        from ... import telemetry as tel

        stats = self.stats()  # publishes the serve gauges as a side effect
        return {"stats": stats, "registry": tel.snapshot()}


# -- subprocess worker --------------------------------------------------------


def _build_server(spec: dict):
    """Boot a ``PredictionServer`` from a worker spec: models come from
    checkpoint paths alone (``add_model_from_checkpoint``); bucket-table
    samples ride a wire-codec file next to the spec. Import cost (jax,
    models) is paid here, inside the worker process."""
    from ..server import PredictionServer, ServingConfig

    serving = dict(spec.get("serving") or {})
    server = PredictionServer(ServingConfig(**serving))
    # serialized-AOT boot: honored only when the fleet block (or the
    # HYDRAGNN_SERIALIZED_BOOT flag) says so — endpoints with an
    # artifact_dir deserialize warm executables instead of recompiling,
    # falling back loudly per bucket on a fingerprint mismatch
    fleet_cfg = FleetConfig.from_config(
        {"fleet": dict(serving.get("fleet") or {})}
    )
    for m in spec["models"]:
        with open(m["samples_file"], "rb") as f:
            samples = wire.samples_from_frame(wire.unpack_arrays(f.read()))
        kwargs = {
            k: m[k]
            for k in ("batch_size", "max_buckets", "denormalize", "epoch")
            if k in m
        }
        if fleet_cfg.serialized_boot and m.get("artifact_dir"):
            kwargs["artifact_dir"] = m["artifact_dir"]
        server.add_model_from_checkpoint(
            m["name"], m["log_name"], path=m.get("path", "./logs/"),
            samples=samples, **kwargs,
        )
    return server


def worker_main(argv=None) -> int:
    """``python -m hydragnn_tpu.serve.fleet.replica spec.json``.

    Boot order is the readiness contract: build → warm (AOT, verified
    lowering-free) → start → bind the wire port → write the ready file.
    A boot failure writes ``{"error": ...}`` to the ready file so the
    parent surfaces the cause instead of timing out blind."""
    argv = sys.argv[1:] if argv is None else argv
    with open(argv[0]) as f:
        spec = json.load(f)

    def _write_ready(payload: dict) -> None:
        ready = spec["ready_file"]
        tmp = ready + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, ready)  # atomic: the parent never reads a torn file

    try:
        from ... import telemetry as tel

        # the worker's own observability surfaces, rooted in its log dir
        # (default: next to the spec): the journal the fleet CLI merges
        # with the router's, and the cost ledger of its warmed executables
        log_dir = spec.get("log_dir") or os.path.dirname(
            os.path.abspath(spec.get("ready_file", argv[0])))
        journal = None
        if tel.enabled():
            journal = tel.open_journal(
                file=os.path.join(log_dir, "events.jsonl"),
                run_id=f"replica-{os.getpid()}",
            )
        server = _build_server(spec)
        server.warmup(verify=True)  # ready MEANS warm: zero first-request compiles
        tel.ledger.maybe_save(os.path.join(log_dir, "ledger.json"))
        server.start()
        host = ReplicaHost(
            server,
            host=spec.get("bind_host", "127.0.0.1"),
            port=int(spec.get("port", 0)),
            auth_token=spec.get("auth"),
            journal=journal,
        )
    except Exception:
        import traceback

        _write_ready({"error": traceback.format_exc(limit=8)})
        return 1

    stop = {"flag": False}

    def _terminate(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    _write_ready({"port": host.port, "pid": os.getpid()})
    while not stop["flag"]:
        time.sleep(0.1)
    host.close()
    server.stop()
    from ... import telemetry as tel

    tel.close_journal()
    return 0


class ReplicaProcess:
    """Handle on one spawned replica worker."""

    def __init__(self, proc: subprocess.Popen, port: int, spec_path: str,
                 log_path: str):
        self.proc = proc
        self.port = port
        self.spec_path = spec_path
        self.log_path = log_path

    def terminate(self, timeout_s: float = 10.0) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout_s)

    def kill(self) -> None:
        """The chaos path: SIGKILL, no teardown — a faithful host loss."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10.0)

    def log_tail(self, n: int = 40) -> str:
        try:
            with open(self.log_path, errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"


def write_samples_file(samples, path: str) -> str:
    """Persist bucket-table samples for a worker spec (wire codec — the
    same no-pickle frame format everything else speaks)."""
    with open(path, "wb") as f:
        f.write(wire.encode_samples(list(samples)))
    return path


def _read_ready_file(path: str) -> dict:
    """Parse a worker's ready file, typed-erroring on anything short of the
    boot contract. ``_write_ready`` is atomic (tmp + ``os.replace``), so a
    HEALTHY writer never leaves a torn file — but a writer killed mid-write,
    a crashed filesystem, or a foreign file can. Those used to surface as an
    opaque ``JSONDecodeError``; now they raise :class:`ReplicaBootError`
    naming the path and the partial contents."""
    try:
        with open(path, errors="replace") as f:
            raw = f.read()
    except OSError as e:
        raise ReplicaBootError(f"ready file {path} unreadable: {e!r}") from e
    try:
        ready = json.loads(raw)
    except ValueError as e:
        raise ReplicaBootError(
            f"ready file {path} is torn/garbage (writer killed mid-write?): "
            f"{e}; partial contents: {raw[:256]!r}"
        ) from e
    if not isinstance(ready, dict) or not (
        "error" in ready or "port" in ready
    ):
        raise ReplicaBootError(
            f"ready file {path} violates the boot contract (expected a dict "
            f"with 'port' or 'error'): {raw[:256]!r}"
        )
    return ready


def spawn_replica(spec: dict, timeout_s: float | None = None,
                  env: dict | None = None) -> ReplicaProcess:
    """Launch one worker subprocess and block until it advertises ready
    (which, per the boot contract, means AOT warm-up finished). Raises
    with the worker's log tail on boot failure/timeout.

    ``timeout_s=None`` (the default) takes ``Serving.fleet.boot_timeout_s``
    from the spec's serving block — one knob for every boot site instead of
    a hardcoded constant; pass an explicit value to override per call."""
    if timeout_s is None:
        timeout_s = FleetConfig.from_config(
            {"fleet": dict((spec.get("serving") or {}).get("fleet") or {})}
        ).boot_timeout_s
    workdir = tempfile.mkdtemp(prefix="hydragnn-fleet-")
    spec = dict(spec)
    spec.setdefault("ready_file", os.path.join(workdir, "ready.json"))
    spec_path = os.path.join(workdir, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    log_path = os.path.join(workdir, "worker.log")
    run_env = dict(os.environ)
    if env:
        run_env.update(env)
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "hydragnn_tpu.serve.fleet.replica",
             spec_path],
            stdout=log, stderr=subprocess.STDOUT, env=run_env,
        )
    handle = ReplicaProcess(proc, port=0, spec_path=spec_path,
                            log_path=log_path)
    deadline = time.monotonic() + float(timeout_s)
    while time.monotonic() < deadline:
        if os.path.exists(spec["ready_file"]):
            try:
                ready = _read_ready_file(spec["ready_file"])
            except ReplicaBootError:
                handle.terminate()
                raise
            if "error" in ready:
                handle.terminate()
                raise RuntimeError(
                    f"replica worker failed to boot:\n{ready['error']}"
                )
            handle.port = int(ready["port"])
            return handle
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica worker exited rc={proc.returncode} before ready:\n"
                f"{handle.log_tail()}"
            )
        time.sleep(0.1)
    handle.terminate()
    raise TimeoutError(
        f"replica worker not ready within {timeout_s}s:\n{handle.log_tail()}"
    )


if __name__ == "__main__":
    sys.exit(worker_main())


__all__ = [
    "ReplicaBootError",
    "ReplicaHost",
    "ReplicaProcess",
    "spawn_replica",
    "worker_main",
    "write_samples_file",
]
