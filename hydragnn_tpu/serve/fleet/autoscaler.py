"""The SLO autoscaler: the control loop that makes the fleet self-healing.

The router substrate (PR 11) already *survives* faults — a dead replica is
quarantined and its in-flight work fails over — and the telemetry plane
(PR 15/18) already *measures* everything: per-class latency windows, queue
depths, shed counts, all in one ``FleetRouter.metrics()`` poll. What was
missing is anything that ACTS on those signals. :class:`Autoscaler` closes
the loop:

* **scale up** when the interactive p99 breaches ``target_p99_ms``, the
  admission backlog exceeds ``max_queue_per_replica`` per active replica,
  or the shed count grows faster than ``shed_tolerance`` per poll — for
  ``up_consecutive`` consecutive polls (hysteresis: one bursty poll is
  noise, a streak is load);
* **scale down** only when the p99 sits UNDER ``down_fraction *
  target_p99_ms`` with an empty backlog and zero fresh sheds for
  ``down_consecutive`` polls — calm must prove itself for longer than
  breach does, because the two mistakes are asymmetric (a spare replica
  costs money; a missing one costs SLO);
* **cooldown** after every action: a fresh replica needs a poll or two of
  traffic before its effect shows in the windows, and acting again inside
  that blind spot double-corrects into oscillation;
* **drain-before-retire**: scale-down picks the busiest-rank-last active
  replica, stops new dispatch (``router.begin_drain``), waits for its
  in-flight round-trips to finish (``router.retire``), and only then
  terminates the process — zero lost requests by construction, the same
  property the failover path guarantees for crashes.

The decision core (:func:`decide`) is a PURE function of
``(AutoscalerConfig, AutoscalerState, signals)`` → action. The thread,
the router, and the subprocess management live around it, not in it — so
the hysteresis/cooldown/budget logic is unit-testable with fake replicas
and a fake clock, no sockets or sleeps (the non-slow stand-in the slow
chaos e2e rides on).

Every decision lands in the telemetry journal as an ``autoscale`` record
(action, reason, signals, replica count) — the fleet CLI's timeline shows
WHY the fleet grew, not just that it did.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ... import telemetry as tel
from .config import AutoscalerConfig

#: decide() return values
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"


@dataclasses.dataclass
class AutoscalerState:
    """Mutable controller state between polls (hysteresis streaks + the
    cooldown clock). Owned by one control loop; never shared."""

    breach_streak: int = 0
    calm_streak: int = 0
    last_action_at: float = float("-inf")
    last_shed: int = 0  # shed counter at the previous poll (rate baseline)


@dataclasses.dataclass
class Signals:
    """One poll's worth of SLO inputs, extracted from router stats."""

    p99_ms: float | None
    queue_depth: int
    shed_total: int
    active_replicas: int

    @staticmethod
    def from_stats(stats: dict) -> "Signals":
        depths = stats.get("queue_depths") or {}
        lat = stats.get("latency_p99_ms") or {}
        return Signals(
            p99_ms=lat.get("interactive"),
            queue_depth=int(sum(depths.values())),
            shed_total=int(stats.get("shed", 0)),
            active_replicas=int(stats.get("active_replicas", 0)),
        )


def decide(cfg: AutoscalerConfig, state: AutoscalerState, sig: Signals,
           now: float) -> tuple[str, str]:
    """One control decision: ``(action, reason)`` with ``action`` one of
    ``scale_up`` / ``scale_down`` / ``hold``. Pure — mutates only
    ``state`` (streaks, shed baseline), reads only its arguments, so tests
    drive it with a fake clock and hand-built signals.

    The caller applies the action and, if it acted, stamps
    ``state.last_action_at = now`` (the cooldown clock)."""
    fresh_shed = max(0, sig.shed_total - state.last_shed)
    state.last_shed = sig.shed_total

    breaches = []
    if sig.p99_ms is not None and sig.p99_ms > cfg.target_p99_ms:
        breaches.append(
            f"p99 {sig.p99_ms:.0f}ms > target {cfg.target_p99_ms:.0f}ms"
        )
    if sig.queue_depth > cfg.max_queue_per_replica * max(
        1, sig.active_replicas
    ):
        breaches.append(
            f"backlog {sig.queue_depth} > "
            f"{cfg.max_queue_per_replica}/replica"
        )
    if fresh_shed > cfg.shed_tolerance:
        breaches.append(f"{fresh_shed} sheds this interval")

    calm = (
        not breaches
        and sig.queue_depth == 0
        and fresh_shed == 0
        and (
            sig.p99_ms is None
            or sig.p99_ms < cfg.down_fraction * cfg.target_p99_ms
        )
    )

    if breaches:
        state.breach_streak += 1
        state.calm_streak = 0
    elif calm:
        state.calm_streak += 1
        state.breach_streak = 0
    else:
        # neither breached nor provably calm (e.g. p99 between the down
        # threshold and the target): both streaks reset — a scale decision
        # needs an unbroken run of evidence
        state.breach_streak = 0
        state.calm_streak = 0

    in_cooldown = now - state.last_action_at < cfg.cooldown_s
    if in_cooldown:
        return HOLD, "cooldown"
    if (
        state.breach_streak >= cfg.up_consecutive
        and sig.active_replicas < cfg.max_replicas
    ):
        return SCALE_UP, "; ".join(breaches)
    if state.breach_streak >= cfg.up_consecutive:
        return HOLD, (
            f"SLO breached ({'; '.join(breaches)}) but at max_replicas "
            f"({cfg.max_replicas})"
        )
    if (
        state.calm_streak >= cfg.down_consecutive
        and sig.active_replicas > cfg.min_replicas
    ):
        return SCALE_DOWN, (
            f"calm for {state.calm_streak} polls "
            f"(p99 {'-' if sig.p99_ms is None else f'{sig.p99_ms:.0f}ms'}, "
            "empty backlog, no sheds)"
        )
    return HOLD, "within targets"


class Autoscaler:
    """The control loop around :func:`decide`.

    ``spawn_fn()`` must boot one replica and return an object the router
    can be attached to — ``(host, port)`` or anything with ``.port`` (a
    ``ReplicaProcess`` from ``spawn_replica``, a ``ReplicaHost``, or a
    test fake); it is also remembered so scale-down can ``terminate()`` it
    if it exposes that. The autoscaler only ever retires replicas IT
    spawned (plus, optionally, ranks handed to ``adopt``) — it never
    retires the seed topology below ``min_replicas``, and never touches
    replicas a rollout owns.
    """

    def __init__(self, router, cfg: "AutoscalerConfig | dict | None" = None,
                 spawn_fn=None):
        self.router = router
        self.cfg = AutoscalerConfig.from_config(cfg).validate()
        self.spawn_fn = spawn_fn
        self.state = AutoscalerState()
        self._lock = threading.Lock()
        # rank -> spawned handle (terminate()-able), for scale-down; only
        # ranks this loop created or adopted are retire candidates
        self._owned: dict = {}  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.actions: list = []  # guarded-by: _lock (decision audit trail)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None and self._thread.is_alive():
            return self
        if self.spawn_fn is None:
            raise ValueError(
                "Autoscaler needs spawn_fn to scale up (a callable booting "
                "one replica)"
            )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, 2 * self.cfg.interval_s))
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def adopt(self, rank: int, handle=None) -> None:
        """Register an existing replica as retire-eligible (scale-down
        candidates are owned ranks only)."""
        with self._lock:
            self._owned[int(rank)] = handle

    # -- the loop ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.step()
            except Exception as e:  # a poll failure must not kill the loop
                tel.emit(
                    "autoscale", action="error",
                    error=f"{type(e).__name__}: {e}",
                )

    def step(self, now: float | None = None) -> tuple[str, str]:
        """One poll + decision + (maybe) action; callable directly by tests
        with a pinned ``now``. Returns ``(action, reason)``."""
        now = time.monotonic() if now is None else now
        sig = Signals.from_stats(self.router.stats())
        action, reason = decide(self.cfg, self.state, sig, now)
        if action == SCALE_UP:
            self._scale_up(reason, sig, now)
        elif action == SCALE_DOWN:
            self._scale_down(reason, sig, now)
        record = {
            "action": action, "reason": reason,
            "p99_ms": sig.p99_ms, "queue_depth": sig.queue_depth,
            "active_replicas": sig.active_replicas,
        }
        with self._lock:
            self.actions.append(record)
        if action != HOLD:
            tel.emit("autoscale", **record)
        return action, reason

    def _scale_up(self, reason: str, sig: Signals, now: float) -> None:
        handle = self.spawn_fn()
        host, port = self._address(handle)
        rank = self.router.attach(host, port)
        with self._lock:
            self._owned[rank] = handle
        self.state.last_action_at = now
        self.state.breach_streak = 0
        tel.emit(
            "autoscale", action="spawned", replica=rank, reason=reason,
        )

    def _scale_down(self, reason: str, sig: Signals, now: float) -> None:
        active = set(self.router.active_ranks())
        with self._lock:
            candidates = sorted(r for r in self._owned if r in active)
        if not candidates:
            return  # nothing owned is active: hold (seed topology stays)
        rank = candidates[-1]  # newest owned replica retires first
        drained = self.router.retire(
            rank, timeout_s=self.cfg.drain_timeout_s
        )
        with self._lock:
            handle = self._owned.pop(rank, None)
        if handle is not None and hasattr(handle, "terminate"):
            handle.terminate()
        self.state.last_action_at = now
        self.state.calm_streak = 0
        tel.emit(
            "autoscale", action="retired", replica=rank,
            drained=bool(drained), reason=reason,
        )

    @staticmethod
    def _address(handle) -> tuple:
        if isinstance(handle, tuple):
            return handle[0], int(handle[1])
        host = getattr(handle, "host", "127.0.0.1")
        return host, int(handle.port)


__all__ = [
    "Autoscaler",
    "AutoscalerState",
    "HOLD",
    "SCALE_DOWN",
    "SCALE_UP",
    "Signals",
    "decide",
]
