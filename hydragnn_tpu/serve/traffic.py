"""Synthetic serving traffic: open/closed-loop request generation + latency
accounting. The bench driver's ``serving_ab`` row and capacity experiments
both drive :class:`PredictionServer` through this one generator so p50/p99
and graphs/sec are measured the same way everywhere.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from .admission import AdmissionError, DeadlineExceededError, QueueFullError


@dataclass
class TrafficReport:
    """Latency/throughput summary of one traffic run. Latency is the
    client-observed submit→result-available wall time per request (measured
    via a done-callback on each future: queueing + coalescing wait +
    dispatch + result split + delivery into the future — everything short of
    the waiter's own wakeup scheduling, which no single-process measurement
    can see)."""

    n_requests: int = 0
    n_served: int = 0
    n_shed: int = 0
    n_deadline: int = 0
    wall_s: float = 0.0
    latencies_s: list = field(default_factory=list)
    # per-tag splits (mixed-priority runs tag each request with its class;
    # empty for untagged runs). Deadline rejections and admission sheds
    # are counted apart, mirroring the untagged n_shed / n_deadline split.
    latencies_by_tag: dict = field(default_factory=dict)
    shed_by_tag: dict = field(default_factory=dict)
    deadline_by_tag: dict = field(default_factory=dict)

    def percentile_ms(self, q: float, tag: str | None = None) -> float | None:
        xs = (
            self.latencies_s if tag is None
            else self.latencies_by_tag.get(tag, [])
        )
        if not xs:
            return None
        return round(1e3 * float(np.percentile(xs, q)), 3)

    def summary(self) -> dict:
        out = {
            "n_requests": self.n_requests,
            "n_served": self.n_served,
            "n_shed": self.n_shed,
            "n_deadline_exceeded": self.n_deadline,
            "p50_ms": self.percentile_ms(50),
            "p99_ms": self.percentile_ms(99),
            "graphs_per_sec": (
                round(self.n_served / self.wall_s, 2) if self.wall_s > 0 else None
            ),
            "wall_s": round(self.wall_s, 4),
        }
        for tag in sorted(self.latencies_by_tag):
            out[f"p99_ms_{tag}"] = self.percentile_ms(99, tag=tag)
        for tag, n in sorted(self.shed_by_tag.items()):
            out[f"n_shed_{tag}"] = n
        for tag, n in sorted(self.deadline_by_tag.items()):
            out[f"n_deadline_{tag}"] = n
        return out


def zipf_duplicate_order(n_requests: int, n_samples: int, alpha: float = 1.1,
                         seed: int = 0) -> np.ndarray:
    """Seeded Zipf-duplicate request order: index ``k`` drawn with weight
    ``1/(k+1)^alpha`` over ``n_samples`` — the heavy-head popularity shape
    of real traffic ("everyone asks about the same few molecules"), which
    is what a content-addressed answer cache exists to exploit. Bounded
    (weights over exactly ``n_samples``, not rejection-clipped) so the
    draw stays deterministic per (n_requests, n_samples, alpha, seed)."""
    if n_samples < 1:
        raise ValueError(f"n_samples must be >= 1, got {n_samples}")
    rng = np.random.default_rng(seed)
    weights = 1.0 / np.power(np.arange(1, n_samples + 1, dtype=np.float64),
                             float(alpha))
    weights /= weights.sum()
    return rng.choice(n_samples, size=int(n_requests), p=weights)


def mixed_priority_plan(n_requests: int, mix: dict | None = None,
                        seed: int = 0) -> list:
    """Seeded per-request priority classes. ``mix`` maps class name ->
    weight (normalized); default is an interactive-light / batch-heavy /
    best-effort-tail blend. Returns a list of class-name strings aligned
    with the request order."""
    mix = mix or {"interactive": 0.2, "batch": 0.5, "best_effort": 0.3}
    names = sorted(mix)
    weights = np.asarray([float(mix[k]) for k in names], np.float64)
    if (weights < 0).any() or weights.sum() <= 0:
        raise ValueError(f"bad priority mix {mix}")
    weights /= weights.sum()
    rng = np.random.default_rng(seed)
    picks = rng.choice(len(names), size=int(n_requests), p=weights)
    return [names[int(i)] for i in picks]


def run_traffic(
    server,
    model: str,
    samples,
    n_requests: int,
    rate_hz: float | None = None,
    seed: int = 0,
    deadline_ms: float | None = None,
    timeout_s: float = 120.0,
    order=None,
    priorities=None,
) -> TrafficReport:
    """Drive ``n_requests`` single-graph requests at the server, drawing
    samples uniformly (seeded) from ``samples``.

    ``rate_hz``: open-loop Poisson arrivals at that mean rate — the
    "millions of users" shape, where arrival times don't wait for results.
    ``None`` = closed burst: submit as fast as admission allows (admission
    shedding then exercises the bounded queue; shed requests are retried
    once after a short backoff, then counted shed).

    ``order``: explicit per-request sample indices (e.g.
    :func:`zipf_duplicate_order` for duplicate-heavy cache traffic);
    ``None`` keeps the original uniform draw — BYTE-COMPATIBLE with
    pre-fleet runs: the same seed consumes the same rng stream whether or
    not the new arguments exist. ``priorities``: per-request class names
    (:func:`mixed_priority_plan`) forwarded to routers that take a
    ``priority=`` submit kwarg; latencies/sheds are then also split per
    class in the report.
    """
    rng = np.random.default_rng(seed)
    if order is None:
        order = rng.integers(0, len(samples), size=n_requests)
    else:
        order = np.asarray(order)
        if len(order) != n_requests:
            raise ValueError(
                f"order has {len(order)} entries for {n_requests} requests"
            )
    if priorities is not None and len(priorities) != n_requests:
        raise ValueError(
            f"priorities has {len(priorities)} entries for "
            f"{n_requests} requests"
        )
    report = TrafficReport(n_requests=n_requests)
    futures = []
    latencies = []  # appended from done-callbacks (dispatcher threads)
    by_tag: dict = {}

    def _submit(sample, tag):
        t_sub = time.perf_counter()
        kw = {} if tag is None else {"priority": tag}
        fut = server.submit(model, sample, deadline_ms=deadline_ms, **kw)

        def _done(f, t_sub=t_sub, tag=tag):
            if f.exception() is None:
                # submit -> result-available: the client-observed latency,
                # stamped the instant the future resolves (polling result()
                # later would overstate early-completing requests)
                lat = time.perf_counter() - t_sub
                latencies.append(lat)
                if tag is not None:
                    by_tag.setdefault(tag, []).append(lat)

        fut.add_done_callback(_done)
        futures.append((fut, tag))

    def _count_shed(tag):
        report.n_shed += 1
        if tag is not None:
            report.shed_by_tag[tag] = report.shed_by_tag.get(tag, 0) + 1

    t0 = time.perf_counter()
    next_arrival = t0
    for i in range(n_requests):
        if rate_hz:
            next_arrival += float(rng.exponential(1.0 / rate_hz))
            now = time.perf_counter()
            if next_arrival > now:
                time.sleep(next_arrival - now)
        sample = samples[int(order[i])]
        tag = None if priorities is None else priorities[i]
        try:
            _submit(sample, tag)
        except QueueFullError:
            # queue-full is the RETRYABLE rejection (backpressure): one
            # retry after a beat, still-full counts as shed. Every other
            # admission error (unknown model, incompatible sample, closed
            # server) is a configuration bug — propagate, don't launder it
            # into the shed count.
            time.sleep(0.002)
            try:
                _submit(sample, tag)
            except QueueFullError:
                _count_shed(tag)
    for fut, tag in futures:
        try:
            fut.result(timeout=timeout_s)
            report.n_served += 1
        except DeadlineExceededError:
            report.n_deadline += 1
            if tag is not None:
                report.deadline_by_tag[tag] = (
                    report.deadline_by_tag.get(tag, 0) + 1
                )
        except AdmissionError:
            _count_shed(tag)
    report.wall_s = time.perf_counter() - t0
    # result() can unblock BEFORE the future's done-callback runs (waiters
    # are notified first in CPython), so give the last callbacks a bounded
    # beat to land — otherwise the tail request's latency goes missing
    wait_until = time.perf_counter() + 1.0
    while len(latencies) < report.n_served and time.perf_counter() < wait_until:
        time.sleep(0.001)
    report.latencies_s = list(latencies)
    report.latencies_by_tag = {k: list(v) for k, v in by_tag.items()}
    return report


__all__ = [
    "TrafficReport",
    "mixed_priority_plan",
    "run_traffic",
    "zipf_duplicate_order",
]
