"""Synthetic serving traffic: open/closed-loop request generation + latency
accounting. The bench driver's ``serving_ab`` row and capacity experiments
both drive :class:`PredictionServer` through this one generator so p50/p99
and graphs/sec are measured the same way everywhere.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from .admission import AdmissionError, DeadlineExceededError, QueueFullError


@dataclass
class TrafficReport:
    """Latency/throughput summary of one traffic run. Latency is the
    client-observed submit→result-available wall time per request (measured
    via a done-callback on each future: queueing + coalescing wait +
    dispatch + result split + delivery into the future — everything short of
    the waiter's own wakeup scheduling, which no single-process measurement
    can see)."""

    n_requests: int = 0
    n_served: int = 0
    n_shed: int = 0
    n_deadline: int = 0
    wall_s: float = 0.0
    latencies_s: list = field(default_factory=list)

    def percentile_ms(self, q: float) -> float | None:
        if not self.latencies_s:
            return None
        return round(1e3 * float(np.percentile(self.latencies_s, q)), 3)

    def summary(self) -> dict:
        return {
            "n_requests": self.n_requests,
            "n_served": self.n_served,
            "n_shed": self.n_shed,
            "n_deadline_exceeded": self.n_deadline,
            "p50_ms": self.percentile_ms(50),
            "p99_ms": self.percentile_ms(99),
            "graphs_per_sec": (
                round(self.n_served / self.wall_s, 2) if self.wall_s > 0 else None
            ),
            "wall_s": round(self.wall_s, 4),
        }


def run_traffic(
    server,
    model: str,
    samples,
    n_requests: int,
    rate_hz: float | None = None,
    seed: int = 0,
    deadline_ms: float | None = None,
    timeout_s: float = 120.0,
) -> TrafficReport:
    """Drive ``n_requests`` single-graph requests at the server, drawing
    samples uniformly (seeded) from ``samples``.

    ``rate_hz``: open-loop Poisson arrivals at that mean rate — the
    "millions of users" shape, where arrival times don't wait for results.
    ``None`` = closed burst: submit as fast as admission allows (admission
    shedding then exercises the bounded queue; shed requests are retried
    once after a short backoff, then counted shed).
    """
    rng = np.random.default_rng(seed)
    order = rng.integers(0, len(samples), size=n_requests)
    report = TrafficReport(n_requests=n_requests)
    futures = []
    latencies = []  # appended from done-callbacks (dispatcher threads)

    def _submit(sample):
        t_sub = time.perf_counter()
        fut = server.submit(model, sample, deadline_ms=deadline_ms)

        def _done(f, t_sub=t_sub):
            if f.exception() is None:
                # submit -> result-available: the client-observed latency,
                # stamped the instant the future resolves (polling result()
                # later would overstate early-completing requests)
                latencies.append(time.perf_counter() - t_sub)

        fut.add_done_callback(_done)
        futures.append(fut)

    t0 = time.perf_counter()
    next_arrival = t0
    for i in range(n_requests):
        if rate_hz:
            next_arrival += float(rng.exponential(1.0 / rate_hz))
            now = time.perf_counter()
            if next_arrival > now:
                time.sleep(next_arrival - now)
        sample = samples[int(order[i])]
        try:
            _submit(sample)
        except QueueFullError:
            # queue-full is the RETRYABLE rejection (backpressure): one
            # retry after a beat, still-full counts as shed. Every other
            # admission error (unknown model, incompatible sample, closed
            # server) is a configuration bug — propagate, don't launder it
            # into the shed count.
            time.sleep(0.002)
            try:
                _submit(sample)
            except QueueFullError:
                report.n_shed += 1
    for fut in futures:
        try:
            fut.result(timeout=timeout_s)
            report.n_served += 1
        except DeadlineExceededError:
            report.n_deadline += 1
        except AdmissionError:
            report.n_shed += 1
    report.wall_s = time.perf_counter() - t0
    # result() can unblock BEFORE the future's done-callback runs (waiters
    # are notified first in CPython), so give the last callbacks a bounded
    # beat to land — otherwise the tail request's latency goes missing
    wait_until = time.perf_counter() + 1.0
    while len(latencies) < report.n_served and time.perf_counter() < wait_until:
        time.sleep(0.001)
    report.latencies_s = list(latencies)
    return report


__all__ = ["TrafficReport", "run_traffic"]
