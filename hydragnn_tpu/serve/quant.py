"""Int8 inference quantization for the serving tier.

PR 6 named this follow-up: serving is memory-bound at the weight fetch, so
an int8 predict variant (4× fewer weight bytes, int8×int8 MXU matmuls via
``ops.quant_matmul``) buys bucket throughput — IF its error is bounded and
certified, never assumed. The pieces:

- **calibration** (:func:`collect_activation_scales`): eager forward passes
  over per-bucket calibration traffic with a flax method interceptor
  recording every ``nn.Dense`` input's abs-max — one activation scale per
  (layer, model, bucket). Eager on purpose: calibration is a boot-time
  observation pass, not a compiled hot path.
- **weight quantization** (:func:`quantize_dense_weights`): symmetric
  per-output-channel int8 for every calibrated Dense kernel; every other
  parameter (biases, norms, embeddings, equivariant tensors) stays fp32.
- **the quantized step** (:func:`make_quantized_predict_step`): the SAME
  ``model.apply`` as the fp32 predict step, with an interceptor swapping
  each calibrated Dense for ``ops.quant_matmul.quant_dense`` at trace time
  — int8 weights ride the executable as constants, scales are compile-time
  per bucket, so the step AOT-compiles into the endpoint's warm table
  exactly like the fp32 one.
- **error certification** (:func:`certify_quant_error`): per-head max
  abs deviation of the quantized answers from the fp32 answers on the
  calibration batches (real rows only). The measured bounds are the
  endpoint's contract; any head's bound above ``Serving.quant_tol`` raises
  :class:`QuantizationError` at warm-up — a model that quantizes badly
  refuses to serve quantized rather than quietly degrading.

The fp32 path is untouched: endpoints keep their fp32 executables, and with
``Serving.quantize`` off (the default) nothing here runs.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quant_matmul import quant_dense, quantize_weight
from ..train.step import _cast_floats


class QuantizationError(RuntimeError):
    """A head's calibrated int8 error exceeds ``Serving.quant_tol``."""


def _apply(model, state, batch, compute_dtype, interceptor=None):
    import flax.linen as nn

    c_params = _cast_floats(state.params, compute_dtype)
    c_batch = _cast_floats(batch, compute_dtype)
    variables = {"params": c_params, "batch_stats": state.batch_stats}
    if interceptor is None:
        return model.apply(variables, c_batch, train=False)
    with nn.intercept_methods(interceptor):
        return model.apply(variables, c_batch, train=False)


def collect_activation_scales(
    model, state, batches: Sequence, compute_dtype=jnp.float32
) -> dict[str, float]:
    """Per-``nn.Dense`` activation scales (abs-max / 127) observed over
    ``batches`` — keys are module paths ("conv_0/lin_l", ...)."""
    import flax.linen as nn

    absmax: dict[str, float] = {}

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if isinstance(mod, nn.Dense) and context.method_name == "__call__":
            path = "/".join(mod.path)
            x = np.asarray(args[0], np.float32)
            cur = float(np.max(np.abs(x))) if x.size else 0.0
            absmax[path] = max(absmax.get(path, 0.0), cur)
        return next_fun(*args, **kwargs)

    for batch in batches:
        _apply(model, state, batch, compute_dtype, interceptor)
    return {p: max(a, 1e-8) / 127.0 for p, a in absmax.items()}


def quantize_dense_weights(params, scales: Mapping[str, float]) -> dict:
    """int8-quantize every Dense kernel named by ``scales``. Returns
    ``{path: (w_q int8, s_w fp32, bias | None)}``; everything else is left
    to the fp32 parameter tree."""
    table: dict[str, tuple] = {}

    def walk(tree, prefix):
        if not isinstance(tree, Mapping):
            return
        kernel = tree.get("kernel")
        path = "/".join(prefix)
        if (
            kernel is not None
            and path in scales
            and getattr(kernel, "ndim", 0) == 2
        ):
            w_q, s_w = quantize_weight(jnp.asarray(kernel, jnp.float32))
            bias = tree.get("bias")
            table[path] = (
                w_q, s_w,
                None if bias is None else jnp.asarray(bias, jnp.float32),
            )
        for key, val in tree.items():
            if isinstance(val, Mapping):
                walk(val, prefix + (key,))

    walk(params, ())
    return table


def make_quantized_predict_step(
    model, scales: Mapping[str, float], weights: Mapping[str, tuple],
    compute_dtype=jnp.float32, use_kernel: bool | None = None,
):
    """``(state, batch) -> per-head predictions`` with every calibrated
    Dense computed int8. Same signature as ``make_predict_step`` so it AOT
    compiles and serves through the identical endpoint machinery. The int8
    weights are trace-time constants: the ``state`` argument still feeds
    every non-quantized parameter (norms, embeddings, head biases)."""
    import flax.linen as nn

    def q_interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if isinstance(mod, nn.Dense) and context.method_name == "__call__":
            path = "/".join(mod.path)
            ent = weights.get(path)
            s_x = scales.get(path)
            if ent is not None and s_x is not None:
                w_q, s_w, bias = ent
                x = args[0]
                x2 = x.reshape(-1, x.shape[-1])
                y = quant_dense(
                    x2, w_q, s_w, s_x,
                    bias if mod.use_bias else None, kernel=use_kernel,
                )
                return y.reshape(x.shape[:-1] + (w_q.shape[1],)).astype(
                    x.dtype
                )
        return next_fun(*args, **kwargs)

    @jax.jit
    def quant_predict_step(state, batch):
        out = _apply(model, state, batch, compute_dtype, q_interceptor)
        return _cast_floats(out, jnp.float32)

    return quant_predict_step


def certify_quant_error(
    predictor, quant_step, batches: Sequence
) -> list[float]:
    """Per-head max abs deviation |int8 − fp32| over the REAL rows of the
    calibration ``batches`` — the bounds the endpoint certifies (and
    ``Serving.quant_tol`` gates) at warm-up."""
    bounds = [0.0] * len(predictor.cols)
    for batch in batches:
        ref = predictor.outputs(batch)
        q = predictor.outputs(batch, step=quant_step)
        _, ref_rows = predictor.gather(batch, out=ref)
        _, q_rows = predictor.gather(batch, out=q)
        for ihead, (r, p) in enumerate(zip(ref_rows, q_rows)):
            if r.size:
                bounds[ihead] = max(
                    bounds[ihead], float(np.max(np.abs(r - p)))
                )
    return bounds


__all__ = [
    "QuantizationError",
    "certify_quant_error",
    "collect_activation_scales",
    "make_quantized_predict_step",
    "quantize_dense_weights",
]
