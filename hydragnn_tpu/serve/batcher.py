"""Dynamic bucketed micro-batching for the serving tier.

Coalesces in-flight requests into the tightest ``PadSpec`` bucket of the
endpoint's table (the SAME table ``graphs.batching`` derives for training —
one padding scheme, one compile budget) under a max-latency flush timer:
the first request of a batch opens a flush window of ``flush_ms``; requests
arriving inside the window join until the batch would overflow the TOP
bucket (or hit the graph-slot cap), then the batch dispatches.

Treedef pinning: training-time ``collate`` certifies per-batch kernel-layout
guarantees into ``BatchMeta`` — static aux data that KEYS the jit cache. A
server fed arbitrary request mixes would flip those bits batch-to-batch and
recompile in steady state, so :func:`serving_collate` pins every batch of a
bucket to one canonical conservative meta (all kernel certs ``False``, one
stable attention bound): every batch of a bucket shares one treedef and the
warm executable table stays complete forever. The cost is that serving always
takes the certified-fallback kernel paths — irrelevant on CPU (the fused
kernels are TPU-only) and a deliberate latency-jitter-vs-peak-throughput
trade on TPU.
"""

from __future__ import annotations

import functools
import time
from typing import Sequence

from ..graphs.batching import PadSpec, collate, pick_bucket
from ..graphs.graph import BatchMeta, GraphBatch, GraphSample
from .admission import (
    DeadlineExceededError,
    OversizeError,
    Request,
    RequestQueue,
)


@functools.lru_cache(maxsize=256)
def _canonical_meta_cached(key: tuple, node_cap: int | None) -> BatchMeta:
    # keyed on (as_tuple(), node_cap) — as_tuple() alone would miss
    # node_cap, which the bound below reads. BatchMeta is an immutable
    # NamedTuple of ints/bools, so sharing ONE instance per bucket across
    # every collate call is safe (and keeps treedefs trivially identical).
    if node_cap:
        # a user attn_cap below node_cap is deliberately NOT used here:
        # serving pins ONE cert level per bucket (no per-batch outlier
        # fallback), and only node_cap covers every admissible graph
        bound = node_cap
    else:
        bound = max(1 << max(key[0] - 1, 0).bit_length(), 8)
    return BatchMeta(
        gs_fits=False, recv_fits=False, send_fits=False, pool_fits=False,
        max_n_node=int(bound), attn_fits=False,
    )


def canonical_meta(pad: PadSpec) -> BatchMeta:
    """The ONE ``BatchMeta`` every served batch of ``pad`` carries.

    Kernel certs pinned ``False`` (conservative: fallback paths are always
    sound). ``max_n_node`` pinned to the bucket's dataset-wide per-graph cap
    when known, else the power-of-two ceiling of the bucket's node slots —
    constant, so GPS dense-vs-flat attention resolves once per bucket at
    warm-up. The bound is only sound for graphs the batcher ADMITS: a graph
    with more nodes than ``max_n_node`` would be certified under a false
    bound (GPS dense blocks would silently truncate it), so the micro-batcher
    sheds such requests as ``OversizeError`` — outside the size envelope the
    endpoint's programs were certified for.

    Memoized per bucket: the meta depends ONLY on the bucket (never on the
    batch contents or graph count), and ``serving_collate`` sits on the
    dispatch hot path — recomputing the bound per call was pure overhead,
    and the bulk-screening executor calls it once per block."""
    return _canonical_meta_cached(pad.as_tuple(), pad.node_cap)


def serving_collate(samples: Sequence[GraphSample], pad: PadSpec) -> GraphBatch:
    """``graphs.batching.collate`` + the bucket's canonical meta — the only
    collate the serving tier runs, so every batch of a bucket shares one
    treedef (zero steady-state recompiles by construction)."""
    return collate(samples, pad, certify=False)._replace(
        meta=canonical_meta(pad)
    )


# how long before a member's deadline the coalescing window closes, so the
# batch DISPATCHES (and passes the dispatch-time expiry re-check) in time
_DISPATCH_MARGIN_S = 0.002


def _totals(sample: GraphSample) -> tuple[int, int, int]:
    t = sample.extras["idx_kj"].shape[0] if "idx_kj" in sample.extras else 0
    return sample.num_nodes, sample.num_edges, t


class MicroBatcher:
    """Forms (requests, bucket) batches from a :class:`RequestQueue`.

    One instance per endpoint, consumed by that endpoint's dispatcher
    thread. Threading contract (the GL1xx audit's note): the batcher owns
    NO locks of its own — every shared structure it touches is the
    queue's, reached only through ``RequestQueue``'s locked methods
    (``get``/``push_back``), and all other state (`members`, totals, the
    flush clock) is dispatcher-thread-local. Deadlines are
    ``time.monotonic()`` throughout (GL105).

    Policy, in order, for each batch:

    1. Block for the first live request (expired ones fail fast with
       :class:`DeadlineExceededError` — serving a dead request wastes the
       bucket slot AND delays live ones behind it).
    2. A request that alone overflows the TOP bucket is shed with
       :class:`OversizeError` — waiting cannot make it fit.
    3. Keep admitting requests until the flush window closes, the batch
       holds ``max_graphs`` requests, or the next request would overflow the
       top bucket (it goes back to the queue HEAD for the next batch).
    4. Collate target: the TIGHTEST table bucket that fits the accumulated
       totals.
    """

    def __init__(self, queue: RequestQueue, buckets: Sequence[PadSpec],
                 flush_s: float, max_graphs: int = 0, on_shed=None):
        self.queue = queue
        self.buckets = sorted(buckets, key=lambda p: p.as_tuple())
        self.flush_s = max(0.0, float(flush_s))
        # graph-slot capacity differs per bucket for caller-supplied tables;
        # the per-bucket check lives in pick_bucket (n_graphs), this cap only
        # bounds coalescing at the largest capacity in the table
        cap = max(b.n_graph - 1 for b in self.buckets)
        self.max_graphs = min(int(max_graphs), cap) if max_graphs > 0 else cap
        # per-bucket certified node bound (canonical_meta.max_n_node): a
        # batch may only collate to a bucket whose bound covers its LARGEST
        # member, or GPS dense-block attention would silently truncate it.
        # node_bound (the max) is the admission envelope: above it no bucket
        # can certify the graph at all.
        self._bounds = {
            b.as_tuple(): canonical_meta(b).max_n_node for b in self.buckets
        }
        self.node_bound = max(self._bounds.values())
        # on_shed(kind): endpoint counter hook — batcher-side sheds
        # ("deadline", "oversize") must show up in stats() like
        # admission-side ones, or submitted != served + shed + failed
        self.on_shed = on_shed or (lambda kind: None)

    def _pick(self, tot_n: int, tot_e: int, tot_t: int, n_graphs: int,
              max_member_n: int) -> "PadSpec | None":
        """Tightest bucket that fits the totals AND certifies the largest
        member graph — both conditions, or the batch is unservable there."""
        certifying = [
            b for b in self.buckets
            if self._bounds[b.as_tuple()] >= max_member_n
        ]
        return pick_bucket(certifying, tot_n, tot_e, tot_t, n_graphs)

    def _admissible(self, req: Request) -> bool:
        """Shed-or-keep gate shared by the batch opener and the coalescing
        loop: expired requests and requests that fit/certify in NO bucket
        even alone are rejected typed (counted "cancelled" when the client's
        own cancel won the race); True means the request is servable."""
        if req.expired():
            kind = "deadline" if req.reject(DeadlineExceededError(
                "deadline passed while queued"
            )) else "cancelled"
            self.on_shed(kind)
            return False
        n, e, t = _totals(req.sample)
        if self._pick(n, e, t, 1, n) is None:
            kind = "oversize" if req.reject(OversizeError(
                f"sample ({n} nodes, {e} edges, {t} triplets) fits no "
                f"serving bucket of this endpoint (largest "
                f"{self.buckets[-1]!r}, certified per-graph node bound "
                f"{self.node_bound}) — outside the envelope its programs "
                "were certified for"
            )) else "cancelled"
            self.on_shed(kind)
            return False
        return True

    def _first_live(self, block: bool) -> Request | None:
        """Oldest admissible request; shed ones are failed on the spot."""
        while True:
            req = self.queue.get(timeout=None if block else 0.25)
            if req is None:
                return None
            if self._admissible(req):
                return req

    def next_batch(self, block: bool = False) -> tuple[list[Request], PadSpec] | None:
        """The next dispatchable micro-batch, or ``None`` if the queue shut
        down (``block=True``) / stayed empty past the poll (``block=False``)."""
        first = self._first_live(block)
        if first is None:
            return None
        members = [first]
        tot_n, tot_e, tot_t = _totals(first.sample)
        max_n = first.sample.num_nodes
        flush_at = time.monotonic() + self.flush_s
        if first.deadline is not None:
            # never coalesce PAST a member's deadline: a lone request with
            # deadline < flush_ms on an idle server must dispatch in time,
            # not wait out the window and get shed at dispatch. The margin
            # closes the window BEFORE the deadline so the dispatch-time
            # expiry re-check doesn't see now == deadline.
            flush_at = min(flush_at, first.deadline - _DISPATCH_MARGIN_S)
        while len(members) < self.max_graphs:
            remaining = flush_at - time.monotonic()
            if remaining <= 0:
                break
            req = self.queue.get(timeout=remaining)
            if req is None:
                break
            if not self._admissible(req):
                continue
            n, e, t = _totals(req.sample)
            if self._pick(tot_n + n, tot_e + e, tot_t + t,
                          len(members) + 1, max(max_n, n)) is None:
                # no bucket holds AND certifies the would-be batch: dispatch
                # what we have, the request re-heads the queue for the next
                # batch (it is individually servable — checked above)
                self.queue.push_back(req)
                break
            members.append(req)
            tot_n, tot_e, tot_t = tot_n + n, tot_e + e, tot_t + t
            max_n = max(max_n, n)
            if req.deadline is not None:
                flush_at = min(flush_at, req.deadline - _DISPATCH_MARGIN_S)
        pad = self._pick(tot_n, tot_e, tot_t, len(members), max_n)
        assert pad is not None  # every admitted member kept the batch viable
        return members, pad


__all__ = ["MicroBatcher", "canonical_meta", "serving_collate"]
