from . import print_utils, tracer

__all__ = ["print_utils", "tracer"]
