"""One retry policy for the whole data/resume plane.

PR 3 grew an exponential-backoff-with-jitter loop inside
``ShardedStore._request``; the elastic data plane needs the identical
discipline in more places (replica failover rounds, checkpoint sidecar
reads on flaky network filesystems). This module is the single
implementation: a frozen ``RetryPolicy`` plus ``call_with_retries`` — so
"how many attempts, how long between them, what counts as transient" is
decided once and tested once, instead of re-derived per call site.

Jitter is multiplicative (``delay * (1 + U(0, jitter))``): when a shard
owner dies, every client notices at the same moment, and synchronized
retries would re-stampede the replacement replica in lockstep.
"""

from __future__ import annotations

import dataclasses
import random
import time
import warnings
from typing import Callable


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``attempts`` total tries (1 = no retrying); sleep before retry k
    (1-based) is ``base_delay * factor**(k-1) * (1 + U(0, jitter))``."""

    attempts: int = 3
    base_delay: float = 0.05
    factor: float = 2.0
    jitter: float = 1.0

    def delay(self, retry_no: int) -> float:
        scale = 1.0 + random.random() * self.jitter
        return self.base_delay * (self.factor ** (retry_no - 1)) * scale


def store_policy() -> RetryPolicy:
    """The ShardedStore fetch policy: attempts from ``HYDRAGNN_STORE_RETRIES``
    (the PR 3 knob), timing constants unchanged from the inline original."""
    from . import flags

    return RetryPolicy(attempts=max(1, int(flags.get(flags.STORE_RETRIES))))


# Sidecar JSON reads retry on transient filesystem errors (EIO blips on
# network filesystems are routine on the clusters the resilience layer
# targets) but never on a genuinely missing file — that is an answer, not
# a fault, and three delayed retries would just slow every cold start.
SIDECAR_POLICY = RetryPolicy(attempts=3, base_delay=0.05)


def call_with_retries(
    fn: Callable,
    *,
    policy: RetryPolicy,
    retry_on: tuple = (ConnectionError, OSError),
    give_up: tuple = (),
    describe: str = "",
    hint: str = "",
):
    """Run ``fn()``; on an exception in ``retry_on`` (and not in
    ``give_up``), sleep per the policy and retry, warning each time, up to
    ``policy.attempts`` total attempts. The last failure re-raises.
    ``describe`` names the operation in the warning; ``hint`` appends a
    remediation note (e.g. the env var that tunes the cap)."""
    attempt = 0
    while True:
        try:
            return fn()
        except give_up:
            raise
        except retry_on as e:
            attempt += 1
            if attempt >= policy.attempts:
                raise
            sleep_s = policy.delay(attempt)
            warnings.warn(
                f"{describe or 'operation'} failed "
                f"({type(e).__name__}: {e}); retry {attempt}/"
                f"{policy.attempts - 1} in {sleep_s:.2f}s"
                + (f" ({hint})" if hint else "")
            )
            time.sleep(sleep_s)


__all__ = ["RetryPolicy", "SIDECAR_POLICY", "call_with_retries", "store_policy"]
