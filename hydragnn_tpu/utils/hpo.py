"""Hyperparameter optimization (reference scope: ``hydragnn/utils/hpo/
deephyper.py`` and the Optuna/DeepHyper drivers in ``examples/qm9_hpo`` /
``examples/multidataset_hpo``).

DeepHyper/Optuna are cluster-side dependencies; the built-in engine here is a
self-contained random search with the same shape (search space dict ->
objective -> best config), so HPO works out of the box and plugs into Optuna
when it is installed (``backend="optuna"``).
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import time
from typing import Any, Callable

import numpy as np


def subprocess_objective(
    worker: str,
    timeout: float = 600.0,
    python: str | None = None,
    extra_env: dict | None = None,
    keep_dir: str | None = None,
) -> Callable[[dict], float]:
    """Trial evaluator that runs each configuration in its OWN OS process —
    the reference's DeepHyper ``ProcessPoolEvaluator``/srun pattern
    (``examples/multidataset_hpo/gfm_deephyper_multi.py:127-170``). Pass the
    returned callable to ``run_hpo(..., workers=N)`` for N concurrent trials:
    the thread pool just supervises; the training itself runs in separate
    interpreters, so JAX state never collides across trials.

    ``worker`` is a script invoked as ``python worker config.json out.json``
    that trains the config and writes ``{"objective": <float>}``. A trial
    that overruns ``timeout``, crashes, or writes garbage scores ``inf``
    (diverged-trial semantics — never beats a finite value). ``keep_dir``
    saves each trial's record (objective, wall-clock span, returncode) as
    ``trial_<n>.json`` for post-hoc analysis/concurrency audits."""
    import subprocess
    import sys

    counter = itertools.count()

    def objective(cfg: dict) -> float:
        import tempfile

        idx = next(counter)
        t0 = time.time()
        value, rc, err = float("inf"), None, None
        with tempfile.TemporaryDirectory() as td:
            cfg_path = os.path.join(td, "config.json")
            out_path = os.path.join(td, "out.json")
            with open(cfg_path, "w") as f:
                json.dump(cfg, f)
            env = dict(os.environ)
            env.update(extra_env or {})
            try:
                r = subprocess.run(
                    [python or sys.executable, worker, cfg_path, out_path],
                    timeout=timeout, capture_output=True, text=True, env=env,
                )
                rc = r.returncode
                if rc == 0:
                    with open(out_path) as f:
                        value = float(json.load(f)["objective"])
                else:
                    err = r.stderr[-2000:]
            except Exception as exc:  # timeout, missing/garbled out.json, ...
                err = f"{type(exc).__name__}: {exc}"
        t1 = time.time()
        if keep_dir:
            # status taxonomy mirrors run_hpo's: a trial the resilience
            # layer aborted (TrainingDivergedError in its stderr) or that
            # returned a non-finite objective is "diverged"; any other
            # crash/timeout is "failed"
            if np.isfinite(value):
                status = "ok"
            elif err and "TrainingDivergedError" in err:
                status = "diverged"
            elif rc == 0:
                status = "diverged"  # clean exit, non-finite objective
            else:
                status = "failed"
            os.makedirs(keep_dir, exist_ok=True)
            with open(os.path.join(keep_dir, f"trial_{idx:03d}.json"), "w") as f:
                json.dump(
                    {"objective": value, "status": status, "t_start": t0,
                     "t_end": t1, "returncode": rc, "error": err},
                    f,
                )
        return value

    return objective


def sample_config(space: dict[str, Any], rng: np.random.Generator) -> dict:
    """Draw one assignment from a search-space dict. Entries may be:
    list -> categorical; ("int", lo, hi) / ("float", lo, hi) /
    ("log_float", lo, hi) -> ranges."""
    out = {}
    for key, spec in space.items():
        if isinstance(spec, list):
            out[key] = spec[rng.integers(len(spec))]
        elif isinstance(spec, tuple) and spec[0] == "int":
            out[key] = int(rng.integers(spec[1], spec[2] + 1))
        elif isinstance(spec, tuple) and spec[0] == "float":
            out[key] = float(rng.uniform(spec[1], spec[2]))
        elif isinstance(spec, tuple) and spec[0] == "log_float":
            out[key] = float(np.exp(rng.uniform(np.log(spec[1]), np.log(spec[2]))))
        else:
            raise ValueError(f"bad search-space entry {key}: {spec}")
    return out


def _set_by_path(config: dict, dotted: str, value) -> None:
    node = config
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def run_hpo(
    base_config: dict,
    space: dict[str, Any],
    objective: Callable[[dict], float],
    n_trials: int = 10,
    seed: int = 0,
    backend: str = "random",
    log_path: str | None = None,
    workers: int = 1,
    walltime_budget: float | None = None,
) -> tuple[dict, float, list]:
    """Minimize ``objective(config)`` over ``space``. Space keys are dotted
    config paths (e.g. ``"NeuralNetwork.Architecture.hidden_dim"``).
    Returns (best_config, best_value, trial history).

    ``workers > 1`` evaluates random-search trials concurrently through a
    thread pool (the reference's DeepHyper ProcessPoolEvaluator width,
    ``examples/multidataset_hpo/gfm_deephyper_multi.py``) — the objective
    must be thread-safe, e.g. ``subprocess_objective``. ``walltime_budget``
    (seconds) stops LAUNCHING new trials once spent; in-flight trials finish
    and count."""
    history = []
    deadline = time.monotonic() + walltime_budget if walltime_budget else None

    def expired() -> bool:
        return deadline is not None and time.monotonic() > deadline

    def build(assignment: dict) -> dict:
        cfg = copy.deepcopy(base_config)
        for key, val in assignment.items():
            _set_by_path(cfg, key, val)
        return cfg

    def evaluate(assignment: dict) -> tuple[float, str]:
        """(objective value, status). A trial killed by the resilience
        layer's divergence abort (``TrainingDivergedError``) is a *result*
        — status ``"diverged"``, objective inf — not a sweep-crashing
        exception; a finite value is ``"ok"``; any other non-finite value
        also records ``"diverged"`` (the pre-existing NaN/inf objective
        semantics, now labeled)."""
        from ..resilience import TrainingDivergedError

        try:
            value = float(objective(build(assignment)))
        except TrainingDivergedError:
            return float("inf"), "diverged"
        return value, ("ok" if np.isfinite(value) else "diverged")

    if backend == "optuna":
        try:
            import optuna
        except ImportError:
            backend = "random"
    if backend == "optuna":
        def opt_objective(trial):
            assignment = {}
            for key, spec in space.items():
                if isinstance(spec, list):
                    assignment[key] = trial.suggest_categorical(key, spec)
                elif spec[0] == "int":
                    assignment[key] = trial.suggest_int(key, spec[1], spec[2])
                elif spec[0] == "float":
                    assignment[key] = trial.suggest_float(key, spec[1], spec[2])
                else:
                    assignment[key] = trial.suggest_float(key, spec[1], spec[2], log=True)
            value, status = evaluate(assignment)
            history.append({"assignment": assignment, "value": value, "status": status})
            return value

        study = optuna.create_study(direction="minimize")
        # optuna implements the walltime budget natively (stops launching new
        # trials once spent — same semantics as the random branch below)
        study.optimize(opt_objective, n_trials=n_trials,
                       n_jobs=max(workers, 1), timeout=walltime_budget)
        best_assignment = study.best_params
        best_value = study.best_value
    else:
        rng = np.random.default_rng(seed)
        assignments = [sample_config(space, rng) for _ in range(n_trials)]
        values: list = [None] * n_trials
        if workers > 1:
            from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

            with ThreadPoolExecutor(max_workers=workers) as pool:
                pending: dict = {}
                i = 0
                while i < n_trials or pending:
                    while i < n_trials and len(pending) < workers and not expired():
                        fut = pool.submit(evaluate, assignments[i])
                        pending[fut] = i
                        i += 1
                    if not pending:
                        break
                    done, _ = wait(pending, return_when=FIRST_COMPLETED)
                    for fut in done:
                        values[pending.pop(fut)] = fut.result()
                    if expired():
                        i = n_trials  # budget spent: drain in-flight, launch no more
        else:
            for i, a in enumerate(assignments):
                if expired():
                    break
                values[i] = evaluate(a)
        best_assignment, best_value = None, float("inf")
        launched = 0
        for assignment, result in zip(assignments, values):
            if result is None:
                continue  # budget cap: trial never launched
            value, status = result
            launched += 1
            history.append({"assignment": assignment, "value": value, "status": status})
            # diverged trials (NaN/inf objective or divergence-abort) never
            # beat any finite value — excluded from best-trial selection
            if status == "ok" and value < best_value:
                best_assignment, best_value = assignment, value
        if best_assignment is None:
            if launched == 0:
                raise RuntimeError(
                    "HPO walltime budget expired before any trial completed "
                    "— increase walltime_budget or shrink per-trial cost "
                    "(this is a budget misconfiguration, not diverged trials)"
                )
            raise RuntimeError(
                f"all {launched} launched HPO trials returned non-finite "
                f"objectives (history: {[h['value'] for h in history]})"
            )

    if log_path:
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "w") as f:
            json.dump(
                {"best": best_assignment, "value": best_value, "trials": history},
                f,
                indent=2,
            )
    return build(best_assignment), best_value, history
