"""Hyperparameter optimization (reference scope: ``hydragnn/utils/hpo/
deephyper.py`` and the Optuna/DeepHyper drivers in ``examples/qm9_hpo`` /
``examples/multidataset_hpo``).

DeepHyper/Optuna are cluster-side dependencies; the built-in engine here is a
self-contained random search with the same shape (search space dict ->
objective -> best config), so HPO works out of the box and plugs into Optuna
when it is installed (``backend="optuna"``).
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Callable

import numpy as np


def sample_config(space: dict[str, Any], rng: np.random.Generator) -> dict:
    """Draw one assignment from a search-space dict. Entries may be:
    list -> categorical; ("int", lo, hi) / ("float", lo, hi) /
    ("log_float", lo, hi) -> ranges."""
    out = {}
    for key, spec in space.items():
        if isinstance(spec, list):
            out[key] = spec[rng.integers(len(spec))]
        elif isinstance(spec, tuple) and spec[0] == "int":
            out[key] = int(rng.integers(spec[1], spec[2] + 1))
        elif isinstance(spec, tuple) and spec[0] == "float":
            out[key] = float(rng.uniform(spec[1], spec[2]))
        elif isinstance(spec, tuple) and spec[0] == "log_float":
            out[key] = float(np.exp(rng.uniform(np.log(spec[1]), np.log(spec[2]))))
        else:
            raise ValueError(f"bad search-space entry {key}: {spec}")
    return out


def _set_by_path(config: dict, dotted: str, value) -> None:
    node = config
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def run_hpo(
    base_config: dict,
    space: dict[str, Any],
    objective: Callable[[dict], float],
    n_trials: int = 10,
    seed: int = 0,
    backend: str = "random",
    log_path: str | None = None,
    workers: int = 1,
) -> tuple[dict, float, list]:
    """Minimize ``objective(config)`` over ``space``. Space keys are dotted
    config paths (e.g. ``"NeuralNetwork.Architecture.hidden_dim"``).
    Returns (best_config, best_value, trial history).

    ``workers > 1`` evaluates random-search trials concurrently through a
    thread pool (the reference's DeepHyper ProcessPoolEvaluator width,
    ``examples/multidataset_hpo/gfm_deephyper_multi.py``) — the objective
    must be thread-safe, e.g. a subprocess launcher."""
    history = []

    def build(assignment: dict) -> dict:
        cfg = copy.deepcopy(base_config)
        for key, val in assignment.items():
            _set_by_path(cfg, key, val)
        return cfg

    if backend == "optuna":
        try:
            import optuna
        except ImportError:
            backend = "random"
    if backend == "optuna":
        def opt_objective(trial):
            assignment = {}
            for key, spec in space.items():
                if isinstance(spec, list):
                    assignment[key] = trial.suggest_categorical(key, spec)
                elif spec[0] == "int":
                    assignment[key] = trial.suggest_int(key, spec[1], spec[2])
                elif spec[0] == "float":
                    assignment[key] = trial.suggest_float(key, spec[1], spec[2])
                else:
                    assignment[key] = trial.suggest_float(key, spec[1], spec[2], log=True)
            value = objective(build(assignment))
            history.append({"assignment": assignment, "value": value})
            return value

        study = optuna.create_study(direction="minimize")
        study.optimize(opt_objective, n_trials=n_trials, n_jobs=max(workers, 1))
        best_assignment = study.best_params
        best_value = study.best_value
    else:
        rng = np.random.default_rng(seed)
        assignments = [sample_config(space, rng) for _ in range(n_trials)]
        if workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=workers) as pool:
                values = list(pool.map(lambda a: float(objective(build(a))), assignments))
        else:
            values = [float(objective(build(a))) for a in assignments]
        best_assignment, best_value = None, float("inf")
        for assignment, value in zip(assignments, values):
            history.append({"assignment": assignment, "value": value})
            # NaN/inf objectives (diverged trials) never beat any finite value
            if np.isfinite(value) and value < best_value:
                best_assignment, best_value = assignment, value
        if best_assignment is None:
            raise RuntimeError(
                f"all {n_trials} HPO trials returned non-finite objectives "
                f"(history: {[h['value'] for h in history]})"
            )

    if log_path:
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "w") as f:
            json.dump(
                {"best": best_assignment, "value": best_value, "trials": history},
                f,
                indent=2,
            )
    return build(best_assignment), best_value, history
