"""Hyperparameter optimization (reference scope: ``hydragnn/utils/hpo/
deephyper.py`` and the Optuna/DeepHyper drivers in ``examples/qm9_hpo`` /
``examples/multidataset_hpo``).

DeepHyper/Optuna are cluster-side dependencies; the built-in engine here is a
self-contained random search with the same shape (search space dict ->
objective -> best config), so HPO works out of the box and plugs into Optuna
when it is installed (``backend="optuna"``).

``backend="vmap"`` replaces the fleet-of-processes shape entirely for
scalar-only spaces: trials whose assignments differ only in vmappable
scalars (learning rate / weight decay / loss weights —
``train/population.py::VMAP_SCALAR_KEYS``) share one architecture and one
compiled program, so they train as ONE vmapped population in-process —
one compile and one dispatch stream for the whole group instead of N of
each. Assignments that change architecture keys still go through the
per-trial ``objective`` (the subprocess path), partitioned so every group
that CAN vmap does.
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import time
from typing import Any, Callable

import numpy as np


def subprocess_objective(
    worker: str,
    timeout: float = 600.0,
    python: str | None = None,
    extra_env: dict | None = None,
    keep_dir: str | None = None,
) -> Callable[[dict], float]:
    """Trial evaluator that runs each configuration in its OWN OS process —
    the reference's DeepHyper ``ProcessPoolEvaluator``/srun pattern
    (``examples/multidataset_hpo/gfm_deephyper_multi.py:127-170``). Pass the
    returned callable to ``run_hpo(..., workers=N)`` for N concurrent trials:
    the thread pool just supervises; the training itself runs in separate
    interpreters, so JAX state never collides across trials.

    ``worker`` is a script invoked as ``python worker config.json out.json``
    that trains the config and writes ``{"objective": <float>}``. A trial
    that overruns ``timeout``, crashes, or writes garbage scores ``inf``
    (diverged-trial semantics — never beats a finite value). ``keep_dir``
    saves each trial's record (objective, wall-clock span, returncode, and
    the sampled ``assignment`` — ``run_hpo`` passes it through, so the
    records are self-describing) as ``trial_<n>.json`` for post-hoc
    analysis/concurrency audits."""
    import subprocess
    import sys

    counter = itertools.count()

    def objective(cfg: dict, assignment: dict | None = None) -> float:
        import tempfile

        idx = next(counter)
        t0 = time.time()
        value, rc, err = float("inf"), None, None
        with tempfile.TemporaryDirectory() as td:
            cfg_path = os.path.join(td, "config.json")
            out_path = os.path.join(td, "out.json")
            with open(cfg_path, "w") as f:
                json.dump(cfg, f)
            env = dict(os.environ)
            env.update(extra_env or {})
            try:
                r = subprocess.run(
                    [python or sys.executable, worker, cfg_path, out_path],
                    timeout=timeout, capture_output=True, text=True, env=env,
                )
                rc = r.returncode
                if rc == 0:
                    with open(out_path) as f:
                        value = float(json.load(f)["objective"])
                else:
                    err = r.stderr[-2000:]
            except Exception as exc:  # timeout, missing/garbled out.json, ...
                err = f"{type(exc).__name__}: {exc}"
        t1 = time.time()
        if keep_dir:
            # status taxonomy mirrors run_hpo's: a trial the resilience
            # layer aborted (TrainingDivergedError in its stderr) or that
            # returned a non-finite objective is "diverged"; any other
            # crash/timeout is "failed"
            if np.isfinite(value):
                status = "ok"
            elif err and "TrainingDivergedError" in err:
                status = "diverged"
            elif rc == 0:
                status = "diverged"  # clean exit, non-finite objective
            else:
                status = "failed"
            os.makedirs(keep_dir, exist_ok=True)
            with open(os.path.join(keep_dir, f"trial_{idx:03d}.json"), "w") as f:
                json.dump(
                    {"objective": value, "status": status, "t_start": t0,
                     "t_end": t1, "returncode": rc, "error": err,
                     "assignment": assignment},
                    f,
                )
        return value

    return objective


def sample_config(space: dict[str, Any], rng: np.random.Generator) -> dict:
    """Draw one assignment from a search-space dict. Entries may be:
    list -> categorical; ("int", lo, hi) / ("float", lo, hi) /
    ("log_float", lo, hi) -> ranges."""
    out = {}
    for key, spec in space.items():
        if isinstance(spec, list):
            out[key] = spec[rng.integers(len(spec))]
        elif isinstance(spec, tuple) and spec[0] == "int":
            out[key] = int(rng.integers(spec[1], spec[2] + 1))
        elif isinstance(spec, tuple) and spec[0] == "float":
            out[key] = float(rng.uniform(spec[1], spec[2]))
        elif isinstance(spec, tuple) and spec[0] == "log_float":
            out[key] = float(np.exp(rng.uniform(np.log(spec[1]), np.log(spec[2]))))
        else:
            raise ValueError(f"bad search-space entry {key}: {spec}")
    return out


def _assignment_key(assignment: dict) -> str:
    """Canonical hashable form of an assignment (values may be lists, e.g.
    task-weight vectors)."""
    return json.dumps(assignment, sort_keys=True, default=str)


def sample_unique_assignments(
    space: dict[str, Any],
    rng: np.random.Generator,
    n_trials: int,
    max_attempts: int | None = None,
) -> list[dict]:
    """Up to ``n_trials`` DISTINCT assignments. Small categorical spaces used
    to burn trials re-running identical configs (4 options, 12 trials ->
    ~8 duplicate trainings); re-drawing duplicates instead spends the budget
    on coverage, and a space with fewer than ``n_trials`` distinct points
    simply yields them all (the attempt cap keeps exhausted spaces from
    looping forever)."""
    seen: set = set()
    out: list[dict] = []
    attempts = 0
    cap = max_attempts or max(20 * n_trials, 100)
    while len(out) < n_trials and attempts < cap:
        attempts += 1
        assignment = sample_config(space, rng)
        key = _assignment_key(assignment)
        if key not in seen:
            seen.add(key)
            out.append(assignment)
    return out


def _set_by_path(config: dict, dotted: str, value) -> None:
    node = config
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def run_hpo(
    base_config: dict,
    space: dict[str, Any],
    objective: Callable[[dict], float],
    n_trials: int = 10,
    seed: int = 0,
    backend: str = "random",
    log_path: str | None = None,
    workers: int = 1,
    walltime_budget: float | None = None,
    population_objective: Callable[[dict, list], list] | None = None,
) -> tuple[dict, float, list]:
    """Minimize ``objective(config)`` over ``space``. Space keys are dotted
    config paths (e.g. ``"NeuralNetwork.Architecture.hidden_dim"``).
    Returns (best_config, best_value, trial history).

    ``workers > 1`` evaluates random-search trials concurrently through a
    thread pool (the reference's DeepHyper ProcessPoolEvaluator width,
    ``examples/multidataset_hpo/gfm_deephyper_multi.py``) — the objective
    must be thread-safe, e.g. ``subprocess_objective``. ``walltime_budget``
    (seconds) stops LAUNCHING new trials once spent; in-flight trials finish
    and count.

    ``backend="vmap"``: trials differing only in vmappable scalars
    (``train/population.py::VMAP_SCALAR_KEYS``) train as ONE in-process
    vmapped population per architecture group via ``population_objective``
    (default: ``make_population_objective()`` reading data from the
    config's ``Dataset`` section); single-assignment groups with
    architecture-changing keys fall back to the per-trial ``objective``."""
    history = []
    deadline = time.monotonic() + walltime_budget if walltime_budget else None

    def expired() -> bool:
        return deadline is not None and time.monotonic() > deadline

    def build(assignment: dict) -> dict:
        cfg = copy.deepcopy(base_config)
        for key, val in assignment.items():
            _set_by_path(cfg, key, val)
        return cfg

    import inspect

    # Does the objective accept (config, assignment=...)? Probed with a bind
    # — a mere `"assignment" in parameters` check wrongly matches objectives
    # whose FIRST positional happens to be named `assignment` (and would
    # call them with the config twice).
    try:
        inspect.signature(objective).bind({}, assignment={})
        _takes_assignment = True
    except (TypeError, ValueError):  # doesn't fit, or C callable w/o signature
        _takes_assignment = False

    def evaluate(assignment: dict) -> tuple[float, str, str | None]:
        """(objective value, status, error text). A trial killed by the
        resilience layer's divergence abort (``TrainingDivergedError``) is a
        *result* — status ``"diverged"``, objective inf — not a
        sweep-crashing exception; a finite value is ``"ok"``; any other
        non-finite value also records ``"diverged"`` (the pre-existing
        NaN/inf objective semantics, now labeled). Any OTHER exception
        records status ``"failed"`` (objective inf) with the exception text
        preserved in the history entry — one crashed trial must not discard
        every completed one (this is what keeps an optuna study alive too;
        it used to append nothing and die), but a systematic setup bug must
        still be diagnosable from the record."""
        from ..resilience import TrainingDivergedError

        try:
            cfg = build(assignment)
            value = float(
                objective(cfg, assignment=assignment)
                if _takes_assignment else objective(cfg)
            )
        except TrainingDivergedError as exc:
            return float("inf"), "diverged", f"{type(exc).__name__}: {exc}"
        except Exception as exc:
            return float("inf"), "failed", f"{type(exc).__name__}: {exc}"
        return value, ("ok" if np.isfinite(value) else "diverged"), None

    def record(history_entry: dict, error: str | None) -> dict:
        if error is not None:
            history_entry["error"] = error
        history.append(history_entry)
        return history_entry

    if backend == "vmap":
        return _run_vmap_backend(
            base_config, space, evaluate, build, population_objective,
            n_trials, seed, expired, history, log_path,
        )
    if backend == "optuna":
        try:
            import optuna
        except ImportError:
            backend = "random"
    if backend == "optuna":
        def opt_objective(trial):
            assignment = {}
            for key, spec in space.items():
                if isinstance(spec, list):
                    assignment[key] = trial.suggest_categorical(key, spec)
                elif spec[0] == "int":
                    assignment[key] = trial.suggest_int(key, spec[1], spec[2])
                elif spec[0] == "float":
                    assignment[key] = trial.suggest_float(key, spec[1], spec[2])
                else:
                    assignment[key] = trial.suggest_float(key, spec[1], spec[2], log=True)
            value, status, err = evaluate(assignment)
            record(
                {"assignment": assignment, "value": value, "status": status}, err
            )
            return value

        study = optuna.create_study(direction="minimize")
        # optuna implements the walltime budget natively (stops launching new
        # trials once spent — same semantics as the random branch below)
        study.optimize(opt_objective, n_trials=n_trials,
                       n_jobs=max(workers, 1), timeout=walltime_budget)
        if not any(h["status"] == "ok" for h in history):
            # evaluate() folds exceptions into inf-scored COMPLETE trials to
            # keep the study alive, so optuna would happily crown an
            # arbitrary inf "best" — die loudly like the other backends
            raise RuntimeError(_all_failed_msg(len(history), history))
        best_assignment = study.best_params
        best_value = study.best_value
    else:
        rng = np.random.default_rng(seed)
        # duplicates re-draw instead of re-training: a small categorical
        # space may yield FEWER than n_trials (every distinct point covered)
        assignments = sample_unique_assignments(space, rng, n_trials)
        n_avail = len(assignments)
        values: list = [None] * n_avail
        if workers > 1:
            from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

            with ThreadPoolExecutor(max_workers=workers) as pool:
                pending: dict = {}
                i = 0
                while i < n_avail or pending:
                    while i < n_avail and len(pending) < workers and not expired():
                        fut = pool.submit(evaluate, assignments[i])
                        pending[fut] = i
                        i += 1
                    if not pending:
                        break
                    done, _ = wait(pending, return_when=FIRST_COMPLETED)
                    for fut in done:
                        values[pending.pop(fut)] = fut.result()
                    if expired():
                        i = n_avail  # budget spent: drain in-flight, launch no more
        else:
            for i, a in enumerate(assignments):
                if expired():
                    break
                values[i] = evaluate(a)
        best_assignment, best_value = None, float("inf")
        launched = 0
        for assignment, result in zip(assignments, values):
            if result is None:
                continue  # budget cap: trial never launched
            value, status, err = result
            launched += 1
            record(
                {"assignment": assignment, "value": value, "status": status}, err
            )
            # diverged trials (NaN/inf objective or divergence-abort) never
            # beat any finite value — excluded from best-trial selection
            if status == "ok" and value < best_value:
                best_assignment, best_value = assignment, value
        if best_assignment is None:
            if launched == 0:
                raise RuntimeError(
                    "HPO walltime budget expired before any trial completed "
                    "— increase walltime_budget or shrink per-trial cost "
                    "(this is a budget misconfiguration, not diverged trials)"
                )
            raise RuntimeError(_all_failed_msg(launched, history))

    if log_path:
        _write_hpo_log(log_path, best_assignment, best_value, history)
    return build(best_assignment), best_value, history


def _all_failed_msg(launched: int, history: list) -> str:
    """The all-trials-dead diagnosis: statuses/values plus the LAST recorded
    error text, so a systematic setup bug (typo'd space key, missing dep)
    surfaces in the exception instead of hiding behind N anonymous infs."""
    msg = (
        f"all {launched} launched HPO trials diverged or failed "
        f"(history: {[(h['status'], h['value']) for h in history]})"
    )
    errors = [h["error"] for h in history if h.get("error")]
    if errors:
        msg += f"; last error: {errors[-1]}"
    return msg


def _write_hpo_log(log_path, best_assignment, best_value, history) -> None:
    os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
    with open(log_path, "w") as f:
        json.dump(
            {"best": best_assignment, "value": best_value, "trials": history},
            f,
            indent=2,
        )


def _run_vmap_backend(
    base_config, space, evaluate, build, population_objective,
    n_trials, seed, expired, history, log_path,
) -> tuple[dict, float, list]:
    """The ``backend="vmap"`` engine: partition deduplicated assignments into
    vmappable groups and train each group as ONE population program.

    Grouping key = the values of every NON-vmappable (architecture-changing)
    space key: within a group the compiled program is identical, so the
    members' scalars (lr / weight decay / loss weights) ride the stacked
    state. A group of one that carries architecture keys gains nothing from
    vmap and goes through the per-trial ``objective`` instead (the
    subprocess path — an architecture change needs a fresh program anyway).
    History entries match the random backend's contract (assignment/value/
    status) plus a ``mode`` field ("vmap" | "fallback") recording how each
    trial actually ran.

    Semantics that differ from the random backend, by design: the walltime
    budget is checked BETWEEN groups (a vmapped population is one in-flight
    unit — like the random backend's in-flight trials, a launched group
    trains to completion), and groups evaluate serially (``workers`` has no
    effect here; an architecture-dominated space that mostly falls back is
    better served by ``backend="random"`` with workers)."""
    from ..train.population import VMAP_SCALAR_KEYS

    scalar_keys = [k for k in space if k in VMAP_SCALAR_KEYS]
    arch_keys = [k for k in space if k not in VMAP_SCALAR_KEYS]
    rng = np.random.default_rng(seed)
    assignments = sample_unique_assignments(space, rng, n_trials)
    if population_objective is None:
        from ..train.population import make_population_objective

        population_objective = make_population_objective()

    groups: dict[str, list] = {}
    for a in assignments:
        sig = _assignment_key({k: a[k] for k in arch_keys})
        groups.setdefault(sig, []).append(a)

    from ..resilience import TrainingDivergedError

    best_assignment, best_value = None, float("inf")
    launched = 0
    for group in groups.values():
        if expired():
            break
        if arch_keys and len(group) == 1:
            results, mode = [evaluate(group[0])], "fallback"
        else:
            cfg_static = build({k: group[0][k] for k in arch_keys})
            members = [{k: a[k] for k in scalar_keys} for a in group]
            try:
                # population objectives return (value, status) pairs;
                # normalize to the evaluate() triple (no per-member error)
                results = [
                    (value, status, None)
                    for value, status in population_objective(cfg_static, members)
                ]
            except TrainingDivergedError as exc:
                err = f"{type(exc).__name__}: {exc}"
                results = [(float("inf"), "diverged", err)] * len(group)
            except Exception as exc:
                err = f"{type(exc).__name__}: {exc}"
                results = [(float("inf"), "failed", err)] * len(group)
            mode = "vmap"
        for a, (value, status, err) in zip(group, results):
            launched += 1
            value = float(value)
            entry = {"assignment": a, "value": value, "status": status, "mode": mode}
            if err is not None:
                entry["error"] = err
            history.append(entry)
            if status == "ok" and np.isfinite(value) and value < best_value:
                best_assignment, best_value = a, value
    if best_assignment is None:
        if launched == 0:
            raise RuntimeError(
                "HPO walltime budget expired before any trial completed "
                "— increase walltime_budget or shrink per-trial cost "
                "(this is a budget misconfiguration, not diverged trials)"
            )
        raise RuntimeError(_all_failed_msg(launched, history))
    if log_path:
        _write_hpo_log(log_path, best_assignment, best_value, history)
    return build(best_assignment), best_value, history
