"""One wire protocol for every TCP tier of the repo.

PR 4 grew a length-prefixed binary frame protocol inside
``datasets/sharded.py`` for the elastic data plane: ``pack_arrays`` /
``unpack_arrays`` codec (no pickle anywhere — object dtypes rejected on
both ends), an HMAC-compared auth token, pooled per-peer sockets, and
watchdog-bracketed round-trips that sever byte-dribbling peers. The fleet
serving tier (``serve/fleet``) needs the identical transport in front of
prediction replicas, so this module is the single implementation — one
transport, not two:

* **framing + codec** — ``send_msg``/``recv_msg`` length-prefixed frames of
  ``pack_arrays`` dict-of-ndarray payloads; zero-copy ``np.frombuffer``
  decode, every length validated before slicing;
* **sample codec** — ``GraphSample`` <-> flat array dict (the ShardedStore
  fetch payload and the fleet's predict request payload);
* **auth** — ``token_field``/``token_ok``: a shared-secret MISCONFIGURATION
  guard (plaintext + replayable — see the trust-model note in
  ``datasets/sharded.py``), compared with ``hmac.compare_digest`` so the
  guard itself doesn't leak the token through timing;
* **ping/pong** — ``pong_frame`` (server) + ``check_pong`` (client): ONE
  pong-validation implementation shared by the ShardedStore re-probe
  prober and the fleet health prober, each validating the identity fields
  it advertised the peer under (range for shards, readiness for replicas)
  before trusting it again — previously each prober carried its own
  inline validation loop;
* **ConnPool / RoundTripper** — pooled per-peer sockets with the
  stale-pool retry discipline, and the watchdog deadline bracketing every
  round-trip so a peer that dribbles bytes (resetting the per-``recv``
  socket timeout forever) is severed from the monitor thread and surfaces
  as an ordinary connection error;
* **WireServer** — the threaded TCP server shell (conn registry,
  instant dead-host ``close()``, malformed-frame drop, auth check, ping
  answer, server-error records) that ``ShardServer`` and the fleet's
  ``ReplicaHost`` both subclass;
* **HealthTable** — the quarantine clock (doubling re-probe backoff,
  healthy-first rotated replica ordering) shared by ShardedStore failover
  and fleet replica failover.
"""

from __future__ import annotations

import hmac
import socket
import socketserver
import struct
import sys
import threading
import time

import numpy as np

from ..graphs.graph import GraphSample
from ..telemetry import journal as _journal, propagation as _propagation

HDR = struct.Struct("<q")  # payload byte length
MAGIC = b"GSX1"

# known op keys, most specific first — the label a served frame's journal
# record carries (fall through to "frame" for ops this module hasn't met)
_OP_KEYS = ("predict", "stats", "metrics", "sizes", "idx")


def frame_op(z: dict) -> str:
    for key in _OP_KEYS:
        if key in z:
            return key
    return "frame"


# -- framing + array codec ----------------------------------------------------


def send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(HDR.pack(len(payload)) + payload)


def pack_arrays(d: dict[str, np.ndarray]) -> bytes:
    """dict[str, ndarray] -> compact binary frame. ~50x faster than ``.npz``
    (zipfile is pure Python and dominated the TCP tier's CPU budget); the
    dtype travels as its ``.str`` spec, never as a pickled object."""
    parts = [MAGIC, struct.pack("<I", len(d))]
    for k, v in d.items():
        v = np.ascontiguousarray(v)
        if v.dtype.hasobject:
            raise ValueError("object arrays are not allowed on the wire")
        name = k.encode()
        dt = v.dtype.str.encode()
        parts.append(struct.pack("<H", len(name)))
        parts.append(name)
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", v.ndim))
        if v.ndim:
            parts.append(struct.pack(f"<{v.ndim}q", *v.shape))
        raw = v.tobytes()
        parts.append(struct.pack("<q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def unpack_arrays(buf: bytes) -> dict[str, np.ndarray]:
    """Inverse of ``pack_arrays``; arrays are zero-copy views into ``buf``.
    Every length is validated against the payload before slicing, and ANY
    malformed frame — bad magic, truncated header, unknown dtype — raises
    ``ValueError`` (never struct.error/TypeError leaking to callers)."""
    try:
        if buf[:4] != MAGIC:
            raise ValueError(
                "bad wire magic (peer speaks a different protocol?)"
            )
        mv = memoryview(buf)
        off = 4
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        out: dict[str, np.ndarray] = {}
        for _ in range(n):
            (nl,) = struct.unpack_from("<H", buf, off)
            off += 2
            if off + nl > len(buf):
                raise ValueError("truncated frame (name)")
            name = bytes(mv[off:off + nl]).decode()
            off += nl
            (dl,) = struct.unpack_from("<B", buf, off)
            off += 1
            if off + dl > len(buf):
                raise ValueError("truncated frame (dtype)")
            dt = np.dtype(bytes(mv[off:off + dl]).decode())
            off += dl
            if dt.hasobject:
                raise ValueError("object arrays are not allowed on the wire")
            (nd,) = struct.unpack_from("<B", buf, off)
            off += 1
            shape = struct.unpack_from(f"<{nd}q", buf, off) if nd else ()
            off += 8 * nd
            (nb,) = struct.unpack_from("<q", buf, off)
            off += 8
            count = int(np.prod(shape, dtype=np.int64)) if nd else 1
            if count < 0 or nb != count * dt.itemsize or off + nb > len(buf):
                raise ValueError(f"corrupt frame for array {name!r}")
            out[name] = np.frombuffer(mv[off:off + nb], dtype=dt).reshape(shape)
            off += nb
        return out
    except ValueError:
        raise
    except (struct.error, TypeError, UnicodeDecodeError) as e:
        raise ValueError(f"corrupt frame: {e}") from None


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> bytes:
    (n,) = HDR.unpack(recv_exact(sock, HDR.size))
    if n < 0 or n > (1 << 33):
        raise ValueError(f"bad message length {n}")
    return recv_exact(sock, n)


# -- text / token fields ------------------------------------------------------


def text_field(s: str) -> np.ndarray:
    """UTF-8 text as a uint8 array — the only way strings travel (the codec
    carries arrays only; pickled str objects never touch the wire)."""
    return np.frombuffer(s.encode(), np.uint8)


def field_text(v: np.ndarray | None, default: str = "") -> str:
    if v is None:
        return default
    return bytes(np.asarray(v, np.uint8)).decode(errors="replace")


def token_field(token: str) -> np.ndarray:
    return np.frombuffer(token.encode(), np.uint8)


def token_ok(frame: dict[str, np.ndarray], token: bytes | None) -> bool:
    """Server-side auth check: True when no token is configured or the
    frame carries a matching one. ``hmac.compare_digest`` so the guard
    itself doesn't leak the token byte-by-byte through timing."""
    if token is None:
        return True
    got = frame.get("token")
    return got is not None and hmac.compare_digest(
        np.asarray(got).tobytes(), token
    )


# -- GraphSample <-> flat dict of arrays (npz-safe: no object dtypes) ---------

_ARRAY_FIELDS = (
    "x", "pos", "senders", "receivers", "edge_attr", "edge_shifts",
    "graph_y", "node_y", "energy_y", "forces_y", "graph_attr",
)
_EXTRA_FIELDS = ("node_table", "graph_table")
# extras that ride the serving plane (PE / triplet indices are part of the
# endpoint signature; a request stripped of them would be shed or served
# angle/PE-blind)
_WIRE_EXTRAS = ("pe", "rel_pe", "idx_kj", "idx_ji")


def sample_to_arrays(s: GraphSample) -> dict[str, np.ndarray]:
    out = {}
    for f in _ARRAY_FIELDS:
        v = getattr(s, f)
        if v is not None:
            out[f] = np.asarray(v)
    for f in _EXTRA_FIELDS + _WIRE_EXTRAS:
        if f in s.extras:
            out["extra_" + f] = np.asarray(s.extras[f])
    out["dataset_id"] = np.asarray(s.dataset_id, np.int32)
    return out


def sample_from_arrays(d: dict[str, np.ndarray]) -> GraphSample:
    # np.array: decoded frames are read-only frombuffer views; samples must
    # be writable (downstream transforms mutate in place)
    kw = {f: np.array(d[f]) for f in _ARRAY_FIELDS if f in d}
    s = GraphSample(dataset_id=int(d["dataset_id"]), **kw)
    for f in _EXTRA_FIELDS + _WIRE_EXTRAS:
        if "extra_" + f in d:
            s.extras[f] = np.array(d["extra_" + f])
    return s


def copy_sample(s: GraphSample) -> GraphSample:
    """Independent deep-ish copy: fresh array buffers, fresh extras dict.
    Caches hand these out because downstream transforms mutate samples in
    place — a cache that returns its own instances corrupts every later
    hit of the same index (ADVICE.md r5)."""
    out = GraphSample.__new__(GraphSample)
    for f in GraphSample.__slots__:
        v = getattr(s, f)
        if isinstance(v, np.ndarray):
            v = v.copy()
        elif f == "extras":
            v = {
                k: (x.copy() if isinstance(x, np.ndarray) else x)
                for k, x in v.items()
            }
        setattr(out, f, v)
    return out


def encode_samples(samples: list[GraphSample]) -> bytes:
    return pack_arrays(sample_fields(samples))


def sample_fields(samples: list[GraphSample]) -> dict[str, np.ndarray]:
    """The flat ``s{i}_*`` field layout of a samples frame — exposed (not
    just ``encode_samples``) so a request can carry samples NEXT TO other
    routing fields (model name, op markers) in one frame."""
    flat: dict[str, np.ndarray] = {}
    for i, s in enumerate(samples):
        for k, v in sample_to_arrays(s).items():
            flat[f"s{i}_{k}"] = v
    flat["n"] = np.asarray(len(samples), np.int64)
    return flat


def samples_from_frame(z: dict[str, np.ndarray]) -> list[GraphSample]:
    n = int(z["n"])
    out = []
    for i in range(n):
        prefix = f"s{i}_"
        d = {k[len(prefix):]: v for k, v in z.items() if k.startswith(prefix)}
        out.append(sample_from_arrays(d))
    return out


# -- ping / pong --------------------------------------------------------------


def pong_frame(**fields: np.ndarray) -> bytes:
    """The server half of a health probe: ``{"n": 0, "pong": 1}`` plus the
    identity fields the prober validates (a shard's served range, a
    replica's readiness bit + model list)."""
    out = {"n": np.asarray(0, np.int64), "pong": np.asarray(1, np.int64)}
    out.update(fields)
    return pack_arrays(out)


def check_pong(z: dict[str, np.ndarray], what: str, **expect) -> None:
    """THE pong validation (client half), shared by the ShardedStore
    re-probe prober and the fleet health prober — two inline copies of
    this loop would silently diverge the first time the policy is tuned.
    Every ``expect`` field must be present in the pong and match exactly
    (``np.array_equal`` after int64 coercion); a missing/mismatched field
    raises ``ConnectionError`` so the caller's quarantine stays armed — a
    peer reborn with a different identity must never be resurrected into
    the address its peers advertise."""
    if int(np.asarray(z.get("pong", 0)).reshape(-1)[0] if "pong" in z else 0) != 1:
        raise ConnectionError(f"{what}: peer answered without a pong")
    for key, want in expect.items():
        got = z.get(key)
        want = np.asarray(want, np.int64)
        if got is None or not np.array_equal(
            np.asarray(got, np.int64).reshape(-1), want.reshape(-1)
        ):
            raise ConnectionError(
                f"{what}: pong advertises {key}="
                f"{None if got is None else np.asarray(got).tolist()}, "
                f"expected {want.tolist()}"
            )


def error_frame(code: int, detail: str | None = None) -> bytes:
    fields = {"n": np.asarray(int(code), np.int64)}
    if detail:
        fields["detail"] = np.frombuffer(detail.encode()[:512], np.uint8)
    return pack_arrays(fields)


def frame_detail(z: dict[str, np.ndarray]) -> str:
    return bytes(np.asarray(z.get("detail", []), np.uint8)).decode(
        errors="replace"
    )


# -- server shell -------------------------------------------------------------


class WireServer:
    """Threaded TCP server answering ``pack_arrays`` frames — the shell
    ``ShardServer`` (sample fetches) and the fleet ``ReplicaHost``
    (predictions) share. Handles, in order, for every request frame: the
    chaos/test delay knob, the auth-token check (``n=-2`` record on
    mismatch), ``ping`` (``pong_frame(**self.pong_fields())``), then
    delegates to :meth:`handle_frame`; an exception out of the handler
    becomes an ``n=-3`` error record telling the CLIENT what broke instead
    of closing with no diagnostics.

    ``close()`` stops serving LIKE A DEAD HOST: immediately (no
    shutdown-poll wait — a chaos kill inside a timed window must not bill
    the victim's teardown to the client) and completely — the listening
    socket AND every established connection are severed, so pooled client
    sockets error on reuse instead of being silently served by a 'dead'
    peer. ``port=0`` picks an ephemeral port; a fixed port lets a
    restarted host come back at the address its peers already advertise,
    so a prober's quarantine-lift finds it.

    Trace propagation: a frame carrying the optional trace-context field
    (``telemetry.propagation``) has its correlation ids entered into the
    handler THREAD's journal scope around ``handle_frame`` — every record
    and span the handler emits shares the client's ``request_id`` — and
    the serve itself emits one ``wire_serve`` record. Legacy frames (no
    field) take the exact pre-existing path. ``journal=`` routes this
    server's records to a private ``EventJournal`` instead of the
    process-global one, so a subprocess replica journals into its own log
    dir (and in-process tests can give router and replica DISTINCT
    journals)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 auth_token: str | None = None, name: str | None = None,
                 journal: "_journal.EventJournal | None" = None,
                 _test_delay_s: float = 0.0):
        outer = self
        tok = None if auth_token is None else auth_token.encode()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                with outer._conns_lock:
                    # registration and the close() snapshot share one lock:
                    # a connection either lands in the snapshot (severed by
                    # close) or observes closed here — no window where a
                    # just-accepted socket outlives the "dead" host
                    if outer.closed:
                        return
                    outer._conns.add(self.request)
                try:
                    self._serve_requests()
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

            def _serve_requests(self) -> None:
                try:
                    while True:
                        try:
                            z = unpack_arrays(recv_msg(self.request))
                        except ValueError:
                            # malformed frame: drop the connection — one
                            # line of diagnostics, no per-request traceback
                            # spam from a misbehaving peer
                            print(
                                f"[{outer._log_name()}] dropping peer "
                                f"{self.client_address}: malformed frame",
                                file=sys.stderr,
                            )
                            return
                        if outer._test_delay_s:
                            time.sleep(outer._test_delay_s)
                        if not token_ok(z, tok):
                            send_msg(self.request, error_frame(-2))
                            continue
                        if "ping" in z:
                            # health probe (piggybacked on the request
                            # protocol): answer with the identity fields a
                            # prober verifies before lifting a quarantine
                            send_msg(
                                self.request,
                                pong_frame(**outer.pong_fields()),
                            )
                            continue
                        ctx = _propagation.extract(z)
                        t0 = time.time()
                        with _propagation.scope(ctx):
                            try:
                                resp = outer.handle_frame(z)
                                if isinstance(resp, dict):
                                    resp = pack_arrays(resp)
                                if ctx:
                                    outer.emit_event(
                                        "wire_serve", op=frame_op(z), ok=1,
                                        dur_s=round(time.time() - t0, 6),
                                    )
                            except Exception as e:
                                # server-side failure: tell the CLIENT what
                                # broke instead of closing with no
                                # diagnostics
                                resp = error_frame(
                                    -3, f"{type(e).__name__}: {e}"
                                )
                                if ctx:
                                    outer.emit_event(
                                        "wire_serve", op=frame_op(z), ok=0,
                                        error=type(e).__name__,
                                        dur_s=round(time.time() - t0, 6),
                                    )
                        send_msg(self.request, resp)
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._name = name or type(self).__name__
        self._journal = journal  # private journal (None = process-global)
        self._test_delay_s = float(_test_delay_s)
        # live handler sockets
        self._conns: set[socket.socket] = set()  # guarded-by: _conns_lock
        self._conns_lock = threading.Lock()
        self._srv = Server((host, int(port)), Handler)
        self.port = self._srv.server_address[1]
        self.closed = False  # guarded-by: _conns_lock

        def _serve() -> None:
            try:
                self._srv.serve_forever()
            except Exception:
                # close() severs the listening socket out from under the
                # select loop for an IMMEDIATE stop; the resulting EBADF
                # is the expected way down, anything else is real
                if not self.closed:
                    raise

        self._thread = threading.Thread(target=_serve, daemon=True)
        self._thread.start()

    # -- subclass hooks --
    def pong_fields(self) -> dict[str, np.ndarray]:
        """Identity fields the ping response advertises (and probers
        validate via :func:`check_pong`)."""
        return {}

    def handle_frame(self, z: dict[str, np.ndarray]) -> "bytes | dict":
        raise NotImplementedError

    def emit_event(self, kind: str, **fields) -> None:
        """Journal one record: to this server's private journal when one
        was attached, else to the process-global one (either way a no-op
        when the plane is off; a telemetry failure never fails a serve)."""
        try:
            if self._journal is not None:
                if _journal.metrics.enabled():
                    self._journal.emit(kind, **fields)
            else:
                _journal.emit(kind, **fields)
        except Exception:
            pass

    # -- chaos / lifecycle --
    def _log_name(self) -> str:
        return f"{self._name}:{self.port}"

    def set_delay(self, seconds: float) -> None:
        """Delay every response by ``seconds`` — the chaos ``slow_peer``
        hook: a response slower than the client's peer timeout makes this
        server a gray failure that callers must fail over around."""
        self._test_delay_s = float(seconds)

    def close(self) -> None:
        with self._conns_lock:
            if self.closed:
                return
            self.closed = True
            conns = list(self._conns)
        self._srv.server_close()  # refuses new connects from this instant
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # reap the serve loop off-thread: BaseServer.shutdown() blocks up
        # to its 0.5s poll interval, which callers should never pay
        threading.Thread(target=self._srv.shutdown, daemon=True).start()


# -- client: pooled sockets + watchdog-bracketed round-trips ------------------


class ConnPool:
    """Per-peer socket pool. Each concurrent caller checks out its own
    socket (creating one when none is idle), runs its request/response
    round-trip WITHOUT any shared lock, and returns the socket afterwards —
    so N workers overlap N remote round-trips. Idle sockets per peer are
    capped; excess ones close on release."""

    def __init__(self, max_idle_per_peer: int = 4, timeout: float = 120.0):
        self._idle: dict[object, list[socket.socket]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._max_idle = int(max_idle_per_peer)
        self._closed = False  # guarded-by: _lock
        self.timeout = float(timeout)  # connect AND per-recv deadline

    def acquire(self, key, host: str, port: int) -> tuple[socket.socket, bool]:
        """Returns (socket, from_pool). A pooled socket may have gone stale
        while idle — callers retry once on a fresh one; a FRESH connection
        failing is a real error. ``self.timeout`` bounds both the connect
        AND every later recv on the socket (``create_connection`` leaves
        its timeout armed), so a hung peer surfaces as ``socket.timeout`` —
        an ``OSError`` failover paths treat as peer-down — instead of
        parking the caller forever."""
        # <=0 means NO deadline (blocking), matching the round-trip guard's
        # "disabled for zero timeouts" convention — socket timeout 0.0 is
        # Python's NON-BLOCKING mode, which would instantly fail every
        # connect with BlockingIOError and quarantine healthy peers
        timeout = self.timeout if self.timeout and self.timeout > 0 else None
        with self._lock:
            stack = self._idle.get(key)
            while stack:
                sock = stack.pop()
                try:
                    sock.settimeout(timeout)  # policy may have changed
                except OSError:
                    continue  # closed while parked: discard, try the next
                return sock, True
        return socket.create_connection((host, port), timeout=timeout), False

    def release(self, key, sock: socket.socket) -> None:
        with self._lock:
            # a release racing close() (in-flight round-trip during
            # teardown) must not re-park into the cleared pool — close it
            if not self._closed:
                stack = self._idle.setdefault(key, [])
                if len(stack) < self._max_idle:
                    stack.append(sock)
                    return
        try:
            sock.close()
        except OSError:
            pass

    def evict(self, key) -> None:
        """Close and drop every idle socket pooled for ``key`` — called
        when a peer is quarantined, so a later un-quarantine never checks
        out a socket that spent the whole outage parked half-dead."""
        with self._lock:
            stack = self._idle.pop(key, [])
        for sock in stack:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for stack in self._idle.values():
                for sock in stack:
                    try:
                        sock.close()
                    except OSError:
                        pass
            self._idle.clear()


class RoundTripper:
    """Pooled, token-stamped, watchdog-bracketed request/reply round trips
    — the client half of the wire protocol, shared by ``ShardedStore``
    (peer fetches + probes) and the fleet router (replica predicts +
    probes).

    Transient-fault policy (requests on this transport are idempotent, so
    retrying is always safe): a stale POOLED socket (dropped by the
    peer/NAT while parked) retries immediately on a fresh connection
    without counting an attempt; a FRESH-connection failure retries per
    the supplied ``RetryPolicy`` (exponential backoff + jitter, a warning
    per retry). The last failure re-raises. A single-attempt policy pins
    one try — failover paths do their own retrying ACROSS replicas, where
    a per-replica backoff loop would multiply the outage by the replica
    count.

    ``guard(host, port, cell)`` arms the watchdog deadline (~1.25x the
    socket timeout) around a round-trip: a peer that dribbles bytes
    forever (resetting the per-``recv`` socket timeout every chunk) gets
    its socket severed from the monitor thread, surfacing as the OSError
    failover paths already handle. A severed pooled socket counts as a
    SPENT deadline, never a stale socket to quietly retry."""

    def __init__(self, timeout: float, auth_token: str | None = None,
                 max_idle_per_peer: int = 4, watchdog_factor: float = 1.25):
        self.pool = ConnPool(max_idle_per_peer, timeout=timeout)
        self._auth_token = auth_token
        self._watchdog = None  # lazy: built on first guarded round-trip
        self._watchdog_factor = float(watchdog_factor)

    @property
    def timeout(self) -> float:
        return self.pool.timeout

    @timeout.setter
    def timeout(self, value: float) -> None:
        self.pool.timeout = float(value)
        self._watchdog = None  # rebuilt with the new deadline on next guard

    def request(self, key, host: str, port: int, *, policy,
                _sock_cell: dict | None = None, **fields) -> bytes:
        """One request/response round-trip on a pooled socket — no shared
        lock held, so concurrent callers overlap their network waits. The
        socket returns to the pool only after a clean round-trip; any
        error closes it (a half-read stream cannot be reused).
        ``_sock_cell`` (when given) exposes the in-flight socket so a
        watchdog can sever a wedged round-trip from its monitor thread."""
        from .retry import call_with_retries

        if self._auth_token is not None:
            fields["token"] = token_field(self._auth_token)
        # trace-context propagation: when armed AND the ambient journal
        # context carries a request_id, one extra frame field rides along
        # (old servers ignore it); disabled, nothing is added — zero bytes
        _propagation.inject(fields)
        req = pack_arrays(fields)

        def attempt_once() -> bytes:
            while True:
                sock, from_pool = self.pool.acquire(key, host, port)
                if _sock_cell is not None:
                    _sock_cell["sock"] = sock
                try:
                    send_msg(sock, req)
                    payload = recv_msg(sock)
                except BaseException as e:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    # a socket parked idle in the pool can be dropped by
                    # the peer/NAT at any time; retry immediately on a
                    # fresh connection without consuming an attempt — but
                    # NEVER when the watchdog severed it: its one-shot
                    # round-trip deadline is already spent, and a silent
                    # fresh-connection retry would face the dribbling peer
                    # unguarded (the unbounded hang the guard exists for)
                    severed = _sock_cell is not None and _sock_cell.get("severed")
                    if (
                        from_pool
                        and not severed
                        and isinstance(e, (ConnectionError, OSError))
                    ):
                        continue
                    raise
                else:
                    self.pool.release(key, sock)
                    return payload

        return call_with_retries(
            attempt_once,
            policy=policy,
            retry_on=(ConnectionError, OSError),
            describe=f"wire round-trip to {host}:{port}",
            hint="HYDRAGNN_STORE_RETRIES tunes the cap",
        )

    def guard(self, host: str, port: int, cell: dict, what: str | None = None):
        """Watchdog context for one round-trip: if it outlives
        ``watchdog_factor`` x the socket timeout (the per-recv deadline
        never fired — a dribbling peer), the monitor thread severs the
        in-flight socket. Disabled for non-finite/zero timeouts."""
        from contextlib import nullcontext

        timeout = self.pool.timeout
        if not (timeout and np.isfinite(timeout)):
            return nullcontext()
        if self._watchdog is None:
            from ..resilience.watchdog import Watchdog

            self._watchdog = Watchdog(timeout * self._watchdog_factor)

        def sever() -> None:
            # flag BEFORE closing: the blocked recv wakes the instant the
            # socket dies, and the error path must already see "severed"
            # (a severed pooled socket is a spent deadline, not a stale
            # socket to quietly retry)
            cell["severed"] = True
            sock = cell.get("sock")
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

        return self._watchdog.guard(
            what or f"wire round-trip to {host}:{port}", on_expire=sever
        )

    def round_trip(self, key, host: str, port: int, *, policy,
                   what: str | None = None, **fields) -> dict[str, np.ndarray]:
        """Guarded request + decode in one call — the common client shape."""
        cell: dict = {"sock": None}
        with self.guard(host, port, cell, what=what):
            return unpack_arrays(self.request(
                key, host, port, policy=policy, _sock_cell=cell, **fields
            ))

    def evict(self, key) -> None:
        self.pool.evict(key)

    def close(self) -> None:
        self.pool.close()


# -- quarantine clock + replica ordering --------------------------------------


class HealthTable:
    """The PR 4 quarantine + doubling re-probe bookkeeping, factored so
    ShardedStore peer failover and fleet replica failover share one clock.
    An entry exists while the peer is suspect; each recorded failure
    pushes the re-probe deadline out by the current backoff and doubles
    the backoff up to the cap (``lift`` — a successful probe or fetch —
    removes the entry). Keys are caller-defined (peer ranks, replica
    ids).

    Each deadline is jittered through the shared ``utils.retry`` policy —
    a pure doubling clock is SYNCHRONIZED across clients (every router /
    store that saw a peer die at the same instant re-probes it in the same
    instant, a thundering herd against a just-recovering process);
    ``jitter`` spreads the deadlines by up to that fraction of the backoff
    (0 restores the old synchronized clock)."""

    def __init__(self, base_s: float, cap_s: float, jitter: float = 0.25):
        from .retry import RetryPolicy

        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        # delay(1) = 1.0 * (1 + U[0, jitter]) — the shared jitter shape,
        # applied as a multiplier on this table's own doubling backoff
        self.policy = RetryPolicy(
            attempts=1, base_delay=1.0, factor=1.0, jitter=float(jitter)
        )
        self.lock = threading.Lock()
        # key -> {"until", "backoff", "failures"}; quarantined while
        # now < until AND the entry exists
        self.entries: dict = {}  # guarded-by: lock

    def quarantined(self, key) -> bool:
        with self.lock:
            h = self.entries.get(key)
            return h is not None and time.monotonic() < h["until"]

    def bump(self, key) -> bool:
        """Record one more failure for ``key`` — THE single implementation
        of the quarantine clock, shared by fetch paths and probers (two
        copies would silently diverge the first time the policy is
        tuned). Returns True when this created the entry (a fresh
        peer-down transition)."""
        with self.lock:
            h = self.entries.get(key)
            fresh = h is None
            if fresh:
                h = self.entries[key] = {
                    "until": 0.0, "backoff": self.base_s, "failures": 0,
                }
            h["failures"] += 1
            h["until"] = time.monotonic() + h["backoff"] * self.policy.delay(1)
            h["backoff"] = min(h["backoff"] * 2.0, self.cap_s)
        return fresh

    def lift(self, key) -> dict | None:
        """Remove ``key`` from the table (the peer answered); returns the
        prior entry (failure count for the announcement) or None."""
        with self.lock:
            return self.entries.pop(key, None)

    def order(self, keys, rot: int = 0) -> list:
        """Failover order over a replica set: healthy peers first, rotated
        by a per-client constant so different clients spread load across
        replicas instead of all hammering the first-listed owner;
        quarantined peers last (soonest-re-probe first) as a final resort
        when nothing healthy is left."""
        keys = list(keys)
        healthy = [k for k in keys if not self.quarantined(k)]
        with self.lock:
            sick = sorted(
                (k for k in keys if k not in healthy and k in self.entries),
                key=lambda k: self.entries[k]["until"],
            )
        sick += [k for k in keys if k not in healthy and k not in sick]
        if healthy:
            r = rot % len(healthy)
            healthy = healthy[r:] + healthy[:r]
        return healthy + sick

    def due_probes(self) -> list:
        """Keys whose re-probe deadline has passed."""
        now = time.monotonic()
        with self.lock:
            return [k for k, h in self.entries.items() if now >= h["until"]]


__all__ = [
    "HDR",
    "MAGIC",
    "ConnPool",
    "HealthTable",
    "RoundTripper",
    "WireServer",
    "check_pong",
    "copy_sample",
    "encode_samples",
    "error_frame",
    "field_text",
    "frame_detail",
    "frame_op",
    "pack_arrays",
    "pong_frame",
    "recv_exact",
    "recv_msg",
    "sample_fields",
    "sample_from_arrays",
    "sample_to_arrays",
    "samples_from_frame",
    "send_msg",
    "text_field",
    "token_field",
    "token_ok",
    "unpack_arrays",
]
