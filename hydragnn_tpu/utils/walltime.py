"""Walltime-aware early stop (reference ``hydragnn/utils/distributed/
distributed.py:614-639``): on SLURM, process 0 polls the remaining job time
and the loop stops before the scheduler kills the run, so the best checkpoint
survives.
"""

from __future__ import annotations

import os
import re
import subprocess
import time


def _parse_slurm_time(s: str) -> float:
    """'[DD-]HH:MM:SS' / 'MM:SS' -> seconds."""
    days = 0
    if "-" in s:
        d, s = s.split("-", 1)
        days = int(d)
    parts = [int(p) for p in s.split(":")]
    while len(parts) < 3:
        parts.insert(0, 0)
    h, m, sec = parts
    return ((days * 24 + h) * 60 + m) * 60 + sec


def remaining_walltime_seconds() -> float | None:
    """Remaining seconds in the current SLURM job, or None outside SLURM."""
    job = os.environ.get("SLURM_JOB_ID")
    end = os.environ.get("SLURM_JOB_END_TIME")
    if end:  # modern slurm exports the epoch end time directly
        try:
            return float(end) - time.time()
        except ValueError:
            pass
    if not job:
        return None
    try:
        out = subprocess.run(
            ["squeue", "-h", "-j", job, "-o", "%L"],
            capture_output=True,
            text=True,
            timeout=10,
        ).stdout.strip()
        if out and re.match(r"^[\d:-]+$", out):
            return _parse_slurm_time(out)
    except (OSError, subprocess.TimeoutExpired):
        pass
    return None


def make_walltime_check(margin_seconds: float = 300.0):
    """Callable for train_validate_test's ``walltime_check`` hook: True when
    the job is within ``margin_seconds`` of its walltime."""

    def check() -> bool:
        rem = remaining_walltime_seconds()
        return rem is not None and rem < margin_seconds

    return check
