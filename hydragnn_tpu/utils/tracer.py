"""Span timers + profiling hooks.

Reference: ``hydragnn/utils/profiling_and_tracing/tracer.py`` — a plugin
registry of tracers (GPTL region timers, Score-P, NVML/ROCm/XPU energy
counters) with ``tr.start/stop(name)`` spans hard-wired around the train loop.

TPU equivalent: a lightweight hierarchical host timer keeping the reference's
span names (dataload/forward/backward/opt_step/train/validate/test), plus an
optional ``jax.profiler`` trace directory for XLA/perfetto dumps. Device-side
timing is meaningless per-span under async dispatch — callers that need exact
device timing should block on results; the ``train`` span brackets whole
epochs, which *is* accurate because the loop syncs on metrics each batch.

Spans are NESTED: each thread keeps an open-span stack, so ``dataload``
inside ``train`` closes innermost-first and — when
``HYDRAGNN_TRACE_EVENTS``/``Telemetry.trace_events`` arms the telemetry
plane — every close emits one Chrome trace-event complete record
(``hydragnn_tpu.telemetry.trace``) tagged with the journal's correlation
ids, making ``logs/<run>/trace.json`` a perfetto-loadable timeline next to
the aggregate timers this module always keeps.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict

from ..telemetry import trace as _trace


class Timer:
    __slots__ = ("count", "total", "t0", "running")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.t0 = 0.0
        self.running = False

    def start(self):
        if not self.running:
            self.t0 = time.perf_counter()
            self.running = True

    def stop(self):
        if self.running:
            self.total += time.perf_counter() - self.t0
            self.count += 1
            self.running = False


_timers: dict[str, Timer] = defaultdict(Timer)
_jax_trace_dir: str | None = None
# per-thread open-span stack [(name, t0_perf, t0_wall), ...] — threads never
# share spans, so nesting needs no lock
_spans = threading.local()


def initialize(trace_dir: str | None = None, enable_jax_profiler: bool = False):
    """Optionally arm jax.profiler tracing (XLA + host, perfetto-viewable)."""
    global _jax_trace_dir
    if enable_jax_profiler and trace_dir:
        _jax_trace_dir = trace_dir
        os.makedirs(trace_dir, exist_ok=True)
        import jax

        jax.profiler.start_trace(trace_dir)


def _span_stack() -> list:
    stack = getattr(_spans, "stack", None)
    if stack is None:
        stack = _spans.stack = []
    return stack


def start(name: str, **_ignored):
    _timers[name].start()
    _span_stack().append((name, time.perf_counter(), time.time()))


def stop(name: str, **_ignored):
    _timers[name].stop()
    stack = _span_stack()
    # pop the INNERMOST open span of this name (spans close LIFO in the
    # loop's usage; the search keeps a stray out-of-order stop from
    # corrupting unrelated open spans)
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == name:
            _, t0_perf, t0_wall = stack.pop(i)
            if _trace.trace_enabled():
                _trace.add_span(name, t0_wall, time.perf_counter() - t0_perf)
            break


@contextlib.contextmanager
def span(name: str):
    start(name)
    try:
        yield
    finally:
        stop(name)


def profile(name: str):
    """Decorator wrapping a function in a span (reference ``@tr.profile``)."""

    def deco(fn):
        def wrapper(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def reset():
    _timers.clear()


@contextlib.contextmanager
def isolated_timers():
    """Swap the process-global aggregate ``Timer`` registry for a fresh
    one for the duration of the scope (single rebind, atomic under the
    GIL) — the tracer half of ``telemetry.isolate()``. Spans started
    inside the scope land in the fresh registry because every accessor
    reads the module global at call time; the previous registry — and any
    half-open spans it held — comes back intact on exit."""
    global _timers
    fresh: dict[str, Timer] = defaultdict(Timer)
    prev, _timers = _timers, fresh
    try:
        yield fresh
    finally:
        _timers = prev


def get(name: str) -> Timer:
    return _timers[name]


def summary() -> dict[str, dict]:
    return {
        k: {"count": t.count, "total_s": t.total, "avg_s": t.total / max(t.count, 1)}
        for k, t in sorted(_timers.items())
    }


def save(path: str = "./logs/", prefix: str = "timing"):
    """Dump per-process timing json (the reference writes ``gp_timing.p{rank}``,
    ``tracer.py:432-458``)."""
    global _jax_trace_dir
    if _jax_trace_dir is not None:
        import jax

        jax.profiler.stop_trace()
        _jax_trace_dir = None
    try:
        import jax

        pid = jax.process_index()
    except Exception:
        pid = 0
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, f"{prefix}.p{pid}.json"), "w") as f:
        json.dump(summary(), f, indent=2)


def print_timers(verbosity_level: int = 0):
    from .print_utils import print_master

    for name, stats in summary().items():
        print_master(
            f"[timer] {name}: total {stats['total_s']:.3f}s over {stats['count']} calls "
            f"(avg {stats['avg_s'] * 1e3:.2f} ms)"
        )
