"""Paired-window ABBA verdict discipline, shared by bench.py's A/B rows and
the kernel-geometry autotuner (``ops/autotune.py``).

Factored out of bench.py (where PR 3 grew it) so an in-package consumer can
issue verdicts the exact same way the bench rows do: overhead is the median
of PAIRED per-window differences over the A-arm median, and the noise floor
is the WORST of the pair-difference IQR and each arm's own window IQR —
repeated runs on throttled CI hosts showed the pair spread alone
underestimates run-to-run noise (pairs can agree with each other while both
arms drift) and issues hard verdicts from scheduler luck. ``pass``/``fail``
are only issued when the measurement resolves the budget; otherwise
``inconclusive`` records the numbers without laundering noise into a
verdict.
"""

from __future__ import annotations

import statistics


def iqr(xs):
    """Interquartile-ish range; under 4 samples, the full range (>= 0)."""
    s = sorted(xs)
    if len(s) < 4:  # too few windows for quartiles: full range (>= 0)
        return s[-1] - s[0]
    q = len(s) // 4
    return s[-1 - q] - s[q]


def abba_verdict(a_ms, b_ms, budget_pct: float):
    """``(overhead_pct, noise_pct, verdict)`` for paired ABBA windows of the
    A (baseline) and B (candidate) arms against an overhead budget in
    percent of the A-arm median. Negative overhead = B is faster."""
    med_a = statistics.median(a_ms)
    diffs = [b - a for a, b in zip(a_ms, b_ms)]
    overhead_pct = 100.0 * statistics.median(diffs) / med_a
    noise_pct = 100.0 * max(iqr(diffs), iqr(a_ms), iqr(b_ms)) / med_a
    if overhead_pct + noise_pct < budget_pct:
        verdict = "pass"  # under budget even pessimistically
    elif overhead_pct - noise_pct > budget_pct:
        verdict = "fail"  # over budget even optimistically
    elif noise_pct <= budget_pct / 2:
        # the floor is well under the budget: the threshold itself resolves
        verdict = "pass" if overhead_pct < budget_pct else "fail"
    else:
        verdict = "inconclusive"  # host too noisy to resolve the budget
    if len(diffs) < 4 and noise_pct > budget_pct / 2:
        # under 4 pairs the range-based floor underestimates the true
        # spread — a stall hitting both windows of one arm can fabricate a
        # confident verdict; only a near-zero floor earns one
        verdict = "inconclusive"
    return overhead_pct, noise_pct, verdict


__all__ = ["abba_verdict", "iqr"]
