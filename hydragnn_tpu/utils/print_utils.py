"""Verbosity-gated printing + logging (reference
``hydragnn/utils/print/print_utils.py``).

Verbosity levels 0-4; ``print_distributed`` only prints on process index 0,
like the reference's rank-0 gating.
"""

from __future__ import annotations

import logging
import os
import sys


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def print_master(*args, **kwargs):
    if _process_index() == 0:
        print(*args, **kwargs)


def print_distributed(verbosity_level: int, *args, **kwargs):
    """Print on process 0 when verbosity >= 1... the reference prints at all
    levels via print_master; keep the gate permissive (>=0)."""
    if _process_index() == 0:
        print(*args, **kwargs)


def device_memory_summary() -> str:
    """Per-device HBM usage: current and peak bytes in use (the reference's
    per-rank peak-GPU-memory print, ``distributed.py:566-581``; on TPU the
    stats come from the PJRT allocator, on CPU they're unavailable)."""
    try:
        import jax

        lines = []
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)() or {}
            in_use = stats.get("bytes_in_use")
            peak = stats.get("peak_bytes_in_use")
            if in_use is None and peak is None:
                continue
            fields = []
            if in_use is not None:
                fields.append(f"in_use {in_use / 2**20:.0f} MiB")
            if peak is not None:
                fields.append(f"peak {peak / 2**20:.0f} MiB")
            lines.append(f"dev{d.id}: " + ", ".join(fields))
        return "; ".join(lines) or "device memory stats unavailable (CPU backend)"
    except Exception as e:  # never break a training epilogue over telemetry
        return f"device memory stats unavailable ({e})"


def iterate_tqdm(iterable, verbosity_level: int, desc: str = "", total=None):
    """Progress-bar iteration at verbosity >= 2 (reference ``iterate_tqdm``);
    falls back to the plain iterable (tqdm may not be installed)."""
    if verbosity_level >= 2 and _process_index() == 0:
        try:
            from tqdm import tqdm

            return tqdm(iterable, desc=desc, total=total)
        except ImportError:
            pass
    return iterable


def setup_log(log_name: str, path: str = "./logs/") -> logging.Logger:
    """Rank-tagged file logger at ``./logs/<run>/run.log`` (reference
    ``print_utils.py:62-111``)."""
    run_dir = os.path.join(path, log_name)
    os.makedirs(run_dir, exist_ok=True)
    logger = logging.getLogger(f"hydragnn_tpu.{log_name}")
    logger.setLevel(logging.INFO)
    if not logger.handlers:
        fh = logging.FileHandler(os.path.join(run_dir, "run.log"))
        fh.setFormatter(
            logging.Formatter(f"%(asctime)s [p{_process_index()}] %(message)s")
        )
        logger.addHandler(fh)
    return logger
