"""Verbosity-gated printing + logging (reference
``hydragnn/utils/print/print_utils.py``).

Verbosity levels 0-4; ``print_distributed`` only prints on process index 0,
like the reference's rank-0 gating.
"""

from __future__ import annotations

import logging
import os
import sys


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def print_master(*args, **kwargs):
    if _process_index() == 0:
        print(*args, **kwargs)


def print_distributed(verbosity_level: int, *args, **kwargs):
    """Print on process 0 when verbosity >= 1... the reference prints at all
    levels via print_master; keep the gate permissive (>=0)."""
    if _process_index() == 0:
        print(*args, **kwargs)


def iterate_tqdm(iterable, verbosity_level: int, desc: str = "", total=None):
    """Progress-bar iteration at verbosity >= 2 (reference ``iterate_tqdm``);
    falls back to the plain iterable (tqdm may not be installed)."""
    if verbosity_level >= 2 and _process_index() == 0:
        try:
            from tqdm import tqdm

            return tqdm(iterable, desc=desc, total=total)
        except ImportError:
            pass
    return iterable


def setup_log(log_name: str, path: str = "./logs/") -> logging.Logger:
    """Rank-tagged file logger at ``./logs/<run>/run.log`` (reference
    ``print_utils.py:62-111``)."""
    run_dir = os.path.join(path, log_name)
    os.makedirs(run_dir, exist_ok=True)
    logger = logging.getLogger(f"hydragnn_tpu.{log_name}")
    logger.setLevel(logging.INFO)
    if not logger.handlers:
        fh = logging.FileHandler(os.path.join(run_dir, "run.log"))
        fh.setFormatter(
            logging.Formatter(f"%(asctime)s [p{_process_index()}] %(message)s")
        )
        logger.addHandler(fh)
    return logger
