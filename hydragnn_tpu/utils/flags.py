"""Typed registry of every ``HYDRAGNN_*`` runtime flag.

The reference scatters ~20 env-var flags across the codebase (SURVEY §5:
``USE_FSDP``, ``VALTEST``, ``MAX_NUM_BATCH``, ``NUM_WORKERS``, ``AFFINITY*``,
``TRACE_LEVEL``, ... — ``hydragnn/utils/distributed/distributed.py:429-436``,
``train/train_validate_test.py:179,343,581,675``, ``preprocess/load_data.py:
121-136,287-292``). This module is the single typed catalogue: one accessor
per flag, a machine-readable table for ``--help``-style dumps, and a warning
for set-but-unknown ``HYDRAGNN_*`` vars (accepting-and-ignoring is worse than
rejecting — VERDICT r1 weak #7).

Flags subsumed by the TPU design (``AGGR_BACKEND``, ``BACKEND``,
``DDSTORE_METHOD``, ``CUSTOM_DATALOADER``, ``FSDP_VERSION``) are recognized
and warn once instead of silently vanishing.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class Flag:
    name: str
    kind: str  # bool | int | float | str | path
    default: object
    help: str
    subsumed: str | None = None  # why the TPU design doesn't need it


_REGISTRY: dict[str, Flag] = {}


def _register(flag: Flag) -> Flag:
    _REGISTRY[flag.name] = flag
    return flag

# -- training loop ----------------------------------------------------------
VALTEST = _register(Flag(
    "HYDRAGNN_VALTEST", "bool", True,
    "Run validate/test each epoch (=0 skips both; reference "
    "train_validate_test.py:343, the SC25 weak-scaling setting)."))
MAX_NUM_BATCH = _register(Flag(
    "HYDRAGNN_MAX_NUM_BATCH", "int", None,
    "Cap batches per epoch (reference train_validate_test.py:179; pins "
    "work for scaling runs)."))
SUPERSTEP = _register(Flag(
    "HYDRAGNN_SUPERSTEP", "int", None,
    "Train steps folded into ONE device dispatch via lax.scan (overrides "
    "Training.steps_per_dispatch; unset/1 disables). K>1 amortizes host "
    "dispatch latency over K steps — the win grows as steps get shorter — "
    "at the cost of device memory for the in-flight K-batch block plus up "
    "to 2 more staged ahead (~3K batches) and coarser (K-step) metric "
    "granularity. Edge-sharded and pipeline modes pin K=1 (their "
    "per-batch placement has no stacked [K, ...] equivalent yet)."))
POPULATION = _register(Flag(
    "HYDRAGNN_POPULATION", "int", None,
    "Train N population members (HPO trials / deep-ensemble replicas) as "
    "ONE jitted program by vmapping the train step over a leading member "
    "axis (train/population.py; overrides Training.population.size, "
    "unset/0/1 disables). Composes with HYDRAGNN_SUPERSTEP: one dispatch "
    "advances N members x K steps. Members share the batch stream and "
    "differ in init seed, lr, weight decay, and loss weights (runtime data, "
    "not compile-time constants); a NaN/Inf member is select-skipped in "
    "program and reported 'diverged' without stalling the rest. Pins "
    "single-program mode: no data mesh, edge-sharding, or pipeline."))
NONFINITE_GUARD = _register(Flag(
    "HYDRAGNN_NONFINITE_GUARD", "bool", None,
    "Force the non-finite step guard on/off (overrides "
    "Training.resilience.nonfinite_guard). The guard select-skips NaN/Inf "
    "optimizer updates inside the jitted step (resilience/guard.py) and "
    "escalates to rollback-with-LR-cut after N consecutive skips."))
FAULT_PLAN = _register(Flag(
    "HYDRAGNN_FAULT_PLAN", "str", None,
    "Deterministic fault-injection plan (resilience/chaos.py): a JSON list "
    "of events or @/path/to/plan.json. Faults: nan_batch (poison node "
    "features at an exact epoch/dispatch), sigterm (preemption rehearsal), "
    "hang (sleep inside the watchdog-guarded dispatch), corrupt_latest "
    "(truncate the newest checkpoint after the epoch), dead_shard (kill a "
    "live ShardServer mid-epoch — the host-loss drill), slow_peer (delay a "
    "server's responses past the fetch timeout — the gray-failure drill), "
    "device_loss / mesh_shrink (mark compute devices dead on the elastic "
    "controller — the COMPUTE-plane host-loss drill; needs "
    "HYDRAGNN_ELASTIC), double_fault (fire a nested fault while a recovery "
    "is already in flight). resilience/campaign.py composes these into "
    "seeded randomized multi-fault schedules."))
ELASTIC = _register(Flag(
    "HYDRAGNN_ELASTIC", "bool", None,
    "In-process elastic recovery (resilience/elastic.py; overrides "
    "Training.resilience.elastic, default off). On a recoverable fault — "
    "chaos device_loss/mesh_shrink, SIGTERM, or a hung-dispatch watchdog "
    "expiry — the run drains to the dispatch boundary, checkpoints, "
    "rebuilds the data mesh from the surviving devices, re-places the "
    "TrainState, and continues the SAME epoch without a process restart "
    "(same-mesh resumes bit-exact incl. K>1 supersteps; shrunk meshes "
    "allclose at lr-scale). Pipeline/edge-sharded/tensor layouts take a "
    "logged restart-fallback policy instead."))
WATCHDOG_DISPATCH_S = _register(Flag(
    "HYDRAGNN_WATCHDOG_DISPATCH_S", "float", None,
    "Per-DISPATCH hang deadline in seconds (overrides "
    "Training.resilience.watchdog_dispatch_s; unset/0 disables): a timer "
    "armed around each train-step dispatch (staging + dispatch + the "
    "backpressure sync) EXCEPT a segment's first, which legitimately pays "
    "the step compile. Expiry warns, and with elastic recovery active it "
    "becomes a recoverable fault — the run drains at the next boundary and "
    "resumes in process instead of burning walltime in silence. Distinct "
    "from resilience.watchdog_timeout, which brackets individual blocking "
    "device syncs/peer round-trips."))
DUMP_TESTDATA = _register(Flag(
    "HYDRAGNN_DUMP_TESTDATA", "bool", False,
    "Dump per-rank test true/pred pickles (reference :908)."))
EPOCH = _register(Flag(
    "HYDRAGNN_EPOCH", "int", None,
    "Exported (not read) by the epoch loop: current epoch number for "
    "subordinate tools (reference :316)."))

# -- parallelism ------------------------------------------------------------
AUTO_PARALLEL = _register(Flag(
    "HYDRAGNN_AUTO_PARALLEL", "bool", True,
    "Auto-build a data mesh over all local devices in run_training."))
HALO = _register(Flag(
    "HYDRAGNN_HALO", "bool", None,
    "Force halo-exchange graph partitioning on/off (overrides "
    "Architecture.halo.enabled). Partitions ONE giant graph's nodes over "
    "the data mesh in Morton order and exchanges only boundary node "
    "features via ppermute before each conv layer (parallel/halo.py) — "
    "the node-resident alternative to replicated edge_sharding."))
USE_FSDP = _register(Flag(
    "HYDRAGNN_USE_FSDP", "bool", False,
    "Shard params+optimizer over the data axis, ZeRO-3 style (reference "
    "distributed.py:429-436)."))
FSDP_STRATEGY = _register(Flag(
    "HYDRAGNN_FSDP_STRATEGY", "str", "FULL_SHARD",
    "FULL_SHARD -> param+opt sharding; NO_SHARD -> replicated (reference "
    "distributed.py:435-437; SHARD_GRAD_OP/HYBRID_SHARD map to FULL_SHARD "
    "— XLA re-materializes gathered params per-step either way)."))
MASTER_ADDR = _register(Flag(
    "HYDRAGNN_MASTER_ADDR", "str", None,
    "Coordinator host for jax.distributed (reference :158)."))
MASTER_PORT = _register(Flag(
    "HYDRAGNN_MASTER_PORT", "int", None,
    "Coordinator port; default derived from the job id (reference :171-219)."))

# -- input pipeline ---------------------------------------------------------
NUM_WORKERS = _register(Flag(
    "HYDRAGNN_NUM_WORKERS", "int", None,
    "Override Training.num_workers collate threads (reference "
    "load_data.py:287)."))
PREFETCH = _register(Flag(
    "HYDRAGNN_PREFETCH", "int", None,
    "Prefetch depth (batches buffered ahead); overrides Training.prefetch; "
    "0 disables (the reference HydraDataLoader role)."))
AFFINITY = _register(Flag(
    "HYDRAGNN_AFFINITY", "bool", False,
    "Pin collate worker threads to cores (reference load_data.py:121-136)."))
AFFINITY_WIDTH = _register(Flag(
    "HYDRAGNN_AFFINITY_WIDTH", "int", 1, "Cores per pinned worker."))
AFFINITY_OFFSET = _register(Flag(
    "HYDRAGNN_AFFINITY_OFFSET", "int", 0, "First core for pinned workers."))

STORE_RETRIES = _register(Flag(
    "HYDRAGNN_STORE_RETRIES", "int", 3,
    "Max connection attempts for a ShardedStore remote fetch; retries use "
    "exponential backoff with jitter, so a transient TCP drop degrades to "
    "a logged retry instead of killing the epoch. 1 disables retrying. "
    "With replication > 1 each attempt is a full failover ROUND over the "
    "live replicas of the range, so a dead owner costs one round at most."))
REPLICATION = _register(Flag(
    "HYDRAGNN_REPLICATION", "int", None,
    "Expected replica count per sample range in the ShardedStore peer "
    "table (overrides Dataset.store.replication_factor). With R>1 every "
    "range is served by R owners and fetches fail over to a live replica "
    "when an owner dies; under-replicated ranges warn at startup."))
PEER_TIMEOUT = _register(Flag(
    "HYDRAGNN_PEER_TIMEOUT", "float", None,
    "Connect/read timeout in seconds for ShardedStore peer sockets "
    "(overrides Dataset.store.peer_timeout; default 120). A peer slower "
    "than this counts as DOWN: the fetch fails over to a replica and the "
    "peer is quarantined until a background probe sees it answer again."))

# -- serving (hydragnn_tpu.serve) -------------------------------------------
SERVE_QUEUE_DEPTH = _register(Flag(
    "HYDRAGNN_SERVE_QUEUE_DEPTH", "int", None,
    "Bounded request-queue depth per served model (overrides "
    "Serving.queue_depth, default 256). Admission beyond it sheds the "
    "request with a typed QueueFullError — the backpressure signal for "
    "clients; deeper queues trade shed rate for tail latency."))
SERVE_FLUSH_MS = _register(Flag(
    "HYDRAGNN_SERVE_FLUSH_MS", "float", None,
    "Micro-batch max-latency flush timer in ms (overrides "
    "Serving.flush_ms, default 5). The first queued request opens the "
    "window; requests arriving inside it coalesce into the tightest pad "
    "bucket. 0 = dispatch immediately (per-request batches)."))
SERVE_WARMUP = _register(Flag(
    "HYDRAGNN_SERVE_WARMUP", "bool", None,
    "AOT-compile every (model, bucket) predict executable at server boot "
    "(overrides Serving.warmup, default on). =0 defers to lazy jit on "
    "first use — first requests then pay the compile the warm-up was "
    "built to hide; the strict zero-recompile guarantee only holds for "
    "warmed endpoints."))
SERVE_QUANT = _register(Flag(
    "HYDRAGNN_SERVE_QUANT", "bool", None,
    "Serve int8-quantized predictions (overrides Serving.quantize, default "
    "off). Warm-up then calibrates per-(model, bucket) activation scales "
    "from the endpoint's calibration samples, AOT-compiles an int8 predict "
    "variant ALONGSIDE the fp32 one, and refuses to boot if any head's "
    "calibrated error vs the fp32 answer exceeds Serving.quant_tol. =0 "
    "serves the fp32 executables only (bit-identical to run_prediction)."))
FLEET_REPLICAS = _register(Flag(
    "HYDRAGNN_FLEET_REPLICAS", "int", None,
    "Replica processes a fleet deployment boots behind the router "
    "(overrides Serving.fleet.replicas, default 2). Each replica is a "
    "subprocess PredictionServer booted from checkpoint paths, AOT-warmed "
    "before it advertises ready; the router health-checks them and fails "
    "a dead/dribbling replica's in-flight requests over transparently."))
FLEET_CACHE_BYTES = _register(Flag(
    "HYDRAGNN_FLEET_CACHE_BYTES", "int", None,
    "Byte budget of the fleet router's content-addressed answer cache "
    "(overrides Serving.fleet.cache_bytes, default 32 MiB; =0 disables). "
    "Keyed on canonicalized graph bytes + model + quant flag: a repeated "
    "graph is answered from the router, byte-identical to replica "
    "compute, at zero replica cost."))
FLEET_AUTOSCALE = _register(Flag(
    "HYDRAGNN_FLEET_AUTOSCALE", "bool", None,
    "Arm the fleet SLO autoscaler (overrides Serving.fleet.autoscale."
    "enabled, default off). The control loop polls FleetRouter.metrics() "
    "and spawns/retires replicas against the interactive p99 + queue-depth "
    "+ shed-rate targets, with hysteresis and cooldowns; retirement drains "
    "in-flight work before the socket closes, so scaling down never loses "
    "a request."))
ROLLOUT_CANARY = _register(Flag(
    "HYDRAGNN_ROLLOUT_CANARY", "bool", None,
    "Require the bit-identity canary before a blue/green cutover "
    "(overrides Serving.fleet.rollout.canary, default on). Green replicas "
    "must serve answers byte-identical to the live set on a pinned probe "
    "batch before the router swaps generations; a mismatch refuses the "
    "rollout and leaves the live set untouched. =0 skips the proof — "
    "only safe when the new checkpoint is known answer-compatible."))
SERIALIZED_BOOT = _register(Flag(
    "HYDRAGNN_SERIALIZED_BOOT", "bool", None,
    "Boot replicas from persisted jax.export executable artifacts instead "
    "of recompiling (overrides Serving.fleet.serialized_boot, default on). "
    "Warm-up saves artifacts keyed model/bucket/backend/precision next to "
    "the compile-cost ledger; a booting worker with a matching fingerprint "
    "deserializes in seconds. A stale/missing artifact falls back to "
    "compile-from-source LOUDLY (logged per bucket), never silently."))

# -- bulk screening (hydragnn_tpu.screen) ------------------------------------
SCREEN_PREFETCH = _register(Flag(
    "HYDRAGNN_SCREEN_PREFETCH", "int", None,
    "Blocks the bulk-screening executor stages ahead of the device "
    "(overrides Screening.prefetch, default 2): a background thread "
    "fetches + collates the next block(s) while the current one computes. "
    "=0 runs fully synchronous — the 'naive' arm the screen_throughput_ab "
    "bench times against; scores are identical either way."))
SCREEN_TOPK = _register(Flag(
    "HYDRAGNN_SCREEN_TOPK", "int", None,
    "Ranked candidates a bulk screen keeps (overrides Screening.topk, "
    "default 16). Ordering is (score desc, index asc) — deterministic, so "
    "an interrupted-and-resumed screen reports the bit-identical list."))

# -- precision --------------------------------------------------------------
PRECISION = _register(Flag(
    "HYDRAGNN_PRECISION", "str", None,
    "Compute dtype for training step programs (overrides "
    "Training.precision): fp32/fp64/bf16/fp16 (+ long aliases) or 'auto' "
    "(bf16 on TPU backends, fp32 elsewhere). Master weights, gradients, "
    "optimizer state, and checkpoints stay fp32 regardless — the flag "
    "changes the per-step cast-to-compute only, and the non-finite guard's "
    "'auto' policy arms itself off the RESOLVED dtype, so forcing bf16/fp16 "
    "here also arms the divergence guard. fp16-class runs can add a static "
    "Training.loss_scale; bf16 never needs one."))

# -- kernels / compilation --------------------------------------------------
OPS_AUTOTUNE = _register(Flag(
    "HYDRAGNN_OPS_AUTOTUNE", "bool", False,
    "Let ops/ kernel wrappers consult the shared geometry autotuner's "
    "on-disk cache (ops/autotune.py; persisted next to "
    "HYDRAGNN_COMPILE_CACHE as ops_autotune.json). A cached per-(kernel, "
    "shape, backend) choice replaces the hard-coded default geometry when "
    "its layout certificate provably transfers; cache misses keep the "
    "default — sweeps only ever run through explicit autotune_* calls "
    "(bench/tooling), never implicitly inside a training step."))
FP8_MATMUL = _register(Flag(
    "HYDRAGNN_FP8_MATMUL", "bool", None,
    "EXPERIMENTAL: route ops.fp8_matmul.fp8_dense through its fused Pallas "
    "kernel (default: on for TPU backends, XLA expression elsewhere). The "
    "fp8 (e4m3/e5m2) dense path is an opt-in experiment with certified "
    "error reporting (certify_fp8_dense) — it is NOT a Training.precision "
    "value and nothing routes through it implicitly."))
FUSED_SCATTER = _register(Flag(
    "HYDRAGNN_FUSED_SCATTER", "bool", None,
    "Force the Pallas fused gather-scatter kernel on/off (default: on for "
    "TPU backends)."))
FUSED_SOFTMAX = _register(Flag(
    "HYDRAGNN_FUSED_SOFTMAX", "bool", None,
    "Force the Pallas fused segment-softmax kernel on/off (default: on for "
    "TPU backends). Collapses segment_softmax's max->exp->sum->divide chain "
    "(four segment ops, three HBM round-trips of [E, H] intermediates) into "
    "one windowed pass (ops/fused_softmax.py); GAT attention and the GPS "
    "dense per-graph softmax route through it. =0 restores the XLA chain "
    "everywhere."))
FUSED_CELL_LIST = _register(Flag(
    "HYDRAGNN_FUSED_CELL_LIST", "bool", None,
    "Force the Pallas fused cell-list neighbor-build kernel on/off "
    "(default: on for TPU backends). md.py's binned radius graph then "
    "filters candidate pairs inside one windowed kernel over cell-sorted "
    "atoms (ops/fused_cell_list.py) instead of materializing the full "
    "[n, 27*capacity] candidate/displacement matrices in HBM. =0 restores "
    "the pure-XLA binned build."))
NATIVE = _register(Flag(
    "HYDRAGNN_NATIVE", "bool", True,
    "Use the native C++ cell-list/gather library (=0 for numpy fallback)."))
COMPILE_CACHE = _register(Flag(
    "HYDRAGNN_COMPILE_CACHE", "path", "./.jax_cache",
    "Persistent XLA compilation cache dir (=0 disables)."))
COMPILE_SENTINEL = _register(Flag(
    "HYDRAGNN_COMPILE_SENTINEL", "str", None,
    "Guard steady-state epochs against silent jit recompilation "
    "(analysis/sentinel.py): 'warn' prints the per-epoch compile delta "
    "after the warm-up epoch, 'strict' raises RecompileError; unset/0 "
    "disables."))
THREADSAN = _register(Flag(
    "HYDRAGNN_THREADSAN", "bool", False,
    "Runtime lock-order sanitizer (analysis/threadsan.py): instrument "
    "every threading.Lock/RLock/Condition the process constructs after "
    "hydragnn_tpu import, record the per-thread lock acquisition-order "
    "graph plus hold-while-blocking events, and expose cycle detection "
    "(potential deadlocks, reported with BOTH acquisition stacks). Tests "
    "use the `threadsan` pytest fixture instead; this flag arms whole "
    "process runs (chaos drills, soak tests). Small per-acquire overhead "
    "— diagnostics, not production serving."))

# -- config / observability -------------------------------------------------
TELEMETRY = _register(Flag(
    "HYDRAGNN_TELEMETRY", "bool", True,
    "The unified telemetry plane (hydragnn_tpu.telemetry): typed metrics "
    "registry, structured event journal (logs/<run>/events.jsonl), and "
    "correlated trace export. =0 turns the WHOLE plane into near-zero-cost "
    "no-ops (accessors hand out a shared no-op instrument; journal emits "
    "return immediately) — the telemetry_overhead_ab bench row holds the "
    "enabled path under a <2% budget. Overrides Telemetry.enabled."))
TRACE_EVENTS = _register(Flag(
    "HYDRAGNN_TRACE_EVENTS", "bool", False,
    "Record every tracer span as a Chrome trace event and let runs write a "
    "perfetto-loadable logs/<run>/trace.json tagged with the journal's "
    "correlation ids (run_id/epoch/step/recovery_id). Off by default — the "
    "aggregate span timers (utils/tracer.py) always run; this arms the "
    "per-span TIMELINE view. Overrides Telemetry.trace_events; requires "
    "HYDRAGNN_TELEMETRY on."))
TRACE_PROPAGATE = _register(Flag(
    "HYDRAGNN_TRACE_PROPAGATE", "bool", True,
    "Propagate the ambient trace context (request_id / parent span / "
    "journal correlation ids) across the wire: RoundTripper.request "
    "stamps one optional frame field, WireServer extracts it into the "
    "handler's journal scope, so a fleet predict or a sharded-store "
    "failover renders as ONE cross-process timeline (telemetry fleet "
    "CLI). =0 removes the field entirely — zero wire bytes, near-zero "
    "cost (the trace_propagation_ab bench row holds the enabled path "
    "under a <2% budget). Overrides Telemetry.trace_propagate; requires "
    "HYDRAGNN_TELEMETRY on."))
LEDGER = _register(Flag(
    "HYDRAGNN_LEDGER", "str", None,
    "Compiled-program cost ledger (telemetry/ledger.py). Unset: every "
    "aot_compile records cost_analysis()/memory_analysis() in memory "
    "(free — the executable already exists); runs that open a journal "
    "persist logs/<run>/ledger.json. '0'/'false': disable capture. A "
    "path: ALSO save the cumulative ledger there after serve warm-up / "
    "screen warm-up (the bench + CI regression-sentinel hook; diff two "
    "ledgers with `python -m hydragnn_tpu.telemetry ledger`)."))
USE_VARIABLE_GRAPH_SIZE = _register(Flag(
    "HYDRAGNN_USE_VARIABLE_GRAPH_SIZE", "bool", None,
    "Force the variable-graph-size config path (reference "
    "config_utils.py:29)."))
TENSORBOARD = _register(Flag(
    "HYDRAGNN_TENSORBOARD", "bool", True,
    "Write TensorBoard scalars on rank 0 (=0 disables)."))
TRACE_LEVEL = _register(Flag(
    "HYDRAGNN_TRACE_LEVEL", "int", 0,
    "Tracer verbosity (reference train_validate_test.py:675): 0 span "
    "timers only, >=1 also start a jax.profiler trace for the first epoch "
    "(written under ./logs/<run>/profile)."))

# -- recognized-but-subsumed (warn once, never silently ignored) ------------
for _name, _why in (
    ("HYDRAGNN_AGGR_BACKEND", "loss scalars ride the one in-program XLA "
     "all-reduce; there is no separate scalar plane to pick a backend for"),
    ("HYDRAGNN_BACKEND", "collectives are XLA-over-ICI/DCN; there is no "
     "NCCL/gloo backend choice"),
    ("HYDRAGNN_MASTER_PORT_RETRIES", "jax.distributed owns the port "
     "lifecycle; retries are not needed"),
    ("HYDRAGNN_DDSTORE_METHOD", "the packed-record store gives every host "
     "O(1) mmap access; there is no RDMA method to select"),
    ("HYDRAGNN_CUSTOM_DATALOADER", "PrefetchLoader is always available via "
     "Training.prefetch / HYDRAGNN_PREFETCH"),
    ("HYDRAGNN_FSDP_VERSION", "one sharding implementation (GSPMD); "
     "see HYDRAGNN_FSDP_STRATEGY"),
    ("HYDRAGNN_SYSTEM", "device selection is jax.devices(); no per-machine "
     "launch quirks"),
):
    _register(Flag(_name, "str", None, "(subsumed)", subsumed=_why))


def _parse(flag: Flag, raw: str):
    if flag.kind == "bool":
        return raw not in ("0", "false", "False")
    if flag.kind == "int":
        return int(raw)
    if flag.kind == "float":
        return float(raw)
    return raw


def get(flag: Flag, default=_REGISTRY):  # sentinel: use flag.default
    """Typed read of one flag; ``default`` overrides the registry default.
    An empty-but-set variable (``HYDRAGNN_X= python ...``) counts as unset."""
    raw = os.getenv(flag.name)
    if raw is None or raw == "":
        return flag.default if default is _REGISTRY else default
    if flag.subsumed is not None:
        _warn_subsumed(flag)
        return flag.default if default is _REGISTRY else default
    return _parse(flag, raw)


_warned: set[str] = set()


def _warn_subsumed(flag: Flag) -> None:
    if flag.name not in _warned:
        _warned.add(flag.name)
        warnings.warn(
            f"{flag.name} is recognized but not used by the TPU build: "
            f"{flag.subsumed}", stacklevel=3)


def warn_unknown() -> list[str]:
    """Warn (once each) about set-but-unregistered HYDRAGNN_* env vars —
    likely typos. Returns the offending names. Also triggers the subsumed
    warnings for set subsumed flags."""
    bad = []
    for name in sorted(os.environ):
        if not name.startswith("HYDRAGNN_"):
            continue
        flag = _REGISTRY.get(name)
        if flag is None:
            bad.append(name)
            if name not in _warned:
                _warned.add(name)
                warnings.warn(f"unknown flag {name} is set; known flags: "
                              "hydragnn_tpu.utils.flags.describe()", stacklevel=2)
        elif flag.subsumed is not None:
            _warn_subsumed(flag)
    return bad


def describe() -> str:
    """Human-readable flag table."""
    lines = []
    for name in sorted(_REGISTRY):
        f = _REGISTRY[name]
        what = f"subsumed: {f.subsumed}" if f.subsumed else f.help
        lines.append(f"{name:38s} [{f.kind}, default={f.default!r}] {what}")
    return "\n".join(lines)
