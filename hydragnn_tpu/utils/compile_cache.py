"""Persistent XLA compilation cache.

First TPU compilation of a train step costs 20-40 s; the persistent cache
makes every subsequent process start (reruns, HPO trials, the bench driver)
hit a disk cache instead. The reference has no analog (torch eager), so this
is pure TPU-side win.

Env: ``HYDRAGNN_COMPILE_CACHE`` — a directory, ``0`` to disable. Default
``./.jax_cache``.
"""

from __future__ import annotations

import os

_enabled = False


def enable_compile_cache(default_dir: str = "./.jax_cache") -> str | None:
    """Idempotently point jax at a persistent compilation cache directory.
    Returns the directory, or None when disabled/unavailable."""
    global _enabled
    from . import flags

    setting = flags.get(flags.COMPILE_CACHE, default=default_dir)
    if setting in ("0", "false", "False", "", None):
        return None
    if _enabled:
        return setting
    try:
        import jax

        os.makedirs(setting, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(setting))
        # cache anything that took meaningful compile time
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
        return setting
    except Exception:
        return None


def shape_structs(tree):
    """Abstract twin of a pytree of arrays: every leaf becomes a
    ``jax.ShapeDtypeStruct`` (static aux data — ``BatchMeta`` — passes
    through untouched). Lets AOT warm-up lower against a batch *signature*
    without materializing or transferring batch-sized buffers."""
    import jax
    import numpy as np

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), tree
    )


def aot_compile(jitted, *args, ledger_entry: dict | None = None):
    """Ahead-of-time lower + compile one signature of a jitted callable and
    return the executable: ``aot_compile(fn, state, shape_structs(batch))``.

    The returned executable is invoked directly (``compiled(state, batch)``)
    and never re-traces — zero ``jaxpr_to_mlir_module`` events per call, which
    is what lets the serving tier's steady state pass the strict recompile
    sentinel. Pair with :func:`enable_compile_cache` first so the backend
    compile itself hits the persistent disk cache across process restarts
    (the 20-40 s first-compile cost becomes a one-time cost per cache dir).

    Args may mix concrete arrays (live params) and ``ShapeDtypeStruct``
    signatures (the per-bucket batch shape).

    ``ledger_entry`` labels the executable's cost-ledger record
    (``{"model": ..., "bucket": ..., "kind": ..., "precision": ...}``) —
    every AOT site feeds the cost observatory
    (``telemetry/ledger.py``); reading ``cost_analysis()`` off an
    already-built executable is free, and capture is a no-op when the
    telemetry plane (or ``HYDRAGNN_LEDGER``) is off. A telemetry failure
    never fails the compile.
    """
    import time

    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    elapsed = time.perf_counter() - t0
    try:
        from ..telemetry import ledger as _ledger

        _ledger.record(compiled, compile_s=elapsed, **(ledger_entry or {}))
    except Exception:
        pass
    return compiled
