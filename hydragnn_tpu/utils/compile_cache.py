"""Persistent XLA compilation cache.

First TPU compilation of a train step costs 20-40 s; the persistent cache
makes every subsequent process start (reruns, HPO trials, the bench driver)
hit a disk cache instead. The reference has no analog (torch eager), so this
is pure TPU-side win.

Env: ``HYDRAGNN_COMPILE_CACHE`` — a directory, ``0`` to disable. Default
``./.jax_cache``.
"""

from __future__ import annotations

import os

_enabled = False


def enable_compile_cache(default_dir: str = "./.jax_cache") -> str | None:
    """Idempotently point jax at a persistent compilation cache directory.
    Returns the directory, or None when disabled/unavailable."""
    global _enabled
    from . import flags

    setting = flags.get(flags.COMPILE_CACHE, default=default_dir)
    if setting in ("0", "false", "False", "", None):
        return None
    if _enabled:
        return setting
    try:
        import jax

        os.makedirs(setting, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(setting))
        # cache anything that took meaningful compile time
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
        return setting
    except Exception:
        return None
