"""Persistent XLA compilation cache + serialized-AOT executable artifacts.

First TPU compilation of a train step costs 20-40 s; the persistent cache
makes every subsequent process start (reruns, HPO trials, the bench driver)
hit a disk cache instead. The reference has no analog (torch eager), so this
is pure TPU-side win.

Env: ``HYDRAGNN_COMPILE_CACHE`` — a directory, ``0`` to disable. Default
``./.jax_cache``.

The serialized-AOT artifact layer (:func:`save_artifact` /
:func:`load_artifact`) goes one step further for the serving fleet: warm-up
persists each per-(model, bucket) predict executable as a ``jax.export``
StableHLO blob keyed like the cost ledger (model/bucket/kind/backend/
precision), so a BOOTING replica deserializes and compiles the exact same
program instead of re-tracing the model — the thing that makes autoscaling
responsive. Artifacts are fingerprinted on the ABSTRACT call signature
(arg shapes/dtypes/tree structure + jax version + backend + precision),
never on parameter values, so a new checkpoint of the same architecture —
the blue/green rollout case — reuses them; any mismatch raises a typed
:class:`ArtifactError` for the caller to fall back LOUDLY to
compile-from-source.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import struct

_enabled = False

#: File magic for serialized-AOT artifacts; bump the trailing digit on any
#: layout change so a stale artifact fails the header check, not deserialize.
ARTIFACT_MAGIC = b"HGNNAOT1"


class ArtifactError(RuntimeError):
    """A serialized-AOT artifact is missing, torn, or does not match the
    current program's fingerprint. Callers treat this as 'compile from
    source instead' — loudly, never silently."""


def enable_compile_cache(default_dir: str = "./.jax_cache") -> str | None:
    """Idempotently point jax at a persistent compilation cache directory.
    Returns the directory, or None when disabled/unavailable."""
    global _enabled
    from . import flags

    setting = flags.get(flags.COMPILE_CACHE, default=default_dir)
    if setting in ("0", "false", "False", "", None):
        return None
    if _enabled:
        return setting
    try:
        import jax

        os.makedirs(setting, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(setting))
        # cache anything that took meaningful compile time
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
        return setting
    except Exception:
        return None


def shape_structs(tree):
    """Abstract twin of a pytree of arrays: every leaf becomes a
    ``jax.ShapeDtypeStruct`` (static aux data — ``BatchMeta`` — passes
    through untouched). Lets AOT warm-up lower against a batch *signature*
    without materializing or transferring batch-sized buffers."""
    import jax
    import numpy as np

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), tree
    )


def aot_compile(jitted, *args, ledger_entry: dict | None = None):
    """Ahead-of-time lower + compile one signature of a jitted callable and
    return the executable: ``aot_compile(fn, state, shape_structs(batch))``.

    The returned executable is invoked directly (``compiled(state, batch)``)
    and never re-traces — zero ``jaxpr_to_mlir_module`` events per call, which
    is what lets the serving tier's steady state pass the strict recompile
    sentinel. Pair with :func:`enable_compile_cache` first so the backend
    compile itself hits the persistent disk cache across process restarts
    (the 20-40 s first-compile cost becomes a one-time cost per cache dir).

    Args may mix concrete arrays (live params) and ``ShapeDtypeStruct``
    signatures (the per-bucket batch shape).

    ``ledger_entry`` labels the executable's cost-ledger record
    (``{"model": ..., "bucket": ..., "kind": ..., "precision": ...}``) —
    every AOT site feeds the cost observatory
    (``telemetry/ledger.py``); reading ``cost_analysis()`` off an
    already-built executable is free, and capture is a no-op when the
    telemetry plane (or ``HYDRAGNN_LEDGER``) is off. A telemetry failure
    never fails the compile.
    """
    import time

    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    elapsed = time.perf_counter() - t0
    try:
        from ..telemetry import ledger as _ledger

        _ledger.record(compiled, compile_s=elapsed, **(ledger_entry or {}))
    except Exception:
        pass
    return compiled


def _register_export_pytrees(args) -> None:
    """``jax.export`` refuses to serialize a pytree whose container types it
    has not been told how to name — and a served call signature is full of
    NamedTuples (``TrainState``, ``GraphBatch``, optax optimizer states).
    Walk ``args`` and register every NamedTuple type under its
    module-qualified name. Idempotent, and the SAME walk runs on the save
    and load sides (both hold the call args), so writer and booting reader
    always agree on the vocabulary."""
    from jax import export as jax_export

    seen: set = set()

    def walk(x):
        t = type(x)
        if isinstance(x, tuple) and hasattr(t, "_fields"):
            if t not in seen:
                seen.add(t)
                try:
                    jax_export.register_namedtuple_serialization(
                        t, serialized_name=f"{t.__module__}.{t.__qualname__}"
                    )
                except ValueError:
                    pass  # already registered (earlier save/load this process)
            for v in x:
                walk(v)
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v)
        elif isinstance(x, dict):
            for v in x.values():
                walk(v)

    walk(args)


def abstract_fingerprint(*args, precision: str | None = None,
                         backend: str | None = None) -> str:
    """Architecture-level fingerprint of an AOT call signature: the abstract
    shapes/dtypes + pytree structure of ``args``, the jax version, the
    backend platform, and the compute precision. Parameter VALUES are
    deliberately excluded — two checkpoints of the same architecture share a
    fingerprint, which is what lets a blue/green rollout boot new-weight
    replicas from the old generation's artifacts."""
    import jax

    if backend is None:
        backend = jax.default_backend()
    leaves, treedef = jax.tree.flatten(shape_structs(args))
    sig = {
        "jax": jax.__version__,
        "backend": str(backend),
        "precision": str(precision),
        "tree": str(treedef),
        "leaves": [[list(x.shape), str(x.dtype)] for x in leaves],
    }
    blob = json.dumps(sig, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def artifact_path(artifact_dir: str, *, model: str, bucket,
                  kind: str = "predict", precision: str | None = None) -> str:
    """Filesystem path of one executable artifact, keyed like the cost
    ledger: ``<dir>/<model>/<kind>--<precision>--<bucket>.aot`` with the
    bucket repr sanitized + hash-suffixed (bucket reprs contain characters
    no filesystem wants)."""
    braw = str(bucket)
    bsafe = re.sub(r"[^A-Za-z0-9._-]+", "_", braw).strip("_")[:80]
    bhash = hashlib.sha1(braw.encode()).hexdigest()[:10]
    psafe = re.sub(r"[^A-Za-z0-9._-]+", "_", str(precision))
    return os.path.join(
        artifact_dir, str(model), f"{kind}--{psafe}--{bsafe}-{bhash}.aot"
    )


def save_artifact(artifact_dir: str, jitted, *args, model: str, bucket,
                  kind: str = "predict", precision: str | None = None,
                  ledger_entry: dict | None = None):
    """Export + persist one AOT signature and return its executable:
    ``(compiled, path)``.

    The executable handed back is compiled FROM the exported StableHLO (not
    from the original traced function), i.e. the very same program a booting
    worker gets back out of :func:`load_artifact` — so serialized boot is
    bit-identical to the warm-up that wrote the artifact, by construction.
    The write is atomic (tmp + ``os.replace``), matching the replica
    ready-file discipline: a reader never sees a torn artifact, only the old
    one or the new one.
    """
    import time

    import jax
    from jax import export as jax_export

    t0 = time.perf_counter()
    _register_export_pytrees(args)
    exported = jax_export.export(jitted)(*args)
    blob = exported.serialize()
    header = {
        "fingerprint": abstract_fingerprint(*args, precision=precision),
        "model": str(model),
        "bucket": str(bucket),
        "kind": str(kind),
        "precision": str(precision),
        "backend": jax.default_backend(),
        "jax": jax.__version__,
    }
    hdr = json.dumps(header, sort_keys=True).encode()
    path = artifact_path(
        artifact_dir, model=model, bucket=bucket, kind=kind,
        precision=precision,
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(ARTIFACT_MAGIC)
        f.write(struct.pack("<I", len(hdr)))
        f.write(hdr)
        f.write(blob)
    os.replace(tmp, path)
    compiled = jax.jit(exported.call).lower(*args).compile()
    elapsed = time.perf_counter() - t0
    try:
        from ..telemetry import ledger as _ledger

        _ledger.record(compiled, compile_s=elapsed, **(ledger_entry or {}))
    except Exception:
        pass
    return compiled, path


def load_artifact(artifact_dir: str, *args, model: str, bucket,
                  kind: str = "predict", precision: str | None = None,
                  ledger_entry: dict | None = None):
    """Deserialize one persisted artifact and compile its StableHLO into a
    live executable — seconds, vs minutes of trace + compile from source.

    Raises :class:`ArtifactError` when the artifact is missing, torn, or its
    fingerprint does not match the CURRENT abstract signature (different jax
    version, backend, precision, or bucket shapes). Callers catch that and
    fall back to compile-from-source loudly; they never serve a stale
    program.
    """
    import jax

    path = artifact_path(
        artifact_dir, model=model, bucket=bucket, kind=kind,
        precision=precision,
    )
    if not os.path.exists(path):
        raise ArtifactError(f"no serialized artifact at {path}")
    try:
        with open(path, "rb") as f:
            magic = f.read(len(ARTIFACT_MAGIC))
            if magic != ARTIFACT_MAGIC:
                raise ArtifactError(
                    f"artifact {path} has bad magic {magic!r} (expected "
                    f"{ARTIFACT_MAGIC!r}) — torn write or foreign file"
                )
            (hdr_len,) = struct.unpack("<I", f.read(4))
            header = json.loads(f.read(hdr_len).decode())
            blob = f.read()
    except ArtifactError:
        raise
    except Exception as e:
        raise ArtifactError(f"artifact {path} unreadable: {e!r}") from e
    want = abstract_fingerprint(*args, precision=precision)
    got = header.get("fingerprint")
    if got != want:
        raise ArtifactError(
            f"artifact {path} fingerprint mismatch (artifact "
            f"{str(got)[:12]}… from jax {header.get('jax')}/"
            f"{header.get('backend')}, current {want[:12]}… from jax "
            f"{jax.__version__}/{jax.default_backend()}) — recompiling "
            "from source"
        )
    from jax import export as jax_export

    import time

    t0 = time.perf_counter()
    _register_export_pytrees(args)
    try:
        exported = jax_export.deserialize(blob)
        compiled = jax.jit(exported.call).lower(*args).compile()
    except Exception as e:
        raise ArtifactError(
            f"artifact {path} failed to deserialize/compile: {e!r}"
        ) from e
    try:
        from ..telemetry import ledger as _ledger

        _ledger.record(
            compiled, compile_s=time.perf_counter() - t0,
            **(ledger_entry or {}),
        )
    except Exception:
        pass
    return compiled
