"""Chrome trace-event export: spans -> perfetto-loadable ``trace.json``.

``utils/tracer.py`` keeps the reference's aggregate span timers
(count/total/avg per region). This module adds the TIMELINE view: every
span open/close pair becomes one Chrome trace-event *complete* ("X")
record — name, microsecond timestamp + duration, pid/tid, and the
process-wide correlation ids (epoch/step/recovery_id) as ``args`` — so
loading ``logs/<run>/trace.json`` into Perfetto / ``chrome://tracing``
shows nested train/dataload/validate spans on the training thread next to
serve dispatcher activity, correlated by the SAME ids the event journal
records carry.

Off by default (``HYDRAGNN_TRACE_EVENTS=1`` / ``Telemetry.trace_events``
arms it); disabled, the tracer pays one boolean check per span close. The
buffer is bounded (``MAX_EVENTS``): a week-long serving process cannot
leak memory through its own telemetry — overflow increments a drop
counter the save reports instead of silently truncating.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading

from ..utils import flags
from . import metrics
from .journal import get_context

# Telemetry.trace_events config override (None = follow the env flag);
# same atomic-assignment pattern as metrics._ENABLED_OVERRIDE
_TRACE_OVERRIDE: bool | None = None

MAX_EVENTS = 200_000


def set_trace_enabled(value: bool | None) -> None:
    global _TRACE_OVERRIDE
    _TRACE_OVERRIDE = None if value is None else bool(value)


def trace_enabled() -> bool:
    """Trace-event recording is armed AND the telemetry plane is live."""
    if not metrics.enabled():
        return False
    if _TRACE_OVERRIDE is not None:
        return _TRACE_OVERRIDE
    return bool(flags.get(flags.TRACE_EVENTS))


class TraceBuffer:
    """Bounded in-memory trace-event sink (thread-safe)."""

    def __init__(self, max_events: int = MAX_EVENTS):
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._events: list[dict] = []  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock

    def add_complete(
        self, name: str, ts_s: float, dur_s: float,
        tid: int | None = None, args: dict | None = None,
    ) -> None:
        """One complete ("X") event; timestamps in SECONDS (converted to
        the trace format's microseconds here, once)."""
        event = {
            "name": str(name),
            "ph": "X",
            "ts": ts_s * 1e6,
            "dur": max(dur_s, 0.0) * 1e6,
            "pid": os.getpid(),
            "tid": int(tid if tid is not None else threading.get_ident()),
        }
        if args:
            event["args"] = dict(args)
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped += 1
                return
            self._events.append(event)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def save(self, path: str) -> str:
        """Write the Chrome trace-event JSON object form
        (``{"traceEvents": [...]}`` — what Perfetto and chrome://tracing
        both load). Returns the path."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": dropped},
        }
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


BUFFER = TraceBuffer()


def add_span(name: str, ts_s: float, dur_s: float,
             args: dict | None = None) -> None:
    """Record one closed span as a trace event, tagged with the ambient
    correlation ids (explicit ``args`` win). The tracer calls this only
    when :func:`trace_enabled` — callers needn't re-check."""
    merged = get_context()
    if args:
        merged.update(args)
    BUFFER.add_complete(name, ts_s, dur_s, args=merged or None)


def trace_events() -> list[dict]:
    return BUFFER.events()


def save_trace(path: str) -> str:
    return BUFFER.save(path)


def reset_trace() -> None:
    BUFFER.reset()


@contextlib.contextmanager
def isolated_buffer():
    """Swap the process-global span ``BUFFER`` for a fresh instance for
    the duration of the scope (same single-rebind pattern as
    ``metrics.isolated_registry``) — trace-event count assertions become
    safe under any suite ordering."""
    global BUFFER
    fresh = TraceBuffer()
    prev, BUFFER = BUFFER, fresh
    try:
        yield fresh
    finally:
        BUFFER = prev


__all__ = [
    "BUFFER",
    "MAX_EVENTS",
    "TraceBuffer",
    "add_span",
    "isolated_buffer",
    "reset_trace",
    "save_trace",
    "set_trace_enabled",
    "trace_enabled",
    "trace_events",
]
