"""Cross-process trace-context propagation over the array-frame wire.

PR 15's telemetry plane is per-process: the moment a request crosses the
wire (fleet router -> replica, sharded-store client -> peer) its
correlation ids die, so one fleet predict can never be rendered as one
timeline. This module carries them across:

* the CLIENT side (``RoundTripper.request``) calls :func:`inject` right
  where the auth token is stamped — when propagation is armed AND the
  ambient journal context holds a ``request_id``, one extra frame field
  (:data:`TRACE_FIELD`, a small JSON blob as uint8 bytes like every other
  string on this wire) rides along;
* the SERVER side (``WireServer``) calls :func:`extract` +
  :func:`scope` around ``handle_frame``, so every journal record and
  trace span the handler emits carries the SAME ``request_id`` the
  client minted — across processes, ``telemetry fleet`` merges them into
  one timeline.

Wire back-compat is by construction: the frame codec packs a dict of
named arrays and every receiver reads only the keys it knows, so an old
peer simply ignores :data:`TRACE_FIELD` and an old client simply never
sends it — no version negotiation, no decode errors (tested both
directions in ``tests/test_trace_propagation.py``). Disabled
(``HYDRAGNN_TRACE_PROPAGATE=0`` / ``Telemetry.trace_propagate: false``),
:func:`inject` returns before touching the frame: ZERO added wire bytes.
"""

from __future__ import annotations

import json
import uuid

import numpy as np

from ..utils import flags
from . import journal, metrics

# The one optional frame field. Leading underscore keeps it visually apart
# from payload keys; no existing op uses the name.
TRACE_FIELD = "_trace_ctx"

# Correlation ids worth shipping. Everything else in the context (large or
# process-local values) stays home; the blob is bounded by construction.
_WIRE_KEYS = ("request_id", "parent_span", "run_id", "epoch", "step",
              "recovery_id")
_MAX_BLOB = 1024  # defensive cap on an inbound context blob

# Telemetry.trace_propagate config override (None = follow the env flag);
# same atomic-assignment pattern as metrics._ENABLED_OVERRIDE.
_PROPAGATE_OVERRIDE: bool | None = None


def set_propagate_enabled(value: bool | None) -> None:
    global _PROPAGATE_OVERRIDE
    _PROPAGATE_OVERRIDE = None if value is None else bool(value)


def propagate_enabled() -> bool:
    """Propagation is armed AND the telemetry plane is live."""
    if not metrics.enabled():
        return False
    if _PROPAGATE_OVERRIDE is not None:
        return _PROPAGATE_OVERRIDE
    return bool(flags.get(flags.TRACE_PROPAGATE))


def new_request_id() -> str:
    """Mint a fleet-unique request id (16 hex chars — short enough to
    read in a journal line, unique enough for any real request volume)."""
    return uuid.uuid4().hex[:16]


def wire_context() -> dict:
    """The shippable subset of the ambient journal context: the wire keys
    only, values coerced to JSON scalars."""
    ctx = journal.get_context()
    out = {}
    for key in _WIRE_KEYS:
        value = ctx.get(key)
        if value is None:
            continue
        out[key] = value if isinstance(value, (int, float)) else str(value)
    return out


def inject(fields: dict, parent_span: str | None = None) -> dict:
    """Stamp the trace-context field into an outgoing frame's fields —
    in place, returning the dict. A no-op (nothing added, zero wire
    bytes) unless propagation is armed and the ambient context carries a
    ``request_id``; an outbound frame with no request to correlate has
    nothing useful to ship."""
    if not propagate_enabled():
        return fields
    ctx = wire_context()
    if not ctx.get("request_id"):
        return fields
    if parent_span is not None:
        ctx["parent_span"] = parent_span
    blob = json.dumps(ctx, separators=(",", ":")).encode()
    fields[TRACE_FIELD] = np.frombuffer(blob, dtype=np.uint8)
    return fields


def extract(frame: dict) -> dict:
    """Pull the trace context out of a decoded inbound frame. Returns
    ``{}`` for legacy frames (no field), oversized blobs, or anything
    that does not decode to a flat dict of scalar ids — a malformed
    context must never kill the request it rode in on."""
    raw = frame.get(TRACE_FIELD)
    if raw is None:
        return {}
    try:
        blob = bytes(np.asarray(raw, dtype=np.uint8))
        if len(blob) > _MAX_BLOB:
            return {}
        ctx = json.loads(blob.decode())
    except Exception:
        return {}
    if not isinstance(ctx, dict):
        return {}
    out = {}
    for key in _WIRE_KEYS:
        value = ctx.get(key)
        if isinstance(value, (str, int, float)):
            out[key] = value
    return out


def scope(ctx: dict):
    """Enter the extracted context as the calling thread's journal scope
    (``journal.scoped_context``); an empty context scopes nothing, so the
    legacy path stays a plain passthrough."""
    return journal.scoped_context(**ctx)


__all__ = [
    "TRACE_FIELD",
    "extract",
    "inject",
    "new_request_id",
    "propagate_enabled",
    "scope",
    "set_propagate_enabled",
    "wire_context",
]
