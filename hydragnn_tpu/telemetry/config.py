"""The validated top-level ``Telemetry`` config block.

Single-source pattern (same as ``ServingConfig`` / ``MDConfig`` /
``StoreConfig``): these dataclass field defaults ARE the schema defaults —
``config/schema.py::update_config`` fills and validates the block through
this class, so the JSON schema and the runtime can't drift. Env flags win
over config (``apply_env``): ``HYDRAGNN_TELEMETRY`` overrides ``enabled``,
``HYDRAGNN_TRACE_EVENTS`` overrides ``trace_events``.
"""

from __future__ import annotations

import dataclasses
import os

from ..utils import flags

# top-level sections of the repo's JSON config schema, for telling "a full
# config without a Telemetry block" apart from "a typo'd telemetry block";
# single-sourced from config/schema.py
from ..config.schema import CONFIG_SECTIONS as _CONFIG_SECTIONS


@dataclasses.dataclass
class TelemetryConfig:
    enabled: bool = True        # the whole plane: registry + journal + traces
    journal: bool = True        # write logs/<run>/events.jsonl during runs
    trace_events: bool = False  # record Chrome trace events (trace.json)
    trace_propagate: bool = True  # ship correlation ids across the wire
    ledger: bool = True         # capture compiled-program cost entries

    @staticmethod
    def from_config(config: dict | None) -> "TelemetryConfig":
        """Accepts a FULL config dict (reads its ``Telemetry`` block,
        absent = defaults) or the block itself; unknown keys raise instead
        of silently booting with defaults."""
        config = config or {}
        block = config.get("Telemetry")
        if block is None and config:
            if any(k in telemetry_config_defaults() for k in config):
                block = config
            elif not any(k in _CONFIG_SECTIONS for k in config):
                raise ValueError(
                    f"unrecognized telemetry config keys {sorted(config)}; "
                    f"expected a full config (sections "
                    f"{sorted(_CONFIG_SECTIONS)}) or a Telemetry block "
                    f"(fields {sorted(telemetry_config_defaults())})"
                )
        block = dict(block or {})
        unknown = set(block) - set(telemetry_config_defaults())
        if unknown:
            raise ValueError(
                f"Unknown Telemetry key(s) {sorted(unknown)}; known: "
                f"{sorted(telemetry_config_defaults())}"
            )
        return TelemetryConfig(**block).apply_env()

    def apply_env(self) -> "TelemetryConfig":
        """Fold env overrides in (idempotent); env beats config so an
        operator can silence or arm telemetry per launch without editing
        the run's JSON. An empty-but-set variable counts as unset
        (the ``utils.flags`` convention)."""
        if os.getenv(flags.TELEMETRY.name):
            self.enabled = bool(flags.get(flags.TELEMETRY))
        if os.getenv(flags.TRACE_EVENTS.name):
            self.trace_events = bool(flags.get(flags.TRACE_EVENTS))
        if os.getenv(flags.TRACE_PROPAGATE.name):
            self.trace_propagate = bool(flags.get(flags.TRACE_PROPAGATE))
        if os.getenv(flags.LEDGER.name):
            # HYDRAGNN_LEDGER is a str flag ('0' disables, a path also
            # arms saving); here only the on/off half applies
            self.ledger = str(flags.get(flags.LEDGER)) not in (
                "0", "false", "no", "off")
        return self

    def validate(self) -> "TelemetryConfig":
        for key in ("enabled", "journal", "trace_events", "trace_propagate",
                    "ledger"):
            value = getattr(self, key)
            if not isinstance(value, bool):
                raise ValueError(
                    f"Telemetry.{key} must be a bool, got {value!r}"
                )
        return self


def telemetry_config_defaults() -> dict:
    return dataclasses.asdict(TelemetryConfig())


__all__ = ["TelemetryConfig", "telemetry_config_defaults"]
