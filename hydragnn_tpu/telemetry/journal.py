"""Append-only structured event journal: ``logs/<run>/events.jsonl``.

One schema'd JSON record per line, one line per event — epoch ends,
superstep dispatch blocks, guard skips, rollbacks, elastic recovery phases,
fleet failovers, sheds, autotune adoptions, quant certifications. Every
record carries:

* ``seq`` — a per-journal monotonic sequence number assigned under the
  writer lock in file order, so post-hoc tooling can prove ordering even
  when wall clocks step;
* ``t_wall`` — wall time (``time.time()``; durations inside records come
  from monotonic clocks, the wall stamp is for humans and cross-process
  correlation only);
* **correlation ids** — ``run_id`` plus whatever the process-wide context
  carries (``epoch`` / ``step`` / ``recovery_id``, set by the train loop and
  the elastic controller via :func:`set_context`), so "what happened during
  that recovery" is one ``grep recovery_id`` after the fact.

Durability contract: the file is opened line-buffered and each record is
written as ONE ``write()`` of a newline-terminated string, so a SIGKILL
tears at most the final line — :func:`read_journal` tolerates exactly that
(a torn tail is dropped, intact records all parse).

The module keeps one ACTIVE journal (``open_journal``/``close_journal``);
:func:`emit` routes to it and is a cheap no-op when no journal is open or
telemetry is disabled — subsystems emit unconditionally and pay nothing in
processes that never opened a journal (benches, unit tests, serving-only
deployments that want metrics but no event log).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from . import metrics

# -- correlation context ------------------------------------------------------

_CTX_LOCK = threading.Lock()
_CONTEXT: dict = {}  # guarded-by: _CTX_LOCK (epoch / step / recovery_id ...)
_TLS = threading.local()  # per-thread scoped overlay (thread-confined, no lock)


def set_context(**ids) -> None:
    """Merge correlation ids into the process-wide context every later
    record carries; a ``None`` value REMOVES the key (so the elastic
    controller can retire a ``recovery_id`` once the run is healthy)."""
    with _CTX_LOCK:
        for key, value in ids.items():
            if value is None:
                _CONTEXT.pop(key, None)
            else:
                _CONTEXT[key] = value


def get_context() -> dict:
    """Process-wide context merged under the calling thread's scoped
    overlay (see :func:`scoped_context`) — a request id set for one
    dispatch thread never leaks into a concurrent handler's records."""
    with _CTX_LOCK:
        ctx = dict(_CONTEXT)
    overlay = getattr(_TLS, "overlay", None)
    if overlay:
        ctx.update(overlay)
    return ctx


def clear_context() -> None:
    with _CTX_LOCK:
        _CONTEXT.clear()
    _TLS.overlay = None


@contextlib.contextmanager
def scoped_context(**ids):
    """Overlay correlation ids for the CURRENT THREAD only, restored on
    exit. This is how per-request ids (``request_id`` / ``parent_span``)
    ride through concurrent server handler and dispatcher threads without
    clobbering each other: each thread sees the process-wide context plus
    its own overlay. Nests — inner scopes merge over outer ones; a
    ``None`` value removes the key for the duration of the scope."""
    prev = getattr(_TLS, "overlay", None)
    merged = dict(prev or {})
    for key, value in ids.items():
        if value is None:
            merged.pop(key, None)
        else:
            merged[key] = value
    _TLS.overlay = merged
    try:
        yield
    finally:
        _TLS.overlay = prev


# -- the journal --------------------------------------------------------------


def _jsonable(obj):
    """JSON fallback for numpy scalars/arrays and anything exotic — a
    telemetry write must never throw TypeError into a training loop."""
    try:
        import numpy as np

        if isinstance(obj, np.integer):
            return int(obj)
        if isinstance(obj, np.floating):
            return float(obj)
        if isinstance(obj, np.ndarray):
            return obj.tolist()
    except Exception:
        pass
    return str(obj)


# Bounded-staleness flush pacing: appends go to the text buffer and a
# flush runs at most once per window, so hot-path emits (a traced fleet
# predict writes ~5 records across router + replica) stay syscall-free —
# a per-record flush put ~0.3 ms of write + GIL churn on every request.
# A SIGKILL loses at most one window of buffered records plus one torn
# line; ``close()`` (and atexit via ``close_journal``) flushes the rest.
_FLUSH_S = 0.2


class EventJournal:
    """One open ``events.jsonl`` writer. Thread model: ``emit`` may be
    called from the training thread, watchdog/monitor threads, and serve
    dispatchers concurrently; ``_lock`` serializes seq assignment + the
    single line write, so seq order and file order provably agree.
    Durability: records become visible on disk within :data:`_FLUSH_S`
    seconds (or at ``close()``), not per record — post-mortem readers
    already tolerate a torn tail line."""

    def __init__(self, path: str, run_id: str | None = None):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.run_id = run_id
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        self._f = open(path, "a")  # guarded-by: _lock
        # 0.0 = flush on the very first emit, so the file shows life early
        self._next_flush = 0.0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock

    def emit(self, kind: str, **fields) -> int | None:
        """Append one record; returns its seq (None when already closed).
        Context ids merge in under explicit fields (an explicit ``epoch=``
        beats the ambient one)."""
        rec = {"kind": str(kind), "t_wall": time.time()}
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        rec.update(get_context())
        for key, value in fields.items():
            if value is not None:
                rec[key] = value
        with self._lock:
            if self._closed:
                return None
            rec["seq"] = self._seq
            self._seq += 1
            self._f.write(json.dumps(rec, default=_jsonable) + "\n")
            now = time.monotonic()
            if now >= self._next_flush:
                self._f.flush()
                self._next_flush = now + _FLUSH_S
            return rec["seq"]

    def flush(self) -> None:
        """Push buffered records to disk now (e.g. before reading the
        file back while the journal stays open)."""
        with self._lock:
            if not self._closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()


_JOURNAL_LOCK = threading.Lock()
_ACTIVE: EventJournal | None = None  # guarded-by: _JOURNAL_LOCK (reads racy-ok)


def open_journal(
    log_name: str | None = None,
    path: str = "./logs",
    file: str | None = None,
    run_id: str | None = None,
) -> EventJournal:
    """Open (and make ACTIVE) the run's journal at
    ``<path>/<log_name>/events.jsonl`` (or an explicit ``file``). An
    already-active journal is closed first — one process, one event log."""
    if file is None:
        if log_name is None:
            raise ValueError("open_journal needs log_name (or an explicit file=)")
        file = os.path.join(path, log_name, "events.jsonl")
    if run_id is None:
        base = log_name or os.path.basename(os.path.dirname(file)) or "run"
        run_id = f"{base}-{os.getpid()}"
    journal = EventJournal(file, run_id=run_id)
    global _ACTIVE
    with _JOURNAL_LOCK:
        prev, _ACTIVE = _ACTIVE, journal
    if prev is not None:
        prev.close()
    return journal


def close_journal() -> None:
    global _ACTIVE
    with _JOURNAL_LOCK:
        prev, _ACTIVE = _ACTIVE, None
    if prev is not None:
        prev.close()


def active_journal() -> EventJournal | None:
    return _ACTIVE


@contextlib.contextmanager
def isolated():
    """Swap out the ACTIVE journal, the process-wide context, and the
    calling thread's overlay for the duration of the scope — the journal
    half of :func:`hydragnn_tpu.telemetry.isolate`. Anything opened inside
    the scope is closed on exit; the previous journal/context come back
    untouched."""
    global _ACTIVE
    with _JOURNAL_LOCK:
        prev_active, _ACTIVE = _ACTIVE, None
    with _CTX_LOCK:
        prev_ctx = dict(_CONTEXT)
        _CONTEXT.clear()
    prev_overlay = getattr(_TLS, "overlay", None)
    _TLS.overlay = None
    try:
        yield
    finally:
        close_journal()
        with _JOURNAL_LOCK:
            _ACTIVE = prev_active
        with _CTX_LOCK:
            _CONTEXT.clear()
            _CONTEXT.update(prev_ctx)
        _TLS.overlay = prev_overlay


def emit(kind: str, **fields) -> int | None:
    """Route one event to the active journal; a no-op (one attribute read)
    when no journal is open or telemetry is disabled."""
    journal = _ACTIVE
    if journal is None or not metrics.enabled():
        return None
    return journal.emit(kind, **fields)


def read_journal(path: str) -> list[dict]:
    """Parse an ``events.jsonl`` back into records, tolerating the torn
    tail the durability contract permits: an undecodable FINAL line is
    dropped silently; an undecodable line elsewhere (should not happen
    under the one-write-per-line contract) is skipped too rather than
    poisoning the whole read — post-mortem tooling wants every intact
    record, not an exception."""
    records: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


__all__ = [
    "EventJournal",
    "active_journal",
    "clear_context",
    "close_journal",
    "emit",
    "get_context",
    "isolated",
    "open_journal",
    "read_journal",
    "scoped_context",
    "set_context",
]
