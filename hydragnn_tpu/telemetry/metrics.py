"""Thread-safe typed metrics registry: Counter / Gauge / Histogram.

The reference ships a whole ``profiling_and_tracing`` plugin registry (GPTL
region timers, Score-P adapters, NVML/ROCm energy counters) because a
supercomputer-scale run is undrivable blind. Our rebuild grew five
DISCONNECTED ad-hoc ``stats()`` dicts (serve/server, fleet router, fleet
replica, answer cache, ShardedStore failover counters) with no shared schema
and no way to read them all in one place. This module is the one place:

* **typed instruments** — :class:`Counter` (monotonic), :class:`Gauge`
  (set-valued), :class:`Histogram` (count/sum/min/max + exponential latency
  buckets), each addressed by ``(name, sorted label set)`` so
  ``counter("serve_requests", model="gin", event="shed")`` names exactly one
  series no matter the call site;
* **near-zero disabled cost** — with ``HYDRAGNN_TELEMETRY=0`` (or a
  ``Telemetry`` config block with ``enabled: false`` applied via
  :func:`set_enabled`) every accessor returns the shared no-op instrument,
  whose ``inc``/``set``/``observe`` are empty methods: the hot paths keep
  ONE cached attribute call and nothing else;
* **stable snapshots** — :meth:`MetricsRegistry.snapshot` returns a fresh
  plain dict (sorted names, sorted ``k=v`` label strings) safe to JSON-dump,
  diff across time, or ship over the fleet wire ``metrics`` op.

Existing ``stats()`` surfaces stay byte-compatible: they dual-write their
counters here (``telemetry.counter(...)`` at each increment site) and mirror
derived values via :func:`publish`, which turns a stats dict's numeric
leaves into gauges without touching the dict.
"""

from __future__ import annotations

import contextlib
import threading

from ..utils import flags

# process-wide override from the validated Telemetry config block (None =
# follow the HYDRAGNN_TELEMETRY env flag). Plain assignment of an immutable
# is atomic in CPython; readers tolerate staleness by design — instruments
# handed out before a flip keep their behavior, documented below.
_ENABLED_OVERRIDE: bool | None = None


def set_enabled(value: bool | None) -> None:
    """Process-level enable override (``telemetry.configure`` routes the
    config block here); ``None`` returns control to the env flag."""
    global _ENABLED_OVERRIDE
    _ENABLED_OVERRIDE = None if value is None else bool(value)


def enabled() -> bool:
    """Is the telemetry plane live? Checked at instrument CREATION (a
    disabled registry hands out no-ops; re-enabling mid-run affects only
    instruments requested afterwards) and per journal emit."""
    if _ENABLED_OVERRIDE is not None:
        return _ENABLED_OVERRIDE
    return bool(flags.get(flags.TELEMETRY))


class _NoopInstrument:
    """The disabled-path singleton: every mutator is an empty method, so a
    cached ``counter(...)`` handle costs one attribute call and a pass."""

    __slots__ = ()

    def inc(self, by: int = 1) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    @property
    def value(self) -> float:
        return 0.0


NOOP = _NoopInstrument()


class Counter:
    """Monotonic event count. ``inc`` with a negative delta raises — a
    counter that can go down is a gauge wearing the wrong type."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0  # guarded-by: _lock

    def inc(self, by: int = 1) -> None:
        if by < 0:
            raise ValueError(
                f"counter {self.name!r} cannot decrease (inc by {by}); "
                "use a gauge for set-valued series"
            )
        with self._lock:
            self._value += by

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def _snapshot_value(self):
        return self.value


class Gauge:
    """Last-written value (queue depth, cache bytes, loss, ...)."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: _lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += float(by)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _snapshot_value(self):
        return self.value


# default boundaries sized for serving/step latencies in SECONDS; the +Inf
# overflow bucket is implicit (count - sum(buckets))
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)


class Histogram:
    """Distribution summary: count/sum/min/max plus cumulative-style bucket
    counts over fixed boundaries (``le`` semantics, Prometheus-shaped)."""

    __slots__ = ("name", "labels", "bounds", "_lock", "_count", "_sum",
                 "_min", "_max", "_buckets")

    def __init__(self, name: str, labels: tuple, bounds=DEFAULT_BUCKETS):
        self.name = name
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in bounds))
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._min = None  # guarded-by: _lock
        self._max = None  # guarded-by: _lock
        self._buckets = [0] * len(self.bounds)  # guarded-by: _lock

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._buckets[i] += 1

    @property
    def value(self) -> int:
        with self._lock:
            return self._count

    def _snapshot_value(self):
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "buckets": {
                    repr(b): n for b, n in zip(self.bounds, self._buckets)
                },
            }


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


class MetricsRegistry:
    """The instrument table: get-or-create by ``(kind, name, labels)``.

    Thread model: ``_lock`` serializes table MUTATION only — the accessor
    hot path is a lock-free dict read (GIL-atomic; instruments are never
    removed except by ``reset()``), so per-request counting from fleet
    dispatchers/serve workers doesn't serialize on one process mutex.
    Value updates ride each instrument's own lock, and a ``snapshot()``
    mid-churn sees each series at some consistent point (never a torn
    value)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict = {}  # guarded-by: _lock (reads lock-free)

    def _get(self, kind: str, name: str, labels: dict, **kw):
        if not enabled():
            return NOOP
        key = (name, _label_key(labels))
        inst = self._instruments.get(key)  # lock-free fast path (hot)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(key)
                if inst is None:
                    inst = _KINDS[kind](name, key[1], **kw)
                    self._instruments[key] = inst
        if not isinstance(inst, _KINDS[kind]):
            raise ValueError(
                f"metric {name!r} {_label_str(key[1])!r} already exists "
                f"as a {type(inst).__name__}, requested as a {kind} — "
                "one series, one type"
            )
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, bounds=DEFAULT_BUCKETS, **labels) -> Histogram:
        return self._get("histogram", name, labels, bounds=bounds)

    def snapshot(self) -> dict:
        """A fresh, stable, JSON-safe dict: ``{"counters": {name: {labels:
        value}}, "gauges": ..., "histograms": ...}`` with names and label
        strings sorted, so two snapshots diff line-by-line."""
        with self._lock:
            items = list(self._instruments.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        section = {Counter: "counters", Gauge: "gauges",
                   Histogram: "histograms"}
        for (name, lkey), inst in sorted(items):
            out[section[type(inst)]].setdefault(name, {})[_label_str(lkey)] = (
                inst._snapshot_value()
            )
        return out

    def reset(self) -> None:
        """Drop every instrument (tests; a fresh process state)."""
        with self._lock:
            self._instruments.clear()


# the process-wide default registry every wired subsystem publishes into
REGISTRY = MetricsRegistry()


def counter(name: str, **labels) -> Counter:
    return REGISTRY.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return REGISTRY.gauge(name, **labels)


def histogram(name: str, bounds=DEFAULT_BUCKETS, **labels) -> Histogram:
    return REGISTRY.histogram(name, bounds=bounds, **labels)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def reset_metrics() -> None:
    REGISTRY.reset()


@contextlib.contextmanager
def isolated_registry():
    """Swap the process-global ``REGISTRY`` for a FRESH instance for the
    duration of the scope, restoring the previous one on exit. Because the
    module-level ``counter()``/``gauge()``/``histogram()``/``snapshot()``
    helpers read the global at call time, everything inside the scope —
    including code in other threads started inside it — lands in the fresh
    registry, so absolute-count assertions are safe under any suite
    ordering (no reset band-aids needed). The swap is a single attribute
    rebind (atomic under the GIL); concurrent readers see either registry,
    never a torn state."""
    global REGISTRY
    fresh = MetricsRegistry()
    prev, REGISTRY = REGISTRY, fresh
    try:
        yield fresh
    finally:
        REGISTRY = prev


def publish(prefix: str, stats: dict, **labels) -> None:
    """Mirror a ``stats()`` dict's numeric leaves into gauges
    (``{prefix}_{key}``) without touching the dict — the bridge that lets
    the five pre-existing ad-hoc stats surfaces keep their test-pinned
    shapes byte-for-byte while still publishing through the registry.
    Non-numeric leaves (lists, nested dicts, strings, None) are skipped;
    bools are skipped too (a flag is not a measurement)."""
    if not enabled():
        return
    for key, value in stats.items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        REGISTRY.gauge(f"{prefix}_{key}", **labels).set(value)


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "NOOP",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "enabled",
    "isolated_registry",
    "publish",
    "reset_metrics",
    "set_enabled",
    "snapshot",
]
