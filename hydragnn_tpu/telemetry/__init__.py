"""The unified telemetry plane: metrics registry + event journal + traces.

One queryable source of truth over the whole train -> serve -> recover
stack (the reference's ``profiling_and_tracing`` plugin registry, rebuilt
as three coherent surfaces instead of five ad-hoc ``stats()`` dicts):

* :mod:`~hydragnn_tpu.telemetry.metrics` — thread-safe typed
  Counter/Gauge/Histogram registry with label sets; ``snapshot()`` is the
  stable dict the fleet ``metrics`` wire op ships;
* :mod:`~hydragnn_tpu.telemetry.journal` — the append-only structured
  event journal (``logs/<run>/events.jsonl``): one schema'd record per
  epoch / dispatch block / guard skip / rollback / recovery phase /
  failover / shed, each carrying monotonic seq + wall time + correlation
  ids (run_id/epoch/step/recovery_id);
* :mod:`~hydragnn_tpu.telemetry.trace` — Chrome trace-event export of the
  tracer's nested spans (perfetto-loadable ``trace.json``), tagged with
  the same correlation ids;
* ``python -m hydragnn_tpu.telemetry <events.jsonl>`` — the post-mortem
  CLI (:mod:`~hydragnn_tpu.telemetry.cli`).

``HYDRAGNN_TELEMETRY=0`` turns the whole plane into near-zero-cost no-ops;
``HYDRAGNN_TRACE_EVENTS=1`` (or ``Telemetry.trace_events``) additionally
records the span timeline. :func:`configure` applies a validated
``Telemetry`` config block process-wide (env flags still win, folded in by
``TelemetryConfig.apply_env``).
"""

from __future__ import annotations

import contextlib

from . import ledger, propagation
from .config import TelemetryConfig, telemetry_config_defaults
from .journal import (
    EventJournal,
    active_journal,
    clear_context,
    close_journal,
    emit,
    get_context,
    open_journal,
    read_journal,
    scoped_context,
    set_context,
)
from .ledger import CostLedger
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NOOP,
    REGISTRY,
    counter,
    enabled,
    gauge,
    histogram,
    publish,
    reset_metrics,
    set_enabled,
    snapshot,
)
from .propagation import new_request_id, propagate_enabled, set_propagate_enabled
from .trace import (
    add_span,
    reset_trace,
    save_trace,
    set_trace_enabled,
    trace_enabled,
    trace_events,
)


def configure(cfg: "TelemetryConfig | dict | None") -> "TelemetryConfig | None":
    """Apply a ``Telemetry`` config block process-wide (``None`` resets
    every override to follow the env flags). Returns the applied config."""
    if cfg is None:
        set_enabled(None)
        set_trace_enabled(None)
        set_propagate_enabled(None)
        return None
    if not isinstance(cfg, TelemetryConfig):
        cfg = TelemetryConfig.from_config(cfg)
    cfg.validate()
    set_enabled(cfg.enabled)
    set_trace_enabled(cfg.trace_events)
    set_propagate_enabled(cfg.trace_propagate)
    return cfg


@contextlib.contextmanager
def isolate():
    """Scoped FRESH-INSTANCE isolation of every process-global telemetry
    surface: metrics registry, trace buffer, tracer timers, cost ledger,
    active journal + correlation context, and the config overrides. The
    previous state is fully restored on exit — the ``telemetry_isolate``
    pytest fixture wraps this, so absolute-count assertions hold under
    any suite ordering without reset band-aids."""
    from ..utils import tracer as _tracer
    from . import journal as _journal, metrics as _metrics, trace as _trace

    prev_enabled = _metrics._ENABLED_OVERRIDE
    prev_trace = _trace._TRACE_OVERRIDE
    prev_prop = propagation._PROPAGATE_OVERRIDE
    with _metrics.isolated_registry(), _trace.isolated_buffer(), \
            _tracer.isolated_timers(), ledger.isolated_ledger(), \
            _journal.isolated():
        try:
            yield
        finally:
            _metrics.set_enabled(prev_enabled)
            _trace.set_trace_enabled(prev_trace)
            propagation.set_propagate_enabled(prev_prop)


__all__ = [
    "CostLedger",
    "Counter",
    "EventJournal",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NOOP",
    "REGISTRY",
    "TelemetryConfig",
    "active_journal",
    "add_span",
    "clear_context",
    "close_journal",
    "configure",
    "counter",
    "emit",
    "enabled",
    "gauge",
    "get_context",
    "histogram",
    "isolate",
    "ledger",
    "new_request_id",
    "open_journal",
    "propagate_enabled",
    "propagation",
    "publish",
    "read_journal",
    "reset_metrics",
    "reset_trace",
    "save_trace",
    "scoped_context",
    "set_context",
    "set_enabled",
    "set_propagate_enabled",
    "set_trace_enabled",
    "snapshot",
    "telemetry_config_defaults",
    "trace_enabled",
    "trace_events",
]
