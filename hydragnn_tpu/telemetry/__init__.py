"""The unified telemetry plane: metrics registry + event journal + traces.

One queryable source of truth over the whole train -> serve -> recover
stack (the reference's ``profiling_and_tracing`` plugin registry, rebuilt
as three coherent surfaces instead of five ad-hoc ``stats()`` dicts):

* :mod:`~hydragnn_tpu.telemetry.metrics` — thread-safe typed
  Counter/Gauge/Histogram registry with label sets; ``snapshot()`` is the
  stable dict the fleet ``metrics`` wire op ships;
* :mod:`~hydragnn_tpu.telemetry.journal` — the append-only structured
  event journal (``logs/<run>/events.jsonl``): one schema'd record per
  epoch / dispatch block / guard skip / rollback / recovery phase /
  failover / shed, each carrying monotonic seq + wall time + correlation
  ids (run_id/epoch/step/recovery_id);
* :mod:`~hydragnn_tpu.telemetry.trace` — Chrome trace-event export of the
  tracer's nested spans (perfetto-loadable ``trace.json``), tagged with
  the same correlation ids;
* ``python -m hydragnn_tpu.telemetry <events.jsonl>`` — the post-mortem
  CLI (:mod:`~hydragnn_tpu.telemetry.cli`).

``HYDRAGNN_TELEMETRY=0`` turns the whole plane into near-zero-cost no-ops;
``HYDRAGNN_TRACE_EVENTS=1`` (or ``Telemetry.trace_events``) additionally
records the span timeline. :func:`configure` applies a validated
``Telemetry`` config block process-wide (env flags still win, folded in by
``TelemetryConfig.apply_env``).
"""

from __future__ import annotations

from .config import TelemetryConfig, telemetry_config_defaults
from .journal import (
    EventJournal,
    active_journal,
    clear_context,
    close_journal,
    emit,
    get_context,
    open_journal,
    read_journal,
    set_context,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NOOP,
    REGISTRY,
    counter,
    enabled,
    gauge,
    histogram,
    publish,
    reset_metrics,
    set_enabled,
    snapshot,
)
from .trace import (
    add_span,
    reset_trace,
    save_trace,
    set_trace_enabled,
    trace_enabled,
    trace_events,
)


def configure(cfg: "TelemetryConfig | dict | None") -> "TelemetryConfig | None":
    """Apply a ``Telemetry`` config block process-wide (``None`` resets
    both overrides to follow the env flags). Returns the applied config."""
    if cfg is None:
        set_enabled(None)
        set_trace_enabled(None)
        return None
    if not isinstance(cfg, TelemetryConfig):
        cfg = TelemetryConfig.from_config(cfg)
    cfg.validate()
    set_enabled(cfg.enabled)
    set_trace_enabled(cfg.trace_events)
    return cfg


__all__ = [
    "Counter",
    "EventJournal",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NOOP",
    "REGISTRY",
    "TelemetryConfig",
    "active_journal",
    "add_span",
    "clear_context",
    "close_journal",
    "configure",
    "counter",
    "emit",
    "enabled",
    "gauge",
    "get_context",
    "histogram",
    "open_journal",
    "publish",
    "read_journal",
    "reset_metrics",
    "reset_trace",
    "save_trace",
    "set_context",
    "set_enabled",
    "set_trace_enabled",
    "snapshot",
    "telemetry_config_defaults",
    "trace_enabled",
    "trace_events",
]
