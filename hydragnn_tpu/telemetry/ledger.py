"""Compiled-program cost observatory: what the AOT executables we serve
actually COST.

Standing constraint #1 (the axon backend has never initialized in any
bench round) means wall-clock alone is weak evidence for compiled-program
claims. XLA's own compiled-artifact introspection is not:
``Compiled.cost_analysis()`` (flops, bytes accessed) and
``memory_analysis()`` (argument/output/temp/generated-code bytes) are
exact properties of the artifact, CPU-provable, and free to read — the
executable already exists by the time we ask. This module captures them
at every ``utils/compile_cache.aot_compile`` site (serve warm-up, quant
executables, screen engine, the flag-gated train-step probe), keyed per
``(model, bucket, backend, precision, kind)``, plus the compile
sentinel's lowering counts, and persists the lot as a schema'd
``logs/<run>/ledger.json``.

The REGRESSION SENTINEL (``python -m hydragnn_tpu.telemetry ledger
<current> --baseline <base>``) diffs two ledgers and fails loudly when
any shared entry's flops / bytes-accessed / peak-bytes inflated beyond a
relative tolerance — the cost analog of the recompile sentinel, wired as
a bench evidence source.

Capture is on whenever the telemetry plane is (``HYDRAGNN_LEDGER=0``
opts out); a path-valued ``HYDRAGNN_LEDGER`` additionally makes warm-up
sites save the cumulative ledger there.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from ..utils import flags
from . import metrics

SCHEMA_VERSION = 1

# cost_analysis() metric names -> ledger field names
_COST_FIELDS = (
    ("flops", "flops"),
    ("bytes accessed", "bytes_accessed"),
    ("transcendentals", "transcendentals"),
)
# CompiledMemoryStats attributes -> ledger field names
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
)
# metrics the diff sentinel compares (absent-on-this-backend keys skip)
DIFF_METRICS = ("flops", "bytes_accessed", "peak_bytes")

_FALSEY = ("0", "false", "no", "off")
_TRUTHY = ("1", "true", "yes", "on")


def capture_enabled() -> bool:
    """Ledger capture rides the telemetry plane; ``HYDRAGNN_LEDGER=0``
    opts out without touching the rest of the plane."""
    if not metrics.enabled():
        return False
    raw = flags.get(flags.LEDGER)
    return raw is None or str(raw) not in _FALSEY


def save_path() -> str | None:
    """An explicit save target from ``HYDRAGNN_LEDGER``: a path value is
    the target; a bare truthy value means the default ``./logs/
    ledger.json``; unset/falsey means the caller decides (runs with a
    journal still persist next to it)."""
    raw = flags.get(flags.LEDGER)
    if raw is None or str(raw) in _FALSEY:
        return None
    raw = str(raw)
    if raw in _TRUTHY:
        return os.path.join(".", "logs", "ledger.json")
    return raw


def _backend_name() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def _lowering_counts() -> dict:
    try:
        from ..analysis.sentinel import compile_counts

        return dict(compile_counts())
    except Exception:
        return {}


def cost_dict(compiled) -> dict:
    """Guarded ``cost_analysis()`` read: tolerate the list-of-dict form
    older jax returns, missing keys (per-backend — CPU omits some), and
    backends that refuse the call entirely."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, list):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out = {}
    for src, dst in _COST_FIELDS:
        value = cost.get(src)
        if isinstance(value, (int, float)):
            out[dst] = float(value)
    return out


def memory_dict(compiled) -> dict:
    """Guarded ``memory_analysis()`` read; ``peak_bytes`` is derived as
    the sum of the populated resident parts (arguments + outputs + temps
    + generated code) so the field exists even on backends that report
    no single peak figure (CPU included)."""
    try:
        stats = compiled.memory_analysis()
    except Exception:
        return {}
    if stats is None:
        return {}
    out = {}
    for attr, dst in _MEMORY_FIELDS:
        value = getattr(stats, attr, None)
        if isinstance(value, (int, float)):
            out[dst] = int(value)
    parts = [out.get(k) for k in (
        "argument_bytes", "output_bytes", "temp_bytes",
        "generated_code_bytes")]
    present = [p for p in parts if p is not None]
    if present:
        out["peak_bytes"] = int(sum(present))
    return out


def entry_key(entry: dict) -> str:
    """The identity a diff matches entries on."""
    return "|".join(str(entry.get(k, "?")) for k in (
        "model", "bucket", "backend", "precision", "kind"))


class CostLedger:
    """In-memory accumulator of per-executable cost entries
    (thread-safe; warm-ups record from dispatcher threads)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}  # guarded-by: _lock

    def record(self, compiled, *, model: str = "?", bucket=None,
               kind: str = "aot", precision: str | None = None,
               compile_s: float | None = None, extra: dict | None = None,
               ) -> dict | None:
        """Capture one compiled executable's cost entry (no-op and None
        when capture is off). Re-recording the same key overwrites — a
        re-warm measures the same artifact."""
        if not capture_enabled():
            return None
        entry = {
            "model": str(model),
            "bucket": list(bucket) if isinstance(bucket, (tuple, list))
            else (bucket if bucket is None else str(bucket)),
            "backend": _backend_name(),
            "precision": str(precision) if precision is not None else "default",
            "kind": str(kind),
            "t_wall": time.time(),
        }
        entry.update(cost_dict(compiled))
        entry.update(memory_dict(compiled))
        if compile_s is not None:
            entry["compile_s"] = round(float(compile_s), 4)
        lowerings = _lowering_counts().get("lowerings")
        if lowerings is not None:
            entry["lowerings_at_capture"] = int(lowerings)
        if extra:
            entry.update(extra)
        key = entry_key(entry)
        with self._lock:
            self._entries[key] = entry
        return dict(entry)

    def entries(self) -> list[dict]:
        with self._lock:
            return [dict(self._entries[k]) for k in sorted(self._entries)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()

    def document(self) -> dict:
        """The schema'd ledger document (what ``save`` writes)."""
        return {
            "schema": SCHEMA_VERSION,
            "created_unix": time.time(),
            "backend": _backend_name(),
            "lowerings": _lowering_counts(),
            "entries": self.entries(),
        }

    def save(self, path: str) -> str | None:
        """Atomically persist the ledger document; empty ledgers write
        nothing (no entries, no file — absence is unambiguous)."""
        doc = self.document()
        if not doc["entries"]:
            return None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path


def load(path: str) -> dict:
    """Read a ledger document back; raises on unreadable/unschema'd input
    (the diff sentinel wants loud failure, not a silent pass)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "entries" not in doc:
        raise ValueError(f"not a ledger document: {path}")
    return doc


def diff(baseline: dict, current: dict, tolerance: float = 0.02) -> dict:
    """Compare two ledger documents entry-by-entry. An entry REGRESSES
    when any :data:`DIFF_METRICS` value grew beyond ``tolerance``
    (relative); shrinkage is reported as an improvement, never a failure.
    Entries present on one side only are listed but do not fail — a new
    bucket is news, not a regression."""
    base_by = {entry_key(e): e for e in baseline.get("entries", [])}
    cur_by = {entry_key(e): e for e in current.get("entries", [])}
    regressions, improvements, compared = [], [], 0
    for key in sorted(set(base_by) & set(cur_by)):
        b, c = base_by[key], cur_by[key]
        compared += 1
        for metric in DIFF_METRICS:
            bv, cv = b.get(metric), c.get(metric)
            if not isinstance(bv, (int, float)) or not isinstance(cv, (int, float)):
                continue
            if bv <= 0:
                continue
            ratio = cv / bv
            delta = {"key": key, "metric": metric, "baseline": bv,
                     "current": cv, "ratio": round(ratio, 6)}
            if ratio > 1.0 + tolerance:
                regressions.append(delta)
            elif ratio < 1.0 - tolerance:
                improvements.append(delta)
    return {
        "tolerance": tolerance,
        "compared": compared,
        "only_in_baseline": sorted(set(base_by) - set(cur_by)),
        "only_in_current": sorted(set(cur_by) - set(base_by)),
        "regressions": regressions,
        "improvements": improvements,
        "ok": not regressions,
    }


# -- the process ledger -------------------------------------------------------

LEDGER = CostLedger()


def record(compiled, **kwargs) -> dict | None:
    return LEDGER.record(compiled, **kwargs)


def entries() -> list[dict]:
    return LEDGER.entries()


def reset_ledger() -> None:
    LEDGER.reset()


def save(path: str) -> str | None:
    return LEDGER.save(path)


def maybe_save(default_path: str | None = None) -> str | None:
    """Persist the process ledger to the flag-armed path, else to the
    caller's default (a run's log dir); a no-op when neither names a
    target or the ledger is empty."""
    path = save_path() or default_path
    if path is None:
        return None
    return LEDGER.save(path)


@contextlib.contextmanager
def isolated_ledger():
    """Swap the process ``LEDGER`` for a fresh instance for the duration
    of the scope (same single-rebind pattern as
    ``metrics.isolated_registry``)."""
    global LEDGER
    fresh = CostLedger()
    prev, LEDGER = LEDGER, fresh
    try:
        yield fresh
    finally:
        LEDGER = prev


__all__ = [
    "DIFF_METRICS",
    "CostLedger",
    "LEDGER",
    "SCHEMA_VERSION",
    "capture_enabled",
    "cost_dict",
    "diff",
    "entries",
    "entry_key",
    "isolated_ledger",
    "load",
    "maybe_save",
    "memory_dict",
    "record",
    "reset_ledger",
    "save",
    "save_path",
]
