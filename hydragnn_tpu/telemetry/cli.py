"""``python -m hydragnn_tpu.telemetry <events.jsonl>`` — the post-mortem
timeline renderer.

Turns a run's structured event journal (plus, when present, its
``trace.json``) into the human answer to "what happened": a chronological
event timeline, every elastic recovery reconstructed phase-by-phase from
its ``recovery_id``-correlated records (fault -> drain -> checkpoint ->
re-mesh -> resume), shed/failover totals, per-epoch throughput, and the
top aggregate spans. Pure stdlib + file reads — it must work on a login
node over the logs of a crashed job.

Two subcommands ride the same entry point:

``python -m hydragnn_tpu.telemetry fleet <dir...>`` merges the journals
of a router process and its N replica log dirs into ONE cross-process
timeline — records are grouped by the ``request_id`` the trace-context
propagation layer (``telemetry/propagation.py``) carried over the wire,
ordered by ``(t_wall, seq)`` within a request, and labeled with the
source dir they came from. ``--trace-out`` additionally merges every
dir's ``trace.json`` into one perfetto-loadable file with a distinct
``pid`` (and a ``process_name`` metadata record) per source. Absent or
torn journals/traces are tolerated per dir, never fatal for the merge.

``python -m hydragnn_tpu.telemetry ledger <current> [--baseline <base>]``
is the cost observatory's regression sentinel: without a baseline it
renders a ``ledger.json`` (``telemetry/ledger.py``); with one it diffs
the two and exits nonzero when any shared executable's flops /
bytes-accessed / peak-bytes inflated beyond ``--tolerance``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

from .journal import read_journal


def _fmt_t(rec: dict, t0: float) -> str:
    return f"+{max(rec.get('t_wall', t0) - t0, 0.0):9.3f}s"


def _fields(rec: dict, skip=("kind", "t_wall", "seq", "run_id")) -> str:
    parts = []
    for key in sorted(rec):
        if key in skip:
            continue
        value = rec[key]
        if isinstance(value, float):
            value = round(value, 6)
        parts.append(f"{key}={value}")
    return " ".join(parts)


def render_timeline(records: list[dict], limit: int = 200) -> str:
    if not records:
        return "timeline: no records"
    t0 = records[0].get("t_wall", 0.0)
    lines = [f"timeline ({len(records)} records):"]
    shown = records if len(records) <= limit else records[-limit:]
    if len(records) > limit:
        lines.append(f"  ... {len(records) - limit} earlier records elided "
                     "(--full shows everything)")
    for rec in shown:
        lines.append(
            f"  {_fmt_t(rec, t0)}  {rec.get('kind', '?'):<18} {_fields(rec)}"
        )
    return "\n".join(lines)


def render_recoveries(records: list[dict]) -> str:
    by_id: dict = defaultdict(list)
    for rec in records:
        rid = rec.get("recovery_id")
        if rid is not None:
            by_id[rid].append(rec)
    if not by_id:
        return "recoveries: none"
    lines = [f"recoveries ({len(by_id)}):"]
    for rid in sorted(by_id):
        phase_recs = by_id[rid]
        t0 = phase_recs[0].get("t_wall", 0.0)
        summary = next(
            (r for r in phase_recs if r.get("kind") == "recovery"), None
        )
        head = f"  {rid}:"
        if summary is not None:
            head += (
                f" mode={summary.get('mode')} "
                f"recovery_ms={round(float(summary.get('recovery_ms', 0)), 1)} "
                f"faults={summary.get('faults')}"
            )
        lines.append(head)
        for rec in phase_recs:
            kind = rec.get("kind")
            if kind == "recovery_phase":
                what = f"phase {rec.get('phase')}"
                if rec.get("detail"):
                    what += f" ({rec['detail']})"
            elif kind == "recovery":
                continue  # already on the header line
            else:
                what = f"{kind} {_fields(rec, skip=('kind', 't_wall', 'seq', 'run_id', 'recovery_id'))}"
            lines.append(f"    {_fmt_t(rec, t0)}  {what}")
    return "\n".join(lines)


def render_epochs(records: list[dict]) -> str:
    epochs = [r for r in records if r.get("kind") == "epoch"]
    if not epochs:
        return "epochs: none recorded"
    lines = ["epoch throughput:"]
    for rec in epochs:
        dur = float(rec.get("duration_s") or 0.0)
        raw = rec.get("raw_batches")
        rate = (
            f"{raw / dur:8.1f} batches/s" if raw and dur > 0 else "        -"
        )
        loss = rec.get("train_loss")
        loss_s = f"{loss:.6f}" if isinstance(loss, (int, float)) else "nan"
        lines.append(
            f"  epoch {rec.get('epoch', '?'):>4}: loss {loss_s}  "
            f"{dur:7.2f}s  {rate}"
            + (f"  val {rec['val_loss']:.6f}"
               if isinstance(rec.get("val_loss"), (int, float)) else "")
        )
    return "\n".join(lines)


def render_sheds(records: list[dict]) -> str:
    sheds = [r for r in records if r.get("kind") == "shed"]
    fails = [r for r in records if r.get("kind") == "failover"]
    if not sheds and not fails:
        return "sheds/failovers: none"
    by_reason: dict = defaultdict(int)
    for rec in sheds:
        key = (rec.get("model") or rec.get("class") or "?", rec.get("reason", "?"))
        by_reason[key] += 1
    lines = [f"sheds ({len(sheds)}) / failovers ({len(fails)}):"]
    for (who, reason), n in sorted(by_reason.items()):
        lines.append(f"  shed {who} [{reason}]: {n}")
    for rec in fails:
        lines.append(
            f"  failover replica={rec.get('replica', rec.get('peer', '?'))} "
            f"error={rec.get('error', '?')}"
        )
    return "\n".join(lines)


def render_top_spans(trace_path: str | None, top: int = 10) -> str:
    if not trace_path or not os.path.exists(trace_path):
        return "top spans: no trace.json"
    try:
        with open(trace_path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        # a torn trace.json (killed mid-save) must not cost the report —
        # the journal sections still render
        return f"top spans: unreadable trace.json ({e})"
    # both Chrome trace forms load: the object form ({"traceEvents": [...]})
    # our writer emits, and the equally valid bare-array form
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        return "top spans: unreadable trace.json (unexpected shape)"
    agg: dict = defaultdict(lambda: [0, 0.0])
    for ev in events:
        if ev.get("ph") != "X":
            continue
        entry = agg[ev.get("name", "?")]
        entry[0] += 1
        entry[1] += float(ev.get("dur", 0.0)) / 1e6
    if not agg:
        return "top spans: trace has no complete events"
    lines = [f"top spans ({os.path.basename(trace_path)}):"]
    ranked = sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
    for name, (count, total) in ranked:
        lines.append(
            f"  {name:<24} total {total:9.3f}s over {count:6d} span(s) "
            f"(avg {1e3 * total / max(count, 1):8.2f} ms)"
        )
    return "\n".join(lines)


def render_report(records: list[dict], trace_path: str | None = None,
                  full: bool = False) -> str:
    run_id = next(
        (r["run_id"] for r in records if "run_id" in r), "<unknown>"
    )
    parts = [
        f"telemetry report — run {run_id}, {len(records)} journal record(s)",
        "",
        render_recoveries(records),
        "",
        render_epochs(records),
        "",
        render_sheds(records),
        "",
        render_top_spans(trace_path),
        "",
        render_timeline(records, limit=10**9 if full else 200),
    ]
    return "\n".join(parts)


# -- fleet: cross-process journal + trace merge -------------------------------


def _events_path(target: str) -> str:
    """A log dir resolves to its ``events.jsonl``; a file path is itself."""
    if os.path.isdir(target):
        return os.path.join(target, "events.jsonl")
    return target


def _source_label(target: str) -> str:
    """A short human label for a merge source: the log dir's basename."""
    if os.path.isdir(target):
        return os.path.basename(os.path.normpath(target)) or target
    parent = os.path.basename(os.path.dirname(os.path.abspath(target)))
    return parent or os.path.basename(target)


def load_fleet(targets: list[str]) -> tuple[list[dict], list[str]]:
    """Read every source's journal, tagging each record with the source
    label under ``_source``. Missing or empty journals produce a warning
    line (returned, not printed) instead of failing the merge — one dead
    replica must not hide the rest of the fleet."""
    tagged: list[dict] = []
    warnings: list[str] = []
    for target in targets:
        path = _events_path(target)
        label = _source_label(target)
        if not os.path.exists(path):
            warnings.append(f"warning: no events journal at {path}")
            continue
        records = read_journal(path)
        if not records:
            warnings.append(f"warning: empty events journal at {path}")
            continue
        for rec in records:
            rec = dict(rec)
            rec["_source"] = label
            tagged.append(rec)
    return tagged, warnings


def render_fleet_requests(tagged: list[dict]) -> str:
    """The cross-process view: every record sharing a ``request_id`` —
    regardless of which process journal it came from — renders as one
    ordered per-request timeline (order: ``(t_wall, seq)``)."""
    by_rid: dict = defaultdict(list)
    for rec in tagged:
        rid = rec.get("request_id")
        if rid is not None:
            by_rid[rid].append(rec)
    if not by_rid:
        return ("requests: no request_id-correlated records (was "
                "HYDRAGNN_TRACE_PROPAGATE off?)")
    # requests in arrival order (earliest record wins)
    order = sorted(
        by_rid, key=lambda rid: min(r.get("t_wall", 0.0) for r in by_rid[rid])
    )
    lines = [f"requests ({len(by_rid)}):"]
    for rid in order:
        recs = sorted(
            by_rid[rid],
            key=lambda r: (r.get("t_wall", 0.0), r.get("seq", 0)),
        )
        t0 = recs[0].get("t_wall", 0.0)
        sources = []
        for rec in recs:
            if rec["_source"] not in sources:
                sources.append(rec["_source"])
        lines.append(f"  {rid} ({len(recs)} records across "
                     f"{len(sources)} process(es): {', '.join(sources)})")
        for rec in recs:
            lines.append(
                f"    {_fmt_t(rec, t0)}  [{rec['_source']:<14}] "
                f"{rec.get('kind', '?'):<16} "
                f"{_fields(rec, skip=('kind', 't_wall', 'seq', 'run_id', 'request_id', '_source'))}"
            )
    return "\n".join(lines)


def render_fleet_timeline(tagged: list[dict], limit: int = 500) -> str:
    """Every record from every source on one wall-clock axis."""
    if not tagged:
        return "fleet timeline: no records"
    recs = sorted(
        tagged, key=lambda r: (r.get("t_wall", 0.0), r.get("seq", 0))
    )
    t0 = recs[0].get("t_wall", 0.0)
    n_src = len({r["_source"] for r in recs})
    lines = [f"fleet timeline ({len(recs)} records from {n_src} source(s)):"]
    shown = recs if len(recs) <= limit else recs[-limit:]
    if len(recs) > limit:
        lines.append(f"  ... {len(recs) - limit} earlier records elided")
    for rec in shown:
        rid = rec.get("request_id")
        rid_s = f" rid={str(rid)[:8]}" if rid is not None else ""
        lines.append(
            f"  {_fmt_t(rec, t0)}  [{rec['_source']:<14}] "
            f"{rec.get('kind', '?'):<16}{rid_s} "
            f"{_fields(rec, skip=('kind', 't_wall', 'seq', 'run_id', 'request_id', '_source'))}"
        )
    return "\n".join(lines)


def merge_fleet_traces(targets: list[str], out_path: str) -> tuple[str | None, list[str]]:
    """Merge every source dir's ``trace.json`` into one Chrome-trace file,
    remapping each source onto a distinct ``pid`` (with a ``process_name``
    metadata record carrying the source label) so perfetto renders the
    fleet as parallel process tracks. Absent or torn traces are skipped
    with a warning. Returns ``(written_path_or_None, warnings)``."""
    merged: list[dict] = []
    warnings: list[str] = []
    n_sources = 0
    for i, target in enumerate(targets):
        trace_path = (
            os.path.join(target, "trace.json") if os.path.isdir(target)
            else os.path.join(os.path.dirname(os.path.abspath(target)),
                              "trace.json")
        )
        label = _source_label(target)
        if not os.path.exists(trace_path):
            warnings.append(f"warning: no trace.json at {trace_path}")
            continue
        try:
            with open(trace_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            warnings.append(f"warning: unreadable trace.json at "
                            f"{trace_path} ({e})")
            continue
        events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
        if not isinstance(events, list):
            warnings.append(f"warning: unexpected trace shape at {trace_path}")
            continue
        n_sources += 1
        merged.append({"ph": "M", "name": "process_name", "pid": i, "tid": 0,
                       "args": {"name": label}})
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = i
            merged.append(ev)
    if n_sources == 0:
        return None, warnings
    doc = {"traceEvents": merged, "displayTimeUnit": "ms"}
    tmp = f"{out_path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return out_path, warnings


def fleet_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.telemetry fleet",
        description="Merge the journals (and traces) of a router + N "
                    "replica log dirs into one cross-process, "
                    "request_id-correlated timeline.",
    )
    parser.add_argument(
        "dirs", nargs="+",
        help="log dirs (or events.jsonl paths) to merge — the router's "
             "and each replica's",
    )
    parser.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="also merge every dir's trace.json into PATH with a distinct "
             "pid per source (perfetto-loadable)",
    )
    parser.add_argument(
        "--limit", type=int, default=500,
        help="cap on flat-timeline records (default 500)",
    )
    args = parser.parse_args(argv)
    tagged, warnings = load_fleet(args.dirs)
    for line in warnings:
        print(line, file=sys.stderr)
    if not tagged:
        print(f"error: no journal records in any of: {', '.join(args.dirs)}",
              file=sys.stderr)
        return 2
    parts = [
        f"fleet report — {len(tagged)} record(s) from "
        f"{len(args.dirs)} source(s)",
        "",
        render_fleet_requests(tagged),
        "",
        render_fleet_timeline(tagged, limit=args.limit),
    ]
    if args.trace_out:
        written, trace_warnings = merge_fleet_traces(args.dirs, args.trace_out)
        for line in trace_warnings:
            print(line, file=sys.stderr)
        parts += ["", f"merged trace: {written or 'no usable trace.json'}"]
    try:
        print("\n".join(parts))
    except BrokenPipeError:
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


# -- ledger: cost-observatory render + regression sentinel --------------------


def render_ledger(doc: dict) -> str:
    entries = doc.get("entries", [])
    lines = [
        f"cost ledger — schema {doc.get('schema', '?')}, backend "
        f"{doc.get('backend', '?')}, {len(entries)} entr(ies)",
    ]
    lowerings = doc.get("lowerings") or {}
    if lowerings:
        lines.append(
            "lowerings: " + " ".join(
                f"{k}={lowerings[k]}" for k in sorted(lowerings))
        )
    for entry in entries:
        head = (f"  {entry.get('model', '?')} | kind={entry.get('kind', '?')} "
                f"| bucket={entry.get('bucket')} "
                f"| {entry.get('precision', '?')}")
        lines.append(head)
        cost_bits = []
        for key in ("flops", "bytes_accessed", "peak_bytes", "temp_bytes",
                    "generated_code_bytes", "compile_s"):
            value = entry.get(key)
            if isinstance(value, (int, float)):
                cost_bits.append(f"{key}={value:g}")
        if cost_bits:
            lines.append("    " + " ".join(cost_bits))
    return "\n".join(lines)


def render_ledger_diff(result: dict) -> str:
    lines = [
        f"ledger diff — {result['compared']} shared entr(ies) compared, "
        f"tolerance {result['tolerance']:.1%}",
    ]
    for key in result["only_in_baseline"]:
        lines.append(f"  only in baseline: {key}")
    for key in result["only_in_current"]:
        lines.append(f"  only in current:  {key}")
    for delta in result["improvements"]:
        lines.append(
            f"  improved  {delta['key']} {delta['metric']}: "
            f"{delta['baseline']:g} -> {delta['current']:g} "
            f"(x{delta['ratio']:.4f})"
        )
    for delta in result["regressions"]:
        lines.append(
            f"  REGRESSED {delta['key']} {delta['metric']}: "
            f"{delta['baseline']:g} -> {delta['current']:g} "
            f"(x{delta['ratio']:.4f})"
        )
    lines.append(
        "ledger diff: OK" if result["ok"]
        else f"ledger diff: FAIL — {len(result['regressions'])} cost "
             f"regression(s) beyond tolerance"
    )
    return "\n".join(lines)


def _load_ledger(path: str) -> dict:
    if os.path.isdir(path):
        path = os.path.join(path, "ledger.json")
    from . import ledger as _ledger

    return _ledger.load(path)


def ledger_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.telemetry ledger",
        description="Render a cost ledger, or diff it against a baseline "
                    "and fail on compiled-cost inflation beyond tolerance.",
    )
    parser.add_argument(
        "current",
        help="path to a ledger.json (or a run log dir containing one)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline ledger.json to diff against (regression sentinel "
             "mode: exit 1 on cost inflation beyond --tolerance)",
    )
    parser.add_argument(
        "--tolerance", type=float, default=0.02,
        help="relative inflation tolerance for the diff (default 0.02)",
    )
    args = parser.parse_args(argv)
    try:
        current = _load_ledger(args.current)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read ledger at {args.current}: {e}",
              file=sys.stderr)
        return 2
    if args.baseline is None:
        print(render_ledger(current))
        return 0
    try:
        baseline = _load_ledger(args.baseline)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read baseline ledger at {args.baseline}: {e}",
              file=sys.stderr)
        return 2
    from . import ledger as _ledger

    result = _ledger.diff(baseline, current, tolerance=args.tolerance)
    print(render_ledger_diff(result))
    return 0 if result["ok"] else 1


# -- entry point --------------------------------------------------------------


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # subcommand dispatch rides in front of the legacy positional form:
    # `... telemetry <events.jsonl>` (PR 15) keeps working unchanged
    if argv and argv[0] == "fleet":
        return fleet_main(argv[1:])
    if argv and argv[0] == "ledger":
        return ledger_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.telemetry",
        description="Render a run's events.jsonl (and trace.json) into a "
                    "human timeline: recoveries, sheds, epoch throughput, "
                    "top spans. Subcommands: `fleet <dir...>` merges a "
                    "router + replica journals into one cross-process "
                    "timeline; `ledger <current> [--baseline <base>]` "
                    "renders/diffs the compiled-cost ledger.",
    )
    parser.add_argument(
        "events",
        help="path to an events.jsonl, or a run log dir containing one",
    )
    parser.add_argument(
        "--trace", default=None,
        help="trace.json for the top-spans section (default: the "
             "events file's sibling trace.json, when present)",
    )
    parser.add_argument(
        "--full", action="store_true",
        help="print every timeline record (default caps at 200)",
    )
    args = parser.parse_args(argv)
    events_path = _events_path(args.events)
    if not os.path.exists(events_path):
        # operator-facing miss (wrong dir, crashed-before-first-write run):
        # one line naming the path, no usage dump, no traceback
        print(f"error: no events journal at {events_path}", file=sys.stderr)
        return 2
    trace_path = args.trace
    if trace_path is None:
        sibling = os.path.join(os.path.dirname(events_path), "trace.json")
        trace_path = sibling if os.path.exists(sibling) else None
    records = read_journal(events_path)
    if not records:
        print(f"error: empty events journal at {events_path}",
              file=sys.stderr)
        return 2
    try:
        print(render_report(records, trace_path=trace_path, full=args.full))
    except BrokenPipeError:
        # `... | head` closed the pipe: normal operator behavior, not an
        # error worth a traceback
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


__all__ = [
    "fleet_main",
    "ledger_main",
    "load_fleet",
    "main",
    "merge_fleet_traces",
    "render_epochs",
    "render_fleet_requests",
    "render_fleet_timeline",
    "render_ledger",
    "render_ledger_diff",
    "render_recoveries",
    "render_report",
    "render_sheds",
    "render_timeline",
    "render_top_spans",
]
