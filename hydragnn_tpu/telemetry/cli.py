"""``python -m hydragnn_tpu.telemetry <events.jsonl>`` — the post-mortem
timeline renderer.

Turns a run's structured event journal (plus, when present, its
``trace.json``) into the human answer to "what happened": a chronological
event timeline, every elastic recovery reconstructed phase-by-phase from
its ``recovery_id``-correlated records (fault -> drain -> checkpoint ->
re-mesh -> resume), shed/failover totals, per-epoch throughput, and the
top aggregate spans. Pure stdlib + file reads — it must work on a login
node over the logs of a crashed job.
"""

from __future__ import annotations

import argparse
import json
import os
from collections import defaultdict

from .journal import read_journal


def _fmt_t(rec: dict, t0: float) -> str:
    return f"+{max(rec.get('t_wall', t0) - t0, 0.0):9.3f}s"


def _fields(rec: dict, skip=("kind", "t_wall", "seq", "run_id")) -> str:
    parts = []
    for key in sorted(rec):
        if key in skip:
            continue
        value = rec[key]
        if isinstance(value, float):
            value = round(value, 6)
        parts.append(f"{key}={value}")
    return " ".join(parts)


def render_timeline(records: list[dict], limit: int = 200) -> str:
    if not records:
        return "timeline: no records"
    t0 = records[0].get("t_wall", 0.0)
    lines = [f"timeline ({len(records)} records):"]
    shown = records if len(records) <= limit else records[-limit:]
    if len(records) > limit:
        lines.append(f"  ... {len(records) - limit} earlier records elided "
                     "(--full shows everything)")
    for rec in shown:
        lines.append(
            f"  {_fmt_t(rec, t0)}  {rec.get('kind', '?'):<18} {_fields(rec)}"
        )
    return "\n".join(lines)


def render_recoveries(records: list[dict]) -> str:
    by_id: dict = defaultdict(list)
    for rec in records:
        rid = rec.get("recovery_id")
        if rid is not None:
            by_id[rid].append(rec)
    if not by_id:
        return "recoveries: none"
    lines = [f"recoveries ({len(by_id)}):"]
    for rid in sorted(by_id):
        phase_recs = by_id[rid]
        t0 = phase_recs[0].get("t_wall", 0.0)
        summary = next(
            (r for r in phase_recs if r.get("kind") == "recovery"), None
        )
        head = f"  {rid}:"
        if summary is not None:
            head += (
                f" mode={summary.get('mode')} "
                f"recovery_ms={round(float(summary.get('recovery_ms', 0)), 1)} "
                f"faults={summary.get('faults')}"
            )
        lines.append(head)
        for rec in phase_recs:
            kind = rec.get("kind")
            if kind == "recovery_phase":
                what = f"phase {rec.get('phase')}"
                if rec.get("detail"):
                    what += f" ({rec['detail']})"
            elif kind == "recovery":
                continue  # already on the header line
            else:
                what = f"{kind} {_fields(rec, skip=('kind', 't_wall', 'seq', 'run_id', 'recovery_id'))}"
            lines.append(f"    {_fmt_t(rec, t0)}  {what}")
    return "\n".join(lines)


def render_epochs(records: list[dict]) -> str:
    epochs = [r for r in records if r.get("kind") == "epoch"]
    if not epochs:
        return "epochs: none recorded"
    lines = ["epoch throughput:"]
    for rec in epochs:
        dur = float(rec.get("duration_s") or 0.0)
        raw = rec.get("raw_batches")
        rate = (
            f"{raw / dur:8.1f} batches/s" if raw and dur > 0 else "        -"
        )
        loss = rec.get("train_loss")
        loss_s = f"{loss:.6f}" if isinstance(loss, (int, float)) else "nan"
        lines.append(
            f"  epoch {rec.get('epoch', '?'):>4}: loss {loss_s}  "
            f"{dur:7.2f}s  {rate}"
            + (f"  val {rec['val_loss']:.6f}"
               if isinstance(rec.get("val_loss"), (int, float)) else "")
        )
    return "\n".join(lines)


def render_sheds(records: list[dict]) -> str:
    sheds = [r for r in records if r.get("kind") == "shed"]
    fails = [r for r in records if r.get("kind") == "failover"]
    if not sheds and not fails:
        return "sheds/failovers: none"
    by_reason: dict = defaultdict(int)
    for rec in sheds:
        key = (rec.get("model") or rec.get("class") or "?", rec.get("reason", "?"))
        by_reason[key] += 1
    lines = [f"sheds ({len(sheds)}) / failovers ({len(fails)}):"]
    for (who, reason), n in sorted(by_reason.items()):
        lines.append(f"  shed {who} [{reason}]: {n}")
    for rec in fails:
        lines.append(
            f"  failover replica={rec.get('replica', rec.get('peer', '?'))} "
            f"error={rec.get('error', '?')}"
        )
    return "\n".join(lines)


def render_top_spans(trace_path: str | None, top: int = 10) -> str:
    if not trace_path or not os.path.exists(trace_path):
        return "top spans: no trace.json"
    with open(trace_path) as f:
        doc = json.load(f)
    # both Chrome trace forms load: the object form ({"traceEvents": [...]})
    # our writer emits, and the equally valid bare-array form
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    agg: dict = defaultdict(lambda: [0, 0.0])
    for ev in events:
        if ev.get("ph") != "X":
            continue
        entry = agg[ev.get("name", "?")]
        entry[0] += 1
        entry[1] += float(ev.get("dur", 0.0)) / 1e6
    if not agg:
        return "top spans: trace has no complete events"
    lines = [f"top spans ({os.path.basename(trace_path)}):"]
    ranked = sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
    for name, (count, total) in ranked:
        lines.append(
            f"  {name:<24} total {total:9.3f}s over {count:6d} span(s) "
            f"(avg {1e3 * total / max(count, 1):8.2f} ms)"
        )
    return "\n".join(lines)


def render_report(records: list[dict], trace_path: str | None = None,
                  full: bool = False) -> str:
    run_id = next(
        (r["run_id"] for r in records if "run_id" in r), "<unknown>"
    )
    parts = [
        f"telemetry report — run {run_id}, {len(records)} journal record(s)",
        "",
        render_recoveries(records),
        "",
        render_epochs(records),
        "",
        render_sheds(records),
        "",
        render_top_spans(trace_path),
        "",
        render_timeline(records, limit=10**9 if full else 200),
    ]
    return "\n".join(parts)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.telemetry",
        description="Render a run's events.jsonl (and trace.json) into a "
                    "human timeline: recoveries, sheds, epoch throughput, "
                    "top spans.",
    )
    parser.add_argument(
        "events",
        help="path to an events.jsonl, or a run log dir containing one",
    )
    parser.add_argument(
        "--trace", default=None,
        help="trace.json for the top-spans section (default: the "
             "events file's sibling trace.json, when present)",
    )
    parser.add_argument(
        "--full", action="store_true",
        help="print every timeline record (default caps at 200)",
    )
    args = parser.parse_args(argv)
    events_path = args.events
    if os.path.isdir(events_path):
        events_path = os.path.join(events_path, "events.jsonl")
    if not os.path.exists(events_path):
        parser.error(f"no events journal at {events_path}")
    trace_path = args.trace
    if trace_path is None:
        sibling = os.path.join(os.path.dirname(events_path), "trace.json")
        trace_path = sibling if os.path.exists(sibling) else None
    records = read_journal(events_path)
    try:
        print(render_report(records, trace_path=trace_path, full=args.full))
    except BrokenPipeError:
        # `... | head` closed the pipe: normal operator behavior, not an
        # error worth a traceback
        import sys

        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


__all__ = [
    "main",
    "render_epochs",
    "render_recoveries",
    "render_report",
    "render_sheds",
    "render_timeline",
    "render_top_spans",
]
