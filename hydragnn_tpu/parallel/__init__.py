from .mesh import (
    DATA_AXIS,
    BRANCH_AXIS,
    make_mesh,
    batch_sharding,
    replicated,
    fsdp_param_specs,
)
from .step import (
    make_parallel_train_step,
    make_parallel_eval_step,
    shard_state,
    stack_device_batches,
    put_batch,
    batch_shardings,
)

__all__ = [
    "DATA_AXIS",
    "BRANCH_AXIS",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "fsdp_param_specs",
    "make_parallel_train_step",
    "make_parallel_eval_step",
    "shard_state",
    "stack_device_batches",
    "put_batch",
    "batch_shardings",
]
