from .mesh import (
    DATA_AXIS,
    BRANCH_AXIS,
    MODEL_AXIS,
    make_mesh,
    batch_sharding,
    host_gather,
    place_like,
    replicated,
    fsdp_param_specs,
    tp_param_specs,
)
from .step import (
    make_parallel_train_step,
    make_parallel_eval_step,
    shard_state,
    stack_device_batches,
    put_batch,
    put_block,
)

__all__ = [
    "DATA_AXIS",
    "BRANCH_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "batch_sharding",
    "host_gather",
    "place_like",
    "replicated",
    "fsdp_param_specs",
    "tp_param_specs",
    "make_parallel_train_step",
    "make_parallel_eval_step",
    "shard_state",
    "stack_device_batches",
    "put_batch",
    "put_block",
]
from .distributed import (  # noqa: E402
    setup_ddp,
    init_comm_size_and_rank,
    get_comm_size_and_rank,
)

__all__ += ["setup_ddp", "init_comm_size_and_rank", "get_comm_size_and_rank"]

from .pipeline import (  # noqa: E402
    STAGE_AXIS,
    make_pipeline_mesh,
    make_pipelined_forward,
    make_pipelined_train_step,
    put_microbatches,
)

__all__ += [
    "STAGE_AXIS",
    "make_pipeline_mesh",
    "make_pipelined_forward",
    "make_pipelined_train_step",
    "put_microbatches",
]
