from .mesh import (
    DATA_AXIS,
    BRANCH_AXIS,
    MODEL_AXIS,
    make_mesh,
    batch_sharding,
    replicated,
    fsdp_param_specs,
    tp_param_specs,
)
from .step import (
    make_parallel_train_step,
    make_parallel_eval_step,
    shard_state,
    stack_device_batches,
    put_batch,
    batch_shardings,
)

__all__ = [
    "DATA_AXIS",
    "BRANCH_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "fsdp_param_specs",
    "tp_param_specs",
    "make_parallel_train_step",
    "make_parallel_eval_step",
    "shard_state",
    "stack_device_batches",
    "put_batch",
    "batch_shardings",
]
from .distributed import (  # noqa: E402
    setup_ddp,
    init_comm_size_and_rank,
    get_comm_size_and_rank,
)

__all__ += ["setup_ddp", "init_comm_size_and_rank", "get_comm_size_and_rank"]
