"""Edge-sharded message passing — graphs too large for one chip's HBM.

The reference has NO long-context mechanism (SURVEY §5: no ring attention /
context parallelism anywhere); its answer to big graphs is radius-cutoff
bounds + data parallelism over many small graphs. The sequence-length analog
for graph learning is *graph size*, and this module is the TPU build's
first-class answer: ONE giant graph partitioned across the mesh by EDGES.

Scheme (the graph analog of ring/all-to-all sequence parallelism):
* node features are replicated (or node-sharded in a later iteration);
* the edge list is sharded over the ``data`` axis — each device holds E/D
  edges and computes messages for them only;
* per-device partial segment-sums over receivers are combined with ONE
  ``psum`` over ICI — the halo exchange. Compute scales 1/D per device,
  communication is a single all-reduce of the [N, F] node accumulator.

Built on ``shard_map`` so the collective is explicit and the edge tensors
never materialize unsharded.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def sharded_segment_sum(
    mesh: Mesh,
    messages: jax.Array,  # [E, F] sharded over edges
    receivers: jax.Array,  # [E] sharded
    num_nodes: int,
) -> jax.Array:
    """Edge-sharded scatter-add: each device reduces its local edge shard,
    then one psum merges the partial node sums (the halo exchange)."""

    def local(messages_shard, receivers_shard):
        partial_sum = jax.ops.segment_sum(
            messages_shard, receivers_shard, num_segments=num_nodes
        )
        return jax.lax.psum(partial_sum, DATA_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),  # replicated result
    )(messages, receivers)


def edge_sharded_conv_step(
    mesh: Mesh,
    node_feats: jax.Array,  # [N, F] replicated
    senders: jax.Array,  # [E] sharded over edges
    receivers: jax.Array,  # [E] sharded
    edge_mask: jax.Array,  # [E] sharded
    weights: jax.Array,  # [F, F] replicated
) -> jax.Array:
    """One GIN-style message-passing layer over an edge-partitioned giant
    graph: gather (local), message transform (local), scatter-add + psum."""

    def local(h, snd, rcv, mask, w):
        msg = h[snd] * mask[:, None]  # gather from replicated nodes
        msg = msg @ w  # MXU work, local to the shard
        agg = jax.ops.segment_sum(msg, rcv, num_segments=h.shape[0])
        return jax.lax.psum(agg, DATA_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=P(),
    )(node_feats, senders, receivers, edge_mask, weights)


def shard_edges(mesh: Mesh, *edge_arrays):
    """Place edge-dimension arrays with their leading axis split over the
    data axis (pad the edge count to a multiple of the axis size first)."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    return tuple(jax.device_put(a, sharding) for a in edge_arrays)
