"""SPMD train/eval steps over a device mesh.

The TPU replacement for the reference's DDP/FSDP/DeepSpeed wrappers
(``hydragnn/utils/distributed/distributed.py:396-536``): one jitted global
program where

* the batch carries a leading device axis ``[D, ...]`` sharded over the mesh's
  ``data`` axis — each device computes its own padded graph batch end-to-end
  with **zero** forward communication (graphs never straddle devices, as in
  the reference's per-rank DataLoader);
* parameters are replicated (DDP semantics) or sharded over ``data`` (FSDP /
  ZeRO-3 semantics, ``fsdp_param_specs``) — the XLA SPMD partitioner inserts
  the gradient all-reduce / per-layer all-gathers that DDP and FSDP implement
  by hand with NCCL;
* the loss is the graph-count-weighted mean over device sub-batches, matching
  the reference's ``x NUM graphs -> allreduce -> / total`` bookkeeping
  (``train_validate_test.py:795-799``).

The same step function runs unchanged on 1 device or a v5p pod — only the
mesh and shardings differ.

Resilience contract: every step factory here returns the generic
``(state, batch) -> (state, metrics)`` shape with a scalar global
``metrics["loss"]``, which is exactly what the non-finite step guard
(``resilience/guard.py``) wraps — a NaN on any device shard reaches the
graph-count-weighted global loss through the in-program all-reduce, so ONE
poisoned shard skips the whole mesh's update in the same dispatch (no
device ever applies a half-poisoned gradient).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graphs.graph import GraphBatch
from ..models.base import HydraModel
from ..models.common import SYNC_BN_AXIS
from ..train.step import TrainState, _cast_floats, donate_state_argnums as _donate
from .mesh import DATA_AXIS, batch_sharding, fsdp_param_specs


def stack_device_batches(batches: list[GraphBatch]) -> GraphBatch:
    """Stack per-device batches into one [D, ...] GraphBatch. The static
    layout metadata merges conservatively — a fused-kernel guarantee holds
    for the stack only if every device's batch carries it."""
    from ..graphs.graph import BatchMeta

    merged = BatchMeta.merge([b.meta for b in batches])
    batches = [b.replace(meta=merged) for b in batches]  # align treedefs
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def _spans_processes(mesh: Mesh) -> bool:
    return mesh.devices.size > len(mesh.local_devices)


def _place(x, mesh: Mesh, spec: P):
    """Place a host array with ``spec`` on a mesh that may span processes.
    Multi-process meshes can't take a plain ``device_put`` of host data, so
    each process contributes its addressable shards via the callback API."""
    sharding = NamedSharding(mesh, spec)
    if not _spans_processes(mesh):
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def shard_state(state: TrainState, mesh: Mesh, param_mode: str = "replicated") -> TrainState:
    """Place a TrainState on the mesh. ``param_mode``: 'replicated' (DDP),
    'fsdp' (ZeRO-3 over data), 'branch' (multibranch decoders sharded over
    the branch axis, encoder replicated), or 'tp' (feature-axis tensor
    parallelism over the model axis). Optimizer state follows the param
    sharding — ZeRO-1 for free."""
    if param_mode == "fsdp":
        pspecs = fsdp_param_specs(state.params, mesh)
    elif param_mode == "branch":
        from .mesh import branch_param_specs

        pspecs = branch_param_specs(state.params, mesh)
    elif param_mode == "tp":
        from .mesh import tp_param_specs

        pspecs = tp_param_specs(state.params, mesh)
    elif param_mode == "replicated":
        pspecs = jax.tree.map(lambda _: P(), state.params)
    else:
        raise ValueError(
            f"unknown param_mode {param_mode!r}; expected one of "
            "'replicated', 'fsdp', 'branch', 'tp'"
        )

    def put(tree, specs):
        return jax.tree.map(lambda x, s: _place(x, mesh, s), tree, specs)

    params = put(state.params, pspecs)
    stats = jax.tree.map(lambda x: _place(x, mesh, P()), state.batch_stats)

    # shard optimizer state leaves that match a param's shape with that
    # param's spec; everything else replicated
    flat_params, treedef = jax.tree.flatten(state.params)
    shape_to_spec = {}
    for p, s in zip(flat_params, jax.tree.leaves(pspecs)):
        shape_to_spec.setdefault((p.shape, p.dtype), s)

    def place_opt(x):
        if hasattr(x, "shape"):
            s = shape_to_spec.get((x.shape, x.dtype), P())
            return _place(x, mesh, s)
        return x

    opt_state = jax.tree.map(place_opt, state.opt_state)
    step = _place(np.asarray(state.step), mesh, P())
    return TrainState(params=params, batch_stats=stats, opt_state=opt_state, step=step)


def put_batch(batch: GraphBatch, mesh: Mesh) -> GraphBatch:
    """Device-put a stacked batch with leading axis over data.

    Single process: ``batch`` carries the full ``[D, ...]`` leading axis.
    Multi-process: each process passes its LOCAL ``[D_local, ...]`` stack and
    the global array is assembled shard-by-shard (the jax.distributed data
    path replacing the reference's per-rank DataLoader + NCCL allreduce)."""
    data_sh = batch_sharding(mesh)
    if _spans_processes(mesh):
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(data_sh, np.asarray(x)),
            batch,
        )
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), data_sh), batch)


def put_block(block: GraphBatch, mesh: Mesh) -> GraphBatch:
    """Device-put a ``[K, D, ...]`` superstep block: axis 0 is the lax.scan
    step axis (replicated — iterated on-device), axis 1 the per-device axis
    sharded over ``data`` exactly like ``put_batch``'s leading axis.

    Multi-process: each process passes its LOCAL ``[K, D_local, ...]`` stack
    and the global array assembles shard-by-shard, same as ``put_batch``."""
    sh = NamedSharding(mesh, P(None, DATA_AXIS))
    if _spans_processes(mesh):
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)),
            block,
        )
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), block)


def merge_replica_stats(new_stats, node_counts):
    """Replica-mean merge of per-replica batch_stats updates, EXCLUDING
    replicas that saw zero real nodes. A plain mean would hand a FILL
    replica (all-masked batch padding a trailing device group — its norms
    keep their old running stats) weight n_fill/n_dev, diluting the real
    batches' EMA step. Weights are binary (count > 0), not proportional:
    real replicas keep the reference's equal-replica-mean semantics (and
    the pipeline ring-norm accumulation matches it bit-for-bit); fill
    replicas get exactly zero. Under SyncBN every replica already holds
    identical (union) stats, so the weighted mean reduces to the same
    value."""
    w = (node_counts > 0).astype(jnp.float32)
    tot = jnp.maximum(w.sum(), 1.0)

    def merge(x):
        wb = w.reshape((-1,) + (1,) * (x.ndim - 1))
        return (x * wb).sum(axis=0) / tot

    return jax.tree.map(merge, new_stats)


def make_parallel_train_step(
    model: HydraModel, optimizer, mesh: Mesh, compute_dtype=jnp.float32,
    loss_scale=None,
):
    """Jitted SPMD train step: (state, stacked_batch[D, ...]) -> (state, metrics).

    Dispatches to the MLIP (energy+force) loss when the spec enables
    interatomic potentials — same contract as the single-device path.

    ``loss_scale`` as in ``train.step._make_step_impl`` (static fp16-class
    scaling; None/1 keeps the historical program byte-for-byte): the scaled
    loss feeds the backward pass, the fp32-cast grads divide the scale back
    out, and metrics report the UNSCALED loss via aux.
    """
    if model.spec.enable_interatomic_potential:
        return _make_parallel_mlip_train_step(
            model, optimizer, mesh, compute_dtype, loss_scale
        )
    loss_scale = None if not loss_scale or float(loss_scale) == 1.0 else float(loss_scale)

    def loss_fn(params, batch_stats, batches: GraphBatch, dropout_rng):
        c_params = _cast_floats(params, compute_dtype)
        c_batches = _cast_floats(batches, compute_dtype)
        n_dev = jax.tree.leaves(batches)[0].shape[0]
        dev_rngs = jax.random.split(dropout_rng, n_dev)

        def per_device(b, rng):
            outputs, updates = model.apply(
                {"params": c_params, "batch_stats": batch_stats},
                b,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": rng},
            )
            pred = _cast_floats(outputs, jnp.float32)
            tot, tasks = model.loss(pred, b)
            ng = b.graph_mask.sum()
            nw = b.node_mask.sum()
            return tot * ng, jnp.stack(tasks) * ng, ng, nw, updates["batch_stats"]

        tots, tasks, ngs, nws, new_stats = jax.vmap(
            per_device, axis_name=SYNC_BN_AXIS
        )(c_batches, dev_rngs)
        denom = jnp.maximum(ngs.sum(), 1.0)
        loss = tots.sum() / denom
        # running stats: node-count-weighted replica merge (reference
        # default replica averaging, with fill replicas at zero weight)
        new_stats = merge_replica_stats(new_stats, nws)
        aux = (tasks.sum(axis=0) / denom, ngs.sum(), new_stats)
        if loss_scale is not None:
            # differentiate the scaled loss; the unscaled one rides out via
            # aux so metrics never see the scale
            return loss * loss_scale, (loss,) + aux
        return loss, aux

    @partial(jax.jit, donate_argnums=_donate())
    def train_step(state: TrainState, batches: GraphBatch):
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0), state.step)
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, batches, dropout_rng)
        from ..train.step import freeze_conv_grads

        grads = _cast_floats(grads, jnp.float32)
        if loss_scale is not None:
            # un-scale AFTER the fp32 cast (2^k scales divide back exactly)
            loss, tasks, ng, new_stats = aux
            grads = jax.tree.map(lambda g: g / loss_scale, grads)
        else:
            tasks, ng, new_stats = aux
        grads = freeze_conv_grads(grads, model.spec)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        return new_state, {"loss": loss, "tasks_loss": tasks, "num_graphs": ng}

    return train_step


def make_parallel_eval_step(model: HydraModel, mesh: Mesh, compute_dtype=jnp.float32):
    @jax.jit
    def eval_step(state: TrainState, batches: GraphBatch):
        c_params = _cast_floats(state.params, compute_dtype)
        c_batches = _cast_floats(batches, compute_dtype)

        def per_device(b):
            outputs = model.apply(
                {"params": c_params, "batch_stats": state.batch_stats}, b, train=False
            )
            pred = _cast_floats(outputs, jnp.float32)
            tot, tasks = model.loss(pred, b)
            sses, counts = model.head_sse(pred, b)
            ng = b.graph_mask.sum()
            return tot * ng, jnp.stack(tasks) * ng, jnp.stack(sses), jnp.stack(counts), ng

        tots, tasks, sses, counts, ngs = jax.vmap(per_device, axis_name=SYNC_BN_AXIS)(c_batches)
        denom = jnp.maximum(ngs.sum(), 1.0)
        return {
            "loss": tots.sum() / denom,
            "tasks_loss": tasks.sum(axis=0) / denom,
            "head_sse": sses.sum(axis=0),
            "head_count": counts.sum(axis=0),
            "num_graphs": ngs.sum(),
        }

    return eval_step


def make_parallel_mlip_eval_step(model: HydraModel, mesh: Mesh, compute_dtype=jnp.float32):
    """Vmapped SPMD MLIP evaluation — all device shards in one program
    (replaces the sequential per-device host loop; same bookkeeping as
    ``make_parallel_eval_step``)."""
    from ..models.mlip import energy_force_loss, make_energy_and_forces

    spec = model.spec
    energy_and_forces = make_energy_and_forces(model)

    @jax.jit
    def eval_step(state: TrainState, batches: GraphBatch):
        c_params = _cast_floats(state.params, compute_dtype)
        c_batches = _cast_floats(batches, compute_dtype)

        def per_device(b, b_raw):
            variables = {"params": c_params, "batch_stats": state.batch_stats}
            graph_e, forces = energy_and_forces(variables, b, False)
            graph_e = graph_e.astype(jnp.float32)
            forces = forces.astype(jnp.float32)
            tot, tasks = energy_force_loss(spec, graph_e, forces, b_raw)
            gm = b_raw.graph_mask
            e_sse = (((graph_e - b_raw.energy_y[:, 0]) ** 2) * gm).sum()
            f_sse = (((forces - b_raw.forces_y) ** 2) * b_raw.node_mask[:, None]).sum()
            ng = gm.sum()
            return (
                tot * ng,
                jnp.stack(tasks) * ng,
                jnp.stack([e_sse, f_sse]),
                jnp.stack([ng, b_raw.node_mask.sum() * 3]),
                ng,
            )

        tots, tasks, sses, counts, ngs = jax.vmap(per_device, axis_name=SYNC_BN_AXIS)(c_batches, batches)
        denom = jnp.maximum(ngs.sum(), 1.0)
        return {
            "loss": tots.sum() / denom,
            "tasks_loss": tasks.sum(axis=0) / denom,
            "head_sse": sses.sum(axis=0),
            "head_count": counts.sum(axis=0),
            "num_graphs": ngs.sum(),
        }

    return eval_step


def _make_parallel_mlip_train_step(
    model: HydraModel, optimizer, mesh: Mesh, compute_dtype=jnp.float32,
    loss_scale=None,
):
    """SPMD MLIP step: per-device inner force grad, global outer param grad.
    ``loss_scale`` scales only the OUTER (param) objective — the inner force
    grad must stay in physical units, since forces feed the loss itself."""
    from ..models.mlip import energy_force_loss, validate_mlip_spec
    from ..graphs import segment

    spec = model.spec
    validate_mlip_spec(spec)
    loss_scale = None if not loss_scale or float(loss_scale) == 1.0 else float(loss_scale)

    def loss_fn(params, batch_stats, batches: GraphBatch, dropout_rng):
        c_params = _cast_floats(params, compute_dtype)
        c_batches = _cast_floats(batches, compute_dtype)
        n_dev = jax.tree.leaves(batches)[0].shape[0]
        dev_rngs = jax.random.split(dropout_rng, n_dev)

        def per_device(b, b_raw, rng):
            def total_energy(pos):
                bb = b.replace(pos=pos)
                pred, updates = model.apply(
                    {"params": c_params, "batch_stats": batch_stats},
                    bb,
                    train=True,
                    mutable=["batch_stats"],
                    rngs={"dropout": rng},
                )
                if spec.var_output:
                    pred = pred[0]
                if spec.output_type[0] == "node":
                    node_e = pred[0] * bb.node_mask[:, None]
                    graph_e = segment.segment_sum(node_e[:, 0], bb.batch, bb.num_graphs)
                else:
                    graph_e = pred[0][:, 0]
                graph_e = (graph_e * bb.graph_mask).astype(jnp.float32)
                return graph_e.sum(), (graph_e, updates["batch_stats"])

            (_, (graph_e, new_stats)), grad_pos = jax.value_and_grad(
                total_energy, has_aux=True
            )(b.pos)
            forces = (-grad_pos * b_raw.node_mask[:, None]).astype(jnp.float32)
            tot, tasks = energy_force_loss(spec, graph_e, forces, b_raw)
            ng = b_raw.graph_mask.sum()
            nw = b_raw.node_mask.sum()
            return tot * ng, jnp.stack(tasks) * ng, ng, nw, new_stats

        tots, tasks, ngs, nws, new_stats = jax.vmap(
            per_device, axis_name=SYNC_BN_AXIS
        )(c_batches, batches, dev_rngs)
        denom = jnp.maximum(ngs.sum(), 1.0)
        new_stats = merge_replica_stats(new_stats, nws)
        loss = tots.sum() / denom
        aux = (tasks.sum(axis=0) / denom, ngs.sum(), new_stats)
        if loss_scale is not None:
            return loss * loss_scale, (loss,) + aux
        return loss, aux

    @partial(jax.jit, donate_argnums=_donate())
    def train_step(state: TrainState, batches: GraphBatch):
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0), state.step)
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, batches, dropout_rng)
        from ..train.step import freeze_conv_grads

        grads = _cast_floats(grads, jnp.float32)
        if loss_scale is not None:
            loss, tasks, ng, new_stats = aux
            grads = jax.tree.map(lambda g: g / loss_scale, grads)
        else:
            tasks, ng, new_stats = aux
        grads = freeze_conv_grads(grads, model.spec)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        return new_state, {"loss": loss, "tasks_loss": tasks, "num_graphs": ng}

    return train_step
