"""Multi-host bootstrap: rank/world discovery + jax.distributed init.

Reference: ``hydragnn/utils/distributed/distributed.py:113-280`` — an env
cascade (OpenMPI -> SLURM -> LSF/PBS -> single process) discovers rank/world,
then a torch process group is built with a master address parsed from the
scheduler's nodelist and a port derived from the job id with EADDRINUSE
retries.

TPU equivalent: the same cascade feeds ``jax.distributed.initialize`` —
afterwards every host sees the global device set and ONE jitted SPMD program
spans the pod; there are no NCCL/Gloo backends to pick because XLA owns the
collectives. On Cloud TPU pods, ``initialize()`` needs no arguments at all
(the runtime provides coordination); the cascade covers
SLURM/MPI-style clusters.
"""

from __future__ import annotations

import os
import re
import subprocess


def init_comm_size_and_rank() -> tuple[int, int]:
    """(world_size, rank) from the scheduler env cascade (reference :113-135)."""
    if os.getenv("OMPI_COMM_WORLD_SIZE"):
        return (
            int(os.environ["OMPI_COMM_WORLD_SIZE"]),
            int(os.environ["OMPI_COMM_WORLD_RANK"]),
        )
    if os.getenv("SLURM_NPROCS") and os.getenv("SLURM_PROCID") is not None:
        return int(os.environ["SLURM_NPROCS"]), int(os.environ["SLURM_PROCID"])
    if os.getenv("PMI_SIZE"):  # PBS/Intel MPI
        return int(os.environ["PMI_SIZE"]), int(os.environ["PMI_RANK"])
    if os.getenv("JAX_NUM_PROCESSES"):
        return int(os.environ["JAX_NUM_PROCESSES"]), int(
            os.environ.get("JAX_PROCESS_ID", 0)
        )
    return 1, 0


def _first_host_from_nodelist() -> str | None:
    """Master host from scheduler nodelists (reference :79-110, 191-215)."""
    lsb = os.getenv("LSB_HOSTS")
    if lsb:
        hosts = [h for h in lsb.split() if h and h != "batch"]
        if hosts:
            return hosts[0]
    slurm = os.getenv("SLURM_NODELIST") or os.getenv("SLURM_JOB_NODELIST")
    if slurm:
        try:
            out = subprocess.run(
                ["scontrol", "show", "hostnames", slurm],
                capture_output=True,
                text=True,
                timeout=10,
            ).stdout.split()
            if out:
                return out[0]
        except (OSError, subprocess.TimeoutExpired):
            pass
        # fallback: expand "prefix[a-b,...]" manually
        m = re.match(r"^([^\[]+)\[(\d+)", slurm)
        if m:
            return f"{m.group(1)}{m.group(2)}"
        return slurm.split(",")[0]
    pbs = os.getenv("PBS_NODEFILE")
    if pbs and os.path.exists(pbs):
        with open(pbs) as f:
            first = f.readline().strip()
            if first:
                return first
    return None


def _port_from_job_id(default: int = 8889) -> int:
    """Deterministic port derived from the job id (reference :171-185)."""
    from ..utils import flags

    port = flags.get(flags.MASTER_PORT)
    if port is not None:
        return port
    job = os.getenv("SLURM_JOB_ID") or os.getenv("LSB_JOBID") or os.getenv("PBS_JOBID")
    if job:
        digits = re.sub(r"\D", "", job) or "0"
        return 10000 + int(digits) % 50000
    return default


def setup_ddp(verbosity: int = 0) -> tuple[int, int]:
    """Initialize multi-host jax (the ``setup_ddp`` entry point, reference
    :151-280). Returns (world_size, rank). Safe to call in single-process
    runs (no-op) and idempotent."""
    import jax

    # live jax state FIRST: a caller that already ran
    # jax.distributed.initialize (tests, notebooks, torchrun-less launches)
    # has no scheduler env vars, and consulting the env cascade first would
    # return (1, 0) on EVERY process — each rank then loads the full
    # dataset (world x duplicated training data) while the SPMD step still
    # spans the global mesh. is_initialized() is side-effect-free;
    # process_count() would materialize the XLA backend, which breaks the
    # jax.distributed.initialize below on scheduler-launched ranks.
    if jax.distributed.is_initialized():
        return jax.process_count(), jax.process_index()
    world, rank = init_comm_size_and_rank()
    if world <= 1:
        return 1, 0

    from ..utils import flags

    coordinator = flags.get(flags.MASTER_ADDR) or _first_host_from_nodelist()
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = f"{coordinator}:{_port_from_job_id()}"
        kwargs["num_processes"] = world
        kwargs["process_id"] = rank
    # On Cloud TPU pods jax.distributed.initialize() self-configures.
    jax.distributed.initialize(**kwargs)
    return jax.process_count(), jax.process_index()


def get_comm_size_and_rank() -> tuple[int, int]:
    """Post-init world/rank (prefers live jax state over env)."""
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_count(), jax.process_index()
    except Exception:
        pass
    return init_comm_size_and_rank()
