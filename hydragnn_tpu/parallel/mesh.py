"""Device meshes + sharding policies — the TPU-native distributed runtime.

Replaces the reference's entire L2 layer (``hydragnn/utils/distributed/
distributed.py``): NCCL/Gloo/XCCL process groups, DDP/FSDP wrappers, and the
MPI data plane all collapse into XLA collectives over a ``jax.sharding.Mesh``.

Axes:
* ``data``   — batch parallelism (DDP equivalent). Batches are sharded along
  their leading axes; gradients are averaged by XLA-inserted all-reduce over
  ICI (replacing DDP's bucketed NCCL ring, ``distributed.py:396-481``).
* ``branch`` — model/task parallelism for multibranch foundation-model
  training (``MultiTaskModelMP``, reference ``models/MultiTaskModelMP.py:269-
  490``): encoder params replicated everywhere, per-branch decoder params
  live on their branch's submesh.
* FSDP equivalent: shard (large) parameters along ``data`` too
  (``param_sharding='fsdp'``) — XLA all-gathers them per layer, the same
  communication schedule ZeRO-3 hand-implements.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
BRANCH_AXIS = "branch"


def make_mesh(
    n_data: int | None = None,
    n_branch: int = 1,
    devices: Sequence | None = None,
) -> Mesh:
    """Build a (branch, data) mesh. Defaults to all devices on one data axis."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_branch
    if n_branch * n_data != len(devices):
        raise ValueError(
            f"mesh ({n_branch} branch x {n_data} data) != {len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(n_branch, n_data)
    return Mesh(arr, (BRANCH_AXIS, DATA_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """GraphBatch arrays shard along their leading (node/edge/graph) axis on
    the data axis — each device owns a slice of every padded batch."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_param_specs(params, mesh: Mesh, min_size_to_shard: int = 2**14):
    """ZeRO-3-style parameter sharding: biggest divisible axis -> data axis."""
    n_data = mesh.shape[DATA_AXIS]

    def spec_for(x):
        if x.ndim == 0 or x.size < min_size_to_shard:
            return P()
        for i in sorted(range(x.ndim), key=lambda i: -x.shape[i]):
            if x.shape[i] % n_data == 0:
                spec = [None] * x.ndim
                spec[i] = DATA_AXIS
                return P(*spec)
        return P()

    return jax.tree.map(spec_for, params)
