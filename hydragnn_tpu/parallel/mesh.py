"""Device meshes + sharding policies — the TPU-native distributed runtime.

Replaces the reference's entire L2 layer (``hydragnn/utils/distributed/
distributed.py``): NCCL/Gloo/XCCL process groups, DDP/FSDP wrappers, and the
MPI data plane all collapse into XLA collectives over a ``jax.sharding.Mesh``.

Axes:
* ``data``   — batch parallelism (DDP equivalent). Batches are sharded along
  their leading axes; gradients are averaged by XLA-inserted all-reduce over
  ICI (replacing DDP's bucketed NCCL ring, ``distributed.py:396-481``).
* ``branch`` — model/task parallelism for multibranch foundation-model
  training (``MultiTaskModelMP``, reference ``models/MultiTaskModelMP.py:269-
  490``): encoder params replicated everywhere, per-branch decoder params
  live on their branch's submesh.
* FSDP equivalent: shard (large) parameters along ``data`` too
  (``param_sharding='fsdp'``) — XLA all-gathers them per layer, the same
  communication schedule ZeRO-3 hand-implements.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
BRANCH_AXIS = "branch"
MODEL_AXIS = "model"


def make_mesh(
    n_data: int | None = None,
    n_branch: int = 1,
    n_model: int = 1,
    devices: Sequence | None = None,
) -> Mesh:
    """Build a (branch, data[, model]) mesh. Defaults to all devices on one
    data axis. ``n_model > 1`` adds a trailing tensor-parallel axis — keep it
    innermost so TP collectives ride the fastest ICI ring."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_branch * n_model)
    if n_branch * n_data * n_model != len(devices):
        raise ValueError(
            f"mesh ({n_branch} branch x {n_data} data x {n_model} model) "
            f"!= {len(devices)} devices"
        )
    if n_model > 1:
        arr = np.asarray(devices).reshape(n_branch, n_data, n_model)
        return Mesh(arr, (BRANCH_AXIS, DATA_AXIS, MODEL_AXIS))
    arr = np.asarray(devices).reshape(n_branch, n_data)
    return Mesh(arr, (BRANCH_AXIS, DATA_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """GraphBatch arrays shard along their leading (node/edge/graph) axis on
    the data axis — each device owns a slice of every padded batch."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def branch_param_specs(params, mesh: Mesh, min_size_to_shard: int = 0):
    """Multibranch decoder placement: branch decoder params (subtree keys
    carrying ``_branch-``) shard their largest divisible axis over the
    ``branch`` axis, so each device holds 1/n_branch of every decoder —
    total decoder memory per device equals ONE branch's decoders, the same
    footprint the reference gets by pinning a branch's decoder to its branch
    process group (``MultiTaskModelMP.py:269-490``). XLA all-gathers a
    decoder over the branch ring right before its (tiny) head matmul —
    ZeRO-3 scheduling on the branch axis. The shared encoder stays
    replicated."""
    n_branch = mesh.shape[BRANCH_AXIS]

    def spec_for_leaf(x):
        if n_branch == 1 or x.ndim == 0 or x.size < max(min_size_to_shard, n_branch):
            return P()
        for i in sorted(range(x.ndim), key=lambda i: -x.shape[i]):
            if x.shape[i] % n_branch == 0:
                spec = [None] * x.ndim
                spec[i] = BRANCH_AXIS
                return P(*spec)
        return P()

    out = {}
    for key, sub in params.items():
        if "_branch-" in key:
            out[key] = jax.tree.map(spec_for_leaf, sub)
        else:
            out[key] = jax.tree.map(lambda _: P(), sub)
    return out


def tp_param_specs(params, mesh: Mesh, min_size_to_shard: int = 2**10):
    """Tensor parallelism: shard every weight's feature (last) axis over the
    ``model`` axis — column-parallel dense layers in Megatron terms. The
    GSPMD partitioner propagates the activation shardings and inserts the
    all-gather/all-reduce pairs that hand-written TP implements explicitly,
    and they ride the innermost (fastest) ICI ring because ``model`` is the
    trailing mesh axis. Per-device parameter + activation memory for the
    hidden dimension drops to 1/n_model — the axis to grow when a model's
    hidden width, not the batch, is what no longer fits."""
    if MODEL_AXIS not in mesh.axis_names:
        raise ValueError("param_mode='tp' needs a mesh with a 'model' axis "
                         "(make_mesh(n_model=...))")
    n_model = mesh.shape[MODEL_AXIS]

    def spec_for(x):
        if x.ndim == 0 or x.size < min_size_to_shard:
            return P()
        if x.shape[-1] % n_model == 0:
            return P(*([None] * (x.ndim - 1)), MODEL_AXIS)
        return P()

    return jax.tree.map(spec_for, params)


def host_gather(tree):
    """Canonical single-replica HOST pytree from a (possibly sharded)
    device pytree — the layout-neutral form of a ``TrainState`` that any
    new mesh can be fed from. Fully-addressable leaves come back in ONE
    batched ``jax.device_get``; leaves this process cannot fully address
    (multi-host shardings) are materialized via
    ``multihost_utils.process_allgather``, so every host ends with the
    complete logical value. Non-array leaves pass through untouched."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten(tree)
    local_idx = [
        i for i, x in enumerate(flat)
        if hasattr(x, "shape") and getattr(x, "is_fully_addressable", True)
    ]
    fetched = jax.device_get([flat[i] for i in local_idx])
    out = list(flat)
    for i, a in zip(local_idx, fetched):
        out[i] = np.asarray(a)
    remote_idx = [
        i for i, x in enumerate(flat)
        if hasattr(x, "shape") and not getattr(x, "is_fully_addressable", True)
    ]
    if remote_idx:
        # ONE allgather over all non-addressable leaves as a single pytree
        # — per-leaf collectives would serialize hundreds of cross-host
        # round-trips on the restore path this function exists to serve
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            [flat[i] for i in remote_idx], tiled=True
        )
        for i, a in zip(remote_idx, gathered):
            out[i] = np.asarray(a)
    return jax.tree_util.tree_unflatten(treedef, out)


def place_like(tree, template):
    """Re-place ``tree``'s leaves with ``template``'s layout: NamedSharding
    leaves go to their mesh via ``jax.device_put`` (resharding across a
    DIFFERENT device count/mesh than the values came from — the elastic
    resume path); everything else becomes an UNCOMMITTED default-device
    array, exactly what ``create_train_state`` produced. Keeping restored
    state's placement identical to fresh state matters beyond correctness:
    a committed single-device placement would re-key the jit cache and the
    first post-restore dispatch would recompile every step program."""
    import jax
    import jax.numpy as jnp

    def one(r, t):
        sh = getattr(t, "sharding", None)
        if isinstance(sh, NamedSharding):
            # hand device_put the value AS IS: host arrays place directly,
            # and device arrays reshard without a host round-trip — a
            # device_get here would both waste a full-params host copy per
            # call and CRASH on multi-process leaves this host cannot
            # fully address (the rollback path restores those)
            return jax.device_put(r, sh)
        return jnp.asarray(np.asarray(r))

    return jax.tree.map(one, tree, template)


def fsdp_param_specs(params, mesh: Mesh, min_size_to_shard: int = 2**14):
    """ZeRO-3-style parameter sharding: biggest divisible axis -> data axis."""
    n_data = mesh.shape[DATA_AXIS]

    def spec_for(x):
        if x.ndim == 0 or x.size < min_size_to_shard:
            return P()
        for i in sorted(range(x.ndim), key=lambda i: -x.shape[i]):
            if x.shape[i] % n_data == 0:
                spec = [None] * x.ndim
                spec[i] = DATA_AXIS
                return P(*spec)
        return P()

    return jax.tree.map(spec_for, params)
