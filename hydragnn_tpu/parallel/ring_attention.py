"""Ring attention over node-sharded graphs — giant-graph global attention.

The brief's long-context requirement (ring / all-to-all context parallelism)
applied to graph learning: GPS global attention over ONE giant graph whose
node arrays are sharded across the mesh. Dense attention materializes
[N, N] logits — impossible at scale; ring attention never does:

* q/k/v stay sharded over the ``data`` axis ([N/D rows per device]);
* the K/V (+ graph-id/mask) shard rotates around the mesh ring via
  ``lax.ppermute`` (ICI neighbor hops, D-1 of them);
* each device folds one K/V block per hop into an ONLINE softmax
  (running max / denominator / weighted accumulator — the flash-attention
  recurrence), so peak memory is O(N/D · H · d) regardless of N.

Same-graph masking (``batch_ids`` equality) makes this the sharded
equivalent of ``GraphMultiheadAttention._flat_attention``; parity is tested
against it on the virtual 8-device mesh.

Used by GPS when ``global_attn_type: "ring"`` with an active mesh (set by
``run_training`` via ``set_global_mesh``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS

_NEG = -1e9

# Trace-time mesh context: the model module can't carry a Mesh (it's not a
# pytree leaf), so run_training publishes the active mesh here before the
# step is traced.
_GLOBAL_MESH: Mesh | None = None


def set_global_mesh(mesh: Mesh | None) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh | None:
    return _GLOBAL_MESH


def ring_attention(
    q: jax.Array,  # [N, H, Dh] node-sharded
    k: jax.Array,
    v: jax.Array,
    batch_ids: jax.Array,  # [N] graph id per node
    node_mask: jax.Array,  # [N] 1 for real nodes
    mesh: Mesh,
) -> jax.Array:
    """Masked same-graph softmax attention with rotating K/V shards."""
    n_dev = mesh.shape[DATA_AXIS]
    N, H, Dh = q.shape
    if N % n_dev:
        raise ValueError(f"node count {N} must divide the data axis ({n_dev})")
    scale = 1.0 / math.sqrt(Dh)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def local(q_b, bid_q, k_b, v_b, bid_kv, m_kv):
        # shard_map gives block-local arrays [n, ...]
        n = q_b.shape[0]

        def rotate(x):
            return jax.lax.ppermute(x, DATA_AXIS, perm)

        mx0 = jnp.full((n, H), _NEG, q_b.dtype)
        den0 = jnp.zeros((n, H), q_b.dtype)
        acc0 = jnp.zeros((n, H, Dh), q_b.dtype)

        def body(_, carry):
            k_c, v_c, bid_c, m_c, mx, den, acc = carry
            logits = jnp.einsum("nhd,mhd->nhm", q_b, k_c) * scale
            valid = (bid_q[:, None] == bid_c[None, :]) & (m_c[None, :] > 0)
            logits = jnp.where(valid[:, None, :], logits, _NEG)
            blk_mx = logits.max(axis=-1)  # [n, H]
            new_mx = jnp.maximum(mx, blk_mx)
            corr = jnp.exp(mx - new_mx)
            p = jnp.exp(logits - new_mx[..., None]) * valid[:, None, :]
            den = den * corr + p.sum(axis=-1)
            acc = acc * corr[..., None] + jnp.einsum("nhm,mhd->nhd", p, v_c)
            return (rotate(k_c), rotate(v_c), rotate(bid_c), rotate(m_c),
                    new_mx, den, acc)

        carry = (k_b, v_b, bid_kv, m_kv, mx0, den0, acc0)
        carry = jax.lax.fori_loop(0, n_dev, body, carry)
        _, _, _, _, _, den, acc = carry
        return acc / jnp.maximum(den, 1e-20)[..., None]

    split = P(DATA_AXIS)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(split, split, split, split, split, split),
        out_specs=split,
        check_rep=False,
    )(q, batch_ids, k, v, batch_ids, node_mask)
