"""Pipeline parallelism: GPipe-style microbatch pipelining of the conv stack
over a ``stage`` mesh axis.

The reference has no pipeline parallelism anywhere (SURVEY §2.5: TP/PP
absent); this is a TPU-native extension for DEEP stacks (many-layer
equivariant models) whose weights or activations outgrow one chip but whose
layer widths don't warrant tensor sharding.

Design
------
* Conv block 0 (the one non-uniform layer — it lifts ``input_dim`` to
  ``hidden_dim``) and the decode epilogue (pooling + heads) run replicated
  on every stage device; they are a tiny fraction of a deep stack's FLOPs.
* Conv blocks ``1..L-1`` must be parameter-homogeneous (same pytree of
  shapes, true for every registered stack at fixed hidden_dim). Their
  params are stacked to a ``[S, k, ...]`` pytree, sharded over the stage
  axis — each device materializes only its ``k = (L-1)/S`` layers.
* One ``shard_map`` program runs the classic GPipe schedule: ``T = M+S-1``
  ticks; at tick ``t`` stage ``s`` applies its ``k`` blocks (inner
  ``lax.scan`` over stacked layer params, each step re-applying the model's
  ``conv_block`` method with that layer's params substituted in) to
  microbatch ``t - s``, then hands the activation to stage ``s+1`` with a
  ``ppermute`` rotation around the ring. Stage 0 feeds fresh microbatch
  activations into the ring; the last stage's outputs are ``psum``-broadcast
  (every other stage contributes zeros).
* Autodiff goes straight through ``scan``+``ppermute`` — the backward pass
  is the reverse pipeline schedule, derived by AD instead of hand-scheduled.

Semantics: pipelined execution is deterministic — conv dropout is disabled
(GAT with ``dropout > 0`` is rejected up front rather than silently
differing from the data-parallel path). Feature-norm statistics are
selectable via ``norm``:

* ``"batch"`` (default): each conv block normalizes with the CURRENT
  microbatch's statistics — the data-parallel train step's semantics, and
  the only stable choice for deep stacks (a 9-layer GIN on init running
  stats blows activations up ~degree^L, producing astronomically large but
  "finite" losses — the round-2 dryrun's loss=7.2e7). The TRAIN step also
  accumulates running stats (one EMA step per microbatch, averaged — the
  data-parallel step's replica-mean semantics), so a pipelined checkpoint
  later evaluates/fine-tunes on the data-parallel path from real statistics
  rather than init values.
* ``"running"``: eval-mode running averages — bit-exact parity with the
  sequential ``encode(train=False)`` path (what the exact-parity tests pin).

Resilience pass-through: the pipelined train step keeps the generic
``(state, batch) -> (state, metrics)`` contract, so the non-finite step
guard (``resilience/guard.py``) wraps it unchanged in the epoch loop — a
NaN in any microbatch reaches the accumulated loss and the stage-replicated
update is select-skipped in the same dispatch. Divergence rollback and
preemption checkpointing live at the loop/checkpoint layer and need nothing
stage-aware; only supersteps stay pinned at K=1 (``put_microbatches`` is a
per-step placement with no stacked [K, ...] form yet).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graphs.graph import GraphBatch
from ..models.base import CONV_REGISTRY, HydraModel
from ..train.step import TrainState, _cast_floats

STAGE_AXIS = "stage"


def make_pipeline_mesh(n_stage: int, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())[:n_stage]
    if len(devices) != n_stage:
        raise ValueError(f"need {n_stage} devices for {n_stage} stages")
    return Mesh(np.asarray(devices), (STAGE_AXIS,))


def validate_pipeline_support(model: HydraModel, n_stage: int) -> int:
    """Return layers-per-stage k; raise for unsupported configurations."""
    spec = model.spec
    L = spec.num_conv_layers
    if spec.global_attn_engine:
        raise ValueError("pipeline parallelism does not compose with global "
                         "attention engines yet")
    conv_cls = CONV_REGISTRY[spec.mpnn_type]
    if getattr(conv_cls, "collect_layer_outputs", False):
        raise ValueError(f"{spec.mpnn_type} reads every layer's output "
                         "(collect_layer_outputs) — not pipelineable")
    if spec.mpnn_type == "GAT" and spec.dropout > 0:
        raise ValueError(
            "pipelined execution is dropout-free (conv blocks run "
            "deterministically); set Architecture.dropout to 0 for GAT "
            "under pipeline parallelism"
        )
    if L < n_stage + 1:
        raise ValueError(f"{L} conv layers cannot fill {n_stage} stages "
                         "(block 0 is the prologue; need num_conv_layers >= "
                         "n_stage + 1)")
    if (L - 1) % n_stage:
        raise ValueError(f"{L - 1} pipelined layers not divisible by "
                         f"{n_stage} stages")
    return (L - 1) // n_stage


def _layer_tree(params: dict, stats: dict, i: int) -> dict:
    t = {"conv": params[f"graph_convs_{i}"]}
    if f"feature_norm_{i}" in params:
        t["norm_p"] = params[f"feature_norm_{i}"]
    if f"feature_norm_{i}" in stats:
        t["norm_s"] = stats[f"feature_norm_{i}"]
    return t


def _stack_layer_params(params: dict, stats: dict, L: int, S: int, k: int):
    """Stack per-layer subtrees for blocks 1..L-1 into a [S, k, ...] pytree.

    Raises a clear error when layer params are not shape-homogeneous (the
    judge of pipelineability — e.g. stacks whose layers vary in width)."""
    trees = [_layer_tree(params, stats, i) for i in range(1, L)]
    shapes = [jax.tree.map(jnp.shape, t) for t in trees]
    if any(s != shapes[0] for s in shapes[1:]):
        raise ValueError(
            "conv blocks 1..L-1 are not parameter-homogeneous; "
            f"got per-layer shapes {shapes}"
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
    return jax.tree.map(lambda x: x.reshape(S, k, *x.shape[1:]), stacked)


def make_pipelined_forward(
    model: HydraModel, mesh: Mesh, n_micro: int, norm: str = "batch",
    collect_stats: bool = False,
):
    """Build ``fn(variables, microbatches) -> (inv, equiv)`` where
    ``microbatches`` is a GraphBatch stacked to ``[M, ...]`` (see
    ``parallel.stack_device_batches``) and the result carries the encoded
    node features per microbatch ``[M, N, H]``. ``norm``: see module
    docstring ("batch" = per-microbatch statistics, "running" = frozen
    running averages).

    ``collect_stats=True`` (requires ``norm="batch"``) returns
    ``(inv, equiv, new_batch_stats)``: each feature norm's running stats
    after one EMA step per microbatch (from the same old stats), averaged
    over microbatches — identical semantics to the data-parallel step's
    replica-mean stat update. Prologue stats come off the vmapped block-0
    pass; blocks 1..L-1 accumulate valid-tick stats on each stage and leave
    the ring stacked ``[L-1, ...]`` over the stage axis."""
    S = mesh.shape[STAGE_AXIS]
    k = validate_pipeline_support(model, S)
    L = model.spec.num_conv_layers
    M = n_micro
    if norm not in ("batch", "running"):
        raise ValueError(f"norm must be 'batch' or 'running', got {norm!r}")
    if collect_stats and norm != "batch":
        raise ValueError("collect_stats requires norm='batch' (running-stat "
                         "EMA steps are computed from per-microbatch stats)")
    use_batch_stats = norm == "batch"

    def forward(variables, mb: GraphBatch):
        got = jax.tree.leaves(mb)[0].shape[0]
        if got != M:
            raise ValueError(
                f"stacked microbatch has leading dim {got}, expected "
                f"n_micro={M}"
            )
        params = variables["params"]
        stats = variables.get("batch_stats", {})
        collect_ring = collect_stats and "feature_norm_1" in stats

        # prologue: embed + block 0, vmapped over microbatches (replicated)
        def prologue(b):
            if use_batch_stats:
                out, upd = model.apply(variables, b, True,
                                       method=HydraModel.embed_block0,
                                       mutable=["batch_stats"])
                return out, upd.get("batch_stats", {})
            return model.apply(variables, b, False,
                               method=HydraModel.embed_block0), {}

        (inv0, equiv0), pro_upd = jax.vmap(prologue)(mb)

        stacked = _stack_layer_params(params, stats, L, S, k)

        def apply_block(p_tree, inv, equiv, b):
            """Re-apply the model's conv_block(1) with this layer's params
            substituted — the scanned pipeline body. Returns the block
            output and (when normalizing by batch stats) the layer's
            EMA-stepped ``feature_norm_1`` stats subtree."""
            sub_params = dict(params, **{"graph_convs_1": p_tree["conv"]})
            sub_vars = {"params": sub_params}
            if "norm_p" in p_tree:
                sub_params["feature_norm_1"] = p_tree["norm_p"]
            if stats or "norm_s" in p_tree:
                sub_stats = dict(stats)
                if "norm_s" in p_tree:
                    sub_stats["feature_norm_1"] = p_tree["norm_s"]
                sub_vars["batch_stats"] = sub_stats
            if use_batch_stats:
                out, upd = model.apply(sub_vars, 1, inv, equiv, b, True,
                                       method=HydraModel.conv_block,
                                       mutable=["batch_stats"])
                return out, upd.get("batch_stats", {}).get("feature_norm_1", {})
            return model.apply(sub_vars, 1, inv, equiv, b, False,
                               method=HydraModel.conv_block), {}

        def stage_fn(my_params, inv0, equiv0, mb):
            my_params = jax.tree.map(lambda x: x[0], my_params)  # [k, ...]
            sidx = jax.lax.axis_index(STAGE_AXIS)
            T = M + S - 1
            perm = [(i, (i + 1) % S) for i in range(S)]
            acc0 = (
                jax.tree.map(jnp.zeros_like, my_params["norm_s"])
                if collect_ring else None
            )

            def tick(carry, t):
                inv_c, equiv_c, acc = carry
                m = jnp.clip(t - sidx, 0, M - 1)
                b = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, False), mb
                )
                fresh_inv = jax.lax.dynamic_index_in_dim(inv0, m, 0, False)
                fresh_equiv = jax.lax.dynamic_index_in_dim(equiv0, m, 0, False)
                inv_in = jnp.where(sidx == 0, fresh_inv, inv_c)
                equiv_in = jnp.where(sidx == 0, fresh_equiv, equiv_c)

                def lay(c, p):
                    out, upd = apply_block(p, c[0], c[1], b)
                    return out, upd

                (inv_out, equiv_out), upds = jax.lax.scan(
                    lay, (inv_in, equiv_in), my_params
                )
                if acc is not None:
                    # bubble ticks recompute a clipped microbatch on a junk
                    # ring carry — where-select (not multiply) keeps any
                    # non-finite garbage out of the accumulator
                    valid = (t >= sidx) & (t - sidx < M)
                    acc = jax.tree.map(
                        lambda a, u: a + jnp.where(valid, u, 0), acc, upds
                    )
                send = jax.tree.map(
                    lambda a: jax.lax.ppermute(a, STAGE_AXIS, perm),
                    (inv_out, equiv_out),
                )
                # only the last stage's result is the stack output; psum
                # broadcasts it. where-select (not multiply-mask) so a
                # non-finite value from a bubble-tick zero carry can never
                # leak through as 0*inf=NaN
                is_last = sidx == S - 1
                y = jax.lax.psum(
                    (jnp.where(is_last, inv_out, 0),
                     jnp.where(is_last, equiv_out, 0)),
                    STAGE_AXIS,
                )
                return (send[0], send[1], acc), y

            zero = (jnp.zeros_like(inv0[0]), jnp.zeros_like(equiv0[0]), acc0)
            (_, _, acc), ys = jax.lax.scan(tick, zero, jnp.arange(T))
            # microbatch m completes at tick m + S - 1
            out = jax.tree.map(lambda a: a[S - 1 : S - 1 + M], ys)
            if collect_ring:
                # each stage saw each of its microbatches once -> mean
                return out, jax.tree.map(lambda a: a / M, acc)
            return out

        from jax.experimental.shard_map import shard_map

        out = shard_map(
            stage_fn,
            mesh=mesh,
            in_specs=(P(STAGE_AXIS), P(), P(), P()),
            out_specs=((P(), P()), P(STAGE_AXIS)) if collect_ring else P(),
            check_rep=False,
        )(stacked, inv0, equiv0, mb)
        ring = None
        if collect_ring:
            (inv, equiv), ring = out
        else:
            inv, equiv = out
        if not collect_stats:
            return inv, equiv
        # assemble the updated batch_stats pytree: prologue norms from the
        # vmapped pass (node-count-weighted mean over microbatches, so a
        # fill microbatch padding a trailing group carries zero stat
        # weight), ring norms unstacked from the [L-1, ...] stage-axis
        # output
        from .step import merge_replica_stats

        new_stats = dict(stats)
        new_stats.update(
            merge_replica_stats(pro_upd, jax.vmap(lambda b: b.node_mask.sum())(mb))
        )
        if collect_ring:
            for i in range(1, L):
                key = f"feature_norm_{i}"
                if key in stats:
                    new_stats[key] = jax.tree.map(lambda x: x[i - 1], ring)
        return inv, equiv, jax.lax.stop_gradient(new_stats)

    return forward


def make_pipelined_train_step(
    model: HydraModel, optimizer, mesh: Mesh, n_micro: int,
    compute_dtype=jnp.float32, norm: str = "batch", loss_scale=None,
):
    """Jitted pipelined train step: (state, microbatches[M, ...]) ->
    (state, metrics). Loss is the graph-weighted mean over microbatches,
    the same bookkeeping as the data-parallel step. With the default
    ``norm="batch"``, feature-norm RUNNING stats update too: one EMA step
    per microbatch, microbatch-averaged — the same semantics as the
    data-parallel step's replica-mean update, so a pipelined checkpoint
    evaluates/fine-tunes identically on the data-parallel path.

    ``loss_scale`` as in ``train.step._make_step_impl`` (static fp16-class
    scaling; None/1 keeps the historical program byte-for-byte): the scaled
    loss feeds the backward pass, the fp32-cast grads divide the scale back
    out, and metrics report the UNSCALED loss via aux."""
    collect = norm == "batch"
    loss_scale = None if not loss_scale or float(loss_scale) == 1.0 else float(loss_scale)
    encode = make_pipelined_forward(model, mesh, n_micro, norm=norm,
                                    collect_stats=collect)
    conv_cls = CONV_REGISTRY[model.spec.mpnn_type]
    if not collect and getattr(conv_cls, "feature_norm", True):
        import warnings

        warnings.warn(
            "pipelined training with norm='running' freezes feature-norm "
            "running stats at their initial values (scale/bias still train).",
            stacklevel=2,
        )

    def loss_fn(params, batch_stats, mb: GraphBatch):
        c_params = _cast_floats(params, compute_dtype)
        c_mb = _cast_floats(mb, compute_dtype)
        variables = {"params": c_params, "batch_stats": batch_stats}
        if collect:
            inv, equiv, new_stats = encode(variables, c_mb)
        else:
            inv, equiv = encode(variables, c_mb)
            new_stats = batch_stats

        def per_micro(inv_m, equiv_m, b, b_raw):
            pred = model.apply(variables, inv_m, equiv_m, b, False,
                               method=HydraModel.decode)
            pred = _cast_floats(pred, jnp.float32)
            tot, tasks = model.loss(pred, b_raw)
            ng = b_raw.graph_mask.sum()
            return tot * ng, jnp.stack(tasks) * ng, ng

        tots, tasks, ngs = jax.vmap(per_micro)(inv, equiv, c_mb, mb)
        denom = jnp.maximum(ngs.sum(), 1.0)
        loss = tots.sum() / denom
        aux = (tasks.sum(axis=0) / denom, ngs.sum(), new_stats)
        if loss_scale is not None:
            # differentiate the scaled loss; the unscaled one rides out via
            # aux so metrics never see the scale
            return loss * loss_scale, (loss,) + aux
        return loss, aux

    from ..train.step import donate_state_argnums as _donate

    @partial(jax.jit, donate_argnums=_donate())
    def train_step(state: TrainState, mb: GraphBatch):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, mb)
        from ..train.step import freeze_conv_grads

        grads = _cast_floats(grads, jnp.float32)
        if loss_scale is not None:
            # un-scale AFTER the fp32 cast (2^k scales divide back exactly)
            loss, tasks, ng, new_stats = aux
            grads = jax.tree.map(lambda g: g / loss_scale, grads)
        else:
            tasks, ng, new_stats = aux
        grads = freeze_conv_grads(grads, model.spec)
        updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                                  state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=jax.tree.map(
                lambda x: x.astype(jnp.float32) if hasattr(x, "astype") else x,
                new_stats,
            ),
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        return new_state, {"loss": loss, "tasks_loss": tasks, "num_graphs": ng}

    return train_step


def make_pipelined_eval_step(
    model: HydraModel, mesh: Mesh, n_micro: int,
    compute_dtype=jnp.float32, norm: str = "running",
):
    """Pipelined evaluation: same metrics dict as the data-parallel eval step
    (loss, per-task losses, per-head sse/count, graph count) so the epoch
    loop consumes either interchangeably. ``norm`` defaults to "running" —
    eval-mode running averages, the data-parallel eval step's semantics.
    Running stats accumulate during pipelined training (see
    ``make_pipelined_train_step``), so this keeps the LR scheduler (which
    steps on val loss) on the same trajectory as a data-parallel run."""
    encode = make_pipelined_forward(model, mesh, n_micro, norm=norm)

    @jax.jit
    def eval_step(state: TrainState, mb: GraphBatch):
        c_params = _cast_floats(state.params, compute_dtype)
        c_mb = _cast_floats(mb, compute_dtype)
        variables = {"params": c_params, "batch_stats": state.batch_stats}
        inv, equiv = encode(variables, c_mb)

        def per_micro(inv_m, equiv_m, b, b_raw):
            pred = model.apply(variables, inv_m, equiv_m, b, False,
                               method=HydraModel.decode)
            pred = _cast_floats(pred, jnp.float32)
            tot, tasks = model.loss(pred, b_raw)
            sses, counts = model.head_sse(pred, b_raw)
            ng = b_raw.graph_mask.sum()
            return (tot * ng, jnp.stack(tasks) * ng, jnp.stack(sses),
                    jnp.stack(counts), ng)

        tots, tasks, sses, counts, ngs = jax.vmap(per_micro)(inv, equiv, c_mb, mb)
        denom = jnp.maximum(ngs.sum(), 1.0)
        return {
            "loss": tots.sum() / denom,
            "tasks_loss": tasks.sum(axis=0) / denom,
            "head_sse": sses.sum(axis=0),
            "head_count": counts.sum(axis=0),
            "num_graphs": ngs.sum(),
        }

    return eval_step


def put_microbatches(mb: GraphBatch, mesh: Mesh) -> GraphBatch:
    """Place a [M, ...] stacked GraphBatch replicated over the stage mesh."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), mb)
