"""Halo-exchange message passing — node-RESIDENT giant graphs.

The third large-graph route, next to plain data parallelism and the
replicated-node edge sharding in ``large_graph.py``. Edge sharding keeps
every node feature on every device and all-reduces the whole ``[N, F]``
accumulator once per conv layer, so per-device memory AND per-layer comm
scale with TOTAL graph size. Here the graph is partitioned *spatially*
(``graphs/partition.py``: cell-list grid, Morton-ordered, count-balanced
contiguous ranges) and each device keeps only

* its OWNED nodes (features, labels, masks — 1/D of the graph at rest),
* its OWNED edges (every edge whose RECEIVER it owns — so each device can
  aggregate its own nodes' messages completely), and
* HALO slots: read-only copies of the remote senders its owned edges touch.

Before every conv layer after the first, ONLY the halo rows are refreshed:
a static ring schedule of ``lax.ppermute`` steps (shift 1 .. D-1 over the
data axis) moves each boundary row from its owner into the neighbors' halo
slots. Morton partitions keep boundaries thin, so the bytes on the wire are
proportional to the partition SURFACE — not to N like the replicated
all-reduce (the bench row ``halo_exchange_ab`` reports the analytic ratio).

The whole exchange is one *static plan* built host-side at collate time
(``HaloPlan``): per-shift send/recv index lists, bucket-padded so the jit
program stays shape-stable across batches. Index VALUES are data — a new
frame with the same buckets reuses the compiled step. Autodiff handles the
reverse exchange for free: the transpose of ``ppermute`` is the inverted
permutation, so halo cotangents flow back to the owner's rows inside the
same backward pass.

Resilience: the steps keep the generic ``(state, batch) -> (state,
metrics)`` contract, so the non-finite guard wraps them unchanged; like the
other K=1-pinned layouts (edge-sharded, pipeline) a device loss routes to
``plan_remesh``'s restart fallback — the partition count is baked into the
program.

Config: ``NeuralNetwork.Architecture.halo`` (single-sourced from
``HaloConfig``) routes ``run_training`` here; env ``HYDRAGNN_HALO``
overrides the ``enabled`` key.
"""

from __future__ import annotations

import dataclasses
from functools import partial as _partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graphs.graph import GraphBatch
from ..graphs.partition import boundary_sets, partition_nodes
from ..graphs.segment import segment_count
from ..models.base import HydraModel
from ..train.step import (
    TrainState,
    _cast_floats,
    donate_state_argnums,
    freeze_conv_grads,
)
from .mesh import DATA_AXIS


# -- config -------------------------------------------------------------------

@dataclasses.dataclass
class HaloConfig:
    """``Architecture.halo`` block — the single source of its defaults.

    ``partitions``      0 = one partition per data-axis device (the only
                        supported value today; a nonzero value must match).
    ``slot_multiple``   halo send/recv slot lists are padded up to this
                        multiple per ring shift — the shape-stability bucket
                        (bigger = fewer recompiles across frames, more pad).
    ``node_multiple`` / ``edge_multiple``
                        per-device node/edge array buckets, same role.
    ``fallback``        what to do when the model or batch is outside halo
                        support: "error" fails fast, "data" falls back to
                        plain data-parallel steps with a log line.
    """

    enabled: bool = False
    partitions: int = 0
    slot_multiple: int = 8
    node_multiple: int = 8
    edge_multiple: int = 128
    fallback: str = "error"

    def validate(self) -> "HaloConfig":
        if self.partitions < 0:
            raise ValueError(f"halo.partitions must be >= 0, got {self.partitions}")
        for key in ("slot_multiple", "node_multiple", "edge_multiple"):
            if int(getattr(self, key)) < 1:
                raise ValueError(f"halo.{key} must be >= 1, got {getattr(self, key)}")
        if self.fallback not in ("error", "data"):
            raise ValueError(
                f"halo.fallback must be 'error' or 'data', got {self.fallback!r}"
            )
        return self


def halo_config_defaults() -> dict:
    return dataclasses.asdict(HaloConfig())


def halo_config(arch_cfg: dict | None) -> HaloConfig:
    """Typed view of ``Architecture.halo`` with defaults back-filled."""
    raw = dict((arch_cfg or {}).get("halo") or {})
    cfg = {**halo_config_defaults(), **raw}
    return HaloConfig(**cfg).validate()


def halo_enabled(arch_cfg: dict | None) -> bool:
    """``HYDRAGNN_HALO`` env flag wins over ``Architecture.halo.enabled``."""
    from ..utils import flags

    cfg = ((arch_cfg or {}).get("halo") or {})
    return bool(flags.get(flags.HALO, default=bool(cfg.get("enabled", False))))


# -- support surface ----------------------------------------------------------

# Conv stacks whose aggregation is receiver-directed (messages land on the
# edge's receiver): owning every in-edge of an owned node makes the local
# aggregate exact, and halo rows only ever serve as gather sources.
HALO_SUPPORTED_CONVS = frozenset(
    {"GIN", "GAT", "PNA", "PNAPlus", "SAGE", "MFC", "CGCNN", "SchNet"}
)


def validate_halo_support(spec) -> None:
    """Fail fast on model features the partitioned step cannot reproduce
    bit-for-bit. Mirrors the edge-sharded path's explicit rejections."""
    if spec.mpnn_type not in HALO_SUPPORTED_CONVS:
        raise ValueError(
            f"halo partitioning does not support mpnn_type={spec.mpnn_type!r} "
            f"(receiver-directed stacks only: {sorted(HALO_SUPPORTED_CONVS)}; "
            "DimeNet triplets and MACE per-layer readouts cross partitions)"
        )
    if spec.equivariance:
        raise ValueError(
            "halo partitioning does not support equivariance: coordinate "
            "updates aggregate by SENDER, and a sender owned elsewhere would "
            "drop its contribution (needs a reverse halo reduction)"
        )
    if spec.global_attn_engine:
        raise ValueError(
            "halo partitioning does not support global attention "
            f"({spec.global_attn_engine}): it is all-to-all over nodes by "
            "construction — use replicated edge_sharding instead"
        )
    if spec.sync_batch_norm:
        raise ValueError(
            "SyncBatchNorm is not supported with halo partitioning: the graph "
            "is ONE giant sample; feature-norm statistics are already psum'd "
            "over the data axis by the halo step itself"
        )
    if spec.enable_interatomic_potential:
        raise ValueError(
            "halo partitioning does not support the interatomic-potential "
            "loss yet: force autograd differentiates through positions that "
            "live on other devices"
        )
    for b in spec.node_heads:
        if (b.node_type or "mlp") != "mlp":
            raise ValueError(
                f"halo partitioning supports only 'mlp' node heads, got "
                f"{b.node_type!r}: per-position banks index GLOBAL node "
                "positions and conv heads need their own halo refreshes"
            )


# -- static plan --------------------------------------------------------------

class HaloPlan(NamedTuple):
    """Static ring-exchange schedule. For each shift ``s`` (1-indexed by
    position: entry ``i`` is shift ``i + 1``):

    ``send_idx[i]``  [D, S_i] — per device, LOCAL indices (into the owned
                     region) of the rows it must send to device ``d + s``;
                     padded with 0 (a real owned row whose copy lands in a
                     trash slot on the receiver).
    ``recv_slot[i]`` [D, S_i] — per device, LOCAL indices (into the halo
                     region) where the rows arriving from device ``d - s``
                     land; padded with the trash slot ``N_loc - 1``.

    Both sides order a pair's rows by ascending GLOBAL node id, so position
    k of a send buffer is position k of the matching recv list. All leaves
    are data — only the bucket-padded widths are baked into the program.
    """

    send_idx: tuple
    recv_slot: tuple


class HaloBatch(NamedTuple):
    """One partitioned frame: every ``batch`` leaf is stacked ``[D, ...]``
    (device d's local view at index d) and placed with its leading axis on
    the mesh's data axis. ``node_global`` ([D, N_loc], -1 = pad) and
    ``n_owned`` ([D]) ride along for host-side reassembly of node-level
    predictions; the step programs never read them."""

    batch: GraphBatch
    plan: HaloPlan
    node_global: jax.Array
    n_owned: jax.Array


def _round_up(v: int, m: int) -> int:
    return int(-(-int(v) // int(m)) * int(m))


# GraphBatch fields gathered per-node / per-edge / per-graph when building
# the local views (everything else is re-derived or replicated).
_NODE_GATHER = ("x", "pos", "node_y", "forces_y", "pe", "z")
_GRAPH_REPLICATE = (
    "graph_attr", "graph_y", "energy_y", "graph_mask", "dataset_id"
)


def partition_graph_batch(
    batch: GraphBatch,
    n_parts: int,
    cfg: HaloConfig | None = None,
    cutoff: float | None = None,
) -> HaloBatch:
    """Split ONE collated single-graph batch into ``n_parts`` device-local
    views + the static exchange plan. Host-side numpy; deterministic.

    Requires exactly one real graph (the giant-graph regime this route
    exists for — the loader runs ``batch_size=1``); the dummy padding graph
    is preserved, so every local view keeps the collate padding convention:
    padded nodes/edges point at slot ``N_loc - 1`` of graph ``G - 1``.
    """
    cfg = cfg or HaloConfig()
    arr = {f: np.asarray(getattr(batch, f)) for f in GraphBatch._fields[:-1]}
    n_real_graphs = int(arr["graph_mask"].sum())
    if n_real_graphs != 1:
        raise ValueError(
            f"halo partitioning expects exactly 1 real graph per batch, got "
            f"{n_real_graphs} (set Training.batch_size=1 for the giant-graph "
            "regime)"
        )
    if n_parts < 2:
        raise ValueError(f"halo partitioning needs >= 2 partitions, got {n_parts}")
    G = arr["graph_y"].shape[0]
    n_real = int(np.round(arr["node_mask"].sum()))
    e_real = int(np.round(arr["edge_mask"].sum()))
    # collate packs real rows first; padding is the tail
    pos = arr["pos"][:n_real]
    senders = arr["senders"][:e_real].astype(np.int64)
    receivers = arr["receivers"][:e_real].astype(np.int64)

    plan = partition_nodes(pos, n_parts, cutoff=cutoff)
    owner = plan.owner
    halos = boundary_sets(senders, receivers, owner, n_parts)

    owned = [plan.part(p) for p in range(n_parts)]
    # halo layout per device: grouped by source partition ascending, each
    # group ascending by global id (the same order the plan's send side uses)
    halo_ids = [
        np.concatenate(
            [halos.get((src, d), np.zeros(0, np.int32)) for src in range(n_parts)]
        ).astype(np.int64)
        for d in range(n_parts)
    ]
    n_owned = np.array([len(o) for o in owned], np.int64)
    recv_owner = owner[receivers]
    edge_of = [np.nonzero(recv_owner == d)[0] for d in range(n_parts)]

    n_loc = _round_up(
        int(max(n_owned[d] + len(halo_ids[d]) for d in range(n_parts))) + 1,
        cfg.node_multiple,
    )
    e_loc = _round_up(
        max(int(max(len(e) for e in edge_of)), 1), cfg.edge_multiple
    )

    # global id -> local slot, per device (owned region then halo region)
    loc_of = []
    for d in range(n_parts):
        m = np.full(n_real, -1, np.int64)
        m[owned[d]] = np.arange(len(owned[d]))
        m[halo_ids[d]] = n_owned[d] + np.arange(len(halo_ids[d]))
        loc_of.append(m)

    fields = {name: [] for name in GraphBatch._fields[:-1]}
    node_global = np.full((n_parts, n_loc), -1, np.int32)
    for d in range(n_parts):
        gids = np.concatenate([owned[d], halo_ids[d]])
        n_here = len(gids)
        node_global[d, :n_here] = gids
        for name in _NODE_GATHER:
            src = arr[name]
            out = np.zeros((n_loc,) + src.shape[1:], src.dtype)
            out[:n_here] = src[gids]
            fields[name].append(out)
        batch_ids = np.full(n_loc, G - 1, arr["batch"].dtype)
        batch_ids[: n_owned[d]] = 0  # halo + pad rows sit in the dummy graph
        fields["batch"].append(batch_ids)
        node_mask = np.zeros(n_loc, arr["node_mask"].dtype)
        node_mask[: n_owned[d]] = 1.0
        fields["node_mask"].append(node_mask)

        eids = edge_of[d]
        snd = np.full(e_loc, n_loc - 1, arr["senders"].dtype)
        rcv = np.full(e_loc, n_loc - 1, arr["receivers"].dtype)
        snd[: len(eids)] = loc_of[d][senders[eids]]
        rcv[: len(eids)] = loc_of[d][receivers[eids]]
        fields["senders"].append(snd)
        fields["receivers"].append(rcv)
        emask = np.zeros(e_loc, arr["edge_mask"].dtype)
        emask[: len(eids)] = 1.0
        fields["edge_mask"].append(emask)
        for name in ("edge_attr", "edge_shifts", "rel_pe"):
            src = arr[name]
            out = np.zeros((e_loc,) + src.shape[1:], src.dtype)
            out[: len(eids)] = src[eids]
            fields[name].append(out)
        nn = np.zeros(G, arr["n_node"].dtype)
        nn[0] = n_owned[d]
        fields["n_node"].append(nn)
        for name in _GRAPH_REPLICATE:
            fields[name].append(arr[name])
        # triplets cross partitions — DimeNet is rejected by
        # validate_halo_support, so local views carry empty triplet arrays
        for name in ("idx_kj", "idx_ji"):
            fields[name].append(np.zeros(0, arr[name].dtype))
        fields["triplet_mask"].append(np.zeros(0, arr["triplet_mask"].dtype))

    stacked = GraphBatch(
        *[np.stack(fields[name]) for name in GraphBatch._fields[:-1]],
        meta=None,
    )

    send_steps, recv_steps = [], []
    for shift in range(1, n_parts):
        widths = [
            len(halos.get((d, (d + shift) % n_parts), ())) for d in range(n_parts)
        ]
        s_w = _round_up(max(widths), cfg.slot_multiple) if max(widths) else 0
        send = np.zeros((n_parts, s_w), np.int32)
        recv = np.full((n_parts, s_w), n_loc - 1, np.int32)
        for d in range(n_parts):
            dst = (d + shift) % n_parts
            ids = halos.get((d, dst))
            if ids is not None:
                send[d, : len(ids)] = loc_of[d][ids]  # owned rows on d
                recv[dst, : len(ids)] = loc_of[dst][ids]  # halo slots on dst
        send_steps.append(send)
        recv_steps.append(recv)

    return HaloBatch(
        batch=stacked,
        plan=HaloPlan(send_idx=tuple(send_steps), recv_slot=tuple(recv_steps)),
        node_global=node_global,
        n_owned=n_owned.astype(np.int32),
    )


def put_halo_batch(
    batch: GraphBatch,
    mesh: Mesh,
    cfg: HaloConfig | None = None,
    cutoff: float | None = None,
) -> HaloBatch:
    """Partition + place one frame: every leaf's leading (device) axis lands
    on the mesh's data axis, so each device holds exactly its local view."""
    cfg = cfg or HaloConfig()
    n_dev = mesh.shape[DATA_AXIS]
    if cfg.partitions and cfg.partitions != n_dev:
        raise ValueError(
            f"halo.partitions={cfg.partitions} != data-axis size {n_dev}; "
            "set 0 to follow the mesh"
        )
    hbatch = partition_graph_batch(batch, n_dev, cfg=cfg, cutoff=cutoff)
    sh = NamedSharding(mesh, P(DATA_AXIS))
    return jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), sh), hbatch)


# -- analytic comm model ------------------------------------------------------

def halo_boundary_bytes(plan: HaloPlan, feat_dim: int, bytes_per_el: int = 4) -> int:
    """Fabric bytes ONE conv layer's halo refresh moves, summed over devices:
    every ring step ships its bucket-padded [S, F] buffer from each device."""
    rows = sum(int(s.shape[0]) * int(s.shape[1]) for s in plan.send_idx)
    return rows * int(feat_dim) * int(bytes_per_el)


def replicated_allreduce_bytes(
    n_nodes: int, feat_dim: int, n_dev: int, bytes_per_el: int = 4
) -> int:
    """Fabric bytes one ring all-reduce of the replicated [N, F] accumulator
    moves, summed over devices: 2 (N F / D) (D - 1) per device (reduce-scatter
    + all-gather), x D devices — the per-layer cost of the edge-sharded
    route this module replaces."""
    return 2 * (int(n_dev) - 1) * int(n_nodes) * int(feat_dim) * int(bytes_per_el)


# -- shard_map'd steps --------------------------------------------------------

def _halo_model(model: HydraModel) -> HydraModel:
    """The same architecture with feature-norm statistics psum'd over the
    data axis — under a partitioned node set, per-device BatchNorm moments
    are not the union-graph moments (parameter tree is unchanged, so the
    caller's TrainState is used as-is)."""
    return HydraModel(
        spec=dataclasses.replace(model.spec, bn_sync_axis=DATA_AXIS)
    )


def _squeeze_local(tree):
    return jax.tree.map(lambda a: a[0], tree)


def _refresh_fn(plan_local, n_dev):
    """Per-device halo refresh: for each ring shift, gather the boundary
    rows, rotate them ``shift`` devices down the data axis, scatter into the
    matching halo slots. Gathers touch only the owned region and scatters
    only the halo region, so steps compose in any order."""
    def refresh(inv, equiv):
        h = inv
        for i, (snd, rcv) in enumerate(plan_local):
            if snd.shape[0] == 0:
                continue  # statically empty shift (bucket width 0)
            shift = i + 1
            perm = [(d, (d + shift) % n_dev) for d in range(n_dev)]
            h = h.at[rcv].set(jax.lax.ppermute(h[snd], DATA_AXIS, perm))
        return h, equiv

    return refresh


def _pool_reduce_fn(kind: str, batch: GraphBatch):
    """Merge per-device partial graph readouts into the union-graph pooled
    value, matching the single-device reduction per pooling kind."""
    if kind in ("add", "sum"):
        return lambda pooled: jax.lax.psum(pooled, DATA_AXIS)
    if kind == "mean":
        def merge(pooled):
            cnt = segment_count(
                batch.batch, batch.num_graphs, weights=batch.node_mask
            )
            num = jax.lax.psum(pooled * cnt[:, None], DATA_AXIS)
            den = jax.lax.psum(cnt, DATA_AXIS)
            return num / jnp.maximum(den, 1e-12)[:, None]

        return merge
    if kind == "max":
        return lambda pooled: jax.lax.pmax(pooled, DATA_AXIS)
    if kind == "min":
        return lambda pooled: jax.lax.pmin(pooled, DATA_AXIS)
    raise ValueError(f"halo partitioning: unsupported graph_pooling {kind!r}")


def make_halo_train_step(
    model: HydraModel, optimizer, mesh: Mesh, compute_dtype=jnp.float32
):
    """Training step over halo-partitioned batches: identical contract to
    ``make_train_step`` (scalar loss / tasks_loss / num_graphs metrics), so
    the non-finite guard and the epoch loop compose unchanged."""
    validate_halo_support(model.spec)
    hmodel = _halo_model(model)
    n_dev = mesh.shape[DATA_AXIS]

    def device_fn(params, batch_stats, step_no, opt_state, hbatch: HaloBatch):
        batch = _squeeze_local(hbatch.batch)
        plan_local = [
            (s[0], r[0])
            for s, r in zip(hbatch.plan.send_idx, hbatch.plan.recv_slot)
        ]
        refresh = _refresh_fn(plan_local, n_dev)
        pool_reduce = _pool_reduce_fn(hmodel.spec.graph_pooling, batch)
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0), step_no)

        def loss_fn(p):
            c_params = _cast_floats(p, compute_dtype)
            c_batch = _cast_floats(batch, compute_dtype)
            outputs, updates = hmodel.apply(
                {"params": c_params, "batch_stats": batch_stats},
                c_batch,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
                layer_hook=refresh,
                pool_reduce=pool_reduce,
            )
            pred = _cast_floats(outputs, jnp.float32)
            # psum'd masked means: every device holds the exact union loss
            tot, tasks = hmodel.loss(pred, batch, loss_axis=DATA_AXIS)
            return tot, (tasks, updates["batch_stats"])

        (tot, (tasks, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        # pmean, NOT psum: every device seeds ITS copy of the (replicated,
        # psum'd) loss with cotangent 1, so the jointly-differentiated
        # objective is sum_d L_d = D * L — the cross-device mean of the
        # local grads is exactly dL/dp (D a power of two on real meshes,
        # so the /D is even bit-exact)
        grads = jax.lax.pmean(grads, DATA_AXIS)
        grads = freeze_conv_grads(_cast_floats(grads, jnp.float32), hmodel.spec)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        metrics = {
            "loss": tot,
            "tasks_loss": jnp.stack(tasks),
            "num_graphs": batch.graph_mask.sum(),
        }
        return new_params, new_stats, new_opt_state, metrics

    sharded = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(DATA_AXIS)),
        out_specs=(P(), P(), P(), P()),
        # outputs are replicated by construction (psum'd loss/grads feed
        # every update) but flow through gathers/scatters the static
        # replication checker cannot track
        check_rep=False,
    )

    @_partial(jax.jit, donate_argnums=donate_state_argnums())
    def step(state: TrainState, hbatch: HaloBatch):
        new_params, new_stats, new_opt, metrics = sharded(
            state.params, state.batch_stats, state.step, state.opt_state, hbatch
        )
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
            step=state.step + 1,
        )
        return new_state, metrics

    return step


def make_halo_eval_step(model: HydraModel, mesh: Mesh, compute_dtype=jnp.float32):
    """(state, halo batch) -> metrics matching ``make_eval_step``'s keys;
    per-head SSE/count sums are psum'd so the epoch RMSE accumulators see
    union-graph totals."""
    validate_halo_support(model.spec)
    hmodel = _halo_model(model)
    n_dev = mesh.shape[DATA_AXIS]

    def device_fn(params, batch_stats, hbatch: HaloBatch):
        batch = _squeeze_local(hbatch.batch)
        plan_local = [
            (s[0], r[0])
            for s, r in zip(hbatch.plan.send_idx, hbatch.plan.recv_slot)
        ]
        c_params = _cast_floats(params, compute_dtype)
        c_batch = _cast_floats(batch, compute_dtype)
        outputs = hmodel.apply(
            {"params": c_params, "batch_stats": batch_stats},
            c_batch,
            train=False,
            layer_hook=_refresh_fn(plan_local, n_dev),
            pool_reduce=_pool_reduce_fn(hmodel.spec.graph_pooling, batch),
        )
        pred = _cast_floats(outputs, jnp.float32)
        tot, tasks = hmodel.loss(pred, batch, loss_axis=DATA_AXIS)
        sses, counts = hmodel.head_sse(pred, batch)
        # node-head rows are PARTITIONED (psum = union total); graph-head
        # rows are REPLICATED on every device (psum over-counts by D)
        scale = jnp.array(
            [1.0 / n_dev if k == "graph" else 1.0 for k in hmodel.spec.output_type]
        )
        return {
            "loss": tot,
            "tasks_loss": jnp.stack(tasks),
            "head_sse": jax.lax.psum(jnp.stack(sses), DATA_AXIS) * scale,
            "head_count": jax.lax.psum(jnp.stack(counts), DATA_AXIS) * scale,
            "num_graphs": batch.graph_mask.sum(),
        }

    sharded = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS)),
        out_specs=P(),
        check_rep=False,
    )

    @jax.jit
    def eval_step(state: TrainState, hbatch: HaloBatch):
        return sharded(state.params, state.batch_stats, hbatch)

    return eval_step


def make_halo_apply(model: HydraModel, mesh: Mesh, compute_dtype=jnp.float32):
    """Jitted halo forward. Returns per-head outputs: graph heads replicated
    ``[G, d]``, node heads stacked ``[D, N_loc, d]`` (reassemble with
    ``gather_node_predictions``)."""
    validate_halo_support(model.spec)
    hmodel = _halo_model(model)
    n_dev = mesh.shape[DATA_AXIS]
    kinds = tuple(hmodel.spec.output_type)

    def device_fn(variables, hbatch: HaloBatch):
        batch = _squeeze_local(hbatch.batch)
        plan_local = [
            (s[0], r[0])
            for s, r in zip(hbatch.plan.send_idx, hbatch.plan.recv_slot)
        ]
        c_vars = {
            "params": _cast_floats(variables["params"], compute_dtype),
            "batch_stats": variables.get("batch_stats", {}),
        }
        outputs = hmodel.apply(
            c_vars,
            _cast_floats(batch, compute_dtype),
            train=False,
            layer_hook=_refresh_fn(plan_local, n_dev),
            pool_reduce=_pool_reduce_fn(hmodel.spec.graph_pooling, batch),
        )
        if hmodel.spec.var_output:
            outputs, _ = outputs
        outputs = [_cast_floats(o, jnp.float32) for o in outputs]
        # node heads keep their leading device axis; graph heads are
        # replicated (pool_reduce psums feed them)
        return [
            o if kind == "graph" else o[None] for o, kind in zip(outputs, kinds)
        ]

    out_specs = [P() if kind == "graph" else P(DATA_AXIS) for kind in kinds]
    sharded = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=out_specs,
        check_rep=False,
    )
    return jax.jit(sharded)


def gather_node_predictions(
    stacked: np.ndarray, hbatch: HaloBatch
) -> np.ndarray:
    """Host-side reassembly of a node head's ``[D, N_loc, d]`` output into
    global node order ``[N_real, d]`` using the owned-slot global ids."""
    stacked = np.asarray(stacked)
    node_global = np.asarray(hbatch.node_global)
    n_owned = np.asarray(hbatch.n_owned)
    n_real = int(max(node_global.max(), -1)) + 1
    out = np.zeros((n_real,) + stacked.shape[2:], stacked.dtype)
    for d in range(stacked.shape[0]):
        k = int(n_owned[d])
        out[node_global[d, :k]] = stacked[d, :k]
    return out
