"""Edge-sharded execution of FULL models — long-context for graphs.

``edge_sharding.py`` holds the manual shard_map primitive (one GIN-style
layer). This module is the production path: ANY ``HydraModel`` forward /
training step runs over a batch whose EDGE-dimension arrays are sharded
across the mesh's data axis while node/graph arrays stay replicated. The
XLA SPMD partitioner then emits, for every conv stack automatically, the
same schedule the primitive hand-writes: local gather from replicated nodes,
edge transforms partitioned E/D per device, partial segment-sums, one
all-reduce of the node accumulator over ICI (the "halo exchange").

This is the graph analog of sequence/context parallelism: graph size is the
sequence length, and the per-device edge shard is the context slice. The
reference has no counterpart (its answer to big structures is radius cutoffs
+ many small graphs); SURVEY §5 marks this as the TPU build's first-class
long-context mechanism.

Config: ``NeuralNetwork.Architecture.edge_sharding: true`` routes
``run_training`` through these steps when more than one device is present.

Resilience pass-through: the train step built here keeps the generic
``(state, batch) -> (state, metrics)`` contract, so the non-finite step
guard (``resilience/guard.py``) wraps it unchanged in the epoch loop —
a NaN on ANY edge shard propagates into the all-reduced loss and the
whole-mesh update is select-skipped in the same dispatch. Divergence
rollback and preemption checkpointing operate at the loop/checkpoint layer
and need nothing mode-specific; only supersteps stay pinned at K=1 (the
per-batch ``put_large_batch`` placement has no stacked [K, ...] form yet).

The Pallas fused-scatter kernel is trace-time disabled on this path (a
pallas_call is opaque to the SPMD partitioner and would force an edge
all-gather); the XLA segment_sum partitions cleanly.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graphs.graph import GraphBatch
from ..models.base import HydraModel
from ..train.step import (
    TrainState,
    _cast_floats,
    donate_state_argnums,
    freeze_conv_grads,
)
from .mesh import DATA_AXIS

# GraphBatch fields whose leading axis is the edge (or triplet) dimension.
_EDGE_FIELDS = frozenset(
    {"senders", "receivers", "edge_attr", "edge_shifts", "edge_mask",
     "idx_kj", "idx_ji", "triplet_mask", "rel_pe"}
)


@contextmanager
def _no_fused_scatter():
    """The fused Pallas kernel can't be partitioned by GSPMD; force the XLA
    path while tracing edge-sharded programs."""
    import os

    prev = os.environ.get("HYDRAGNN_FUSED_SCATTER")
    os.environ["HYDRAGNN_FUSED_SCATTER"] = "0"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("HYDRAGNN_FUSED_SCATTER", None)
        else:
            os.environ["HYDRAGNN_FUSED_SCATTER"] = prev


# GraphBatch fields whose leading axis is the node dimension.
_NODE_FIELDS = frozenset(
    {"x", "pos", "batch", "node_y", "forces_y", "node_mask", "pe", "z"}
)


def edge_batch_shardings(mesh: Mesh, shard_nodes: bool = False) -> GraphBatch:
    """Edge-dimension fields split over the data axis; node fields split too
    when ``shard_nodes`` (at-rest node memory 1/D — XLA all-gathers node
    features right before each layer's gather, ZeRO-style); everything else
    replicated."""
    split = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())

    def pick(f):
        if f in _EDGE_FIELDS:
            return split
        if shard_nodes and f in _NODE_FIELDS:
            return split
        return rep

    # meta=None matches put_large_batch, which invalidates the collate-time
    # layout certificate (padding here changes the edge layout anyway, and
    # the edge-sharded path always runs the XLA segment_sum)
    return GraphBatch(*[pick(f) for f in GraphBatch._fields[:-1]], meta=None)


def put_large_batch(
    batch: GraphBatch, mesh: Mesh, shard_nodes: bool = False
) -> GraphBatch:
    """Place one (possibly giant) collated batch with edge (and optionally
    node) arrays sharded. Pads the sharded dimensions to multiples of the
    data-axis size with masked fill (shape-preserving semantics)."""
    n_dev = mesh.shape[DATA_AXIS]
    n_node = np.asarray(batch.x).shape[0]
    e_padded = np.asarray(batch.senders).shape[0]
    e_padded += -e_padded % n_dev
    n_graph = np.asarray(batch.graph_y).shape[0]
    sharded_fields = _EDGE_FIELDS | (_NODE_FIELDS if shard_nodes else frozenset())

    def pad_field(name, arr):
        arr = np.asarray(arr)
        if name not in sharded_fields:
            return arr
        pad = -arr.shape[0] % n_dev
        if not pad:
            return arr
        if name in ("senders", "receivers"):
            fill = n_node - 1  # masked pad edges wired to the padding node
        elif name in ("idx_kj", "idx_ji"):
            fill = e_padded - 1  # pad triplets point at a padded edge
        elif name == "batch":
            fill = n_graph - 1  # pad nodes belong to the dummy graph
        else:
            fill = 0
        width = ((0, pad),) + ((0, 0),) * (arr.ndim - 1)
        return np.pad(arr, width, constant_values=fill)

    # node padding changes num_nodes: pad-edge endpoints must still point at
    # a PADDING node; node n_node-1 is one by the collate contract, and pads
    # added here extend the padding tail, so fills above stay valid.
    batch = GraphBatch(
        *[pad_field(f, getattr(batch, f)) for f in GraphBatch._fields[:-1]],
        meta=None,
    )
    sh = edge_batch_shardings(mesh, shard_nodes)
    return jax.tree.map(lambda x, s: jax.device_put(jnp.asarray(x), s), batch, sh)


def make_edge_sharded_apply(model: HydraModel, mesh: Mesh):
    """Jitted inference over an edge-sharded batch; returns model outputs
    (replicated)."""

    @jax.jit
    def forward(variables, batch: GraphBatch):
        return model.apply(variables, batch, train=False)

    def apply(variables, batch: GraphBatch):
        with _no_fused_scatter():
            return forward(variables, batch)

    return apply


def make_edge_sharded_train_step(
    model: HydraModel, optimizer, mesh: Mesh, compute_dtype=jnp.float32
):
    """Training step over edge-sharded batches: identical contract to
    ``make_train_step`` — XLA inserts the node-accumulator all-reduces and
    the gradient psum from the shardings alone."""
    if model.spec.sync_batch_norm:
        raise ValueError(
            "SyncBatchNorm is not supported with edge_sharding: the graph is "
            "ONE giant sample split across devices (there is no per-device "
            "batch whose statistics could be synced); feature norms already "
            "see the full node set"
        )

    def loss_fn(params, batch_stats, batch: GraphBatch, dropout_rng):
        c_params = _cast_floats(params, compute_dtype)
        c_batch = _cast_floats(batch, compute_dtype)
        outputs, updates = model.apply(
            {"params": c_params, "batch_stats": batch_stats},
            c_batch,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": dropout_rng},
        )
        pred = _cast_floats(outputs, jnp.float32)
        tot, tasks = model.loss(pred, batch)
        return tot, (tasks, updates["batch_stats"])

    from functools import partial as _p

    @_p(jax.jit, donate_argnums=donate_state_argnums())
    def step(state: TrainState, batch: GraphBatch):
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0), state.step)
        (tot, (tasks, new_stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.batch_stats, batch, dropout_rng
        )
        grads = freeze_conv_grads(_cast_floats(grads, jnp.float32), model.spec)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        metrics = {
            "loss": tot,
            "tasks_loss": jnp.stack(tasks),
            "num_graphs": batch.graph_mask.sum(),
        }
        return new_state, metrics

    def train_step(state: TrainState, batch: GraphBatch):
        with _no_fused_scatter():
            return step(state, batch)

    return train_step


def make_edge_sharded_eval_step(model: HydraModel, mesh: Mesh, compute_dtype=jnp.float32):
    from ..train.step import make_eval_step

    inner = make_eval_step(model, compute_dtype)

    def eval_step(state: TrainState, batch: GraphBatch):
        with _no_fused_scatter():
            return inner(state, batch)

    return eval_step
