"""Simple pickle dataset: one file per sample + a metadata pickle.

Reference: ``hydragnn/utils/datasets/pickledataset.py:14-183``
(``SimplePickleWriter``/``SimplePickleDataset``), including the optional
subdirectory sharding per 10k samples so directories stay listable.
"""

from __future__ import annotations

import os
import pickle

from ..graphs.graph import GraphSample

_PER_DIR = 10_000


def _sample_path(basedir: str, label: str, i: int, use_subdir: bool) -> str:
    if use_subdir:
        sub = os.path.join(basedir, str(i // _PER_DIR))
        os.makedirs(sub, exist_ok=True)
        return os.path.join(sub, f"{label}-{i}.pkl")
    return os.path.join(basedir, f"{label}-{i}.pkl")


class SimplePickleWriter:
    def __init__(
        self,
        samples,
        basedir: str,
        label: str = "total",
        use_subdir: bool = False,
        attrs: dict | None = None,
    ):
        os.makedirs(basedir, exist_ok=True)
        meta = {
            "total_ns": len(samples),
            "use_subdir": use_subdir,
            "attrs": attrs or {},
        }
        with open(os.path.join(basedir, f"{label}-meta.pkl"), "wb") as f:
            pickle.dump(meta, f)
        for i, s in enumerate(samples):
            with open(_sample_path(basedir, label, i, use_subdir), "wb") as f:
                pickle.dump(s, f)


class SimplePickleDataset:
    """Lazy per-sample reads; supports len/getitem and full materialization."""

    def __init__(self, basedir: str, label: str = "total"):
        with open(os.path.join(basedir, f"{label}-meta.pkl"), "rb") as f:
            self.meta = pickle.load(f)
        self.basedir = basedir
        self.label = label

    def __len__(self) -> int:
        return self.meta["total_ns"]

    @property
    def attrs(self) -> dict:
        return self.meta.get("attrs", {})

    def __getitem__(self, i: int) -> GraphSample:
        path = _sample_path(
            self.basedir, self.label, i, self.meta.get("use_subdir", False)
        )
        with open(path, "rb") as f:
            return pickle.load(f)

    def load_all(self) -> list[GraphSample]:
        return [self[i] for i in range(len(self))]
