"""Non-shared-filesystem data plane: per-host packed shards + TCP sample
exchange — the role of the reference's DDStore
(``hydragnn/utils/datasets/distdataset.py:72-367``: each rank materializes
only its window and serves remote ``get()`` fetches over MPI RMA windows).

``GlobalShuffleStore`` (``packed.py``) assumes every host can mmap the SAME
packed file — a shared filesystem or pre-replicated copy. When each host
instead holds only its own shard on local disk, ``ShardedStore`` fills the
gap:

* host ``h`` owns global indices ``[start_h, stop_h)`` backed by its local
  ``PackedDataset`` shard;
* a per-host ``ShardServer`` thread answers batched index fetches over TCP
  (the MPI-RMA → TCP translation; one request per owner per batch);
* the address book (host, port, index range) is exchanged once through
  ``jax.experimental.multihost_utils.process_allgather`` when running under
  ``jax.distributed`` — or passed explicitly (``peers=``) for tests;
* reads of any global index then work from every host: local → zero-copy
  mmap, remote → fetch + bounded LRU cache.

Feed the store straight to ``GraphLoader(..., rank, world, shuffle=True)``:
each host's per-epoch stride of the shared global permutation now spans the
WHOLE corpus (the DDStore property), fetching the ~(world-1)/world
non-local samples from their owners.

Wire format is a length-prefixed binary array framing (name + dtype str +
shape + raw bytes per array): decode is ``np.frombuffer`` views — no
pickle anywhere, and object dtypes are rejected on both ends, so a
malicious peer cannot execute code on load. The trust model is the
reference's — an internal cluster network, like its MPI windows. The
optional ``auth_token`` and bindable listen interface protect against
MISCONFIGURATION (two jobs sharing a fabric, a peer dialing the wrong
port), not against a network attacker: the token travels plaintext over
unencrypted TCP and is replayable. Genuinely untrusted networks need
transport security (TLS/WireGuard) underneath, same as MPI would.
"""

from __future__ import annotations

import hmac
import socket
import socketserver
import struct
import sys
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..graphs.graph import GraphSample
from .packed import PackedDataset

_HDR = struct.Struct("<q")  # payload byte length


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HDR.pack(len(payload)) + payload)


_MAGIC = b"GSX1"


def _pack_arrays(d: dict[str, np.ndarray]) -> bytes:
    """dict[str, ndarray] -> compact binary frame. ~50x faster than ``.npz``
    (zipfile is pure Python and dominated the TCP tier's CPU budget); the
    dtype travels as its ``.str`` spec, never as a pickled object."""
    parts = [_MAGIC, struct.pack("<I", len(d))]
    for k, v in d.items():
        v = np.ascontiguousarray(v)
        if v.dtype.hasobject:
            raise ValueError("object arrays are not allowed on the wire")
        name = k.encode()
        dt = v.dtype.str.encode()
        parts.append(struct.pack("<H", len(name)))
        parts.append(name)
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", v.ndim))
        if v.ndim:
            parts.append(struct.pack(f"<{v.ndim}q", *v.shape))
        raw = v.tobytes()
        parts.append(struct.pack("<q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _unpack_arrays(buf: bytes) -> dict[str, np.ndarray]:
    """Inverse of ``_pack_arrays``; arrays are zero-copy views into ``buf``.
    Every length is validated against the payload before slicing, and ANY
    malformed frame — bad magic, truncated header, unknown dtype — raises
    ``ValueError`` (never struct.error/TypeError leaking to callers)."""
    try:
        if buf[:4] != _MAGIC:
            raise ValueError(
                "bad wire magic (peer speaks a different protocol?)"
            )
        mv = memoryview(buf)
        off = 4
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        out: dict[str, np.ndarray] = {}
        for _ in range(n):
            (nl,) = struct.unpack_from("<H", buf, off)
            off += 2
            if off + nl > len(buf):
                raise ValueError("truncated frame (name)")
            name = bytes(mv[off:off + nl]).decode()
            off += nl
            (dl,) = struct.unpack_from("<B", buf, off)
            off += 1
            if off + dl > len(buf):
                raise ValueError("truncated frame (dtype)")
            dt = np.dtype(bytes(mv[off:off + dl]).decode())
            off += dl
            if dt.hasobject:
                raise ValueError("object arrays are not allowed on the wire")
            (nd,) = struct.unpack_from("<B", buf, off)
            off += 1
            shape = struct.unpack_from(f"<{nd}q", buf, off) if nd else ()
            off += 8 * nd
            (nb,) = struct.unpack_from("<q", buf, off)
            off += 8
            count = int(np.prod(shape, dtype=np.int64)) if nd else 1
            if count < 0 or nb != count * dt.itemsize or off + nb > len(buf):
                raise ValueError(f"corrupt frame for array {name!r}")
            out[name] = np.frombuffer(mv[off:off + nb], dtype=dt).reshape(shape)
            off += nb
        return out
    except ValueError:
        raise
    except (struct.error, TypeError, UnicodeDecodeError) as e:
        raise ValueError(f"corrupt frame: {e}") from None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if n < 0 or n > (1 << 33):
        raise ValueError(f"bad message length {n}")
    return _recv_exact(sock, n)


# GraphSample <-> flat dict of arrays (npz-safe: no object dtypes)
_ARRAY_FIELDS = (
    "x", "pos", "senders", "receivers", "edge_attr", "edge_shifts",
    "graph_y", "node_y", "energy_y", "forces_y", "graph_attr",
)
_EXTRA_FIELDS = ("node_table", "graph_table")


def _sample_to_arrays(s: GraphSample) -> dict[str, np.ndarray]:
    out = {}
    for f in _ARRAY_FIELDS:
        v = getattr(s, f)
        if v is not None:
            out[f] = np.asarray(v)
    for f in _EXTRA_FIELDS:
        if f in s.extras:
            out["extra_" + f] = np.asarray(s.extras[f])
    out["dataset_id"] = np.asarray(s.dataset_id, np.int32)
    return out


def _sample_from_arrays(d: dict[str, np.ndarray]) -> GraphSample:
    # np.array: decoded frames are read-only frombuffer views; samples must
    # be writable (downstream transforms mutate in place)
    kw = {f: np.array(d[f]) for f in _ARRAY_FIELDS if f in d}
    s = GraphSample(dataset_id=int(d["dataset_id"]), **kw)
    for f in _EXTRA_FIELDS:
        if "extra_" + f in d:
            s.extras[f] = np.array(d["extra_" + f])
    return s


def _copy_sample(s: GraphSample) -> GraphSample:
    """Independent deep-ish copy: fresh array buffers, fresh extras dict.
    The LRU cache hands these out because downstream transforms mutate
    samples in place — a cache that returns its own instances corrupts
    every later hit of the same index (ADVICE.md r5)."""
    out = GraphSample.__new__(GraphSample)
    for f in GraphSample.__slots__:
        v = getattr(s, f)
        if isinstance(v, np.ndarray):
            v = v.copy()
        elif f == "extras":
            v = {
                k: (x.copy() if isinstance(x, np.ndarray) else x)
                for k, x in v.items()
            }
        setattr(out, f, v)
    return out


def _encode_samples(samples: list[GraphSample]) -> bytes:
    flat = {}
    for i, s in enumerate(samples):
        for k, v in _sample_to_arrays(s).items():
            flat[f"s{i}_{k}"] = v
    flat["n"] = np.asarray(len(samples), np.int64)
    return _pack_arrays(flat)


def _samples_from_frame(z: dict[str, np.ndarray]) -> list[GraphSample]:
    n = int(z["n"])
    out = []
    for i in range(n):
        prefix = f"s{i}_"
        d = {k[len(prefix):]: v for k, v in z.items() if k.startswith(prefix)}
        out.append(_sample_from_arrays(d))
    return out


class ShardServer:
    """Threaded TCP server answering batched sample fetches from the local
    shard. Request: a ``_pack_arrays`` frame {"idx": int64[k] LOCAL indices,
    "range": [start, stop] the GLOBAL range the client believes this server
    owns}; response:
    the encoded samples, or an error record when the range doesn't match —
    a misrouted connection (e.g. every host advertising a loopback address,
    so peers dial their OWN server) must fail LOUDLY, not silently serve
    wrong samples.

    ``host`` restricts the listening interface (default all interfaces —
    the reference's MPI-window trust model on an isolated cluster fabric);
    ``auth_token`` adds a per-request shared-secret check (n=-2 error
    record on mismatch). The token is a MISCONFIGURATION guard — it stops
    a peer from another job/cluster accidentally reading this shard — not
    network security: it travels plaintext and is replayable, so an
    attacker who can sniff the fabric already has the data. The compare is
    ``hmac.compare_digest`` so the guard itself doesn't leak the token
    byte-by-byte through timing. ``_test_delay_s`` is a test hook: a
    per-request sleep that makes fetch-overlap measurements deterministic
    instead of timing-noise-bound."""

    def __init__(self, ds: PackedDataset, start: int, stop: int,
                 host: str = "0.0.0.0", auth_token: str | None = None,
                 _test_delay_s: float = 0.0):
        outer = self
        tok = None if auth_token is None else auth_token.encode()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    while True:
                        try:
                            z = _unpack_arrays(_recv_msg(self.request))
                        except ValueError:
                            # malformed frame: drop the connection — one
                            # line of diagnostics, no per-request traceback
                            # spam from a misbehaving peer
                            print(
                                f"[ShardServer:{outer.port}] dropping peer "
                                f"{self.client_address}: malformed frame",
                                file=sys.stderr,
                            )
                            return
                        if outer._test_delay_s:
                            time.sleep(outer._test_delay_s)
                        got_tok = z.get("token")
                        if tok is not None and (
                            got_tok is None
                            or not hmac.compare_digest(
                                np.asarray(got_tok).tobytes(), tok
                            )
                        ):
                            _send_msg(self.request, _pack_arrays(
                                {"n": np.asarray(-2, np.int64)}
                            ))
                            continue
                        want = z.get("range")
                        if want is not None and (
                            int(want[0]) != outer.start or int(want[1]) != outer.stop
                        ):
                            _send_msg(self.request, _pack_arrays({
                                "n": np.asarray(-1, np.int64),
                                "have": np.asarray(
                                    [outer.start, outer.stop], np.int64
                                ),
                            }))
                            continue
                        try:
                            if "sizes" in z:
                                # size-table op: (num_nodes, num_edges) for
                                # the whole shard straight from the count
                                # index — bucket planning never pulls
                                # sample content
                                resp = _pack_arrays({
                                    "n": np.asarray(0, np.int64),
                                    "sizes": outer.ds.sample_sizes(
                                        range(outer.stop - outer.start)
                                    ),
                                })
                            else:
                                resp = _encode_samples(
                                    [outer.ds[int(i)] for i in z["idx"]]
                                )
                        except Exception as e:
                            # server-side failure: tell the CLIENT what
                            # broke instead of closing with no diagnostics
                            resp = _pack_arrays({
                                "n": np.asarray(-3, np.int64),
                                "detail": np.frombuffer(
                                    f"{type(e).__name__}: {e}".encode()[:512],
                                    np.uint8,
                                ),
                            })
                        _send_msg(self.request, resp)
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.ds = ds
        self.start, self.stop = int(start), int(stop)
        self._test_delay_s = float(_test_delay_s)
        self._srv = Server((host, 0), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class _ConnPool:
    """Per-peer socket pool. Each concurrent ``fetch`` checks out its own
    socket (creating one when none is idle), runs its request/response
    round-trip WITHOUT any shared lock, and returns the socket afterwards —
    so N prefetch workers overlap N remote fetches, the concurrency the
    reference gets from per-rank MPI RMA windows
    (``distdataset.py:72-367``). Idle sockets per peer are capped; excess
    ones close on release."""

    def __init__(self, max_idle_per_peer: int = 4):
        self._idle: dict[int, list[socket.socket]] = {}
        self._lock = threading.Lock()
        self._max_idle = int(max_idle_per_peer)
        self._closed = False

    def acquire(self, rank: int, host: str, port: int) -> tuple[socket.socket, bool]:
        """Returns (socket, from_pool). A pooled socket may have gone stale
        while idle — callers retry once on a fresh one; a FRESH connection
        failing is a real error."""
        with self._lock:
            stack = self._idle.get(rank)
            if stack:
                return stack.pop(), True
        return socket.create_connection((host, port), timeout=120), False

    def release(self, rank: int, sock: socket.socket) -> None:
        with self._lock:
            # a release racing close() (in-flight fetch during teardown)
            # must not re-park into the cleared pool — close the socket
            if not self._closed:
                stack = self._idle.setdefault(rank, [])
                if len(stack) < self._max_idle:
                    stack.append(sock)
                    return
        try:
            sock.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for stack in self._idle.values():
                for sock in stack:
                    try:
                        sock.close()
                    except OSError:
                        pass
            self._idle.clear()


class ShardedStore:
    """Global-index Sequence over per-host shards (see module docstring).

    ``peers``: list over ranks of ``(host, port, start, stop)``. When None,
    exchanged via ``multihost_utils.process_allgather`` (requires
    ``jax.distributed`` to be initialized).
    """

    def __init__(
        self,
        shard_path: str,
        start: int,
        stop: int,
        peers: list[tuple[str, int, int, int]] | None = None,
        cache_size: int = 4096,
        advertise_host: str | None = None,
        bind_host: str = "0.0.0.0",
        auth_token: str | None = None,
        max_idle_conns_per_peer: int = 4,
        _test_delay_s: float = 0.0,
    ):
        self.ds = PackedDataset(shard_path)
        if len(self.ds.subset) != stop - start:
            raise ValueError(
                f"shard {shard_path} holds {len(self.ds.subset)} samples but "
                f"claims global range [{start}, {stop})"
            )
        self.start, self.stop = int(start), int(stop)
        self.server = ShardServer(self.ds, start, stop, host=bind_host,
                                  auth_token=auth_token,
                                  _test_delay_s=_test_delay_s)
        if peers is None:
            peers = self._allgather_peers(advertise_host)
        self.peers = sorted(peers, key=lambda p: p[2])  # by start index
        self.total = max(p[3] for p in self.peers)
        spans = [(p[2], p[3]) for p in self.peers]
        cursor = 0
        for s0, s1 in spans:
            if s0 != cursor:
                raise ValueError(f"shard ranges not contiguous: {spans}")
            cursor = s1
        self._auth_token = auth_token
        self._pool = _ConnPool(max_idle_conns_per_peer)
        # the lock guards ONLY cache/telemetry bookkeeping; network
        # round-trips run outside it so concurrent fetches overlap
        self._lock = threading.Lock()
        self._cache: OrderedDict[int, GraphSample] = OrderedDict()
        self._cache_size = int(cache_size)
        self._sizes: np.ndarray | None = None  # lazy global size table
        self._sizes_lock = threading.Lock()
        self._executor: ThreadPoolExecutor | None = None  # lazy, persistent
        self.remote_fetches = 0  # telemetry: audited by tests/bench

    def _allgather_peers(self, advertise_host: str | None):
        from jax.experimental import multihost_utils

        host = advertise_host or socket.gethostbyname(socket.gethostname())
        mine = np.array(
            [_ip_to_int(host), self.server.port, self.start, self.stop], np.int64
        )
        allv = np.asarray(multihost_utils.process_allgather(mine))
        return [
            (_int_to_ip(int(r[0])), int(r[1]), int(r[2]), int(r[3])) for r in allv
        ]

    # -- Sequence API --------------------------------------------------------
    def __len__(self) -> int:
        return self.total

    @property
    def attrs(self) -> dict:
        return self.ds.attrs

    def _owner(self, i: int):
        for rank, (h, p, s0, s1) in enumerate(self.peers):
            if s0 <= i < s1:
                return rank, h, p, s0
        raise IndexError(i)

    def _request(self, rank: int, host: str, port: int, **fields) -> bytes:
        """One request/response round-trip on a pooled socket — no shared
        lock held, so concurrent callers overlap their network waits. The
        socket returns to the pool only after a clean round-trip; any error
        closes it (a half-read stream cannot be reused).

        Transient-fault policy (the request is idempotent, so retrying is
        always safe): a stale POOLED socket (dropped by the peer/NAT while
        parked) retries immediately on a fresh connection without counting
        an attempt; a FRESH-connection failure — connect refused/reset/
        timed out mid-stream — retries up to ``HYDRAGNN_STORE_RETRIES``
        total attempts with exponential backoff + jitter, warning per retry,
        so a blip in the fabric degrades to a logged pause instead of
        killing the epoch. The last failure re-raises."""
        import random
        import warnings

        from ..utils import flags

        if self._auth_token is not None:
            fields["token"] = np.frombuffer(self._auth_token.encode(), np.uint8)
        req = _pack_arrays(fields)
        attempts = max(1, int(flags.get(flags.STORE_RETRIES)))
        attempt = 0
        delay = 0.05
        while True:
            try:
                sock, from_pool = self._pool.acquire(rank, host, port)
            except (ConnectionError, OSError) as e:
                sock, from_pool, err = None, False, e
            else:
                err = None
                try:
                    _send_msg(sock, req)
                    payload = _recv_msg(sock)
                except BaseException as e:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    # a socket parked idle in the pool can be dropped by the
                    # peer/NAT at any time; retry immediately on a fresh
                    # connection without consuming an attempt
                    if from_pool and isinstance(e, (ConnectionError, OSError)):
                        continue
                    if not isinstance(e, (ConnectionError, OSError)):
                        raise
                    err = e
                else:
                    self._pool.release(rank, sock)
                    return payload
            attempt += 1
            if attempt >= attempts:
                raise err
            sleep_s = delay * (2 ** (attempt - 1)) * (1.0 + random.random())
            warnings.warn(
                f"shard fetch from {host}:{port} failed "
                f"({type(err).__name__}: {err}); retry {attempt}/"
                f"{attempts - 1} in {sleep_s:.2f}s "
                "(HYDRAGNN_STORE_RETRIES tunes the cap)"
            )
            time.sleep(sleep_s)

    @staticmethod
    def _check_status(z: dict[str, np.ndarray], host: str, port: int,
                      s0: int, s1: int):
        n = int(z["n"])
        if n == -3:
            detail = bytes(np.asarray(z.get("detail", []), np.uint8)).decode(
                errors="replace"
            )
            raise RuntimeError(
                f"shard server at {host}:{port} failed serving the request: "
                f"{detail or 'unknown error'}"
            )
        if n == -2:
            raise RuntimeError(
                f"shard fetch rejected by {host}:{port}: auth token "
                "mismatch (pass the same auth_token on every host)"
            )
        if n == -1:
            have = z.get("have", "?")
            raise RuntimeError(
                f"shard fetch misrouted: peer at {host}:{port} "
                f"owns global range {have}, expected [{s0}, {s1})"
                " — check the advertised addresses (loopback "
                "hostnames on multi-host clusters are the usual "
                "cause; pass advertise_host explicitly)"
            )

    def __getitem__(self, i) -> GraphSample:
        i = int(i)
        if self.start <= i < self.stop:
            return self.ds[i - self.start]
        return self.fetch([i])[0]

    def sample_sizes(self, indices) -> np.ndarray:
        """[k, 2] (num_nodes, num_edges) for arbitrary GLOBAL indices. The
        full size table is exchanged ONCE (one request per peer, a few
        int64s per sample), so bucket planning never turns into per-sample
        content fetches across the network."""
        if self._sizes is None:
            with self._sizes_lock:
                if self._sizes is None:
                    self._sizes = self._fetch_all_sizes()
        return self._sizes[np.asarray(indices, np.int64)]

    def _fetch_all_sizes(self) -> np.ndarray:
        out = np.zeros((self.total, 2), np.int64)
        for rank, (host, port, s0, s1) in enumerate(self.peers):
            if s0 == self.start and s1 == self.stop:
                out[s0:s1] = self.ds.sample_sizes(range(s1 - s0))
                continue
            z = _unpack_arrays(self._request(
                rank, host, port,
                idx=np.zeros((0,), np.int64),
                range=np.asarray([s0, s1], np.int64),
                sizes=np.asarray(1, np.int64),
            ))
            self._check_status(z, host, port, s0, s1)
            out[s0:s1] = z["sizes"]
        return out

    def fetch(self, indices) -> list[GraphSample]:
        """Batched read of arbitrary GLOBAL indices: local ones from mmap,
        remote ones with ONE request per owning host. Only the cache
        bookkeeping is serialized; the network round-trips run on pooled
        per-call sockets, so concurrent callers (PrefetchLoader workers)
        overlap their remote fetches.

        Mutability contract: LOCAL samples are zero-copy READ-ONLY mmap
        views (an in-place write raises — loud, safe, and free); REMOTE
        samples are independent writable copies (the LRU cache keeps its
        own pristine instance, so a caller mutating one can never corrupt
        a later cache hit). Transforms that write in place must copy
        first; transforms that build new arrays work on both."""
        out: dict[int, GraphSample] = {}
        by_owner: dict[int, list[int]] = {}
        remote: list[int] = []
        for i in map(int, indices):
            if self.start <= i < self.stop:
                out[i] = self.ds[i - self.start]  # zero-copy mmap read
            else:
                remote.append(i)
        if remote:
            pending: set[int] = set()
            hits: dict[int, GraphSample] = {}
            with self._lock:
                for i in remote:
                    if i in self._cache:
                        self._cache.move_to_end(i)
                        hits[i] = self._cache[i]  # reference only under lock
                    elif i not in pending:
                        pending.add(i)
                        rank = self._owner(i)[0]
                        by_owner.setdefault(rank, []).append(i)
            # copy on hit OUTSIDE the lock (the lock serializes bookkeeping
            # only — array memcpy under it would stall concurrent workers):
            # callers mutate samples in place (transforms); the cache's
            # instance stays pristine
            for i, s in hits.items():
                out[i] = _copy_sample(s)
        def fetch_owner(item):
            rank, idxs = item
            host, port, s0, s1 = self.peers[rank]
            z = _unpack_arrays(self._request(
                rank, host, port,
                idx=np.asarray([i - s0 for i in idxs], np.int64),
                range=np.asarray([s0, s1], np.int64),
            ))
            self._check_status(z, host, port, s0, s1)
            return idxs, _samples_from_frame(z)

        if len(by_owner) <= 1:
            results = [fetch_owner(it) for it in by_owner.items()]
        else:
            # a shuffled global batch touches many owners — issue those
            # round-trips concurrently instead of paying one RTT per owner.
            # The executor is persistent (created once, closed with the
            # store): per-batch spawn/teardown would burn host CPU in the
            # hot path it exists to hide.
            if self._executor is None:
                with self._lock:
                    if self._executor is None:
                        # sized for CONCURRENT callers, not one fetch: N
                        # prefetch workers each fanning out to several
                        # owners share this pool, so a peers-count cap
                        # would serialize them against each other
                        self._executor = ThreadPoolExecutor(16)
            results = list(self._executor.map(fetch_owner, by_owner.items()))
        for idxs, samples in results:
            # the caller gets the freshly decoded instance; the cache keeps
            # its OWN copy (made before taking the lock) so later hits are
            # unaffected by whatever the caller does to this one
            cache_copies = [_copy_sample(s) for s in samples]
            with self._lock:
                self.remote_fetches += len(samples)
                for i, s, c in zip(idxs, samples, cache_copies):
                    out[i] = s
                    self._cache[i] = c
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
        # duplicate REMOTE indices must not share one writable instance
        # across result positions (the isolation contract above); local
        # read-only mmap views are safe to share
        result: list[GraphSample] = []
        emitted: set[int] = set()
        for i in map(int, indices):
            s = out[i]
            if i in emitted and not (self.start <= i < self.stop):
                s = _copy_sample(s)
            else:
                emitted.add(i)
            result.append(s)
        return result

    def pad_spec(self, batch_size: int, node_multiple: int = 8, edge_multiple: int = 128):
        """PadSpec from shard-local writer stats, maxed across hosts when
        under jax.distributed (stats are per-shard)."""
        a = dict(self.attrs)
        if "max_nodes" not in a:
            raise ValueError("packed shard lacks size stats; re-write with PackedWriter")
        try:
            import jax

            multi = jax.process_count() > 1
        except Exception:
            multi = False
        if multi:
            # MUST succeed: silently falling back to shard-local maxima
            # would give hosts different static shapes and hang/crash the
            # SPMD program far from the root cause
            from jax.experimental import multihost_utils

            stats = np.asarray(
                multihost_utils.process_allgather(
                    np.array([a["max_nodes"], a["max_edges"]], np.int64)
                )
            )
            a["max_nodes"] = int(stats[:, 0].max())
            a["max_edges"] = int(stats[:, 1].max())
        from .packed import pad_spec_from_stats

        return pad_spec_from_stats(a, batch_size, node_multiple, edge_multiple)

    def loader(
        self,
        batch_size: int,
        rank: int = 0,
        world: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        pad=None,
        **kw,
    ):
        from ..graphs.batching import GraphLoader

        return GraphLoader(
            self,
            batch_size,
            pad=pad or self.pad_spec(batch_size),
            shuffle=shuffle,
            seed=seed,
            rank=rank,
            world=world,
            **kw,
        )

    def close(self) -> None:
        self.server.close()
        if self._executor is not None:
            self._executor.shutdown(wait=False)
        self._pool.close()


def _ip_to_int(ip: str) -> int:
    return int.from_bytes(socket.inet_aton(ip), "big")


def _int_to_ip(v: int) -> str:
    return socket.inet_ntoa(v.to_bytes(4, "big"))


__all__ = ["ShardedStore", "ShardServer"]
