"""Non-shared-filesystem data plane: per-host packed shards + TCP sample
exchange — the role of the reference's DDStore
(``hydragnn/utils/datasets/distdataset.py:72-367``: each rank materializes
only its window and serves remote ``get()`` fetches over MPI RMA windows).

``GlobalShuffleStore`` (``packed.py``) assumes every host can mmap the SAME
packed file — a shared filesystem or pre-replicated copy. When each host
instead holds only its own shard on local disk, ``ShardedStore`` fills the
gap:

* host ``h`` owns global indices ``[start_h, stop_h)`` backed by its local
  ``PackedDataset`` shard;
* a per-host ``ShardServer`` thread answers batched index fetches over TCP
  (the MPI-RMA → TCP translation; one request per owner per batch);
* the address book (host, port, index range) is exchanged once through
  ``jax.experimental.multihost_utils.process_allgather`` when running under
  ``jax.distributed`` — or passed explicitly (``peers=``) for tests;
* reads of any global index then work from every host: local → zero-copy
  mmap, remote → fetch + bounded LRU cache.

Feed the store straight to ``GraphLoader(..., rank, world, shuffle=True)``:
each host's per-epoch stride of the shared global permutation now spans the
WHOLE corpus (the DDStore property), fetching the ~(world-1)/world
non-local samples from their owners.

Wire format is ``.npz`` (``allow_pickle=False`` — a malicious peer cannot
execute code on load); the trust model is otherwise the reference's: an
internal cluster network, like its MPI windows.
"""

from __future__ import annotations

import io
import socket
import socketserver
import struct
import threading
from collections import OrderedDict

import numpy as np

from ..graphs.graph import GraphSample
from .packed import PackedDataset

_HDR = struct.Struct("<q")  # payload byte length


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if n < 0 or n > (1 << 33):
        raise ValueError(f"bad message length {n}")
    return _recv_exact(sock, n)


# GraphSample <-> flat dict of arrays (npz-safe: no object dtypes)
_ARRAY_FIELDS = (
    "x", "pos", "senders", "receivers", "edge_attr", "edge_shifts",
    "graph_y", "node_y", "energy_y", "forces_y", "graph_attr",
)
_EXTRA_FIELDS = ("node_table", "graph_table")


def _sample_to_arrays(s: GraphSample) -> dict[str, np.ndarray]:
    out = {}
    for f in _ARRAY_FIELDS:
        v = getattr(s, f)
        if v is not None:
            out[f] = np.asarray(v)
    for f in _EXTRA_FIELDS:
        if f in s.extras:
            out["extra_" + f] = np.asarray(s.extras[f])
    out["dataset_id"] = np.asarray(s.dataset_id, np.int32)
    return out


def _sample_from_arrays(d: dict[str, np.ndarray]) -> GraphSample:
    kw = {f: d[f] for f in _ARRAY_FIELDS if f in d}
    s = GraphSample(dataset_id=int(d["dataset_id"]), **kw)
    for f in _EXTRA_FIELDS:
        if "extra_" + f in d:
            s.extras[f] = d["extra_" + f]
    return s


def _encode_samples(samples: list[GraphSample]) -> bytes:
    buf = io.BytesIO()
    flat = {}
    for i, s in enumerate(samples):
        for k, v in _sample_to_arrays(s).items():
            flat[f"s{i}_{k}"] = v
    flat["n"] = np.asarray(len(samples), np.int64)
    np.savez(buf, **flat)
    return buf.getvalue()


def _decode_samples(payload: bytes) -> list[GraphSample]:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        n = int(z["n"])
        out = []
        for i in range(n):
            prefix = f"s{i}_"
            d = {k[len(prefix):]: z[k] for k in z.files if k.startswith(prefix)}
            out.append(_sample_from_arrays(d))
    return out


class ShardServer:
    """Threaded TCP server answering batched sample fetches from the local
    shard. Request: npz {"idx": int64[k] LOCAL indices, "range": [start,
    stop] the GLOBAL range the client believes this server owns}; response:
    the encoded samples, or an error record when the range doesn't match —
    a misrouted connection (e.g. every host advertising a loopback address,
    so peers dial their OWN server) must fail LOUDLY, not silently serve
    wrong samples."""

    def __init__(self, ds: PackedDataset, start: int, stop: int,
                 host: str = "0.0.0.0"):
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    while True:
                        req = _recv_msg(self.request)
                        with np.load(io.BytesIO(req), allow_pickle=False) as z:
                            idx = z["idx"]
                            want = z["range"] if "range" in z.files else None
                        if want is not None and (
                            int(want[0]) != outer.start or int(want[1]) != outer.stop
                        ):
                            buf = io.BytesIO()
                            np.savez(
                                buf, n=np.asarray(-1, np.int64),
                                have=np.asarray([outer.start, outer.stop], np.int64),
                            )
                            _send_msg(self.request, buf.getvalue())
                            continue
                        if "sizes" in z.files:
                            # size-table op: (num_nodes, num_edges) for the
                            # whole shard straight from the count index —
                            # bucket planning never pulls sample content
                            buf = io.BytesIO()
                            np.savez(
                                buf, n=np.asarray(0, np.int64),
                                sizes=outer.ds.sample_sizes(
                                    range(outer.stop - outer.start)
                                ),
                            )
                            _send_msg(self.request, buf.getvalue())
                            continue
                        samples = [outer.ds[int(i)] for i in idx]
                        _send_msg(self.request, _encode_samples(samples))
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.ds = ds
        self.start, self.stop = int(start), int(stop)
        self._srv = Server((host, 0), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class ShardedStore:
    """Global-index Sequence over per-host shards (see module docstring).

    ``peers``: list over ranks of ``(host, port, start, stop)``. When None,
    exchanged via ``multihost_utils.process_allgather`` (requires
    ``jax.distributed`` to be initialized).
    """

    def __init__(
        self,
        shard_path: str,
        start: int,
        stop: int,
        peers: list[tuple[str, int, int, int]] | None = None,
        cache_size: int = 4096,
        advertise_host: str | None = None,
    ):
        self.ds = PackedDataset(shard_path)
        if len(self.ds.subset) != stop - start:
            raise ValueError(
                f"shard {shard_path} holds {len(self.ds.subset)} samples but "
                f"claims global range [{start}, {stop})"
            )
        self.start, self.stop = int(start), int(stop)
        self.server = ShardServer(self.ds, start, stop)
        if peers is None:
            peers = self._allgather_peers(advertise_host)
        self.peers = sorted(peers, key=lambda p: p[2])  # by start index
        self.total = max(p[3] for p in self.peers)
        spans = [(p[2], p[3]) for p in self.peers]
        cursor = 0
        for s0, s1 in spans:
            if s0 != cursor:
                raise ValueError(f"shard ranges not contiguous: {spans}")
            cursor = s1
        self._conns: dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self._cache: OrderedDict[int, GraphSample] = OrderedDict()
        self._cache_size = int(cache_size)
        self._sizes: np.ndarray | None = None  # lazy global size table
        self.remote_fetches = 0  # telemetry: audited by tests/bench

    def _allgather_peers(self, advertise_host: str | None):
        from jax.experimental import multihost_utils

        host = advertise_host or socket.gethostbyname(socket.gethostname())
        mine = np.array(
            [_ip_to_int(host), self.server.port, self.start, self.stop], np.int64
        )
        allv = np.asarray(multihost_utils.process_allgather(mine))
        return [
            (_int_to_ip(int(r[0])), int(r[1]), int(r[2]), int(r[3])) for r in allv
        ]

    # -- Sequence API --------------------------------------------------------
    def __len__(self) -> int:
        return self.total

    @property
    def attrs(self) -> dict:
        return self.ds.attrs

    def _owner(self, i: int):
        for rank, (h, p, s0, s1) in enumerate(self.peers):
            if s0 <= i < s1:
                return rank, h, p, s0
        raise IndexError(i)

    def _conn(self, rank: int, host: str, port: int) -> socket.socket:
        sock = self._conns.get(rank)
        if sock is None:
            sock = socket.create_connection((host, port), timeout=120)
            self._conns[rank] = sock
        return sock

    def __getitem__(self, i) -> GraphSample:
        i = int(i)
        if self.start <= i < self.stop:
            return self.ds[i - self.start]
        return self.fetch([i])[0]

    def sample_sizes(self, indices) -> np.ndarray:
        """[k, 2] (num_nodes, num_edges) for arbitrary GLOBAL indices. The
        full size table is exchanged ONCE (one request per peer, a few
        int64s per sample), so bucket planning never turns into per-sample
        content fetches across the network."""
        if self._sizes is None:
            self._sizes = self._fetch_all_sizes()
        return self._sizes[np.asarray(indices, np.int64)]

    def _fetch_all_sizes(self) -> np.ndarray:
        out = np.zeros((self.total, 2), np.int64)
        with self._lock:
            for rank, (host, port, s0, s1) in enumerate(self.peers):
                if s0 == self.start and s1 == self.stop:
                    out[s0:s1] = self.ds.sample_sizes(range(s1 - s0))
                    continue
                sock = self._conn(rank, host, port)
                buf = io.BytesIO()
                np.savez(buf, idx=np.zeros((0,), np.int64),
                         range=np.asarray([s0, s1], np.int64),
                         sizes=np.asarray(1, np.int64))
                _send_msg(sock, buf.getvalue())
                with np.load(io.BytesIO(_recv_msg(sock)),
                             allow_pickle=False) as z:
                    if int(z["n"]) < 0:
                        raise RuntimeError(
                            f"size-table fetch misrouted at {host}:{port} "
                            f"(expected range [{s0}, {s1}))"
                        )
                    out[s0:s1] = z["sizes"]
        return out

    def fetch(self, indices) -> list[GraphSample]:
        """Batched read of arbitrary GLOBAL indices: local ones from mmap,
        remote ones with ONE request per owning host."""
        out: dict[int, GraphSample] = {}
        by_owner: dict[int, list[int]] = {}
        with self._lock:
            for i in map(int, indices):
                if self.start <= i < self.stop:
                    out[i] = self.ds[i - self.start]
                elif i in self._cache:
                    self._cache.move_to_end(i)
                    out[i] = self._cache[i]
                else:
                    rank = self._owner(i)[0]
                    by_owner.setdefault(rank, []).append(i)
            for rank, idxs in by_owner.items():
                host, port, s0, s1 = self.peers[rank]
                sock = self._conn(rank, host, port)
                buf = io.BytesIO()
                np.savez(buf, idx=np.asarray([i - s0 for i in idxs], np.int64),
                         range=np.asarray([s0, s1], np.int64))
                _send_msg(sock, buf.getvalue())
                payload = _recv_msg(sock)
                with np.load(io.BytesIO(payload), allow_pickle=False) as z:
                    if int(z["n"]) < 0:
                        have = z["have"] if "have" in z.files else "?"
                        raise RuntimeError(
                            f"shard fetch misrouted: peer at {host}:{port} "
                            f"owns global range {have}, expected [{s0}, {s1})"
                            " — check the advertised addresses (loopback "
                            "hostnames on multi-host clusters are the usual "
                            "cause; pass advertise_host explicitly)"
                        )
                samples = _decode_samples(payload)
                self.remote_fetches += len(samples)
                for i, s in zip(idxs, samples):
                    out[i] = s
                    self._cache[i] = s
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
        return [out[int(i)] for i in indices]

    def pad_spec(self, batch_size: int, node_multiple: int = 8, edge_multiple: int = 128):
        """PadSpec from shard-local writer stats, maxed across hosts when
        under jax.distributed (stats are per-shard)."""
        a = dict(self.attrs)
        if "max_nodes" not in a:
            raise ValueError("packed shard lacks size stats; re-write with PackedWriter")
        try:
            import jax

            multi = jax.process_count() > 1
        except Exception:
            multi = False
        if multi:
            # MUST succeed: silently falling back to shard-local maxima
            # would give hosts different static shapes and hang/crash the
            # SPMD program far from the root cause
            from jax.experimental import multihost_utils

            stats = np.asarray(
                multihost_utils.process_allgather(
                    np.array([a["max_nodes"], a["max_edges"]], np.int64)
                )
            )
            a["max_nodes"] = int(stats[:, 0].max())
            a["max_edges"] = int(stats[:, 1].max())
        from .packed import pad_spec_from_stats

        return pad_spec_from_stats(a, batch_size, node_multiple, edge_multiple)

    def loader(
        self,
        batch_size: int,
        rank: int = 0,
        world: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        pad=None,
        **kw,
    ):
        from ..graphs.batching import GraphLoader

        return GraphLoader(
            self,
            batch_size,
            pad=pad or self.pad_spec(batch_size),
            shuffle=shuffle,
            seed=seed,
            rank=rank,
            world=world,
            **kw,
        )

    def close(self) -> None:
        self.server.close()
        with self._lock:
            for sock in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()


def _ip_to_int(ip: str) -> int:
    return int.from_bytes(socket.inet_aton(ip), "big")


def _int_to_ip(v: int) -> str:
    return socket.inet_ntoa(v.to_bytes(4, "big"))


__all__ = ["ShardedStore", "ShardServer"]
