"""Non-shared-filesystem data plane: per-host packed shards + TCP sample
exchange — the role of the reference's DDStore
(``hydragnn/utils/datasets/distdataset.py:72-367``: each rank materializes
only its window and serves remote ``get()`` fetches over MPI RMA windows).

``GlobalShuffleStore`` (``packed.py``) assumes every host can mmap the SAME
packed file — a shared filesystem or pre-replicated copy. When each host
instead holds only its own shard on local disk, ``ShardedStore`` fills the
gap:

* host ``h`` owns global indices ``[start_h, stop_h)`` backed by its local
  ``PackedDataset`` shard;
* a per-host ``ShardServer`` thread answers batched index fetches over TCP
  (the MPI-RMA → TCP translation; one request per owner per batch);
* the address book (host, port, index range) is exchanged once through
  ``jax.experimental.multihost_utils.process_allgather`` when running under
  ``jax.distributed`` — or passed explicitly (``peers=``) for tests;
* reads of any global index then work from every host: local → zero-copy
  mmap, remote → fetch + bounded LRU cache.

Feed the store straight to ``GraphLoader(..., rank, world, shuffle=True)``:
each host's per-epoch stride of the shared global permutation now spans the
WHOLE corpus (the DDStore property), fetching the ~(world-1)/world
non-local samples from their owners.

Elastic tier (replication + failover): peer ranges may OVERLAP — with
``replication_factor=R`` every range is served by R owners holding mirror
shards, a dead/slow owner fails over to a replica instead of stalling the
fleet, dead peers are quarantined with re-probe backoff (a background
prober pings them over the same protocol and lifts the quarantine when the
host returns), and watchdog deadlines bracket every replica round-trip so
even a byte-dribbling peer cannot park an epoch. See the ``ShardedStore``
docstring and README "Elastic data plane".

Wire format is a length-prefixed binary array framing (name + dtype str +
shape + raw bytes per array): decode is ``np.frombuffer`` views — no
pickle anywhere, and object dtypes are rejected on both ends, so a
malicious peer cannot execute code on load. The trust model is the
reference's — an internal cluster network, like its MPI windows. The
optional ``auth_token`` and bindable listen interface protect against
MISCONFIGURATION (two jobs sharing a fabric, a peer dialing the wrong
port), not against a network attacker: the token travels plaintext over
unencrypted TCP and is replayable. Genuinely untrusted networks need
transport security (TLS/WireGuard) underneath, same as MPI would.

The transport itself (framing, codec, auth check, pooled sockets,
watchdog-bracketed round-trips, quarantine clock) lives in
``hydragnn_tpu.utils.wire`` — ONE implementation shared with the fleet
serving tier (``serve/fleet``), factored out of this module where PR 4
grew it. This module keeps the data-plane policy: shard ownership,
replica failover, the re-probe prober, the sample cache.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time
import warnings
import weakref
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..graphs.graph import GraphSample
from ..utils import wire
from ..utils.wire import (
    ConnPool as _ConnPool,  # noqa: F401  (back-compat alias)
    HealthTable,
    RoundTripper,
    WireServer,
    check_pong,
)
from .packed import PackedDataset

# back-compat aliases: the wire protocol grew here (PR 4) and tests/tools
# import these by their original private names
_pack_arrays = wire.pack_arrays
_unpack_arrays = wire.unpack_arrays
_send_msg = wire.send_msg
_recv_msg = wire.recv_msg
_recv_exact = wire.recv_exact
_sample_to_arrays = wire.sample_to_arrays
_sample_from_arrays = wire.sample_from_arrays
_copy_sample = wire.copy_sample
_encode_samples = wire.encode_samples
_samples_from_frame = wire.samples_from_frame


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    """Elastic data-plane knobs, single-sourced: these field defaults ARE
    the ``Dataset.store`` config defaults (``config.update_config`` fills
    the block from ``store_config_defaults``) and the ``ShardedStore``
    constructor defaults — one place to tune, nothing to drift.

    * ``replication_factor`` — owners expected per sample range. R=1 is the
      PR 3 data plane (a dead owner stalls the fleet); R>1 lets ``fetch``
      fail over to a live replica and quarantine the dead peer.
    * ``peer_timeout`` — connect/read deadline per peer socket. A peer
      slower than this IS down for failover purposes (gray failures stall
      epochs exactly like crashes; the reference's MPI windows simply hang).
    * ``probe_interval`` — how often the background prober re-pings
      quarantined peers so a recovered host rejoins without operator action.
    * ``quarantine_base_s``/``quarantine_cap_s`` — re-probe backoff window:
      each consecutive failed probe doubles the quarantine, capped so a
      rebooted host waits at most the cap before serving again.
    """

    replication_factor: int = 1
    peer_timeout: float = 120.0
    probe_interval: float = 2.0
    quarantine_base_s: float = 1.0
    quarantine_cap_s: float = 30.0


def store_config_defaults() -> dict:
    """``{config key: default}`` for the ``Dataset.store`` block. EVERY
    ``StoreConfig`` field is a config key, so the mapping is derived from
    ``dataclasses.fields`` — a hand-maintained key tuple would let a future
    field silently drop out of the schema/apply_config plumbing."""
    return {f.name: f.default for f in dataclasses.fields(StoreConfig)}


# Live ShardServer registry (creation order, weakly held): the chaos
# harness's ``dead_shard``/``slow_peer`` faults need a handle on "one of
# the running shard servers" without threading store objects through the
# train loop's fault hooks.
_LIVE_SERVERS: "weakref.WeakValueDictionary[int, ShardServer]" = (
    weakref.WeakValueDictionary()
)
_LIVE_SERVERS_SEQ = [0]
_LIVE_SERVERS_LOCK = threading.Lock()


def live_servers() -> "list[ShardServer]":
    """Currently-running ShardServers in this process, creation order."""
    with _LIVE_SERVERS_LOCK:
        items = sorted(_LIVE_SERVERS.items())
    return [srv for _, srv in items if not srv.closed]


class ShardServer(WireServer):
    """Threaded TCP server answering batched sample fetches from the local
    shard (on the shared ``utils.wire`` transport — auth, ping, instant
    dead-host ``close()``, and chaos ``set_delay`` live in ``WireServer``).
    Request: a ``pack_arrays`` frame {"idx": int64[k] LOCAL indices,
    "range": [start, stop] the GLOBAL range the client believes this server
    owns}; response:
    the encoded samples, or an error record when the range doesn't match —
    a misrouted connection (e.g. every host advertising a loopback address,
    so peers dial their OWN server) must fail LOUDLY, not silently serve
    wrong samples.

    ``host`` restricts the listening interface (default all interfaces —
    the reference's MPI-window trust model on an isolated cluster fabric);
    ``auth_token`` adds a per-request shared-secret check (n=-2 error
    record on mismatch). The token is a MISCONFIGURATION guard — it stops
    a peer from another job/cluster accidentally reading this shard — not
    network security: it travels plaintext and is replayable, so an
    attacker who can sniff the fabric already has the data. The compare is
    ``hmac.compare_digest`` so the guard itself doesn't leak the token
    byte-by-byte through timing. ``_test_delay_s`` is a test hook: a
    per-request sleep that makes fetch-overlap measurements deterministic
    instead of timing-noise-bound."""

    def __init__(self, ds: PackedDataset, start: int, stop: int,
                 host: str = "0.0.0.0", auth_token: str | None = None,
                 port: int = 0, _test_delay_s: float = 0.0):
        self.ds = ds
        self.start, self.stop = int(start), int(stop)
        # port=0 picks an ephemeral port (the default); a fixed port lets a
        # restarted host come back at the address its peers already
        # advertise, so the prober's quarantine-lift finds it
        super().__init__(host=host, port=port, auth_token=auth_token,
                         name="ShardServer", _test_delay_s=_test_delay_s)
        with _LIVE_SERVERS_LOCK:
            _LIVE_SERVERS_SEQ[0] += 1
            _LIVE_SERVERS[_LIVE_SERVERS_SEQ[0]] = self

    def pong_fields(self) -> dict:
        # the prober verifies it is talking to the peer it thinks it is
        # (the advertised range) before lifting a quarantine
        return {"have": np.asarray([self.start, self.stop], np.int64)}

    def handle_frame(self, z: dict) -> bytes | dict:
        want = z.get("range")
        if want is not None and (
            int(want[0]) != self.start or int(want[1]) != self.stop
        ):
            return {
                "n": np.asarray(-1, np.int64),
                "have": np.asarray([self.start, self.stop], np.int64),
            }
        if "sizes" in z:
            # size-table op: (num_nodes, num_edges) for the whole shard
            # straight from the count index — bucket planning never pulls
            # sample content
            return {
                "n": np.asarray(0, np.int64),
                "sizes": self.ds.sample_sizes(range(self.stop - self.start)),
            }
        return _encode_samples([self.ds[int(i)] for i in z["idx"]])


class ShardedStore:
    """Global-index Sequence over per-host shards (see module docstring).

    ``peers``: list over ranks of ``(host, port, start, stop)``. When None,
    exchanged via ``multihost_utils.process_allgather`` (requires
    ``jax.distributed`` to be initialized).

    Elastic data plane (replication + failover): peer ranges may OVERLAP —
    with ``replication_factor=R`` every sample range is advertised by R
    owners (each holding a mirror copy of the range in its local shard
    file), and a remote fetch walks the owners in locality-preferring order
    (healthy replicas first, rotated per client so load spreads; quarantined
    peers last, as a final resort). A connect/timeout failure fails over to
    the next replica instead of raising, quarantines the dead peer (its
    pooled sockets are evicted, re-probe backoff doubles up to a cap), and a
    background prober pings quarantined peers — piggybacked on the fetch
    protocol — so a recovered host rejoins without operator action. A
    watchdog deadline brackets every replica round-trip: a byte-dribbling
    peer that never trips the per-``recv`` socket timeout is forcibly
    disconnected and quarantined rather than stalling the epoch. Only
    transport faults fail over; protocol errors (auth mismatch, misroute,
    server-side exception) stay loud — a *reachable but wrong* peer is a
    configuration bug replicas must not paper over.
    """

    def __init__(
        self,
        shard_path: str,
        start: int,
        stop: int,
        peers: list[tuple[str, int, int, int]] | None = None,
        cache_size: int = 4096,
        advertise_host: str | None = None,
        bind_host: str = "0.0.0.0",
        auth_token: str | None = None,
        max_idle_conns_per_peer: int = 4,
        replication_factor: int | None = None,
        peer_timeout: float | None = None,
        probe_interval: float | None = None,
        quarantine_base_s: float | None = None,
        quarantine_cap_s: float | None = None,
        _test_delay_s: float = 0.0,
    ):
        self.ds = PackedDataset(shard_path)
        if len(self.ds.subset) != stop - start:
            raise ValueError(
                f"shard {shard_path} holds {len(self.ds.subset)} samples but "
                f"claims global range [{start}, {stop})"
            )
        self.start, self.stop = int(start), int(stop)
        self.server = ShardServer(self.ds, start, stop, host=bind_host,
                                  auth_token=auth_token,
                                  _test_delay_s=_test_delay_s)
        if peers is None:
            peers = self._allgather_peers(advertise_host)
        self.peers = sorted(peers, key=lambda p: (p[2], p[3]))
        self.total = max(p[3] for p in self.peers)
        # coverage check: the UNION of peer spans must cover [0, total)
        # with no gap — overlaps (replicas) are the feature, gaps are fatal
        spans = sorted({(p[2], p[3]) for p in self.peers})
        cursor = 0
        for s0, s1 in spans:
            if s0 > cursor:
                raise ValueError(
                    f"shard ranges leave [{cursor}, {s0}) unserved: {spans}"
                )
            cursor = max(cursor, s1)
        self._auth_token = auth_token
        # elastic knobs, precedence: env flag > constructor-explicit arg >
        # Dataset.store config block (apply_config) > StoreConfig default.
        # Explicit args are REMEMBERED so a later apply_config of a
        # schema-filled block (which carries defaults for every key) can't
        # silently clobber what the caller asked for.
        self._explicit_cfg = {
            key
            for key, val in (
                ("replication_factor", replication_factor),
                ("peer_timeout", peer_timeout),
                ("probe_interval", probe_interval),
                ("quarantine_base_s", quarantine_base_s),
                ("quarantine_cap_s", quarantine_cap_s),
            )
            if val is not None
        }
        d = StoreConfig()
        self.replication_factor = int(
            replication_factor if replication_factor is not None
            else d.replication_factor
        )
        self.peer_timeout = float(
            peer_timeout if peer_timeout is not None else d.peer_timeout
        )
        self.probe_interval = float(
            probe_interval if probe_interval is not None else d.probe_interval
        )
        self.quarantine_base_s = float(
            quarantine_base_s if quarantine_base_s is not None
            else d.quarantine_base_s
        )
        self.quarantine_cap_s = float(
            quarantine_cap_s if quarantine_cap_s is not None
            else d.quarantine_cap_s
        )
        self._apply_env_overrides()
        self._check_replication()
        # deterministic per-client replica rotation (see _replica_order):
        # clients prefer DIFFERENT replicas so replicated reads spread
        # instead of hammering each range's first-listed owner
        self._rot = (self.start * 2654435761 + self.stop) % (1 << 31)
        # the shared wire client: pooled sockets + token stamping +
        # watchdog-bracketed round-trips (utils.wire.RoundTripper)
        self._rt = RoundTripper(
            self.peer_timeout, auth_token=auth_token,
            max_idle_per_peer=max_idle_conns_per_peer,
        )
        # the lock guards ONLY cache/telemetry bookkeeping; network
        # round-trips run outside it so concurrent fetches overlap
        self._lock = threading.Lock()
        self._cache: OrderedDict[int, GraphSample] = OrderedDict()  # guarded-by: _lock
        self._cache_size = int(cache_size)
        self._sizes: np.ndarray | None = None  # guarded-by: _sizes_lock
        self._sizes_lock = threading.Lock()
        self._executor: ThreadPoolExecutor | None = None  # guarded-by: _lock
        self.remote_fetches = 0  # guarded-by: _lock (audited by tests/bench)
        self.failover_fetches = 0  # guarded-by: _lock (replica re-fetches)
        self.quarantine_events = 0  # guarded-by: _lock (peer-down events)
        # quarantine clock: rank -> {"until", "backoff", "failures"}; a rank
        # is quarantined while now < until AND the entry exists (the prober —
        # or a successful last-resort fetch — removes it). Shared
        # implementation with the fleet router (utils.wire.HealthTable).
        self._health_table = HealthTable(
            self.quarantine_base_s, self.quarantine_cap_s
        )
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None

    @property
    def _pool(self):
        """The per-peer socket pool (tests poke ``_idle``/``timeout``)."""
        return self._rt.pool

    @property
    def _health(self) -> dict:
        return self._health_table.entries

    @property
    def _health_lock(self):
        return self._health_table.lock

    def _apply_env_overrides(self) -> None:
        from ..utils import flags

        env_r = flags.get(flags.REPLICATION)
        if env_r is not None:
            self.replication_factor = int(env_r)
        env_t = flags.get(flags.PEER_TIMEOUT)
        if env_t is not None:
            self.peer_timeout = float(env_t)

    def apply_config(self, cfg: dict) -> None:
        """Apply a ``Dataset.store`` config block (schema-filled defaults)
        to a live store: ``run_training`` calls this so a store constructed
        before the config was loaded still honors it. Knobs the caller set
        EXPLICITLY at construction are kept — the schema fills the block
        with defaults for every key, and letting those overwrite an
        explicit ``replication_factor=2`` would silently disable the
        elastic layer. Env flags keep the last word, matching every other
        HYDRAGNN_* knob."""
        for key in store_config_defaults():
            if key in self._explicit_cfg:
                continue
            if cfg.get(key) is not None:
                setattr(self, key, type(getattr(self, key))(cfg[key]))
        self._apply_env_overrides()
        # the timeout setter also drops the armed watchdog so the next
        # round-trip rebuilds it with the new deadline
        self._rt.timeout = self.peer_timeout
        self._health_table.base_s = self.quarantine_base_s
        self._health_table.cap_s = self.quarantine_cap_s
        self._check_replication()

    def _check_replication(self) -> None:
        """Warn when any elementary range has fewer owners than the
        configured replication factor — an under-replicated range is one
        host loss away from stalling the fleet, which is exactly what
        replication_factor > 1 was supposed to prevent."""
        if self.replication_factor <= 1:
            return
        bounds = sorted({b for p in self.peers for b in (p[2], p[3])})
        worst, where = None, None
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            n = sum(1 for p in self.peers if p[2] <= lo and hi <= p[3])
            if worst is None or n < worst:
                worst, where = n, (lo, hi)
        if worst is not None and worst < self.replication_factor:
            warnings.warn(
                f"range [{where[0]}, {where[1]}) has {worst} owner(s) but "
                f"replication_factor={self.replication_factor} — a single "
                "host loss can stall fetches for under-replicated ranges"
            )

    def _allgather_peers(self, advertise_host: str | None):
        from jax.experimental import multihost_utils

        host = advertise_host or socket.gethostbyname(socket.gethostname())
        mine = np.array(
            [_ip_to_int(host), self.server.port, self.start, self.stop], np.int64
        )
        allv = np.asarray(multihost_utils.process_allgather(mine))
        return [
            (_int_to_ip(int(r[0])), int(r[1]), int(r[2]), int(r[3])) for r in allv
        ]

    # -- Sequence API --------------------------------------------------------
    def __len__(self) -> int:
        return self.total

    @property
    def attrs(self) -> dict:
        return self.ds.attrs

    def _is_self(self, rank: int) -> bool:
        _, port, s0, s1 = self.peers[rank]
        return (
            s0 == self.start
            and s1 == self.stop
            and port in (0, self.server.port)
        )

    def _owners(self, i: int) -> tuple[int, ...]:
        """Every REMOTE peer rank whose advertised span contains global
        index ``i`` (self-entries excluded — local reads never touch the
        network). With replication this is the replica set a fetch may
        fail over across."""
        ranks = tuple(
            rank
            for rank, (_, _, s0, s1) in enumerate(self.peers)
            if s0 <= i < s1 and not self._is_self(rank)
        )
        if not ranks and not (self.start <= i < self.stop):
            raise IndexError(i)
        return ranks

    # -- peer health / quarantine -------------------------------------------
    def _quarantined(self, rank: int) -> bool:
        return self._health_table.quarantined(rank)

    def _bump_quarantine(self, rank: int) -> bool:
        """Record one more failure for ``rank`` in the health table —
        re-probe deadline pushed out by the current backoff, backoff
        doubled up to the cap (``utils.wire.HealthTable`` — THE single
        implementation of the quarantine clock, shared by the fetch path,
        the prober, and the fleet router). Returns True when this created
        the entry (a fresh peer-down transition)."""
        return self._health_table.bump(rank)

    def _mark_peer_down(self, rank: int, err: BaseException, failover: bool) -> None:
        """Quarantine a peer after a transport failure: evict its pooled
        sockets (they spent the outage half-dead), arm the re-probe backoff,
        and wake the background prober so the peer rejoins automatically
        when it answers pings again."""
        host, port, s0, s1 = self.peers[rank]
        announce = self._bump_quarantine(rank)
        self._pool.evict(rank)
        if announce:
            with self._lock:
                self.quarantine_events += 1
            from .. import telemetry as tel

            tel.counter("store_quarantine_events_total").inc()
            tel.emit(
                "failover", peer=rank, host=host, port=port,
                error=type(err).__name__,
                has_replica=bool(failover),
            )
            warnings.warn(
                f"shard peer {host}:{port} (range [{s0}, {s1})) is down "
                f"({type(err).__name__}: {err}): quarantined"
                + (", failing over to a replica" if failover else
                   " — range has NO live replica; fetches keep attempting it")
            )
        self._ensure_prober()

    def _mark_peer_up(self, rank: int, announce: bool = False) -> None:
        was = self._health_table.lift(rank)
        if was is not None and announce:
            host, port, s0, s1 = self.peers[rank]
            warnings.warn(
                f"shard peer {host}:{port} (range [{s0}, {s1})) answers "
                f"again after {was['failures']} failed probe(s): quarantine "
                "lifted"
            )

    def stats(self) -> dict:
        """The data plane's counters in the same shape the serve surfaces
        use (and published through the same telemetry registry): remote /
        failover fetch totals, peer-down events, cache occupancy, and the
        current quarantine census — the operator's one-call health view of
        the elastic store."""
        with self._lock:
            out = {
                "remote_fetches": self.remote_fetches,
                "failover_fetches": self.failover_fetches,
                "quarantine_events": self.quarantine_events,
                "cache_entries": len(self._cache),
                "cache_size": self._cache_size,
            }
        with self._health_lock:
            out["quarantined_peers"] = len(self._health)
        out["peers"] = len(self.peers)
        from .. import telemetry as tel

        tel.publish("sharded_store", out)
        return out

    def _replica_order(self, ranks) -> list[int]:
        """Failover order over a replica set: healthy peers first, rotated
        by a per-client constant so different clients spread load across
        replicas instead of all hammering the first-listed owner;
        quarantined peers last (soonest-re-probe first) as a final resort
        when nothing healthy is left (``utils.wire.HealthTable.order``)."""
        return self._health_table.order(ranks, rot=self._rot)

    def _ensure_prober(self) -> None:
        with self._health_lock:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                return
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="hydragnn-shard-prober",
                daemon=True,
            )
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        """Background re-probe of quarantined peers (one lazy daemon
        thread, alive only while something is quarantined): ping — a
        protocol op the server answers without touching its dataset — and
        lift the quarantine when the peer responds with the range it was
        advertised for. A wrong-range pong stays quarantined: resurrecting
        a restarted-with-different-data peer would silently serve wrong
        samples."""
        while not self._probe_stop.wait(self.probe_interval):
            with self._health_lock:
                if not self._health:
                    # all clear: exit. Clearing the handle UNDER the lock
                    # closes the race with _ensure_prober — a quarantine
                    # recorded while this thread is still is_alive() but
                    # past its exit decision must start a fresh prober,
                    # not trust a dying one
                    self._probe_thread = None
                    return
                now = time.monotonic()
                due = [r for r, h in self._health.items() if now >= h["until"]]
            for rank in due:
                host, port, s0, s1 = self.peers[rank]
                try:
                    # watchdog-bracketed like any replica round-trip: a
                    # quarantined peer reborn as a byte-dribbler would
                    # otherwise wedge THE prober thread forever (it is a
                    # singleton — a hung probe means no quarantine is ever
                    # probe-lifted again for the rest of the process)
                    cell: dict = {"sock": None}
                    with self._guard_round_trip(host, port, cell):
                        z = _unpack_arrays(self._request(
                            rank, host, port, attempts=1, _sock_cell=cell,
                            ping=np.asarray(1, np.int64),
                        ))
                    # the shared pong validation (wire.check_pong): the
                    # peer must advertise the exact range it is listed for
                    check_pong(
                        z, f"probe of shard peer {host}:{port}",
                        have=[s0, s1],
                    )
                except (ConnectionError, OSError):
                    self._bump_quarantine(rank)
                    continue
                self._mark_peer_up(rank, announce=True)

    def _request(
        self, rank: int, host: str, port: int, attempts: int | None = None,
        _sock_cell: dict | None = None, **fields,
    ) -> bytes:
        """One request/response round-trip on a pooled socket — no shared
        lock held, so concurrent callers overlap their network waits. The
        socket returns to the pool only after a clean round-trip; any error
        closes it (a half-read stream cannot be reused).

        Transient-fault policy (the request is idempotent, so retrying is
        always safe): a stale POOLED socket (dropped by the peer/NAT while
        parked) retries immediately on a fresh connection without counting
        an attempt; a FRESH-connection failure — connect refused/reset/
        timed out mid-stream — retries per the shared ``utils.retry``
        policy (``HYDRAGNN_STORE_RETRIES`` total attempts, exponential
        backoff + jitter, a warning per retry), so a blip in the fabric
        degrades to a logged pause instead of killing the epoch. The last
        failure re-raises. ``attempts=1`` pins a single try — the failover
        path does its own retrying ACROSS replicas, where a per-replica
        backoff loop would multiply the outage by the replica count.
        ``_sock_cell`` (when given) exposes the in-flight socket so a
        watchdog can sever a wedged round-trip from its monitor thread.
        The round-trip itself is ``utils.wire.RoundTripper.request`` —
        this wrapper only resolves the retry policy (store flag vs pinned
        attempts)."""
        from ..utils.retry import RetryPolicy, store_policy

        policy = (
            store_policy() if attempts is None
            else RetryPolicy(attempts=max(1, int(attempts)))
        )
        return self._rt.request(
            rank, host, port, policy=policy, _sock_cell=_sock_cell, **fields
        )

    def _failover_request(self, owner_ranks, fields_for, what: str):
        """One replicated request: walk the replica set in
        ``_replica_order``, one attempt per replica per round — a transport
        failure quarantines the peer and moves on; only when EVERY replica
        failed does a round end, sleeping per the shared retry policy
        before the next sweep (the fabric may be blipping, not the hosts).
        Protocol errors (``_check_status``) raise immediately on purpose.

        A watchdog deadline brackets each attempt: a peer that dribbles
        bytes forever (resetting the per-recv socket timeout every chunk)
        gets its socket severed from the monitor thread, which surfaces
        here as an OSError and takes the normal quarantine+failover path.

        When trace propagation is armed, the walk runs under one
        ``request_id`` (adopted from the ambient context or minted here),
        every hop emits a ``store_hop`` child record naming the peer it
        tried (``outcome=quarantined`` for the transport-failed peer,
        ``outcome=served`` for the winner), and the peer sees the same id
        in its own journal — one fetch, one cross-process timeline.

        Returns ``(decoded frame, rank, s0, s1)`` of the replica that
        answered. ``fields_for(s0, s1)`` builds the request for an owner
        advertising ``[s0, s1)`` — replicas of one range may be advertised
        with different spans, and local indices are span-relative."""
        from .. import telemetry as tel

        traced = tel.propagate_enabled()
        if not traced:
            return self._failover_walk(owner_ranks, fields_for, what, False)
        rid = tel.get_context().get("request_id") or tel.new_request_id()
        with tel.scoped_context(request_id=rid):
            return self._failover_walk(owner_ranks, fields_for, what, True)

    def _failover_walk(self, owner_ranks, fields_for, what: str,
                       traced: bool):
        from .. import telemetry as tel
        from ..utils.retry import store_policy

        policy = store_policy()
        last_err: BaseException | None = None
        failed_over = False
        hop = 0
        for rnd in range(policy.attempts):
            if rnd:
                sleep_s = policy.delay(rnd)
                warnings.warn(
                    f"{what}: every replica failed "
                    f"({type(last_err).__name__}: {last_err}); retry round "
                    f"{rnd}/{policy.attempts - 1} in {sleep_s:.2f}s "
                    "(HYDRAGNN_STORE_RETRIES tunes the cap)"
                )
                time.sleep(sleep_s)
            order = self._replica_order(owner_ranks)
            for rank in order:
                host, port, s0, s1 = self.peers[rank]
                cell: dict = {"sock": None}
                t0_wall = time.time()
                try:
                    with self._guard_round_trip(host, port, cell):
                        z = _unpack_arrays(self._request(
                            rank, host, port, attempts=1, _sock_cell=cell,
                            **fields_for(s0, s1),
                        ))
                except (ConnectionError, OSError) as e:
                    last_err = e
                    failed_over = True
                    if traced:
                        tel.emit(
                            "store_hop", hop=hop, peer=rank, host=host,
                            port=port, outcome="quarantined",
                            error=type(e).__name__,
                        )
                        if tel.trace_enabled():
                            tel.add_span(
                                f"store_hop:{rank}", t0_wall,
                                time.time() - t0_wall,
                                args={"peer": rank, "outcome": "quarantined"},
                            )
                    hop += 1
                    self._mark_peer_down(rank, e, failover=len(order) > 1)
                    continue
                self._check_status(z, host, port, s0, s1)
                self._mark_peer_up(rank)
                if traced:
                    tel.emit(
                        "store_hop", hop=hop, peer=rank, host=host,
                        port=port, outcome="served",
                        failed_over=bool(failed_over),
                        dur_s=round(time.time() - t0_wall, 6),
                    )
                    if tel.trace_enabled():
                        tel.add_span(
                            f"store_hop:{rank}", t0_wall,
                            time.time() - t0_wall,
                            args={"peer": rank, "outcome": "served"},
                        )
                if failed_over:
                    n = int(z.get("n", np.asarray(0)))
                    with self._lock:
                        self.failover_fetches += max(n, 0)
                    tel.counter("store_failover_fetches_total").inc(max(n, 0))
                return z, rank, s0, s1
        raise ConnectionError(
            f"{what}: all {len(owner_ranks)} replica(s) failed after "
            f"{policy.attempts} round(s); last error: "
            f"{type(last_err).__name__}: {last_err}"
        )

    def _guard_round_trip(self, host: str, port: int, cell: dict):
        """Watchdog context for one replica round-trip
        (``utils.wire.RoundTripper.guard``): if the round-trip outlives
        ~1.25x the peer timeout (the per-recv socket timeout never fired —
        a dribbling peer), the monitor thread severs the in-flight socket,
        converting the hang into the OSError the failover path already
        handles. Disabled for non-finite/zero timeouts."""
        return self._rt.guard(
            host, port, cell, what=f"shard round-trip to {host}:{port}"
        )

    @staticmethod
    def _check_status(z: dict[str, np.ndarray], host: str, port: int,
                      s0: int, s1: int):
        n = int(z["n"])
        if n == -3:
            detail = bytes(np.asarray(z.get("detail", []), np.uint8)).decode(
                errors="replace"
            )
            raise RuntimeError(
                f"shard server at {host}:{port} failed serving the request: "
                f"{detail or 'unknown error'}"
            )
        if n == -2:
            raise RuntimeError(
                f"shard fetch rejected by {host}:{port}: auth token "
                "mismatch (pass the same auth_token on every host)"
            )
        if n == -1:
            have = z.get("have", "?")
            raise RuntimeError(
                f"shard fetch misrouted: peer at {host}:{port} "
                f"owns global range {have}, expected [{s0}, {s1})"
                " — check the advertised addresses (loopback "
                "hostnames on multi-host clusters are the usual "
                "cause; pass advertise_host explicitly)"
            )

    def __getitem__(self, i) -> GraphSample:
        i = int(i)
        if self.start <= i < self.stop:
            return self.ds[i - self.start]
        return self.fetch([i])[0]

    def sample_sizes(self, indices) -> np.ndarray:
        """[k, 2] (num_nodes, num_edges) for arbitrary GLOBAL indices. The
        full size table is exchanged ONCE (one request per peer, a few
        int64s per sample), so bucket planning never turns into per-sample
        content fetches across the network."""
        if self._sizes is None:
            with self._sizes_lock:
                if self._sizes is None:
                    self._sizes = self._fetch_all_sizes()
        return self._sizes[np.asarray(indices, np.int64)]

    def _fetch_all_sizes(self) -> np.ndarray:
        out = np.zeros((self.total, 2), np.int64)
        covered = np.zeros(self.total, bool)
        out[self.start:self.stop] = self.ds.sample_sizes(
            range(self.stop - self.start)
        )
        covered[self.start:self.stop] = True
        by_span: dict[tuple[int, int], list[int]] = {}
        for rank, (_, _, s0, s1) in enumerate(self.peers):
            if not self._is_self(rank):
                by_span.setdefault((s0, s1), []).append(rank)
        errors: list[str] = []
        for (s0, s1), ranks in sorted(by_span.items()):
            if covered[s0:s1].all():
                continue  # mirror of a span already served (e.g. our own)
            try:
                z, _, a0, a1 = self._failover_request(
                    ranks,
                    lambda a0, a1: dict(
                        idx=np.zeros((0,), np.int64),
                        range=np.asarray([a0, a1], np.int64),
                        sizes=np.asarray(1, np.int64),
                    ),
                    what=f"size table for range [{s0}, {s1})",
                )
            except (ConnectionError, OSError) as e:
                # a dead span GROUP is not yet fatal: replicas advertised
                # under different span boundaries may still cover this
                # data (a later, finer span fills it in) — only genuinely
                # uncovered indices after the sweep are an error
                errors.append(f"[{s0}, {s1}): {e}")
                continue
            out[a0:a1] = z["sizes"]
            covered[a0:a1] = True
        if not covered.all():
            lo = int(np.argmin(covered))
            raise ConnectionError(
                f"size table incomplete: no live owner covers index {lo} "
                f"(failed spans: {'; '.join(errors) or 'none'})"
            )
        return out

    def fetch(self, indices) -> list[GraphSample]:
        """Batched read of arbitrary GLOBAL indices: local ones from mmap,
        remote ones with ONE request per owning host. Only the cache
        bookkeeping is serialized; the network round-trips run on pooled
        per-call sockets, so concurrent callers (PrefetchLoader workers)
        overlap their remote fetches.

        Mutability contract: LOCAL samples are zero-copy READ-ONLY mmap
        views (an in-place write raises — loud, safe, and free); REMOTE
        samples are independent writable copies (the LRU cache keeps its
        own pristine instance, so a caller mutating one can never corrupt
        a later cache hit). Transforms that write in place must copy
        first; transforms that build new arrays work on both."""
        out: dict[int, GraphSample] = {}
        by_owner: dict[tuple[int, ...], list[int]] = {}
        remote: list[int] = []
        for i in map(int, indices):
            if self.start <= i < self.stop:
                out[i] = self.ds[i - self.start]  # zero-copy mmap read
            else:
                remote.append(i)
        if remote:
            pending: set[int] = set()
            hits: dict[int, GraphSample] = {}
            with self._lock:
                for i in remote:
                    if i in self._cache:
                        self._cache.move_to_end(i)
                        hits[i] = self._cache[i]  # reference only under lock
                    elif i not in pending:
                        pending.add(i)
                        # grouped by REPLICA SET, not single owner: every
                        # index in a group can fail over across the same
                        # peers, so one dead host re-routes the whole
                        # request instead of killing the batch
                        by_owner.setdefault(self._owners(i), []).append(i)
            # copy on hit OUTSIDE the lock (the lock serializes bookkeeping
            # only — array memcpy under it would stall concurrent workers):
            # callers mutate samples in place (transforms); the cache's
            # instance stays pristine
            for i, s in hits.items():
                out[i] = _copy_sample(s)
        def fetch_owner(item):
            ranks, idxs = item
            z, _, _, _ = self._failover_request(
                ranks,
                lambda a0, a1: dict(
                    idx=np.asarray([i - a0 for i in idxs], np.int64),
                    range=np.asarray([a0, a1], np.int64),
                ),
                what=f"fetch of {len(idxs)} sample(s) from range "
                     f"[{min(idxs)}, {max(idxs)}]",
            )
            return idxs, _samples_from_frame(z)

        if len(by_owner) <= 1:
            results = [fetch_owner(it) for it in by_owner.items()]
        else:
            # a shuffled global batch touches many owners — issue those
            # round-trips concurrently instead of paying one RTT per owner.
            # The executor is persistent (created once, closed with the
            # store): per-batch spawn/teardown would burn host CPU in the
            # hot path it exists to hide.
            if self._executor is None:
                with self._lock:
                    if self._executor is None:
                        # sized for CONCURRENT callers, not one fetch: N
                        # prefetch workers each fanning out to several
                        # owners share this pool, so a peers-count cap
                        # would serialize them against each other
                        self._executor = ThreadPoolExecutor(16)
            results = list(self._executor.map(fetch_owner, by_owner.items()))
        for idxs, samples in results:
            # the caller gets the freshly decoded instance; the cache keeps
            # its OWN copy (made before taking the lock) so later hits are
            # unaffected by whatever the caller does to this one
            cache_copies = [_copy_sample(s) for s in samples]
            from .. import telemetry as tel

            tel.counter("store_remote_fetches_total").inc(len(samples))
            with self._lock:
                self.remote_fetches += len(samples)
                for i, s, c in zip(idxs, samples, cache_copies):
                    out[i] = s
                    self._cache[i] = c
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
        # duplicate REMOTE indices must not share one writable instance
        # across result positions (the isolation contract above); local
        # read-only mmap views are safe to share
        result: list[GraphSample] = []
        emitted: set[int] = set()
        for i in map(int, indices):
            s = out[i]
            if i in emitted and not (self.start <= i < self.stop):
                s = _copy_sample(s)
            else:
                emitted.add(i)
            result.append(s)
        return result

    def fetch_many(self, indices) -> list[GraphSample]:
        """Bulk streaming read: the screening planner's wire op
        (``hydragnn_tpu.screen``). Same replica-set grouping and failover as
        :meth:`fetch` — ONE framed request per span per replica set, local
        spans straight from mmap — but it BYPASSES the LRU cache entirely:

        * no cache-bookkeeping lock traffic and no pristine-copy memcpy per
          sample on the hot path (a screen touches each sample exactly once,
          so a hit can never pay back the copy), and
        * no pollution — a multi-million-graph sweep would otherwise evict
          the training/serving working set the cache exists for.

        The per-sample :meth:`fetch` surface (cache, copy-on-hit isolation,
        duplicate-instance contract) is untouched; ``fetch`` remains the
        right call for loaders that revisit samples. Remote samples are
        freshly decoded (writable) instances; LOCAL spans remain zero-copy
        READ-ONLY mmap views, as in ``fetch``. Duplicate remote indices get
        independent copies (same isolation contract as ``fetch``)."""
        out: dict[int, GraphSample] = {}
        by_owner: dict[tuple[int, ...], list[int]] = {}
        for i in map(int, indices):
            if self.start <= i < self.stop:
                out[i] = self.ds[i - self.start]  # zero-copy mmap read
            elif i not in out:
                out[i] = None  # type: ignore[assignment]  # placeholder: dedup
                by_owner.setdefault(self._owners(i), []).append(i)

        def fetch_owner(item):
            ranks, idxs = item
            z, _, _, _ = self._failover_request(
                ranks,
                lambda a0, a1: dict(
                    idx=np.asarray([i - a0 for i in idxs], np.int64),
                    range=np.asarray([a0, a1], np.int64),
                ),
                what=f"bulk fetch of {len(idxs)} sample(s) from range "
                     f"[{min(idxs)}, {max(idxs)}]",
            )
            return idxs, _samples_from_frame(z)

        if len(by_owner) <= 1:
            results = [fetch_owner(it) for it in by_owner.items()]
        else:
            # same persistent fan-out pool as fetch: many owners, one RTT
            if self._executor is None:
                with self._lock:
                    if self._executor is None:
                        self._executor = ThreadPoolExecutor(16)
            results = list(self._executor.map(fetch_owner, by_owner.items()))
        n_remote = 0
        for idxs, samples in results:
            n_remote += len(samples)
            for i, s in zip(idxs, samples):
                out[i] = s
        if n_remote:
            from .. import telemetry as tel

            tel.counter("store_remote_fetches_total").inc(n_remote)
            with self._lock:
                self.remote_fetches += n_remote
        result: list[GraphSample] = []
        emitted: set[int] = set()
        for i in map(int, indices):
            s = out[i]
            if i in emitted and not (self.start <= i < self.stop):
                s = _copy_sample(s)
            else:
                emitted.add(i)
            result.append(s)
        return result

    def pad_spec(self, batch_size: int, node_multiple: int = 8, edge_multiple: int = 128):
        """PadSpec from shard-local writer stats, maxed across hosts when
        under jax.distributed (stats are per-shard)."""
        a = dict(self.attrs)
        if "max_nodes" not in a:
            raise ValueError("packed shard lacks size stats; re-write with PackedWriter")
        try:
            import jax

            multi = jax.process_count() > 1
        except Exception:
            multi = False
        if multi:
            # MUST succeed: silently falling back to shard-local maxima
            # would give hosts different static shapes and hang/crash the
            # SPMD program far from the root cause
            from jax.experimental import multihost_utils

            stats = np.asarray(
                multihost_utils.process_allgather(
                    np.array([a["max_nodes"], a["max_edges"]], np.int64)
                )
            )
            a["max_nodes"] = int(stats[:, 0].max())
            a["max_edges"] = int(stats[:, 1].max())
        from .packed import pad_spec_from_stats

        return pad_spec_from_stats(a, batch_size, node_multiple, edge_multiple)

    def loader(
        self,
        batch_size: int,
        rank: int = 0,
        world: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        pad=None,
        **kw,
    ):
        from ..graphs.batching import GraphLoader

        return GraphLoader(
            self,
            batch_size,
            pad=pad or self.pad_spec(batch_size),
            shuffle=shuffle,
            seed=seed,
            rank=rank,
            world=world,
            **kw,
        )

    def close(self) -> None:
        self._probe_stop.set()
        self.server.close()
        if self._executor is not None:
            self._executor.shutdown(wait=False)
        self._pool.close()


def _ip_to_int(ip: str) -> int:
    return int.from_bytes(socket.inet_aton(ip), "big")


def _int_to_ip(v: int) -> str:
    return socket.inet_ntoa(v.to_bytes(4, "big"))


__all__ = [
    "ShardServer",
    "ShardedStore",
    "StoreConfig",
    "live_servers",
    "store_config_defaults",
]
