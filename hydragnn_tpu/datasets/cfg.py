"""AtomEye CFG format reader (reference ``hydragnn/preprocess/
cfg_raw_dataset_loader.py`` via ``ase.io.read_cfg``; ASE-free implementation).

Supports the extended CFG layout:
    Number of particles = N
    A = <alat> Angstrom ...
    H0(i,j) = <cell component>
    [.NO_VELOCITY.]
    [entry_count = ...]
    then per-species blocks:  mass line / symbol line / "x y z [aux...]" rows
    (fractional coordinates), or legacy rows "mass symbol x y z ...".

Like the reference, a sibling ``*.bulk`` file (if present) supplies the
graph-level target (bulk modulus, ``cfg_raw_dataset_loader``'s FIXME path).
"""

from __future__ import annotations

import os
import re

import numpy as np

from ..graphs.graph import GraphSample
from .xyz import _Z


def read_cfg_file(path: str) -> GraphSample:
    with open(path) as f:
        lines = [ln.strip() for ln in f.readlines()]

    n = None
    alat = 1.0
    H = np.eye(3)
    body_start = 0
    for i, ln in enumerate(lines):
        if ln.lower().startswith("number of particles"):
            n = int(ln.split("=")[1])
        elif ln.startswith("A ") or ln.startswith("A="):
            alat = float(re.findall(r"[-\d.eE+]+", ln.split("=")[1])[0])
        elif ln.startswith("H0("):
            m = re.match(r"H0\((\d),(\d)\)\s*=\s*([-\d.eE+]+)", ln)
            if m:
                H[int(m.group(1)) - 1, int(m.group(2)) - 1] = float(m.group(3))
        elif ln and not ln.startswith((".", "#")) and "=" not in ln and i > 0:
            body_start = i
            break
    if n is None:
        raise ValueError(f"{path}: missing 'Number of particles'")

    cell = H * alat
    zs, frac = [], []
    cur_z = 0
    i = body_start
    while i < len(lines) and len(zs) < n:
        ln = lines[i]
        i += 1
        if not ln or ln.startswith("#"):
            continue
        parts = ln.split()
        if len(parts) == 1:
            if parts[0] in _Z:  # species symbol line
                cur_z = _Z[parts[0]]
            # else: mass line — skip
            continue
        if parts[0] in _Z:  # legacy "symbol x y z" rows
            cur_z = _Z[parts[0]]
            coords = [float(v) for v in parts[1:4]]
        elif len(parts) >= 5 and parts[1] in _Z:  # "mass symbol x y z"
            cur_z = _Z[parts[1]]
            coords = [float(v) for v in parts[2:5]]
        else:
            coords = [float(v) for v in parts[:3]]
        zs.append(cur_z)
        frac.append(coords)

    frac = np.asarray(frac, np.float64)
    pos = frac @ cell
    z = np.asarray(zs, np.float64).reshape(-1, 1)

    graph_target = 0.0
    bulk = os.path.splitext(path)[0] + ".bulk"
    if os.path.exists(bulk):
        with open(bulk) as f:
            graph_target = float(f.read().split()[0])

    return GraphSample(
        x=z,
        pos=pos,
        cell=cell,
        pbc=np.array([True, True, True]),
        extras={
            "node_table": z,
            "graph_table": np.array([graph_target], np.float64),
        },
    )


def load_cfg_dir(path: str) -> list[GraphSample]:
    samples = []
    for name in sorted(os.listdir(path)):
        if name.endswith(".cfg"):
            samples.append(read_cfg_file(os.path.join(path, name)))
    if not samples:
        raise FileNotFoundError(f"no .cfg files under {path}")
    return samples
