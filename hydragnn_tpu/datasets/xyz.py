"""(Extended) XYZ format reader (reference ``hydragnn/utils/datasets/
xyzdataset.py`` via ASE; ASE-free implementation).

Standard XYZ: line 1 = atom count, line 2 = comment (optionally extended-xyz
``key=value`` pairs incl. ``energy=...`` and ``Lattice="ax ay az bx ..."``),
then ``SYMBOL x y z [fx fy fz]`` rows. Multiple frames per file supported.
"""

from __future__ import annotations

import os
import re

import numpy as np

from ..graphs.graph import GraphSample

_SYMBOLS = (
    "X H He Li Be B C N O F Ne Na Mg Al Si P S Cl Ar K Ca Sc Ti V Cr Mn Fe Co "
    "Ni Cu Zn Ga Ge As Se Br Kr Rb Sr Y Zr Nb Mo Tc Ru Rh Pd Ag Cd In Sn Sb Te "
    "I Xe Cs Ba La Ce Pr Nd Pm Sm Eu Gd Tb Dy Ho Er Tm Yb Lu Hf Ta W Re Os Ir "
    "Pt Au Hg Tl Pb Bi Po At Rn Fr Ra Ac Th Pa U Np Pu"
).split()
_Z = {s: i for i, s in enumerate(_SYMBOLS)}


def _parse_comment(comment: str) -> dict:
    out = {}
    for m in re.finditer(r'(\w+)=("([^"]*)"|\S+)', comment):
        key = m.group(1).lower()
        val = m.group(3) if m.group(3) is not None else m.group(2)
        out[key] = val
    return out


def _float(tok: str) -> float:
    """Float parse tolerating QM9's Mathematica exponents (``1.66*^-6``)."""
    return float(tok.replace("*^", "e"))


# QM9 raw xyz property line: 'gdb <id>' then 15 scalars in this order
# (torch_geometric.datasets.QM9 target layout; U0 = internal energy at 0K).
_QM9_PROPS = (
    "A", "B", "C", "mu", "alpha", "homo", "lumo", "gap", "r2",
    "zpve", "U0", "U", "H", "G", "Cv",
)


def _parse_qm9_comment(comment: str) -> dict | None:
    """Detect and parse QM9's raw comment line ('gdb 123\\t<15 values>').
    Returns {prop: value} (+ '_qm9': True) or None if not QM9-shaped."""
    parts = comment.split()
    if len(parts) < 2 + len(_QM9_PROPS) or parts[0] != "gdb":
        return None
    try:
        vals = [_float(t) for t in parts[2 : 2 + len(_QM9_PROPS)]]
    except ValueError:
        return None
    out = dict(zip(_QM9_PROPS, vals))
    out["_qm9"] = True
    return out


def _forces_column(meta: dict) -> int | None:
    """Column index of fx in an extended-xyz Properties= spec, or None."""
    props = meta.get("properties")
    if not props:
        return None
    col = 0
    for name, _kind, width in zip(*[iter(props.split(":"))] * 3):
        w = int(width)
        if name.lower() in ("forces", "force"):
            return col
        col += w
    return None


def read_xyz_file(path: str, limit: int | None = None) -> list[GraphSample]:
    samples = []
    with open(path) as f:
        lines = f.readlines()
    i = 0
    while i < len(lines):
        if limit is not None and len(samples) >= limit:
            break
        if not lines[i].strip():
            i += 1
            continue
        n = int(lines[i].strip())
        qm9 = _parse_qm9_comment(lines[i + 1])
        meta = _parse_comment(lines[i + 1]) if qm9 is None else {}
        rows = [lines[i + 2 + j].split() for j in range(n)]
        # forces: take the column named in Properties=; else the conventional
        # columns 4:7, but ONLY when every row carries them (a partial or
        # differently-typed tail would silently misassign forces)
        f_col = _forces_column(meta)
        if f_col is None and all(len(r) >= 7 for r in rows):
            f_col = 4
        zs, pos, forces = [], [], []
        for parts in rows:
            zs.append(_Z.get(parts[0], 0) if not parts[0].isdigit() else int(parts[0]))
            pos.append([_float(v) for v in parts[1:4]])
            if f_col is not None and len(parts) >= f_col + 3:
                forces.append([_float(v) for v in parts[f_col : f_col + 3]])
        z = np.asarray(zs, np.float64).reshape(-1, 1)
        cell = pbc = None
        if "lattice" in meta:
            cell = np.array([float(v) for v in meta["lattice"].split()]).reshape(3, 3)
            pbc = np.array([True, True, True])
        if qm9 is not None:
            # QM9 atom rows end with a Mulliken charge column, not forces
            forces = []
            energy = qm9["U0"]
            graph_table = np.array([qm9[p] for p in _QM9_PROPS], np.float64)
        else:
            energy = float(meta["energy"]) if "energy" in meta else 0.0
            graph_table = np.array([energy], np.float64)
        if forces and len(forces) != n:
            forces = []  # inconsistent rows: drop rather than misassign
        s = GraphSample(
            x=z,
            pos=np.asarray(pos),
            energy_y=np.array([energy]),
            forces_y=np.asarray(forces) if forces else None,
            cell=cell,
            pbc=pbc,
            extras={
                "node_table": z,
                "graph_table": graph_table,
            },
        )
        samples.append(s)
        i += 2 + n
        if qm9 is not None:
            # skip QM9 trailing records (frequencies, SMILES, InChI) up to
            # the next frame header (a bare atom-count line) or EOF
            while i < len(lines):
                tok = lines[i].strip()
                if tok and tok.split()[0].isdigit() and len(tok.split()) == 1:
                    break
                i += 1
    return samples


def load_xyz_dir(path: str, limit: int | None = None) -> list[GraphSample]:
    samples = []
    for name in sorted(os.listdir(path)):
        if limit is not None and len(samples) >= limit:
            break
        if name.endswith(".xyz"):
            left = None if limit is None else limit - len(samples)
            samples.extend(read_xyz_file(os.path.join(path, name), limit=left))
    if not samples:
        raise FileNotFoundError(f"no .xyz files under {path}")
    return samples
