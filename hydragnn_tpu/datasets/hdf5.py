"""HDF5 corpus readers for the SC25 GFM pretraining mix: ANI1x-style and
qm7x-style files (reference ``examples/ani1_x/train.py:236-257`` and
``examples/qm7x/train.py:153-190``) — the last ingestion format the packed
pipeline was missing (round-4 verdict missing #3).

Two public layouts:

* **ANI1x**: one group per formula, datasets ``atomic_numbers`` [Na] and
  ``coordinates`` [Nc, Na, 3] plus per-conformation property columns
  (``wb97x_dz.energy`` [Nc], ``wb97x_dz.forces`` [Nc, Na, 3], ...). Rows
  with NaN in a requested property are dropped, like the reference.
* **qm7x**: two-level nesting molecule-id -> conformation-id, each
  conformation a group with ``atNUM`` [Na], ``atXYZ`` [Na, 3] and scalar/
  vector properties (``ePBE0+MBD``, ``totFOR``, ...).

``read_hdf5`` sniffs the flavor; ``convert.read_structures`` routes
``.h5``/``.hdf5`` here, so ``python -m hydragnn_tpu.datasets.convert
foo.h5 out.gpk`` (and everything downstream: packed stores, sharded
stores, training) ingests either corpus.
"""

from __future__ import annotations

import numpy as np

from ..graphs.graph import GraphSample

# default property columns per flavor (the reference examples' choices)
_ANI1X_ENERGY = "wb97x_dz.energy"
_ANI1X_FORCES = "wb97x_dz.forces"
_QM7X_ENERGY = "ePBE0+MBD"
_QM7X_FORCES = "totFOR"


def _require_h5py():
    try:
        import h5py  # noqa: F401

        return h5py
    except ImportError as e:  # pragma: no cover - h5py is baked in here
        raise ImportError(
            "reading .h5 corpora needs h5py (not installed in this "
            "environment)"
        ) from e


def _sample(z, pos, energy=None, forces=None) -> GraphSample:
    z = np.asarray(z, np.float32).reshape(-1, 1)
    kw = {}
    if energy is not None:
        kw["energy_y"] = np.asarray(energy, np.float32).reshape(1)
        # own buffer, not a view of energy_y: an in-place edit of one target
        # must never silently rewrite the other
        kw["graph_y"] = np.array(kw["energy_y"])
    if forces is not None:
        kw["forces_y"] = np.asarray(forces, np.float32).reshape(-1, 3)
    return GraphSample(x=z, pos=np.asarray(pos, np.float32).reshape(-1, 3), **kw)


def read_ani1x_h5(
    path: str,
    energy_key: str | None = _ANI1X_ENERGY,
    forces_key: str | None = _ANI1X_FORCES,
    limit: int | None = None,
) -> list[GraphSample]:
    """Group-per-formula layout -> one GraphSample per (formula,
    conformation); conformations with NaN in a requested property are
    dropped (reference ``iter_data_buckets``). Missing property columns
    degrade gracefully (coordinates-only corpora still convert)."""
    h5py = _require_h5py()
    out: list[GraphSample] = []
    with h5py.File(path, "r") as f:
        for grp in f.values():
            coords = np.asarray(grp["coordinates"])
            z = np.asarray(grp["atomic_numbers"])
            nc = coords.shape[0]
            e = fo = None
            mask = np.ones(nc, bool)
            if energy_key and energy_key in grp:
                e = np.asarray(grp[energy_key]).reshape(nc, -1)
                mask &= ~np.isnan(e).any(axis=1)
            if forces_key and forces_key in grp:
                fo = np.asarray(grp[forces_key]).reshape(nc, -1)
                mask &= ~np.isnan(fo).any(axis=1)
            for i in np.nonzero(mask)[0]:
                out.append(_sample(
                    z, coords[i],
                    energy=e[i].sum() if e is not None else None,
                    forces=fo[i] if fo is not None else None,
                ))
                if limit is not None and len(out) >= limit:
                    return out
    return out


def read_qm7x_h5(
    path: str,
    energy_key: str | None = _QM7X_ENERGY,
    forces_key: str | None = _QM7X_FORCES,
    limit: int | None = None,
) -> list[GraphSample]:
    """Molecule-id -> conformation-id nesting (reference qm7x loader)."""
    h5py = _require_h5py()
    out: list[GraphSample] = []
    with h5py.File(path, "r") as f:
        for mol in f.values():
            for conf in mol.values():
                e = (
                    np.asarray(conf[energy_key]).sum()
                    if energy_key and energy_key in conf else None
                )
                fo = (
                    np.asarray(conf[forces_key])
                    if forces_key and forces_key in conf else None
                )
                out.append(_sample(conf["atNUM"], conf["atXYZ"],
                                   energy=e, forces=fo))
                if limit is not None and len(out) >= limit:
                    return out
    return out


def read_hdf5(
    path: str, flavor: str = "auto", limit: int | None = None, **kw
) -> list[GraphSample]:
    """Flavor-sniffing entry: top-level groups carrying ``coordinates`` +
    ``atomic_numbers`` datasets -> ANI1x; groups of groups carrying
    ``atXYZ``/``atNUM`` -> qm7x."""
    if flavor == "ani1x":
        return read_ani1x_h5(path, limit=limit, **kw)
    if flavor == "qm7x":
        return read_qm7x_h5(path, limit=limit, **kw)
    if flavor != "auto":
        raise ValueError(f"unknown HDF5 flavor {flavor!r} "
                         "(expected 'auto', 'ani1x', or 'qm7x')")
    h5py = _require_h5py()
    with h5py.File(path, "r") as f:
        for grp in f.values():
            if isinstance(grp, h5py.Group):
                if "coordinates" in grp and "atomic_numbers" in grp:
                    fl = "ani1x"
                    break
                sub = next(iter(grp.values()), None)
                if isinstance(sub, h5py.Group) and "atXYZ" in sub:
                    fl = "qm7x"
                    break
        else:
            raise ValueError(
                f"{path}: neither ANI1x (coordinates/atomic_numbers groups) "
                "nor qm7x (mol/conf/atXYZ nesting) layout"
            )
    return read_hdf5(path, flavor=fl, limit=limit, **kw)


__all__ = ["read_ani1x_h5", "read_hdf5", "read_qm7x_h5"]
