"""Packed-record dataset format — the scale-out data plane.

Reference design: ADIOS2 .bp files with per-key concatenated global arrays,
one ragged dimension, and ``variable_count``/``variable_offset`` index arrays
plus global attributes (minmax, pna_deg, dataset_name) — ``hydragnn/utils/
datasets/adiosdataset.py:48-352``. The TPU build keeps the same count/offset
index design in a single flat file:

    [8B magic 'GPKDATA1'][8B header_len][header JSON]
    [per key: counts int64[n_samples], then concatenated row-major data]

Header JSON: {"n_samples": N, "keys": [{"name", "dtype", "cols", "offset",
"counts_offset"}...], "attrs": {...}}. Every key is a per-node/edge/graph
array with a leading ragged dimension; scalars are 1-row keys.

Reads are zero-copy ``np.memmap`` slices; per-host shard windows
(``subset``) reproduce AdiosDataset's ``setsubset`` (``:864-890``); the
native ``gather_blocks`` path batches many samples' rows without the GIL.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..graphs.graph import GraphSample

MAGIC = b"GPKDATA1"

# GraphSample fields serialized per sample: (name, dtype, trailing_cols_fn)
_FIELDS = (
    ("x", np.float32),
    ("pos", np.float32),
    ("senders", np.int32),
    ("receivers", np.int32),
    ("edge_attr", np.float32),
    ("edge_shifts", np.float32),
    ("graph_y", np.float32),
    ("node_y", np.float32),
    ("energy_y", np.float32),
    ("forces_y", np.float32),
    ("graph_attr", np.float32),
    ("node_table", np.float32),
    ("graph_table", np.float32),
)


def _field_value(s: GraphSample, name: str) -> np.ndarray:
    if name in ("node_table", "graph_table"):
        v = s.extras.get(name)
        if v is None:
            return np.zeros((0, 1), np.float32)
        v = np.asarray(v)
        return v.reshape(-1, v.shape[-1]) if v.ndim > 1 else v.reshape(-1, 1)
    v = getattr(s, name)
    v = np.asarray(v)
    return v.reshape(-1, 1) if v.ndim == 1 else v


class PackedWriter:
    """Serialize a list of GraphSamples into one packed file."""

    def __init__(self, samples, path: str, attrs: dict | None = None):
        n = len(samples)
        keys = []
        blobs = []
        for name, dtype in _FIELDS:
            vals = [_field_value(s, name).astype(dtype) for s in samples]
            # zero-width columns (e.g. absent edge_attr) are preserved as 0
            widths = {v.shape[1] for v in vals}
            if len(widths) > 1:
                raise ValueError(
                    f"key '{name}' has inconsistent column widths {sorted(widths)} "
                    "across samples; packed files require a homogeneous schema"
                )
            cols = widths.pop() if widths else 1
            counts = np.array([v.shape[0] for v in vals], np.int64)
            # per-graph vectors (graph_y targets, graph_attr conditioning)
            # ride the ragged dim with cols=1, so the width check above can't
            # catch per-sample length mismatches — which would collate into
            # broadcast errors far from the write site
            if name in ("graph_y", "graph_attr") and len(np.unique(counts)) > 1:
                raise ValueError(
                    f"{name} length differs across samples "
                    f"({sorted(set(counts.tolist()))}); per-graph vectors "
                    "must be homogeneous (or absent everywhere)"
                )
            data = (
                np.concatenate(vals, axis=0)
                if vals
                else np.zeros((0, cols), dtype)
            )
            keys.append(
                {"name": name, "dtype": np.dtype(dtype).str, "cols": int(cols)}
            )
            blobs.append((counts, np.ascontiguousarray(data)))

        # extra per-sample scalars
        dsid = np.array([s.dataset_id for s in samples], np.int32).reshape(-1, 1)
        keys.append({"name": "dataset_id", "dtype": "<i4", "cols": 1})
        blobs.append((np.ones(n, np.int64), dsid))

        offset = 0
        payload = []
        for k, (counts, data) in zip(keys, blobs):
            k["counts_offset"] = offset
            offset += counts.nbytes
            k["offset"] = offset
            offset += data.nbytes
            payload.append((counts, data))

        # size stats let loaders build pad specs without a full scan
        final_attrs = dict(attrs or {})
        if samples:
            final_attrs.setdefault(
                "max_nodes", int(max(s.num_nodes for s in samples))
            )
            final_attrs.setdefault(
                "max_edges", int(max(s.num_edges for s in samples))
            )
        header = json.dumps(
            {"n_samples": n, "keys": keys, "attrs": final_attrs}
        ).encode()
        with open(path, "wb") as f:
            f.write(MAGIC)
            f.write(np.int64(len(header)).tobytes())
            for counts, data in payload:
                f.write(counts.tobytes())
                f.write(data.tobytes())
            f.write(header)
            f.write(np.int64(len(header)).tobytes())  # trailer for locating header


class PackedDataset:
    """Memory-mapped reads with per-process subset windows."""

    def __init__(self, path: str, subset: range | None = None):
        self.path = path
        with open(path, "rb") as f:
            magic = f.read(8)
            if magic != MAGIC:
                raise ValueError(f"{path}: not a packed dataset (magic {magic!r})")
            f.seek(-8, os.SEEK_END)
            header_len = int(np.frombuffer(f.read(8), np.int64)[0])
            f.seek(-8 - header_len, os.SEEK_END)
            self.meta = json.loads(f.read(header_len))
        self._mm = np.memmap(path, dtype=np.uint8, mode="r")
        self._base = 16  # magic + header_len prefix
        self._keys = {k["name"]: k for k in self.meta["keys"]}
        self._counts = {}
        self._offsets = {}
        n = self.meta["n_samples"]
        for k in self.meta["keys"]:
            c = np.frombuffer(
                self._mm, np.int64, count=n, offset=self._base + k["counts_offset"]
            )
            self._counts[k["name"]] = c
            self._offsets[k["name"]] = np.concatenate(
                [[0], np.cumsum(c)]
            )  # row offsets
        self.subset = subset if subset is not None else range(n)

    def __len__(self) -> int:
        return len(self.subset)

    @property
    def attrs(self) -> dict:
        return self.meta.get("attrs", {})

    def _read(self, name: str, i: int) -> np.ndarray:
        k = self._keys[name]
        dtype = np.dtype(k["dtype"])
        cols = k["cols"]
        row0 = self._offsets[name][i]
        rows = self._counts[name][i]
        start = self._base + k["offset"] + row0 * cols * dtype.itemsize
        out = np.frombuffer(
            self._mm, dtype, count=rows * cols, offset=int(start)
        ).reshape(rows, cols)
        return out

    def __getitem__(self, idx: int) -> GraphSample:
        i = self.subset[idx]
        get = self._read
        s = GraphSample(
            x=get("x", i),
            pos=get("pos", i),
            senders=get("senders", i)[:, 0],
            receivers=get("receivers", i)[:, 0],
            edge_attr=get("edge_attr", i),
            edge_shifts=get("edge_shifts", i),
            graph_y=get("graph_y", i)[:, 0],
            node_y=get("node_y", i),
            energy_y=get("energy_y", i)[:, 0],
            forces_y=get("forces_y", i),
            # absent from pre-graph_attr files: stays None -> zero-width
            graph_attr=(
                get("graph_attr", i)[:, 0]
                if "graph_attr" in self._keys and self._counts["graph_attr"][i]
                else None
            ),
            dataset_id=int(get("dataset_id", i)[0, 0]),
        )
        nt = get("node_table", i)
        gt = get("graph_table", i)
        if nt.size:
            s.extras["node_table"] = nt
        if gt.size:
            s.extras["graph_table"] = gt[:, 0]
        return s

    def sample_sizes(self, indices) -> np.ndarray:
        """[k, 2] (num_nodes, num_edges) per sample straight from the
        count index — size queries (bucket planning) never materialize
        sample content."""
        idx = np.fromiter((self.subset[int(i)] for i in indices), np.int64,
                          count=len(indices))
        return np.stack(
            [self._counts["x"][idx], self._counts["senders"][idx]], axis=1
        )

    def load_all(self) -> list[GraphSample]:
        return [self[i] for i in range(len(self))]

    def setsubset(self, start: int, stop: int) -> "PackedDataset":
        """Per-rank shard window (AdiosDataset.setsubset semantics)."""
        self.subset = range(start, stop)
        return self


def pad_spec_from_stats(
    attrs: dict, batch_size: int, node_multiple: int = 8,
    edge_multiple: int = 128,
):
    """PadSpec from writer-recorded ``max_nodes``/``max_edges`` stats — the
    ONE place the padding formula lives (GlobalShuffleStore and ShardedStore
    both derive their static shapes here, so they can never diverge)."""
    from ..graphs.batching import PadSpec

    if "max_nodes" not in attrs:
        raise ValueError("packed file lacks size stats; re-write with PackedWriter")
    import math

    def up(v, m):
        return int(math.ceil(max(v, 1) / m) * m)

    return PadSpec(
        n_node=up(attrs["max_nodes"] * batch_size + 1, node_multiple),
        n_edge=up(attrs["max_edges"] * batch_size + 1, edge_multiple),
        n_graph=batch_size + 1,
    )


class GlobalShuffleStore:
    """DDStore-equivalent cross-host sample store (reference
    ``hydragnn/utils/datasets/distdataset.py:72-367`` and AdiosDataset's
    remote-read mode ``adiosdataset.py:643-757``).

    The reference needs an in-RAM distributed store with remote ``get()``
    fetches because each rank materializes only its window of the dataset.
    The packed format already gives every host O(1) random access to ANY
    sample by offset (mmap + count/offset index; the OS page cache is the
    shared RAM tier), so cross-host global shuffle needs no message passing
    at all: every rank derives the SAME per-epoch permutation from the shared
    seed and lazily reads its stride-slice — the "index exchange" is
    deterministic replay instead of communication.

    This object is a lazy Sequence over the whole file: feed it straight to
    ``GraphLoader(..., rank, world, shuffle=True)`` and each host's stream
    (a) spans the entire dataset across epochs instead of a fixed window and
    (b) reshuffles globally every epoch — the two DDStore properties the
    per-host ``setsubset`` windows lack.
    """

    def __init__(self, path: str):
        self.ds = PackedDataset(path)

    def __len__(self) -> int:
        return self.ds.meta["n_samples"]

    def __getitem__(self, i: int) -> GraphSample:
        return self.ds[int(i)]

    def sample_sizes(self, indices) -> np.ndarray:
        return self.ds.sample_sizes(indices)

    @property
    def attrs(self) -> dict:
        return self.ds.attrs

    def pad_spec(self, batch_size: int, node_multiple: int = 8, edge_multiple: int = 128):
        """PadSpec from writer-recorded size stats — no full scan."""
        return pad_spec_from_stats(self.attrs, batch_size, node_multiple,
                                   edge_multiple)

    def loader(
        self,
        batch_size: int,
        rank: int = 0,
        world: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        pad=None,
        **kw,
    ):
        from ..graphs.batching import GraphLoader

        return GraphLoader(
            self,
            batch_size,
            pad=pad or self.pad_spec(batch_size),
            shuffle=shuffle,
            seed=seed,
            rank=rank,
            world=world,
            **kw,
        )
