from .synthetic import deterministic_graph_data
from .lennard_jones import lennard_jones_data
from .lsms import load_lsms_dir, read_lsms_file, write_lsms_file
from .xyz import load_xyz_dir, read_xyz_file
from .cfg import load_cfg_dir, read_cfg_file
from .pickledataset import SimplePickleDataset, SimplePickleWriter
from .packed import PackedDataset, PackedWriter
from .sharded import ShardedStore


import os


def load_raw_dataset(config: dict):
    """Dispatch on ``Dataset.format`` to a raw loader (reference
    ``transform_raw_data_to_serialized`` + per-format loaders,
    ``hydragnn/preprocess/raw_dataset_loader.py``)."""
    ds = config["Dataset"]
    fmt = (ds.get("format") or "").lower()
    path = ds.get("path")
    if isinstance(path, dict):
        path = path.get("total") or next(iter(path.values()))
    if fmt == "lsms":
        return load_lsms_dir(path, charge_density_update=ds.get("charge_density", False))
    if fmt == "xyz":
        if os.path.isfile(path):
            return read_xyz_file(path)
        return load_xyz_dir(path)
    if fmt == "cfg":
        return load_cfg_dir(path)
    if fmt == "pickle":
        return SimplePickleDataset(path, ds.get("label", "total")).load_all()
    if fmt == "packed":
        return PackedDataset(path).load_all()
    if fmt in ("adios", "bp"):
        # reference configs say "format": "adios" — read their .bp store
        # directly (datasets/convert.read_bp_dataset)
        from .convert import read_bp_dataset

        return read_bp_dataset(path, label=ds.get("label", "trainset"))
    if fmt in ("hdf5", "h5"):
        from .hdf5 import read_hdf5

        return read_hdf5(path, flavor=ds.get("hdf5_flavor", "auto"))
    raise ValueError(
        f"Dataset format '{fmt}' has no registered loader; supported: "
        "LSMS, XYZ, CFG, pickle, packed, adios/bp, hdf5 (or pass samples= "
        "directly)"
    )


__all__ = [
    "deterministic_graph_data",
    "lennard_jones_data",
    "load_raw_dataset",
    "load_lsms_dir",
    "read_lsms_file",
    "write_lsms_file",
    "load_xyz_dir",
    "read_xyz_file",
    "load_cfg_dir",
    "read_cfg_file",
    "SimplePickleDataset",
    "SimplePickleWriter",
    "PackedDataset",
    "PackedWriter",
]
