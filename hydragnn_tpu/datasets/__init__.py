from .synthetic import deterministic_graph_data


def load_raw_dataset(config: dict):
    """Dispatch on ``Dataset.format`` to a raw loader (reference
    ``transform_raw_data_to_serialized`` + per-format loaders). Formats are
    registered as the datasets package grows (LSMS/CFG/XYZ/pickle)."""
    fmt = config["Dataset"].get("format")
    raise ValueError(
        f"Dataset format '{fmt}' has no registered loader yet; pass samples= directly"
    )


__all__ = ["deterministic_graph_data", "load_raw_dataset"]
