"""LSMS raw text format reader/writer.

Reference: ``hydragnn/preprocess/lsms_raw_dataset_loader.py:26-106`` and the
test fixture writer ``tests/deterministic_graph_data.py:80-173``. Format:

    GRAPH_OUTPUT[S...]
    FEAT  INDEX  X  Y  Z  OUT1  OUT2  OUT3 ...
    ...

The reader builds full feature tables (``extras['node_table']`` /
``graph_table``) so ``apply_variables_of_interest`` can column-select inputs
and targets; the optional LSMS charge-density correction (``x[:,1] -= x[:,0]``,
reference ``:90-106``) applies when two leading node features are present.
"""

from __future__ import annotations

import os

import numpy as np

from ..graphs.graph import GraphSample


def write_lsms_file(path: str, graph_feats, node_table, positions) -> None:
    """Write one LSMS sample: graph features line + per-node rows
    [feat, index, x, y, z, outputs...]."""
    with open(path, "w") as f:
        f.write("\t".join(str(float(v)) for v in np.atleast_1d(graph_feats)))
        node_table = np.asarray(node_table)
        positions = np.asarray(positions)
        for i in range(node_table.shape[0]):
            feat = node_table[i, 0]
            outputs = node_table[i, 1:]
            row = [feat, float(i), *positions[i], *outputs]
            f.write("\n" + "\t".join(f"{float(v):.8g}" for v in row))


def read_lsms_file(path: str, charge_density_update: bool = False) -> GraphSample:
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    graph_feats = np.array([float(v) for v in lines[0].split()], np.float64)
    rows = [np.array([float(v) for v in ln.split()], np.float64) for ln in lines[1:] if ln.strip()]
    table = np.stack(rows)
    pos = table[:, 2:5]
    feat_cols = np.concatenate([table[:, :1], table[:, 5:]], axis=1)
    if charge_density_update and feat_cols.shape[1] >= 2:
        feat_cols[:, 1] -= feat_cols[:, 0]
    return GraphSample(
        x=feat_cols[:, :1],
        pos=pos,
        extras={"node_table": feat_cols, "graph_table": graph_feats},
    )


def load_lsms_dir(path: str, charge_density_update: bool = False) -> list[GraphSample]:
    samples = []
    for name in sorted(os.listdir(path)):
        if name.endswith(".txt"):
            samples.append(
                read_lsms_file(os.path.join(path, name), charge_density_update)
            )
    if not samples:
        raise FileNotFoundError(f"no LSMS .txt files under {path}")
    return samples
