"""Deterministic synthetic BCC dataset — the framework's convergence-test
fixture.

Reproduces reference ``tests/deterministic_graph_data.py:20-173``: random BCC
supercells (2 atoms per conventional cell), integer node types, nodal outputs
built from a k-nearest-neighbor average ``x`` of the types (simulating one
round of message passing so the targets are learnable by a GNN):

    NODAL_OUTPUT1 = x
    NODAL_OUTPUT2 = x^2 + type
    NODAL_OUTPUT3 = x^3
    GLOBAL_OUTPUT = sum over nodes of (out1 + out2 + out3)

The generated ``GraphSample``s carry full feature tables in ``extras``
(``node_table`` columns: [type, out1, out2, out3]; ``graph_table``: [total]);
``apply_variables_of_interest`` (preprocess) then selects model inputs/targets
per the config — the analog of the reference's raw-loader +
``update_predicted_values`` column selection.
"""

from __future__ import annotations

import numpy as np

from ..graphs.graph import GraphSample
from ..graphs.radius import radius_graph


def _bcc_positions(uc_x: int, uc_y: int, uc_z: int) -> np.ndarray:
    grid = np.stack(
        np.meshgrid(np.arange(uc_x), np.arange(uc_y), np.arange(uc_z), indexing="ij"),
        axis=-1,
    ).reshape(-1, 3).astype(np.float64)
    corner = grid
    center = grid + 0.5
    # interleave corner/center like the reference's count_pos ordering
    pos = np.empty((corner.shape[0] * 2, 3), np.float64)
    pos[0::2] = corner
    pos[1::2] = center
    return pos


def _knn_average(pos: np.ndarray, values: np.ndarray, k: int) -> np.ndarray:
    """Mean of each node's k nearest neighbors' values (including self at
    distance 0 — sklearn KNeighborsRegressor.predict on the training points
    includes the point itself, matching reference :128-131)."""
    d2 = np.sum((pos[None, :, :] - pos[:, None, :]) ** 2, axis=-1)
    nearest = np.argsort(d2, axis=1)[:, :k]
    return values[nearest].mean(axis=1)


def deterministic_graph_data(
    number_configurations: int = 500,
    unit_cell_x_range=(1, 3),
    unit_cell_y_range=(1, 3),
    unit_cell_z_range=(1, 2),
    number_types: int = 3,
    number_neighbors: int = 2,
    linear_only: bool = False,
    radius: float = 2.0,
    max_neighbours: int | None = 100,
    seed: int = 0,
) -> list[GraphSample]:
    """Generate the synthetic dataset as ``GraphSample``s with radius graphs
    attached (the reference writes LSMS text files and re-reads them; we keep
    the text round-trip in the LSMS loader tests instead of the hot path)."""
    rng = np.random.default_rng(seed)
    samples = []
    for _ in range(number_configurations):
        uc_x = int(rng.integers(unit_cell_x_range[0], unit_cell_x_range[1]))
        uc_y = int(rng.integers(unit_cell_y_range[0], unit_cell_y_range[1]))
        uc_z = int(rng.integers(unit_cell_z_range[0], unit_cell_z_range[1]))
        pos = _bcc_positions(uc_x, uc_y, uc_z)
        n = pos.shape[0]
        node_type = rng.integers(0, number_types, size=(n, 1)).astype(np.float64)

        if linear_only:
            out1 = node_type.copy()
        else:
            out1 = _knn_average(pos, node_type, number_neighbors)
        out2 = out1**2 + node_type
        out3 = out1**3
        total = out1.sum() + (0.0 if linear_only else out2.sum() + out3.sum())

        node_table = np.concatenate([node_type, out1, out2, out3], axis=1)
        graph_table = np.array([total], np.float64)

        senders, receivers, shifts = radius_graph(
            pos, radius=radius, max_neighbours=max_neighbours
        )
        s = GraphSample(
            x=node_type,
            pos=pos,
            senders=senders,
            receivers=receivers,
            edge_shifts=shifts,
            extras={"node_table": node_table, "graph_table": graph_table},
        )
        samples.append(s)
    return samples
