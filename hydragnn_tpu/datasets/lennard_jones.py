"""Lennard-Jones synthetic MLIP dataset with analytic energies and forces.

Reference: ``examples/LennardJones/LJ_data.py`` — perturbed cubic lattices
(lattice constant 3.8, relative displacement 0.1) under PBC, with
LJ(epsilon=1.0, sigma=3.4) total energies and analytic forces. The fixture for
energy-conserving force training (forces via jax.grad must recover these).
"""

from __future__ import annotations

import numpy as np

from ..graphs.graph import GraphSample
from ..graphs.radius import radius_graph

LATTICE_CONSTANT = 3.8
EPSILON = 1.0
SIGMA = 3.4


def lj_energy_forces(
    pos: np.ndarray, senders, receivers, shifts, eps: float = EPSILON, sigma: float = SIGMA
) -> tuple[float, np.ndarray]:
    """Total energy (each pair counted once over directed edges via 0.5x) and
    per-atom analytic forces from the neighbor list."""
    vec = pos[receivers] - pos[senders] + shifts  # r_ij vectors (i=sender)
    r = np.linalg.norm(vec, axis=1)
    sr6 = (sigma / r) ** 6
    sr12 = sr6**2
    energy = 0.5 * np.sum(4.0 * eps * (sr12 - sr6))
    # dU/dr; force on sender i from j: -dU/dr * (pos_i - pos_j)/r = dU/dr * vec/r
    dudr = 4.0 * eps * (-12.0 * sr12 + 6.0 * sr6) / r
    f_edge = (dudr / r)[:, None] * vec  # force contribution on the sender
    forces = np.zeros_like(pos)
    np.add.at(forces, senders, f_edge)
    return float(energy), forces


def lennard_jones_data(
    number_configurations: int = 300,
    cells_per_dim: int = 3,
    radius: float = 5.0,
    max_neighbours: int = 100,
    relative_maximum_atomic_displacement: float = 0.1,
    seed: int = 0,
) -> list[GraphSample]:
    rng = np.random.default_rng(seed)
    a = LATTICE_CONSTANT
    n_side = cells_per_dim
    base = (
        np.stack(
            np.meshgrid(*(np.arange(n_side),) * 3, indexing="ij"), axis=-1
        ).reshape(-1, 3)
        * a
    )
    cell = np.eye(3) * (n_side * a)
    pbc = np.array([True, True, True])
    samples = []
    for _ in range(number_configurations):
        disp = rng.uniform(
            -relative_maximum_atomic_displacement,
            relative_maximum_atomic_displacement,
            size=base.shape,
        ) * a
        pos = base + disp
        s_idx, r_idx, shifts = radius_graph(
            pos, radius=radius, cell=cell, pbc=pbc, max_neighbours=max_neighbours
        )
        energy, forces = lj_energy_forces(pos, s_idx, r_idx, shifts)
        n = pos.shape[0]
        samples.append(
            GraphSample(
                x=np.ones((n, 1), np.float32),  # single atom type (LJ_data atom_types=[1])
                pos=pos,
                senders=s_idx,
                receivers=r_idx,
                edge_shifts=shifts,
                energy_y=np.array([energy], np.float32),
                forces_y=forces,
                cell=cell,
                pbc=pbc,
                extras={
                    "node_table": np.ones((n, 1), np.float32),
                    "graph_table": np.array([energy], np.float32),
                },
            )
        )
    return samples
