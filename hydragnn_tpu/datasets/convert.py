"""Real-dataset ingestion: public structure files → the packed record store.

The reference trains its headline workloads from real public corpora — QM9
raw xyz via PyG (``torch_geometric.datasets.QM9``), OC20/OMat24 via
ASE/LMDB readers (reference ``examples/open_catalyst_2020/train.py``,
``hydragnn/preprocess/raw_dataset_loader.py:26-277``), LSMS/CFG text. This
module is the TPU build's equivalent front door: read any supported on-disk
format into ``GraphSample``s, build (PBC-aware) radius graphs, and write one
``PackedWriter`` store that every scale driver (`examples/oc20`,
``examples/qm9``, multidataset) trains from.

CLI:

    python -m hydragnn_tpu.datasets.convert INPUT OUTPUT.gpk \
        [--radius 5.0] [--max-neighbours 40] [--limit N] [--name NAME]

Supported inputs (by extension / shape):

* ``.xyz`` / ``.extxyz`` — (extended) XYZ, multi-frame; QM9's raw flavor
  (``gdb`` comment line, ``*^`` float exponents) is auto-detected and its 15
  scalar targets stored columnar in ``graph_table``;
* directory of ``.xyz`` files — e.g. an unpacked QM9 download;
* ``.cfg`` — AtomEye/MTP configurations;
* LSMS text directory (``--format lsms``);
* ``.db`` / ``.traj`` — ASE databases, when ``ase`` is installed (gated:
  this image ships without it);
* ``.lmdb`` — OC20 S2EF LMDBs, when ``lmdb`` is installed (gated).

Zero-copy principle: conversion happens ONCE; training reads the packed
store through mmap (``PackedDataset`` / ``GlobalShuffleStore``) with O(1)
random access from every host.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from ..graphs.graph import GraphSample


def attach_radius_graph(
    samples: list[GraphSample],
    radius: float,
    max_neighbours: int | None = None,
    progress_every: int = 0,
) -> list[GraphSample]:
    """Build each sample's neighbor list in place (PBC-aware when the sample
    carries a cell). Skips samples that already have edges."""
    from ..graphs.radius import build_radius_graph

    for i, s in enumerate(samples):
        if s.num_edges:
            continue
        build_radius_graph(s, radius, max_neighbours=max_neighbours)
        if progress_every and (i + 1) % progress_every == 0:
            print(f"  neighbor lists: {i + 1}/{len(samples)}", file=sys.stderr)
    return samples


def _read_ase(path: str, limit: int | None = None) -> list[GraphSample]:
    try:
        from ase.io import iread
    except ImportError as exc:  # pragma: no cover - image has no ase
        raise ImportError(
            f"reading {path!r} needs the 'ase' package (not installed); "
            "export your data to extended XYZ instead: "
            "`ase convert in.db out.extxyz`"
        ) from exc
    out = []
    for atoms in iread(path):
        if limit is not None and len(out) >= limit:
            break
        out.append(sample_from_ase_atoms(atoms))
    return out


def sample_from_ase_atoms(atoms) -> GraphSample:
    """ASE ``Atoms`` (duck-typed) -> edge-less ``GraphSample``. Factored out
    of the file reader so the parsing is unit-testable without the ``ase``
    package (absent from this image)."""
    energy = 0.0
    forces = None
    try:
        energy = float(atoms.get_potential_energy())
        forces = np.asarray(atoms.get_forces())
    except Exception:
        pass
    z = np.asarray(atoms.get_atomic_numbers()).astype(np.float64).reshape(-1, 1)
    pbc = np.asarray(atoms.pbc)
    return GraphSample(
        x=z,
        pos=np.asarray(atoms.get_positions()),
        energy_y=np.array([energy]),
        forces_y=forces,
        cell=np.asarray(atoms.get_cell()) if pbc.any() else None,
        pbc=pbc if pbc.any() else None,
        extras={"node_table": z, "graph_table": np.array([energy])},
    )


def sample_from_fairchem(d) -> GraphSample:
    """fairchem/OCP ``Data`` record (duck-typed: ``atomic_numbers``, ``pos``,
    optional ``y``/``force``/``cell``) -> edge-less ``GraphSample``."""
    z = np.asarray(d.atomic_numbers, np.float64).reshape(-1, 1)
    cell = np.asarray(d.cell).reshape(3, 3) if getattr(d, "cell", None) is not None else None
    energy = float(getattr(d, "y", 0.0) or 0.0)
    force = getattr(d, "force", None)
    return GraphSample(
        x=z,
        pos=np.asarray(d.pos),
        energy_y=np.array([energy]),
        forces_y=np.asarray(force) if force is not None else None,
        cell=cell,
        pbc=np.array([True, True, True]) if cell is not None else None,
        extras={"node_table": z, "graph_table": np.array([energy])},
    )


def _decode_length(val) -> int | None:
    """The OC20/fairchem S2EF LMDBs store the ``length`` key PICKLED; older /
    hand-built stores use ascii. Try pickle first, fall back to int-decode
    (round-3 advisor finding: ``.decode()`` raises UnicodeDecodeError on any
    real OC20 LMDB)."""
    if val is None:
        return None
    import pickle

    try:
        return int(pickle.loads(val))
    except Exception:
        try:
            return int(val.decode())
        except Exception:
            return None


def _read_oc20_lmdb(path: str, limit: int | None = None) -> list[GraphSample]:
    try:
        import lmdb  # noqa: F401
    except ImportError as exc:  # pragma: no cover - image has no lmdb
        raise ImportError(
            f"reading {path!r} needs the 'lmdb' package (not installed); "
            "convert the trajectory to extended XYZ first"
        ) from exc
    import pickle

    env = lmdb.open(
        path, subdir=False, readonly=True, lock=False, readahead=False, meminit=False
    )
    out = []
    with env.begin() as txn:
        n = _decode_length(txn.get(b"length"))
        cur = txn.cursor()
        for key, val in cur:
            if key == b"length":
                continue
            d = pickle.loads(val)  # fairchem Data object (duck-typed access)
            out.append(sample_from_fairchem(d))
            if (n and len(out) >= n) or (limit is not None and len(out) >= limit):
                break
    return out


def read_structures(
    path: str, fmt: str | None = None, limit: int | None = None
) -> list[GraphSample]:
    """Read any supported input into (edge-less) ``GraphSample``s."""
    from .cfg import read_cfg_file
    from .lsms import load_lsms_dir
    from .xyz import load_xyz_dir, read_xyz_file

    ext = os.path.splitext(path)[1].lower()
    if fmt == "lsms":
        return load_lsms_dir(path)[:limit]
    if os.path.isdir(path):
        return load_xyz_dir(path, limit=limit)
    if ext in (".xyz", ".extxyz"):
        return read_xyz_file(path, limit=limit)
    if ext == ".cfg":
        return [read_cfg_file(path)][:limit]
    if ext in (".db", ".traj"):
        return _read_ase(path, limit=limit)
    if ext == ".lmdb":
        return _read_oc20_lmdb(path, limit=limit)
    raise ValueError(
        f"unrecognized dataset input {path!r} (expected .xyz/.extxyz/.cfg/"
        ".db/.traj/.lmdb, a directory of .xyz files, or --format lsms)"
    )


def convert_to_packed(
    input_path: str,
    output_path: str,
    radius: float = 5.0,
    max_neighbours: int | None = 40,
    fmt: str | None = None,
    limit: int | None = None,
    dataset_name: str | None = None,
) -> int:
    """Read ``input_path``, build radius graphs, write a packed store.
    Returns the number of structures written."""
    from .packed import PackedWriter

    samples = read_structures(input_path, fmt=fmt, limit=limit)
    if not samples:
        raise ValueError(f"no structures found in {input_path!r}")
    attach_radius_graph(samples, radius, max_neighbours, progress_every=1000)
    PackedWriter(
        samples,
        output_path,
        attrs={
            "dataset_name": dataset_name or os.path.basename(input_path),
            "source": os.path.abspath(input_path),
            "radius": radius,
            "max_neighbours": max_neighbours or 0,
        },
    )
    return len(samples)


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="Convert a public structure file to a packed training store"
    )
    ap.add_argument("input", help=".xyz/.extxyz/.cfg/.db/.traj/.lmdb file or xyz dir")
    ap.add_argument("output", help="output packed store (.gpk)")
    ap.add_argument("--radius", type=float, default=5.0)
    ap.add_argument("--max-neighbours", type=int, default=40)
    ap.add_argument("--format", dest="fmt", default=None, choices=[None, "lsms"])
    ap.add_argument("--limit", type=int, default=None, help="convert first N only")
    ap.add_argument("--name", default=None, help="dataset_name attr")
    args = ap.parse_args(argv)
    n = convert_to_packed(
        args.input,
        args.output,
        radius=args.radius,
        max_neighbours=args.max_neighbours,
        fmt=args.fmt,
        limit=args.limit,
        dataset_name=args.name,
    )
    print(f"wrote {n} structures -> {args.output}")


if __name__ == "__main__":
    main()
