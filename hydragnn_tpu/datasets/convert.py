"""Real-dataset ingestion: public structure files → the packed record store.

The reference trains its headline workloads from real public corpora — QM9
raw xyz via PyG (``torch_geometric.datasets.QM9``), OC20/OMat24 via
ASE/LMDB readers (reference ``examples/open_catalyst_2020/train.py``,
``hydragnn/preprocess/raw_dataset_loader.py:26-277``), LSMS/CFG text. This
module is the TPU build's equivalent front door: read any supported on-disk
format into ``GraphSample``s, build (PBC-aware) radius graphs, and write one
``PackedWriter`` store that every scale driver (`examples/oc20`,
``examples/qm9``, multidataset) trains from.

CLI:

    python -m hydragnn_tpu.datasets.convert INPUT OUTPUT.gpk \
        [--radius 5.0] [--max-neighbours 40] [--limit N] [--name NAME]

Supported inputs (by extension / shape):

* ``.xyz`` / ``.extxyz`` — (extended) XYZ, multi-frame; QM9's raw flavor
  (``gdb`` comment line, ``*^`` float exponents) is auto-detected and its 15
  scalar targets stored columnar in ``graph_table``;
* directory of ``.xyz`` files — e.g. an unpacked QM9 download;
* ``.cfg`` — AtomEye/MTP configurations;
* LSMS text directory (``--format lsms``);
* ``.db`` / ``.traj`` — ASE databases, when ``ase`` is installed (gated:
  this image ships without it);
* ``.lmdb`` — OC20 S2EF LMDBs, when ``lmdb`` is installed (gated).

Zero-copy principle: conversion happens ONCE; training reads the packed
store through mmap (``PackedDataset`` / ``GlobalShuffleStore``) with O(1)
random access from every host.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from ..graphs.graph import GraphSample


def attach_radius_graph(
    samples: list[GraphSample],
    radius: float,
    max_neighbours: int | None = None,
    progress_every: int = 0,
) -> list[GraphSample]:
    """Build each sample's neighbor list in place (PBC-aware when the sample
    carries a cell). Skips samples that already have edges."""
    from ..graphs.radius import build_radius_graph

    for i, s in enumerate(samples):
        if s.num_edges:
            continue
        build_radius_graph(s, radius, max_neighbours=max_neighbours)
        if progress_every and (i + 1) % progress_every == 0:
            print(f"  neighbor lists: {i + 1}/{len(samples)}", file=sys.stderr)
    return samples


def _read_ase(path: str, limit: int | None = None) -> list[GraphSample]:
    try:
        from ase.io import iread
    except ImportError as exc:  # pragma: no cover - image has no ase
        raise ImportError(
            f"reading {path!r} needs the 'ase' package (not installed); "
            "export your data to extended XYZ instead: "
            "`ase convert in.db out.extxyz`"
        ) from exc
    out = []
    for atoms in iread(path):
        if limit is not None and len(out) >= limit:
            break
        out.append(sample_from_ase_atoms(atoms))
    return out


def sample_from_ase_atoms(atoms) -> GraphSample:
    """ASE ``Atoms`` (duck-typed) -> edge-less ``GraphSample``. Factored out
    of the file reader so the parsing is unit-testable without the ``ase``
    package (absent from this image)."""
    energy = 0.0
    forces = None
    try:
        energy = float(atoms.get_potential_energy())
        forces = np.asarray(atoms.get_forces())
    except Exception:
        pass
    z = np.asarray(atoms.get_atomic_numbers()).astype(np.float64).reshape(-1, 1)
    pbc = np.asarray(atoms.pbc)
    return GraphSample(
        x=z,
        pos=np.asarray(atoms.get_positions()),
        energy_y=np.array([energy]),
        forces_y=forces,
        cell=np.asarray(atoms.get_cell()) if pbc.any() else None,
        pbc=pbc if pbc.any() else None,
        extras={"node_table": z, "graph_table": np.array([energy])},
    )


def sample_from_fairchem(d) -> GraphSample:
    """fairchem/OCP ``Data`` record (duck-typed: ``atomic_numbers``, ``pos``,
    optional ``y``/``force``/``cell``) -> edge-less ``GraphSample``."""
    z = np.asarray(d.atomic_numbers, np.float64).reshape(-1, 1)
    cell = np.asarray(d.cell).reshape(3, 3) if getattr(d, "cell", None) is not None else None
    energy = float(getattr(d, "y", 0.0) or 0.0)
    force = getattr(d, "force", None)
    return GraphSample(
        x=z,
        pos=np.asarray(d.pos),
        energy_y=np.array([energy]),
        forces_y=np.asarray(force) if force is not None else None,
        cell=cell,
        pbc=np.array([True, True, True]) if cell is not None else None,
        extras={"node_table": z, "graph_table": np.array([energy])},
    )


def _decode_length(val) -> int | None:
    """The OC20/fairchem S2EF LMDBs store the ``length`` key PICKLED; older /
    hand-built stores use ascii. Try pickle first, fall back to int-decode
    (round-3 advisor finding: ``.decode()`` raises UnicodeDecodeError on any
    real OC20 LMDB)."""
    if val is None:
        return None
    import pickle

    try:
        return int(pickle.loads(val))
    except Exception:
        try:
            return int(val.decode())
        except Exception:
            return None


def _read_oc20_lmdb(path: str, limit: int | None = None) -> list[GraphSample]:
    try:
        import lmdb  # noqa: F401
    except ImportError as exc:  # pragma: no cover - image has no lmdb
        raise ImportError(
            f"reading {path!r} needs the 'lmdb' package (not installed); "
            "convert the trajectory to extended XYZ first"
        ) from exc
    import pickle

    env = lmdb.open(
        path, subdir=False, readonly=True, lock=False, readahead=False, meminit=False
    )
    out = []
    with env.begin() as txn:
        n = _decode_length(txn.get(b"length"))
        cur = txn.cursor()
        for key, val in cur:
            if key == b"length":
                continue
            d = pickle.loads(val)  # fairchem Data object (duck-typed access)
            out.append(sample_from_fairchem(d))
            if (n and len(out) >= n) or (limit is not None and len(out) >= limit):
                break
    return out


# reference PyG Data keys -> GraphSample fields (adiosdataset.py write
# layout); edge_index is handled separately (split into senders/receivers)
_BP_FIELD_MAP = {
    "x": "x", "pos": "pos", "edge_attr": "edge_attr",
    "edge_shifts": "edge_shifts", "y": "graph_y", "energy": "energy_y",
    "forces": "forces_y", "cell": "cell", "pbc": "pbc",
}


def _open_bp(path: str):
    """Version-tolerant adios2 read handle: FileReader (>= 2.9) or the
    legacy ``adios2.open`` stream API. Returns (attrs: dict, read: name ->
    ndarray, close)."""
    try:
        import adios2
    except ImportError as e:
        raise ImportError(
            "reading ADIOS .bp stores needs the adios2 package "
            "(pip install adios2); alternatively re-convert the raw corpus "
            "with hydragnn_tpu.datasets.convert"
        ) from e

    if hasattr(adios2, "FileReader"):
        fh = adios2.FileReader(path)
        attrs = {}
        for name in fh.available_attributes():
            a = fh.inquire_attribute(name)
            v = a.data_string() if a.type() == "string" else np.asarray(a.data())
            attrs[name] = v
        return attrs, (lambda name: np.asarray(fh.read(name))), fh.close
    fh = adios2.open(path, "r")  # legacy API
    attrs = {}
    for name, info in fh.available_attributes().items():
        v = info.get("Value", "")
        if info.get("Type") == "string":
            attrs[name] = [s.strip().strip('"') for s in v.strip("{}").split(",")]
        else:
            attrs[name] = np.fromstring(v.strip("{}"), sep=",")
    return attrs, (lambda name: np.asarray(fh.read(name))), fh.close


def read_bp_dataset(
    path: str, label: str = "trainset", limit: int | None = None
) -> list[GraphSample]:
    """Read-only importer for a reference-HydraGNN-written ADIOS ``.bp``
    store (write layout ``hydragnn/utils/datasets/adiosdataset.py:100-264``:
    per key one concatenated global array along ``variable_dim`` plus
    ``variable_count``/``variable_offset`` index arrays). Anyone migrating
    from the reference points this at their existing corpus instead of
    re-converting raw files."""
    attrs, read, close = _open_bp(path)
    try:
        keys = attrs.get(f"{label}/keys")
        if keys is None:
            have = sorted(
                k.split("/")[0] for k in attrs if k.endswith("/keys")
            )
            raise ValueError(
                f"{path}: no label {label!r} (available: {have})"
            )
        keys = [k.decode() if isinstance(k, bytes) else str(k) for k in keys]
        ndata = int(np.asarray(attrs[f"{label}/ndata"]).ravel()[0])
        n = ndata if limit is None else min(ndata, limit)
        per_key = {}
        for k in keys:
            if k == "dataset_name":
                continue
            arr = read(f"{label}/{k}")
            vdim = int(
                np.asarray(attrs.get(f"{label}/{k}/variable_dim", 0)).ravel()[0]
            )
            count = read(f"{label}/{k}/variable_count").astype(np.int64)
            offset = read(f"{label}/{k}/variable_offset").astype(np.int64)
            per_key[k] = (arr, vdim, count, offset)
        samples = []
        for i in range(n):
            fields = {}
            for k, (arr, vdim, count, offset) in per_key.items():
                sl = [slice(None)] * arr.ndim
                sl[vdim] = slice(offset[i], offset[i] + count[i])
                fields[k] = np.asarray(arr[tuple(sl)])
            samples.append(_sample_from_bp_fields(fields))
        return samples
    finally:
        close()


def _sample_from_bp_fields(fields: dict) -> GraphSample:
    kw = {}
    extras = {}
    ei = fields.pop("edge_index", None)
    for k, v in fields.items():
        if k in _BP_FIELD_MAP:
            kw[_BP_FIELD_MAP[k]] = v
        else:
            extras[k] = v
    s = GraphSample(**kw)
    if ei is not None:
        ei = np.asarray(ei, np.int64).reshape(2, -1)
        s.senders, s.receivers = ei[0], ei[1]
        if s.edge_shifts is None or len(s.edge_shifts) != s.senders.size:
            # .bp stores without per-edge shifts (open-boundary corpora):
            # zero shifts, matching the in-cell edge convention
            s.edge_shifts = np.zeros((s.senders.size, 3), np.float32)
    # reference semantics: Data.x is the FULL node-feature table and y the
    # graph-target vector — expose them as the columnar tables so
    # Variables_of_interest column selection works downstream. (Node-level
    # targets inside the reference's y_loc-encoded y are ambiguous without
    # y_loc and must travel as their own .bp keys.)
    if s.x is not None:
        s.extras.setdefault("node_table", np.asarray(s.x))
    if s.graph_y is not None:
        s.extras.setdefault(
            "graph_table", np.asarray(s.graph_y, np.float64).reshape(-1)
        )
    s.extras.update(extras)
    return s


def read_structures(
    path: str, fmt: str | None = None, limit: int | None = None
) -> list[GraphSample]:
    """Read any supported input into (edge-less) ``GraphSample``s."""
    from .cfg import read_cfg_file
    from .lsms import load_lsms_dir
    from .xyz import load_xyz_dir, read_xyz_file

    ext = os.path.splitext(path)[1].lower()
    if fmt == "lsms":
        return load_lsms_dir(path)[:limit]
    if ext == ".bp":  # ADIOS stores are directories — route before isdir
        return read_bp_dataset(path, limit=limit)
    if os.path.isdir(path):
        return load_xyz_dir(path, limit=limit)
    if ext in (".xyz", ".extxyz"):
        return read_xyz_file(path, limit=limit)
    if ext == ".cfg":
        return [read_cfg_file(path)][:limit]
    if ext in (".db", ".traj"):
        return _read_ase(path, limit=limit)
    if ext == ".lmdb":
        return _read_oc20_lmdb(path, limit=limit)
    if ext in (".h5", ".hdf5"):
        from .hdf5 import read_hdf5

        return read_hdf5(path, limit=limit)
    raise ValueError(
        f"unrecognized dataset input {path!r} (expected .xyz/.extxyz/.cfg/"
        ".db/.traj/.lmdb/.h5/.hdf5/.bp, a directory of .xyz files, or "
        "--format lsms)"
    )


def convert_to_packed(
    input_path: str,
    output_path: str,
    radius: float = 5.0,
    max_neighbours: int | None = 40,
    fmt: str | None = None,
    limit: int | None = None,
    dataset_name: str | None = None,
) -> int:
    """Read ``input_path``, build radius graphs, write a packed store.
    Returns the number of structures written."""
    from .packed import PackedWriter

    samples = read_structures(input_path, fmt=fmt, limit=limit)
    if not samples:
        raise ValueError(f"no structures found in {input_path!r}")
    attach_radius_graph(samples, radius, max_neighbours, progress_every=1000)
    PackedWriter(
        samples,
        output_path,
        attrs={
            "dataset_name": dataset_name or os.path.basename(input_path),
            "source": os.path.abspath(input_path),
            "radius": radius,
            "max_neighbours": max_neighbours or 0,
        },
    )
    return len(samples)


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="Convert a public structure file to a packed training store"
    )
    ap.add_argument(
        "input",
        help=".xyz/.extxyz/.cfg/.db/.traj/.lmdb/.h5/.hdf5/.bp file or xyz dir",
    )
    ap.add_argument("output", help="output packed store (.gpk)")
    ap.add_argument("--radius", type=float, default=5.0)
    ap.add_argument("--max-neighbours", type=int, default=40)
    ap.add_argument("--format", dest="fmt", default=None, choices=[None, "lsms"])
    ap.add_argument("--limit", type=int, default=None, help="convert first N only")
    ap.add_argument("--name", default=None, help="dataset_name attr")
    args = ap.parse_args(argv)
    n = convert_to_packed(
        args.input,
        args.output,
        radius=args.radius,
        max_neighbours=args.max_neighbours,
        fmt=args.fmt,
        limit=args.limit,
        dataset_name=args.name,
    )
    print(f"wrote {n} structures -> {args.output}")


if __name__ == "__main__":
    main()
