"""Fused cell-list neighbor build: the MD graph-rebuild hot op.

``md.binned_radius_graph`` (the on-device vesin role) is pure XLA today: it
gathers every atom's 27-cell candidate set into a ``[n, 27*capacity]`` id
matrix, gathers candidate positions ``[n, C, 3]``, and materializes
displacement/shift/distance matrices of the same extent in HBM before the
distance filter — ~20+ bytes per candidate round-tripped per MD step. This
kernel runs the candidate walk → min-image displacement → distance filter
INSIDE one Pallas pass over cell-sorted atoms, so the only candidate-extent
array that ever reaches HBM is the final 1-byte hit mask.

Geometry (the ``fused_scatter`` playbook, adapted to cells):

* atoms are sorted by cell id (XLA prelude — sort stays outside the kernel);
  every cell's atoms then form one contiguous run of the sorted array;
* grid = one program per cell. The program's central atoms and each of its
  27 neighbor-cell candidate runs are fixed-width ``W`` windows into the
  sorted position array (``W`` = capacity rounded for 8-aligned starts);
  the 27 × (start, first, count) window descriptors ride scalar prefetch,
  and exact run membership is recovered in-kernel by comparing window
  offsets against (first, count) — clamping/alignment can therefore never
  admit a wrong atom or drop a real one;
* the kernel emits the ``[cells, W, 27·W]`` int8 hit mask; a thin XLA
  epilogue decodes hit coordinates back to sorted indices arithmetically
  (cell/slot/window math — no candidate id matrix is ever built), maps them
  through the sort order, and recomputes the per-edge PBC shift for just the
  selected pairs.

Semantics are edge-for-edge identical to the XLA build (same binning, same
min-image formula, same self-exclusion, same ``max_edges`` truncation
telltale and capacity-overflow poisoning of ``n_edges``) except EDGE ORDER:
hits stream out cell-major instead of atom-major. Every consumer
(``energy_fn`` segment sums) is order-insensitive up to fp association, and
the parity tests compare edge SETS plus end-to-end energies.

The build's outputs carry no useful position gradients (ids are integers;
shifts are piecewise-constant in ``pos``, gradient 0 — same as the XLA
path), so kernel inputs are ``stop_gradient``-wrapped and the epilogue's
differentiable shift recompute preserves the XLA path's (zero) gradient
structure exactly.

A/B switch: ``HYDRAGNN_FUSED_CELL_LIST=0|1``; default on for TPU backends,
off (but testable via ``interpret=True``) elsewhere. Statically ineligible
geometries (tiny systems, VMEM/SMEM budget) return ``None`` and the caller
keeps the XLA path — correctness never depends on the kernel running.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable without TPU; interpret mode runs anywhere
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

Array = jax.Array

# resident sorted positions + per-j [W, W, 3] displacement block budget
_VMEM_RESIDENT_LIMIT = 10 * 1024 * 1024
# the 6 scalar-prefetch descriptor arrays are O(cells·27) SMEM ints; cap the
# cell count so their footprint stays bounded (beyond this the XLA path is
# memory-bound anyway and atoms should shard over the mesh first)
_MAX_CELLS = 8192


def _flag_enabled() -> bool | None:
    from ..utils import flags

    return flags.get(flags.FUSED_CELL_LIST)


def _auto_enabled() -> bool:
    flag = _flag_enabled()
    if flag is not None:
        return flag
    return jax.default_backend() == "tpu"


def cell_window(capacity: int) -> int:
    """Window width per cell run: ``capacity`` atoms plus slack for the
    8-aligned start (a clamped-down start can sit up to 7 rows early)."""
    return int(-(-(capacity + 7) // 8) * 8)


def _cell_kernel(
    cstart_ref,   # SMEM [cells] central window start (8-aligned, clamped)
    cfirst_ref,   # SMEM [cells] first sorted index of the central run
    ccount_ref,   # SMEM [cells] central run length
    nstart_ref,   # SMEM [cells*27] neighbor window starts
    nfirst_ref,   # SMEM [cells*27] neighbor run firsts
    ncount_ref,   # SMEM [cells*27] neighbor run lengths (0 = invalid cell)
    spos_ref,     # VMEM [n, 3] cell-sorted positions, resident
    cellm_ref,    # VMEM [3, 3] cell matrix
    inv_ref,      # VMEM [3, 3] inverse cell matrix
    pbc_ref,      # VMEM [1, 3] periodic-axis mask (1.0 / 0.0)
    out_ref,      # VMEM [1, W, 27*W] int8 hit mask block for this cell
    *,
    window: int,
    cutoff2: float,
):
    c = pl.program_id(0)
    w = window
    cellm = cellm_ref[...].astype(jnp.float32)
    inv = inv_ref[...].astype(jnp.float32)
    pbcf = pbc_ref[0, :].astype(jnp.float32)  # [3]

    c0 = cstart_ref[c]
    catoms = spos_ref[pl.ds(c0, w), :].astype(jnp.float32)  # [W, 3]
    lane = jax.lax.broadcasted_iota(jnp.int32, (w,), 0)
    cidx = c0 + lane
    cvalid = (cidx >= cfirst_ref[c]) & (cidx < cfirst_ref[c] + ccount_ref[c])

    for j in range(27):
        s0 = nstart_ref[c * 27 + j]
        f0 = nfirst_ref[c * 27 + j]
        ct = ncount_ref[c * 27 + j]
        watoms = spos_ref[pl.ds(s0, w), :].astype(jnp.float32)  # [W, 3]
        ridx = s0 + lane
        rvalid = (ridx >= f0) & (ridx < f0 + ct)
        disp = watoms[None, :, :] - catoms[:, None, :]  # [W, W, 3]
        frac = jnp.dot(disp.reshape(-1, 3), inv,
                       preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
        wrap = jnp.round(frac) * pbcf[None, :]
        shift = -jnp.dot(wrap, cellm, preferred_element_type=jnp.float32,
                         precision=jax.lax.Precision.HIGHEST)
        dispw = disp + shift.reshape(w, w, 3)
        d2 = jnp.sum(dispw * dispw, axis=-1)  # [W, W]
        within = (
            (d2 <= cutoff2)
            & cvalid[:, None]
            & rvalid[None, :]
            & (cidx[:, None] != ridx[None, :])
        )
        out_ref[0, :, j * w:(j + 1) * w] = within.astype(jnp.int8)


def _static_ok(n: int, n_cells: int, window: int) -> bool:
    if pltpu is None:
        return False
    if n < window or n_cells > _MAX_CELLS:
        return False
    if n_cells * window * 27 * window >= 2**31:  # flat nonzero index space
        return False
    vmem = n * 3 * 4 + 2 * window * window * 3 * 4 + window * 27 * window
    if vmem > _VMEM_RESIDENT_LIMIT:
        return False
    return True


def fused_binned_radius_graph(
    pos: Array,
    cutoff: float,
    max_edges: int,
    cell: Array,
    pbc: Array,
    grid: tuple[int, int, int],
    capacity: int,
    pad_id: int = 0,
    interpret: bool | None = None,
    window: int | None = None,
):
    """Fused-kernel twin of ``md.binned_radius_graph`` — same arguments,
    same ``(senders, receivers, shifts, edge_mask, n_edges)`` contract (edge
    ORDER differs: cell-major, documented above). Returns ``None`` when the
    static geometry checks rule the kernel out; the caller then runs the
    XLA path. ``grid``/``capacity`` come from ``md.plan_cell_grid``.

    ``window`` overrides the per-cell window width (autotuner axis; default
    ``cell_window(capacity)``). Any 8-aligned width at or above that minimum
    is exact — the in-kernel (first, count) membership check means window
    slack can never admit or drop an atom; when ``HYDRAGNN_OPS_AUTOTUNE`` is
    set, a cached per-shape choice from ``ops/autotune.py`` is used."""
    n = pos.shape[0]
    gx, gy, gz = (int(g) for g in grid)
    n_cells = gx * gy * gz
    base = cell_window(int(capacity))
    w = base
    if window is not None:
        w = int(window)
        if w < base or w % 8:
            raise ValueError(
                f"window must be an 8-aligned width >= cell_window(capacity)"
                f"={base}, got {w}"
            )
    else:
        from .autotune import tuned_cell_list_window

        tuned = tuned_cell_list_window(n, n_cells, int(capacity))
        if tuned is not None:
            w = tuned
    if not _static_ok(n, n_cells, w):
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    g = jnp.asarray([gx, gy, gz], jnp.int32)
    cellm = jnp.asarray(cell, jnp.float32).reshape(3, 3)
    inv = jnp.linalg.inv(cellm)
    pbc_b = jnp.asarray(pbc, bool).reshape(3)

    # ---- prelude (XLA): binning + sort + per-cell run/window descriptors.
    # Bit-identical binning to the XLA build: same wrapped/clamped fractional
    # coordinates, same cell linearization.
    posf = pos.astype(jnp.float32)
    frac = posf @ inv
    fw = jnp.where(pbc_b, frac % 1.0, jnp.clip(frac, 0.0, 1.0 - 1e-9))
    idx3 = jnp.clip((fw * g).astype(jnp.int32), 0, g - 1)
    cid = (idx3[:, 0] * gy + idx3[:, 1]) * gz + idx3[:, 2]
    order = jnp.argsort(cid).astype(jnp.int32)
    spos = posf[order]
    cs = cid[order]
    cell_ids = jnp.arange(n_cells, dtype=cid.dtype)
    cell_start = jnp.searchsorted(cs, cell_ids, side="left").astype(jnp.int32)
    occ = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), cid, num_segments=n_cells
    )
    max_occ = occ.max()

    from ..md import _CELL_OFFSETS

    coords = jnp.stack([
        cell_ids // (gy * gz), (cell_ids // gz) % gy, cell_ids % gz,
    ], axis=-1)  # [cells, 3]
    offs = jnp.asarray(_CELL_OFFSETS)
    nbr3 = coords[:, None, :] + offs[None, :, :]  # [cells, 27, 3]
    wrapped = nbr3 % g
    valid = (pbc_b | ((nbr3 >= 0) & (nbr3 < g))).all(-1)  # [cells, 27]
    ncid = (wrapped[..., 0] * gy + wrapped[..., 1]) * gz + wrapped[..., 2]

    firsts = cell_start[ncid]  # [cells, 27]
    counts = jnp.where(valid, occ[ncid], 0).astype(jnp.int32)
    hi = max(n - w, 0)
    starts8 = jnp.clip((firsts // 8) * 8, 0, hi).astype(jnp.int32)
    cstart8 = jnp.clip((cell_start // 8) * 8, 0, hi).astype(jnp.int32)

    # ---- kernel: the candidate walk + distance filter, nothing but the
    # int8 hit mask leaves the chip. The build carries no position gradient
    # (ids + piecewise-constant shifts), so kernel inputs are detached —
    # pallas_call never enters the autodiff graph.
    sg = jax.lax.stop_gradient
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(n_cells,),
        in_specs=[
            pl.BlockSpec((n, 3), lambda c, *_: (0, 0)),  # spos resident
            pl.BlockSpec((3, 3), lambda c, *_: (0, 0)),
            pl.BlockSpec((3, 3), lambda c, *_: (0, 0)),
            pl.BlockSpec((1, 3), lambda c, *_: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, w, 27 * w), lambda c, *_: (c, 0, 0)),
    )
    within = pl.pallas_call(
        functools.partial(
            _cell_kernel, window=w, cutoff2=float(cutoff) ** 2
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_cells, w, 27 * w), jnp.int8),
        interpret=interpret,
    )(
        cstart8, cell_start, occ.astype(jnp.int32),
        starts8.reshape(-1), firsts.reshape(-1).astype(jnp.int32),
        counts.reshape(-1),
        sg(spos), sg(cellm), sg(inv),
        sg(jnp.where(pbc_b, 1.0, 0.0).reshape(1, 3).astype(jnp.float32)),
    )

    # ---- epilogue (XLA): decode hit coordinates arithmetically, map
    # through the sort, recompute shifts for selected pairs only.
    hits = within.reshape(-1) != 0
    n_real = hits.sum()
    flat_idx = jnp.nonzero(hits, size=max_edges, fill_value=0)[0]
    c_of = (flat_idx // (w * 27 * w)).astype(jnp.int32)
    rem = flat_idx % (w * 27 * w)
    a_of = (rem // (27 * w)).astype(jnp.int32)
    col = rem % (27 * w)
    j_of = (col // w).astype(jnp.int32)
    i_of = (col % w).astype(jnp.int32)
    sidx = cstart8[c_of] + a_of
    ridx = starts8[c_of, j_of] + i_of
    senders = order[sidx]
    receivers = order[ridx]
    edge_mask = (jnp.arange(max_edges) < n_real).astype(pos.dtype)

    disp = pos[receivers] - pos[senders]
    wrap = jnp.round(disp @ inv.astype(pos.dtype)) * jnp.where(pbc_b, 1.0, 0.0)
    shift = -(wrap @ cellm.astype(pos.dtype))
    shifts = shift * edge_mask[:, None]
    senders = jnp.where(edge_mask > 0, senders, pad_id)
    receivers = jnp.where(edge_mask > 0, receivers, pad_id)
    # same overflow poison as the XLA build: a cell past capacity means
    # candidates were (or could have been) dropped — trip the caller's
    # n_edges telltale rather than silently missing edges
    n_edges = jnp.where(max_occ > capacity, max_edges + max_occ, n_real)
    return senders, receivers, shifts, edge_mask, n_edges


__all__ = ["cell_window", "fused_binned_radius_graph"]
