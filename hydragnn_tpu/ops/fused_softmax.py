"""Fused segment-softmax Pallas kernels: the attention-normalization hot op.

``graphs.segment.segment_softmax`` (GAT attention, reference PyG
``softmax(src, index)``) lowers to FOUR segment ops — ``segment_max`` →
gather → ``exp`` → ``segment_sum`` → gather → divide — with three HBM
round-trips of ``[E, H]`` intermediates. This module collapses the chain
into ONE windowed Pallas pass, following the ``fused_scatter`` playbook:

* edges arrive (near-)sorted by receiver (collate layout), so each block of
  ``block_edges`` consecutive edges touches a narrow node window; per-block
  window starts ride scalar prefetch (SMEM);
* the kernel runs the grid THREE phases over the same blocks (grid =
  ``(3, G)``, phase-major): phase 0 accumulates per-segment maxima into a
  VMEM-resident ``[N, H]`` stats buffer, phase 1 accumulates
  ``sum(exp(x - max))`` (one-hot MXU gathers/scatters against the stats
  window), phase 2 writes the normalized outputs — logits are read from HBM
  but no ``[E, H]`` intermediate is ever written back;
* a same-program ``lax.cond`` falls back to the XLA reference chain when a
  block's span exceeds the window, unless the caller supplies a host-side
  layout certificate (``fits``, from collate's ``BatchMeta``) that makes
  the choice trace-time static.

Out-of-window ids (collate's reserved dummy slot under the pad exemption,
see ``fused_scatter.window_fits_host``) get output 0 — they only ever feed
masked dummy rows; the XLA reference gives them a finite nonzero value
instead, so parity holds exactly for every certified-in-window entry.

The op's custom VJP uses the saved output directly
(``ds = s * (dy - Σ_seg s·dy)``) — one segment reduction instead of
differentiating through the four-op chain.

``fused_masked_softmax`` is the dense sibling for GPS's per-graph attention
blocks: rows are independent, so mask → max → exp → sum → divide fuses into
a single one-pass kernel with no stats buffer and no fallback (exact for
every layout).

A/B switch: ``HYDRAGNN_FUSED_SOFTMAX=0|1`` (env); default on for TPU
backends, off (but testable via ``interpret=True``) elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused_scatter import _window_starts

try:  # pltpu is importable without TPU; interpret mode runs anywhere
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

Array = jax.Array

# The (window, block_edges) geometry the collate-side attention certificate
# (BatchMeta.attn_fits) is checked against. Window == block: GAT's appended
# self-loop section is a strictly increasing arange whose 256-blocks span
# exactly 256 ids — a 128 window could never certify it.
SM_CERT_WINDOW = 256
SM_CERT_BLOCK = 256

# VMEM budget for the resident stats + per-block broadcast intermediates.
_VMEM_RESIDENT_LIMIT = 10 * 1024 * 1024
_MAX_HEADS = 16  # phase-0 builds a [BE, W, H] broadcast; cap its VMEM bill

# empty-segment sentinel for the resident max stats. Finite on purpose:
# Mosaic (Pallas TPU) has no is_finite lowering, and -inf would turn the
# one-hot stats gather into 0·(-inf) = NaN. Any real logit is far above the
# threshold (GAT's mask fill is -1e9), so sentinel detection is exact.
_NEG_INIT = -3.0e38
_NEG_THRESH = -1.0e38


def self_loop_pad(num_edges: int) -> int:
    """Alignment padding GAT inserts between the real-edge section and the
    appended self-loop arange, so the arange section starts on a
    ``SM_CERT_BLOCK`` boundary (its blocks then span exactly the certified
    window). The SINGLE source for both the model-side layout
    (``models/gat.py``) and the host-side certificate
    (``graphs.batching._batch_meta``) — they must describe the same array."""
    return -num_edges % SM_CERT_BLOCK


def _flag_enabled() -> bool | None:
    from ..utils import flags

    return flags.get(flags.FUSED_SOFTMAX)


def _auto_enabled() -> bool:
    flag = _flag_enabled()
    if flag is not None:
        return flag
    return jax.default_backend() == "tpu"


def reference_segment_softmax(
    logits: Array, segment_ids: Array, num_segments: int
) -> Array:
    """The XLA baseline: the exact ``graphs.segment.segment_softmax`` chain
    (kept in lockstep by tests — parity gates compare against THIS)."""
    seg_max = jax.ops.segment_max(
        jax.lax.stop_gradient(logits), segment_ids, num_segments=num_segments
    )
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, jnp.zeros_like(seg_max))
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    denom = jnp.maximum(denom, 1e-12)
    return exp / denom[segment_ids]


def _softmax_kernel(
    starts_ref,  # SMEM [G] scalar-prefetch: per-block segment-window start
    logits_ref,  # VMEM [1, BE, H] logits block
    rl_ref,  # VMEM [1, 1, BE] segment ids local to the block's window
    out_ref,  # VMEM [BE, H] output block
    max_ref,  # VMEM [N, H] fp32 per-segment max, resident across the grid
    sum_ref,  # VMEM [N, H] fp32 per-segment exp-sum, resident across the grid
    *,
    window: int,
    block_edges: int,
):
    p = pl.program_id(0)  # phase: 0 = max, 1 = exp-sum, 2 = normalize
    k = pl.program_id(1)  # edge block

    @pl.when(jnp.logical_and(p == 0, k == 0))
    def _init():
        max_ref[...] = jnp.full_like(max_ref, _NEG_INIT)
        sum_ref[...] = jnp.zeros_like(sum_ref)

    r0 = starts_ref[k]
    rl = rl_ref[0, 0, :]  # [BE]
    logits = logits_ref[0].astype(jnp.float32)  # [BE, H]
    lane = jax.lax.broadcasted_iota(jnp.int32, (block_edges, window), 1)
    onehot_b = lane == rl[:, None]  # [BE, W] bool
    # out-of-window entries (pad-exempt ids): contribute nothing, output 0
    inw = ((rl >= 0) & (rl < window)).astype(jnp.float32)  # [BE]
    prec = jax.lax.Precision.HIGHEST

    @pl.when(p == 0)
    def _phase_max():
        masked = jnp.where(onehot_b[:, :, None], logits[:, None, :], _NEG_INIT)
        blockmax = masked.max(axis=0)  # [W, H]
        cur = max_ref[pl.ds(r0, window), :]
        max_ref[pl.ds(r0, window), :] = jnp.maximum(cur, blockmax)
        out_ref[...] = jnp.zeros_like(out_ref)

    # phases 1/2 share the gather of this block's per-segment stats: a
    # one-hot MXU matmul against the stats window (exact — one operand is
    # 0/1 and fp32 HIGHEST forbids bf16 rounding). Empty window rows still
    # hold the _NEG_INIT sentinel; sanitize to 0 (the reference's
    # isfinite→0 rule) BEFORE the dot, where a huge-negative times a
    # one-hot zero would lose precision against real accumulands. (A finite
    # sentinel, not -inf: Mosaic has no is_finite lowering and 0·(-inf)
    # would manufacture NaN in the matmul.)
    onehot = onehot_b.astype(jnp.float32)
    maxw = max_ref[pl.ds(r0, window), :]  # [W, H]
    maxw = jnp.where(maxw > _NEG_THRESH, maxw, jnp.zeros_like(maxw))
    sel_max = jnp.dot(onehot, maxw, preferred_element_type=jnp.float32,
                      precision=prec)  # [BE, H]
    # in-window entries have shifted <= 0 exactly (their max dominates), so
    # the clamp is a no-op for them; it only bounds out-of-window garbage
    shifted = jnp.minimum(logits - sel_max, 0.0)
    e = jnp.exp(shifted) * inw[:, None]

    @pl.when(p == 1)
    def _phase_sum():
        part = jnp.dot(onehot.T, e, preferred_element_type=jnp.float32,
                       precision=prec)  # [W, H]
        sum_ref[pl.ds(r0, window), :] += part
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(p == 2)
    def _phase_out():
        sumw = sum_ref[pl.ds(r0, window), :]
        sel_sum = jnp.dot(onehot, sumw, preferred_element_type=jnp.float32,
                          precision=prec)
        out = e / jnp.maximum(sel_sum, 1e-12)
        out_ref[...] = out.astype(out_ref.dtype)


def _pallas_softmax(
    logits: Array, segment_ids: Array, num_segments: int,
    window: int, block_edges: int, interpret: bool,
) -> tuple[Array, Array]:
    """Returns (out [E, H], fits) — caller selects vs fallback on fits."""
    n, h = num_segments, logits.shape[1]
    e = logits.shape[0]
    g = e // block_edges
    starts, local, fits = _window_starts(segment_ids, g, block_edges, window, n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(3, g),
        in_specs=[
            pl.BlockSpec((1, block_edges, h), lambda p, k, *_: (k, 0, 0)),
            pl.BlockSpec((1, 1, block_edges), lambda p, k, *_: (k, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_edges, h), lambda p, k, *_: (k, 0)),
            pl.BlockSpec((n, h), lambda p, k, *_: (0, 0)),  # max resident
            pl.BlockSpec((n, h), lambda p, k, *_: (0, 0)),  # sum resident
        ],
    )
    out, _mx, _sm = pl.pallas_call(
        functools.partial(
            _softmax_kernel, window=window, block_edges=block_edges
        ),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((e, h), logits.dtype),
            jax.ShapeDtypeStruct((n, h), jnp.float32),
            jax.ShapeDtypeStruct((n, h), jnp.float32),
        ),
        interpret=interpret,
    )(starts, logits.reshape(g, block_edges, h),
      local.reshape(g, 1, block_edges))
    return out, fits


def _sm_static_ok(logits, segment_ids, num_segments: int, window: int) -> bool:
    if pltpu is None:
        return False
    if logits.ndim != 2 or not jnp.issubdtype(logits.dtype, jnp.floating):
        return False
    n, h = num_segments, logits.shape[1]
    if segment_ids.shape[0] == 0 or h == 0 or h > _MAX_HEADS:
        return False
    if n < window or n % 8:
        return False
    # resident stats (2·N·H) + the phase-0 [BE, W, H] broadcast
    if (2 * n * h + SM_CERT_BLOCK * window * h) * 4 > _VMEM_RESIDENT_LIMIT:
        return False
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _fused(logits, segment_ids, num_segments, window, block_edges, interpret,
           fits_static):
    return _fused_fwd(
        logits, segment_ids, num_segments, window, block_edges, interpret,
        fits_static,
    )[0]


def _fused_fwd(logits, segment_ids, num_segments, window, block_edges,
               interpret, fits_static):
    out, fits = _pallas_softmax(
        logits, segment_ids, num_segments, window, block_edges, interpret
    )
    if fits_static:
        out = out.astype(logits.dtype)
    else:
        ref = lambda: reference_segment_softmax(
            logits, segment_ids, num_segments
        )
        out = jax.lax.cond(fits, lambda: out, ref).astype(logits.dtype)
    return out, (out, segment_ids)


def _fused_bwd(num_segments, window, block_edges, interpret, fits_static,
               res, dout):
    # softmax VJP from the saved output: ds_i = s_i (dy_i - Σ_{j∈seg(i)} s_j
    # dy_j) — valid for BOTH the kernel and the cond-fallback forward (they
    # compute the same function), so no cond is needed here.
    out, segment_ids = res
    g = out.astype(jnp.float32) * dout.astype(jnp.float32)
    t = jax.ops.segment_sum(g, segment_ids, num_segments=num_segments)
    ds = out.astype(jnp.float32) * (dout.astype(jnp.float32) - t[segment_ids])
    return ds.astype(out.dtype), None


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_segment_softmax(
    logits: Array,
    segment_ids: Array,
    num_segments: int,
    fits: bool | None = None,
    interpret: bool | None = None,
) -> Array:
    """Numerically-stable per-segment softmax of 2D ``[E, H]`` logits in one
    Pallas pass. ``fits`` is the host-certified layout guarantee: True →
    kernel only, False → XLA chain only, None → in-program ``lax.cond``
    fallback (correct for any layout, but the dynamic cond costs both
    branches under ``vmap``).

    Certificate compatibility: the kernel's geometry is
    ``(SM_CERT_WINDOW=256, SM_CERT_BLOCK=256)``. ``BatchMeta.attn_fits`` is
    checked at exactly this geometry. The 128-window scatter certificates
    (``recv_fits``/``send_fits``, same 256 block) are STRONGER: a block that
    fits an 8-aligned 128 window from its clamped start also fits the 256
    window from the (≤) 256-clamped start — if the 256 start is unclamped it
    equals the 128 one (span < 128 < 256); if clamped to ``n-256`` the
    window reaches ``n`` and covers any id. So both certificate families are
    accepted here (``num_segments >= 256`` is required by the static check,
    keeping the clamp argument valid)."""
    window, block_edges = SM_CERT_WINDOW, SM_CERT_BLOCK
    if fits is False or not _sm_static_ok(
        logits, segment_ids, num_segments, window
    ):
        return reference_segment_softmax(logits, segment_ids, num_segments)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    e = logits.shape[0]
    e_pad = -e % block_edges
    if e_pad:
        # pad entries point at the reserved dummy segment; their (sliced-off)
        # outputs and their contribution to that segment's stats follow the
        # same pad-exemption soundness as the scatter kernels
        logits = jnp.pad(logits, ((0, e_pad), (0, 0)))
        segment_ids = jnp.pad(
            segment_ids, (0, e_pad), constant_values=num_segments - 1
        )
    out = _fused(
        logits, segment_ids, num_segments, window, block_edges, interpret,
        bool(fits),
    )
    return out[:e] if e_pad else out


# ---------------------------------------------------------------------------
# Dense masked row softmax (GPS per-graph attention blocks)
# ---------------------------------------------------------------------------

_ROW_BLOCK = 8
_MASK_FILL = -1e9  # the GPS dense path's mask fill — matched exactly


def _row_softmax_kernel(x_ref, m_ref, o_ref):
    # no stop_gradient: kernels are never differentiated (the custom VJP
    # below owns the gradient), and Mosaic has no lowering for it anyway
    x = jnp.where(m_ref[...] > 0, x_ref[...].astype(jnp.float32), _MASK_FILL)
    mx = x.max(axis=-1, keepdims=True)
    e = jnp.exp(x - mx)
    o_ref[...] = (e / e.sum(axis=-1, keepdims=True)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_rows(x, mask, interpret):
    return _fused_rows_fwd(x, mask, interpret)[0]


def _fused_rows_fwd(x, mask, interpret):
    r, m = x.shape
    g = r // _ROW_BLOCK
    out = pl.pallas_call(
        _row_softmax_kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, m), lambda k: (k, 0)),
            pl.BlockSpec((_ROW_BLOCK, m), lambda k: (k, 0)),
        ],
        out_specs=pl.BlockSpec((_ROW_BLOCK, m), lambda k: (k, 0)),
        out_shape=jax.ShapeDtypeStruct((r, m), x.dtype),
        interpret=interpret,
    )(x, mask)
    return out, out


def _fused_rows_bwd(interpret, out, dout):
    s = out.astype(jnp.float32)
    dy = dout.astype(jnp.float32)
    ds = s * (dy - (s * dy).sum(axis=-1, keepdims=True))
    # masked positions have s == 0, so their gradient is 0 — exactly the
    # reference path, where `where(mask, x, -1e9)` routes no gradient to x
    return ds.astype(out.dtype), jnp.zeros_like(out)


_fused_rows.defvjp(_fused_rows_fwd, _fused_rows_bwd)


def fused_masked_softmax(
    logits: Array, mask: Array, interpret: bool | None = None
) -> Array:
    """``jax.nn.softmax(where(mask, logits, -1e9), axis=-1)`` fused into one
    row-local Pallas pass — the GPS dense-attention normalization
    (``[G, H, n, m]`` blocks). Rows are independent, so there is no window
    contract and no fallback path: the kernel is exact for every input;
    oversized/degenerate shapes take the XLA expression below instead."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m = logits.shape[-1]
    mask_b = jnp.broadcast_to(mask, logits.shape)
    if (
        pltpu is None
        or not jnp.issubdtype(logits.dtype, jnp.floating)
        or m == 0
        or logits.size == 0
        or _ROW_BLOCK * m * 4 * 3 > _VMEM_RESIDENT_LIMIT
    ):
        return jax.nn.softmax(
            jnp.where(mask_b, logits, _MASK_FILL), axis=-1
        )
    x2 = logits.reshape(-1, m)
    m2 = mask_b.reshape(-1, m).astype(logits.dtype)
    r = x2.shape[0]
    r_pad = -r % _ROW_BLOCK
    if r_pad:
        # all-masked pad rows produce a uniform (finite) row, sliced off
        x2 = jnp.pad(x2, ((0, r_pad), (0, 0)))
        m2 = jnp.pad(m2, ((0, r_pad), (0, 0)))
    out = _fused_rows(x2, m2, interpret)
    if r_pad:
        out = out[:r]
    return out.reshape(logits.shape)


__all__ = [
    "SM_CERT_BLOCK",
    "SM_CERT_WINDOW",
    "fused_masked_softmax",
    "fused_segment_softmax",
    "reference_segment_softmax",
    "self_loop_pad",
]
