"""Fused gather→scale→scatter-add Pallas kernel: the message-passing hot op.

The role of torch_scatter in the reference (``hydragnn/models/Base.py:23``,
EGNN's ``unsorted_segment_sum``): every conv stack computes

    out[r] += weight[e] * h[s]          for each edge e = (s, r)

XLA's ``segment_sum`` lowering materializes the gathered messages ``[E, C]``
in HBM and scatters them; this kernel keeps the whole gather→scale→scatter
chain in VMEM and turns both the gather and the scatter into small *windowed*
one-hot matmuls on the MXU:

* edges arrive sorted by receiver (``radius_graph`` emits them sorted, and
  ``collate`` preserves per-sample order under increasing node offsets), so
  each block of ``block_edges`` consecutive edges touches only a narrow,
  contiguous window of node rows — for both endpoints, since molecular edges
  never cross graph boundaries;
* per block, gather = ``onehot[s_local] @ h[window]`` and scatter-add =
  ``onehot[r_local].T @ msgs`` with window width a static ``window`` — O(E ·
  window · C) MXU FLOPs instead of O(E · N · C) for a full one-hot, and zero
  HBM round-trip for the messages.

Window starts are data-dependent, so they ride Pallas *scalar prefetch*
(SMEM), and a same-program ``lax.cond`` falls back to the reference
``segment_sum`` path whenever a block's span exceeds the window (pathological
edge orderings, giant graphs) — correctness never depends on the layout.

The op is linear in ``h``, so the custom VJP is the same kernel with gather
and scatter roles swapped; the weight gradient is a windowless gather-dot.

A/B switch: ``HYDRAGNN_FUSED_SCATTER=0|1`` (env) or the ``fused`` argument;
default is on for TPU backends, off (but testable via ``interpret=True``)
elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable without TPU; interpret mode runs anywhere
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

Array = jax.Array

# VMEM budget for the resident h + out blocks (bytes); above this the wrapper
# statically falls back to the XLA path rather than risk a VMEM OOM.
_VMEM_RESIDENT_LIMIT = 10 * 1024 * 1024

# The (window, block_edges) geometry collate's host-side layout certificate
# (BatchMeta.gs_fits) is checked against; a certificate is only honored for
# exactly this geometry.
GS_CERT_WINDOW = 256
GS_CERT_BLOCK = 256


def _flag_enabled() -> bool | None:
    from ..utils import flags

    return flags.get(flags.FUSED_SCATTER)


def _auto_enabled() -> bool:
    flag = _flag_enabled()
    if flag is not None:
        return flag
    return jax.default_backend() == "tpu"


def reference_gather_scatter(
    h: Array, senders: Array, receivers: Array, num_nodes: int, weight: Array | None
) -> Array:
    """The XLA baseline: gather, scale, segment_sum (fp32 accumulate)."""
    msgs = jnp.take(h, senders, axis=0).astype(jnp.float32)
    if weight is not None:
        w = weight if weight.ndim == 2 else weight[:, None]
        msgs = msgs * w.astype(jnp.float32)
    return jax.ops.segment_sum(msgs, receivers, num_segments=num_nodes)


def _kernel(
    s_starts_ref,  # SMEM [G] scalar-prefetch: per-block sender window start
    r_starts_ref,  # SMEM [G] scalar-prefetch: per-block receiver window start
    h_ref,  # VMEM [N, C] resident input features
    sl_ref,  # VMEM [1, 1, BE] sender ids local to the block's sender window
    rl_ref,  # VMEM [1, 1, BE] receiver ids local to the block's receiver window
    w_ref,  # VMEM [1, 1, BE] or [1, BE, C] edge weights (mask folded in)
    out_ref,  # VMEM [N, C] fp32 accumulator, resident across the grid
    *,
    window: int,
    block_edges: int,
    w_per_channel: bool,
):
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    s0 = s_starts_ref[k]
    r0 = r_starts_ref[k]
    dtype = h_ref.dtype
    # bf16 inputs: default MXU passes are exact (one operand is 0/1). fp32
    # inputs: default precision would round h/msgs to bf16 inside the MXU —
    # force the full-precision multi-pass mode to keep fp32 parity with the
    # XLA segment_sum path.
    prec = (
        jax.lax.Precision.HIGHEST
        if dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )

    hw = h_ref[pl.ds(s0, window), :]  # [W, C]
    sl = sl_ref[0, 0, :]  # [BE]
    lane = jax.lax.broadcasted_iota(jnp.int32, (block_edges, window), 1)
    onehot_s = (lane == sl[:, None]).astype(dtype)
    msgs = jnp.dot(
        onehot_s, hw, preferred_element_type=jnp.float32, precision=prec
    )  # [BE, C]

    if w_per_channel:
        msgs = msgs * w_ref[0, :, :].astype(jnp.float32)
    else:
        msgs = msgs * w_ref[0, 0, :].astype(jnp.float32)[:, None]

    rl = rl_ref[0, 0, :]
    onehot_r = (lane == rl[:, None]).astype(jnp.float32)
    partial = jnp.dot(
        onehot_r.T, msgs, preferred_element_type=jnp.float32, precision=prec
    )  # [W, C]
    out_ref[pl.ds(r0, window), :] += partial


def _window_starts(ids: Array, n_blocks: int, block_edges: int, window: int, n: int):
    """Per-block window start (8-aligned, clamped) + whether every block fits."""
    blocks = ids.reshape(n_blocks, block_edges)
    lo = blocks.min(axis=1)
    hi = blocks.max(axis=1)
    start = jnp.clip((lo // 8) * 8, 0, max(n - window, 0)).astype(jnp.int32)
    fits = jnp.all(hi - start < window)
    return start, blocks - start[:, None], fits


def _pallas_gather_scatter(
    h: Array,
    senders: Array,
    receivers: Array,
    weight: Array,
    num_nodes: int,
    window: int,
    block_edges: int,
    interpret: bool,
) -> tuple[Array, Array]:
    """Returns (out_fp32 [N, C], fits) — caller selects vs fallback on fits."""
    n, c = num_nodes, h.shape[1]
    e = senders.shape[0]
    g = e // block_edges

    s_starts, s_local, s_fits = _window_starts(senders, g, block_edges, window, n)
    r_starts, r_local, r_fits = _window_starts(receivers, g, block_edges, window, n)
    fits = jnp.logical_and(s_fits, r_fits)

    # TPU tiling rule: the last two dims of every block shape must divide
    # (8, 128) or equal the array's dims — so per-block 1-D payloads ride a
    # leading grid axis with the block covering the trailing dims entirely.
    w_per_channel = weight.ndim == 2
    if w_per_channel:
        w_blocked = weight.reshape(g, block_edges, c)
        w_spec = pl.BlockSpec((1, block_edges, c), lambda k, *_: (k, 0, 0))
    else:
        w_blocked = weight.reshape(g, 1, block_edges)
        w_spec = pl.BlockSpec((1, 1, block_edges), lambda k, *_: (k, 0, 0))

    kernel = functools.partial(
        _kernel, window=window, block_edges=block_edges, w_per_channel=w_per_channel
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((n, c), lambda k, *_: (0, 0)),  # h resident
            pl.BlockSpec((1, 1, block_edges), lambda k, *_: (k, 0, 0)),
            pl.BlockSpec((1, 1, block_edges), lambda k, *_: (k, 0, 0)),
            w_spec,
        ],
        out_specs=pl.BlockSpec((n, c), lambda k, *_: (0, 0)),  # out resident
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, c), jnp.float32),
        interpret=interpret,
    )(
        s_starts,
        r_starts,
        h,
        s_local.reshape(g, 1, block_edges),
        r_local.reshape(g, 1, block_edges),
        w_blocked,
    )
    return out, fits


def segment_window(num_segments: int) -> int:
    """The window ``fused_segment_sum`` picks for a given segment count —
    exposed so host-side certification (collate → BatchMeta) uses the exact
    same value."""
    return 128 if num_segments >= 128 else num_segments


def window_fits_host(
    ids: np.ndarray, num_nodes: int, window: int, block_edges: int,
    exempt_pad_id: bool = False,
) -> bool:
    """Host (numpy) replica of the kernel's per-block window-fit check, with
    the same pad-to-``block_edges`` convention ``fused_gather_scatter`` /
    ``fused_segment_sum`` apply. Collate uses this to certify the layout
    contract STATICALLY (``BatchMeta``), so the in-program ``lax.cond``
    fallback — which ``vmap`` would turn into executing both branches —
    never enters the traced program. Kept adjacent to ``_window_starts`` so
    the two stay in lockstep (tests assert they agree).

    ``exempt_pad_id``: ignore ids equal to ``num_nodes - 1`` — collate's
    reserved zero-contribution slot (pad edges carry mask weight 0; pad
    nodes feed the masked dummy graph). Without the exemption, the ONE
    boundary block mixing real edges with trailing pad edges always spans
    the whole array and vetoes certification for every production-size
    batch. Soundness: an out-of-window id matches no lane in the kernel's
    one-hot comparison, so its edge contributes exactly 0 on that side —
    identical to the XLA fallback everywhere except possibly the reserved
    dummy row itself, which collate guarantees is never read unmasked."""
    ids = np.asarray(ids, np.int64)
    e = ids.shape[0]
    if e == 0:
        return True
    e_pad = -e % block_edges
    if e_pad:
        ids = np.concatenate([ids, np.full(e_pad, num_nodes - 1, np.int64)])
    blocks = ids.reshape(-1, block_edges)
    if exempt_pad_id:
        real = blocks != num_nodes - 1
        if not real.any():
            return True
        lo = np.where(real, blocks, np.int64(num_nodes)).min(axis=1)
        hi = np.where(real, blocks, np.int64(-1)).max(axis=1)
        has_real = real.any(axis=1)
        start = np.clip((lo // 8) * 8, 0, max(num_nodes - window, 0))
        return bool(np.all(~has_real | (hi - start < window)))
    lo = blocks.min(axis=1)
    hi = blocks.max(axis=1)
    start = np.clip((lo // 8) * 8, 0, max(num_nodes - window, 0))
    return bool(np.all(hi - start < window))


def _static_ok(h, senders, num_nodes, window) -> bool:
    if pltpu is None:
        return False
    n, c = num_nodes, h.shape[1]
    if senders.shape[0] == 0 or n < window or n % 8:
        return False
    itemsize = 4  # h promoted via fp32 accumulate; out is fp32
    if 2 * n * c * itemsize > _VMEM_RESIDENT_LIMIT:
        return False
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 5, 6, 7, 8))
def _fused(
    h, senders, receivers, num_nodes, weight, window, block_edges, interpret, fits_static
):
    return _fused_fwd(
        h, senders, receivers, num_nodes, weight, window, block_edges, interpret,
        fits_static,
    )[0]


def _fused_fwd(
    h, senders, receivers, num_nodes, weight, window, block_edges, interpret, fits_static
):
    out, fits = _pallas_gather_scatter(
        h, senders, receivers, weight, num_nodes, window, block_edges, interpret
    )
    if fits_static:
        # layout certified host-side (BatchMeta.gs_fits): kernel output is
        # exact, no fallback in the program at all
        out = out.astype(h.dtype)
    else:
        ref = lambda: reference_gather_scatter(h, senders, receivers, num_nodes, weight)
        out = jax.lax.cond(fits, lambda: out, ref).astype(h.dtype)
    return out, (h, senders, receivers, weight)


def _fused_bwd(num_nodes, window, block_edges, interpret, fits_static, res, dout):
    h, senders, receivers, weight = res
    # out is linear in h: dh is the same fused op with endpoints swapped
    # (gather rows of dout by receiver, scale, scatter-add onto senders).
    # fits_static covers this transposed call too: the fit check is per-array
    # and role-independent, and the fwd certified BOTH senders and receivers.
    dh_out, fits = _pallas_gather_scatter(
        dout.astype(h.dtype), receivers, senders, weight, num_nodes,
        window, block_edges, interpret,
    )
    if fits_static:
        dh = dh_out.astype(h.dtype)
    else:
        ref = lambda: reference_gather_scatter(
            dout.astype(h.dtype), receivers, senders, num_nodes, weight
        )
        dh = jax.lax.cond(fits, lambda: dh_out, ref).astype(h.dtype)
    # dw[e] = <h[s_e], dout[r_e]> (summed over C for scalar weights)
    hs = jnp.take(h, senders, axis=0).astype(jnp.float32)
    dr = jnp.take(dout, receivers, axis=0).astype(jnp.float32)
    dw = hs * dr if weight.ndim == 2 else (hs * dr).sum(axis=-1)
    return dh, None, None, dw.astype(weight.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_gather_scatter(
    h: Array,
    senders: Array,
    receivers: Array,
    num_nodes: int,
    weight: Array | None = None,
    *,
    window: int = 256,
    block_edges: int = 256,
    interpret: bool | None = None,
    fits: bool | None = None,
    cert_geometry: tuple[int, int] | None = None,
) -> Array:
    """``segment_sum(weight * h[senders], receivers, num_nodes)`` fused in one
    Pallas kernel. ``fits`` is the host-certified layout guarantee
    (``BatchMeta.gs_fits``): True → kernel only, False → XLA path only,
    None → in-program ``lax.cond`` fallback (correctness never depends on
    edge layout, but the dynamic cond costs both branches under ``vmap``).

    A ``fits`` certificate is only sound for the (window, block_edges) it was
    checked against — collate certifies the defaults
    (``GS_CERT_WINDOW``/``GS_CERT_BLOCK``); any other geometry drops the
    certificate and re-enters the dynamic in-program check rather than
    silently trusting an uncertified layout. A caller that ran
    ``window_fits_host`` itself against a non-default geometry states that
    via ``cert_geometry=(window, block_edges)`` to keep its certificate
    (the autotune sweep's path)."""
    if (window, block_edges) not in ((GS_CERT_WINDOW, GS_CERT_BLOCK),
                                     cert_geometry):
        fits = None
    if weight is None:
        weight = jnp.ones(senders.shape[0], dtype=h.dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if fits is False or not _static_ok(h, senders, num_nodes, window):
        return reference_gather_scatter(h, senders, receivers, num_nodes, weight).astype(
            h.dtype
        )
    e = senders.shape[0]
    e_pad = -e % block_edges
    if e_pad:
        # zero-weight pad edges wired to the last node; jnp.pad is
        # differentiable, so gradients are un-padded by autodiff.
        senders = jnp.pad(senders, (0, e_pad), constant_values=num_nodes - 1)
        receivers = jnp.pad(receivers, (0, e_pad), constant_values=num_nodes - 1)
        weight = jnp.pad(weight, ((0, e_pad),) + ((0, 0),) * (weight.ndim - 1))
    return _fused(
        h, senders, receivers, num_nodes, weight, window, block_edges, interpret,
        bool(fits),
    )


def _scatter_kernel(
    r_starts_ref,  # SMEM [G] scalar-prefetch: per-block receiver window start
    data_ref,  # VMEM [BE, C] message block
    rl_ref,  # VMEM [1, 1, BE] receiver ids local to the window
    out_ref,  # VMEM [N, C] fp32 accumulator, resident across the grid
    *,
    window: int,
    block_edges: int,
):
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    r0 = r_starts_ref[k]
    rl = rl_ref[0, 0, :]
    prec = (
        jax.lax.Precision.HIGHEST
        if data_ref.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    lane = jax.lax.broadcasted_iota(jnp.int32, (block_edges, window), 1)
    onehot_r = (lane == rl[:, None]).astype(jnp.float32)
    partial = jnp.dot(
        onehot_r.T, data_ref[...].astype(jnp.float32),
        preferred_element_type=jnp.float32, precision=prec,
    )
    out_ref[pl.ds(r0, window), :] += partial


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _fused_scatter(
    data, segment_ids, num_segments, window, block_edges, interpret, fits_static
):
    return _fused_scatter_fwd(
        data, segment_ids, num_segments, window, block_edges, interpret, fits_static
    )[0]


def _fused_scatter_fwd(
    data, segment_ids, num_segments, window, block_edges, interpret, fits_static
):
    n, c = num_segments, data.shape[1]
    e = data.shape[0]
    g = e // block_edges
    r_starts, r_local, fits = _window_starts(segment_ids, g, block_edges, window, n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((block_edges, c), lambda k, *_: (k, 0)),
            pl.BlockSpec((1, 1, block_edges), lambda k, *_: (k, 0, 0)),
        ],
        out_specs=pl.BlockSpec((n, c), lambda k, *_: (0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_scatter_kernel, window=window, block_edges=block_edges),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, c), jnp.float32),
        interpret=interpret,
    )(r_starts, data, r_local.reshape(g, 1, block_edges))
    if fits_static:
        out = out.astype(data.dtype)
    else:
        ref = lambda: jax.ops.segment_sum(
            data.astype(jnp.float32), segment_ids, num_segments=n
        )
        out = jax.lax.cond(fits, lambda: out, ref).astype(data.dtype)
    return out, segment_ids


def _fused_scatter_bwd(
    num_segments, window, block_edges, interpret, fits_static, segment_ids, dout
):
    return jnp.take(dout, segment_ids, axis=0), None


_fused_scatter.defvjp(_fused_scatter_fwd, _fused_scatter_bwd)


def fused_segment_sum(
    data: Array, segment_ids: Array, num_segments: int, fits: bool | None = None
) -> Array:
    """Windowed Pallas scatter-add: drop-in for ``jax.ops.segment_sum`` on 2D
    float data with (near-)sorted ids — the layout every collated batch has
    for edge→node and node→graph reductions. ``fits`` as in
    ``fused_gather_scatter`` (host-certified via ``BatchMeta``)."""
    if (
        fits is False
        or not _static_ok(data, segment_ids, num_segments, 128)
        or data.ndim != 2
        or not jnp.issubdtype(data.dtype, jnp.floating)
    ):
        return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    window = 128 if num_segments >= 128 else num_segments
    block_edges = 256
    interpret = jax.default_backend() != "tpu"
    e = data.shape[0]
    e_pad = -e % block_edges
    if e_pad:
        data = jnp.pad(data, ((0, e_pad), (0, 0)))
        segment_ids = jnp.pad(
            segment_ids, (0, e_pad), constant_values=num_segments - 1
        )
    return _fused_scatter(
        data, segment_ids, num_segments, window, block_edges, interpret, bool(fits)
    )


def gather_scatter_sum(
    h: Array,
    senders: Array,
    receivers: Array,
    num_nodes: int,
    weight: Array | None = None,
    fused: bool | None = None,
    hints=None,
) -> Array:
    """Conv-stack entry point: fused kernel when enabled (flag/env/backend
    auto), XLA gather+``segment_sum`` otherwise. ``hints`` is the source
    ``GraphBatch``: its collate-certified ``BatchMeta.gs_fits`` makes the
    kernel-vs-fallback choice trace-time static (no cond under vmap).

    With ``HYDRAGNN_OPS_AUTOTUNE`` set, a cached per-shape geometry from
    the shared autotuner replaces the default — but only when the default
    certificate provably transfers to it (``autotune.gs_cert_compatible``:
    same block, wider window), so the certified static path survives the
    geometry swap. The lookup is one in-memory dict read at trace time."""
    if fused is None:
        fused = _auto_enabled()
    if fused:
        fits = None
        if hints is not None and hints.meta is not None:
            if senders is hints.senders and receivers is hints.receivers:
                fits = hints.meta.gs_fits
            elif senders is hints.receivers and receivers is hints.senders:
                fits = hints.meta.gs_fits  # transposed flow: same certificate
        from .autotune import tuned_gather_scatter_geometry

        tuned = tuned_gather_scatter_geometry(
            num_nodes, senders.shape[0], h.shape[1], h.dtype
        )
        if tuned is not None:
            window, block_edges = tuned
            return fused_gather_scatter(
                h, senders, receivers, num_nodes, weight, fits=fits,
                window=window, block_edges=block_edges,
                cert_geometry=(window, block_edges),
            )
        return fused_gather_scatter(h, senders, receivers, num_nodes, weight, fits=fits)
    out = reference_gather_scatter(h, senders, receivers, num_nodes, weight)
    return out.astype(h.dtype)
