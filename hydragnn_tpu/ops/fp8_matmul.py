"""EXPERIMENTAL fp8 (e4m3 / e5m2) dense matmul — the step below bf16.

The MXU's native 8-bit float formats promise ~2× bf16 matmul throughput and
half the weight bytes, but fp8 training is NOT a validated precision here:
``Training.precision`` stops at bf16/fp16 (schema-enforced), and this module
is the contained experiment bench — the ``quant_matmul`` playbook re-run at
fp8:

    y = (q8(x / s_x) · q8(w / s_w)) · (s_x ⊗ s_w) + b

with ``q8`` a saturating cast to ``float8_e4m3fn`` (3 mantissa bits, max
448 — the forward/weight format) or ``float8_e5m2`` (2 mantissa bits, max
57344, fp16's exponent — the gradient format), weights scaled per OUTPUT
channel and activations per tensor. Like the int8 serving path, the
arithmetic has ONE definition (``reference_fp8_dense``); the Pallas kernel
is an execution strategy over the same expression, and
``certify_fp8_dense`` reports the measured error against the fp32 answer —
the same certify-then-serve contract ``serve.quant`` enforces at warm-up,
here exposed directly because there is no product path to arm yet.

A/B: ``HYDRAGNN_FP8_MATMUL`` picks the kernel-vs-XLA route (default: kernel
on TPU backends only; interpret=True testable anywhere). Nothing routes
through fp8 implicitly — callers opt in per matmul.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable without TPU; interpret mode runs anywhere
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

Array = jax.Array

FP8_FORMATS = {
    "e4m3": jnp.float8_e4m3fn,
    "e5m2": jnp.float8_e5m2,
}
# largest finite value per format (the saturating-clip bound before cast)
FP8_MAX = {"e4m3": 448.0, "e5m2": 57344.0}

_ROW_BLOCK = 8
_VMEM_LIMIT = 8 * 1024 * 1024


def _flag_enabled() -> bool | None:
    from ..utils import flags

    return flags.get(flags.FP8_MATMUL)


def resolve_fp8_format(fmt: str):
    try:
        return FP8_FORMATS[fmt]
    except KeyError:
        raise ValueError(f"Unknown fp8 format {fmt!r}; one of "
                         f"{sorted(FP8_FORMATS)}")


def quantize_weight_fp8(w: Array, fmt: str = "e4m3") -> tuple[Array, Array]:
    """Per-output-channel fp8 weight quantization: ``(w_q fp8 [K, N],
    s_w fp32 [N])`` with ``w ≈ w_q · s_w`` — the ``quantize_weight`` shape
    at 8-bit float instead of int8 (scales map each column's absmax onto
    the format's finite range)."""
    dtype = resolve_fp8_format(fmt)
    absmax = jnp.max(jnp.abs(w), axis=0)
    s_w = jnp.maximum(absmax, 1e-12) / FP8_MAX[fmt]
    w_q = _quantize_fp8(w / s_w[None, :], fmt, dtype)
    return w_q, s_w.astype(jnp.float32)


def activation_scale_fp8(x: Array, fmt: str = "e4m3") -> Array:
    """Per-tensor activation scale (absmax onto the format range) — traced,
    so experiments can run without a calibration pass; an AOT deployment
    would bake a calibrated float like the int8 serving tier."""
    return jnp.maximum(jnp.max(jnp.abs(x)), 1e-12) / FP8_MAX[fmt]


def _quantize_fp8(x: Array, fmt: str, dtype) -> Array:
    # clip BEFORE the cast: e5m2 has inf, and an over-range cast would
    # manufacture it; e4m3fn saturates anyway, so the clip only pins the
    # two formats to the same (saturating) convention
    bound = FP8_MAX[fmt]
    return jnp.clip(x.astype(jnp.float32), -bound, bound).astype(dtype)


def reference_fp8_dense(
    x: Array, w_q: Array, s_w: Array, s_x, bias: Array | None,
    fmt: str = "e4m3",
) -> Array:
    """The XLA route — the single definition of the fp8 arithmetic (the
    kernel must match it exactly; tests pin this)."""
    dtype = resolve_fp8_format(fmt)
    x_q = _quantize_fp8(x / s_x, fmt, dtype)
    acc = jax.lax.dot_general(
        x_q, w_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y = acc * (jnp.asarray(s_x, jnp.float32) * s_w)[None, :]
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y


def _fp8_kernel(x_ref, wq_ref, sw_ref, sx_ref, b_ref, o_ref, *, fmt: str):
    dtype = FP8_FORMATS[fmt]
    s_x = sx_ref[0, 0]
    x_q = _quantize_fp8(x_ref[...] / s_x, fmt, dtype)
    acc = jax.lax.dot_general(
        x_q, wq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y = acc * (s_x * sw_ref[0, :])[None, :]
    o_ref[...] = y + b_ref[0, :][None, :]


def fp8_dense(
    x: Array,
    w: Array,
    bias: Array | None = None,
    fmt: str = "e4m3",
    s_x=None,
    kernel: bool | None = None,
    interpret: bool | None = None,
) -> Array:
    """Experimental fp8 dense layer ``[M, K] × [K, N] → fp32 [M, N]``:
    quantize activations (per-tensor) and weights (per-output-channel) to
    ``fmt``, matmul with fp32 accumulation, dequantize + bias. ``s_x`` may
    be a pre-calibrated float; default derives it from ``x`` in-program.
    Route: ``HYDRAGNN_FP8_MATMUL`` > backend default (kernel on TPU only);
    both routes compute the identical expression."""
    resolve_fp8_format(fmt)
    if kernel is None:
        flag = _flag_enabled()
        kernel = flag if flag is not None else jax.default_backend() == "tpu"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    w_q, s_w = quantize_weight_fp8(w, fmt)
    if s_x is None:
        s_x = activation_scale_fp8(x, fmt)
    m, k = x.shape
    n = w_q.shape[1]
    eligible = (
        kernel
        and pltpu is not None
        and m >= _ROW_BLOCK
        and (k * n + _ROW_BLOCK * (k + 2 * n)) * 4 <= _VMEM_LIMIT
        and jnp.issubdtype(x.dtype, jnp.floating)
    )
    if not eligible:
        return reference_fp8_dense(x, w_q, s_w, s_x, bias, fmt)
    b = (bias if bias is not None else jnp.zeros((n,), jnp.float32))
    m_pad = -m % _ROW_BLOCK
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
    g = x.shape[0] // _ROW_BLOCK
    out = pl.pallas_call(
        functools.partial(_fp8_kernel, fmt=fmt),
        grid=(g,),
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),  # weights resident
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_ROW_BLOCK, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], n), jnp.float32),
        interpret=interpret,
    )(x.astype(jnp.float32), w_q, s_w.reshape(1, n),
      jnp.asarray(s_x, jnp.float32).reshape(1, 1),
      b.astype(jnp.float32).reshape(1, n))
    return out[:m] if m_pad else out


def certify_fp8_dense(
    x: Array, w: Array, bias: Array | None = None, fmt: str = "e4m3",
) -> dict:
    """Measured error of the fp8 expression against the fp32 matmul on this
    exact input — the serving tier's certify-before-serve discipline applied
    to the experiment: callers get numbers, not vibes. Returns max-abs and
    relative-Frobenius error plus the format's structural parameters."""
    w_q, s_w = quantize_weight_fp8(w, fmt)
    s_x = activation_scale_fp8(x, fmt)
    got = reference_fp8_dense(x, w_q, s_w, s_x, bias, fmt)
    want = x.astype(jnp.float32) @ w.astype(jnp.float32)
    if bias is not None:
        want = want + bias.astype(jnp.float32)
    diff = got - want
    denom = jnp.maximum(jnp.linalg.norm(want), 1e-12)
    return {
        "format": fmt,
        "max_abs_err": float(jnp.max(jnp.abs(diff))),
        "rel_fro_err": float(jnp.linalg.norm(diff) / denom),
        "mantissa_bits": 3 if fmt == "e4m3" else 2,
        "max_finite": FP8_MAX[fmt],
    }


__all__ = [
    "FP8_FORMATS",
    "FP8_MAX",
    "activation_scale_fp8",
    "certify_fp8_dense",
    "fp8_dense",
    "quantize_weight_fp8",
    "reference_fp8_dense",
    "resolve_fp8_format",
]
