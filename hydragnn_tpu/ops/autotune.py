"""Shared kernel-geometry autotuner with per-shape cached choices.

``bench_fused_autotune`` proved (VERDICT r4) that the fused gather-scatter
kernel's ``(window, block_edges)`` geometry is worth real throughput — and
then threw the answer away every round. This module generalizes that sweep
into ONE autotuner for the whole ops/ kernel library (``fused_scatter``,
``fused_softmax``, ``fused_cell_list``, ``quant_matmul``):

* **candidates** are enumerated per kernel and filtered by that kernel's own
  static-fit and certificate rules BEFORE anything is timed — a geometry the
  wrapper would statically reject, or whose layout certificate cannot be
  established, never enters the sweep;
* **timing** uses the repo's ABBA paired-window discipline
  (``utils.abtest.abba_verdict`` — the exact verdict function every bench
  A/B row uses): each candidate is interleaved against the current incumbent
  in alternating windows after an untimed burn-in pair, and it is adopted
  only when it is faster beyond the host's own noise floor. Ties and
  inconclusive measurements keep the incumbent — the hard-coded default can
  only ever be replaced by a measured win;
* **choices** are keyed per ``(kernel, backend, shape-signature)`` and
  persisted to a small JSON cache NEXT TO the persistent XLA compile cache
  (``<HYDRAGNN_COMPILE_CACHE>/ops_autotune.json``), so steady-state runs pay
  zero sweep cost: a warm lookup is one in-memory dict read at trace time.
  The backend is part of the key because CPU windows time interpret-mode
  kernels — tuning data for the MECHANISM, never for the TPU. Bump
  ``_SCHEMA_VERSION`` when a kernel's cert rules change: a version mismatch
  discards the whole file (stale geometry certificates must not outlive the
  proof they were filtered by).

Sweeps run ONLY through the explicit ``autotune_*`` entry points (bench
rows, operator tooling) — never implicitly inside a training step. The
wrappers' side of the contract is ``tuned_*`` lookups gated on
``HYDRAGNN_OPS_AUTOTUNE``: a cached choice is honored only when the
collate-side layout certificate provably transfers to it (see
``gs_cert_compatible``), otherwise the default geometry stands.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable

import jax
import numpy as np

Array = jax.Array

# Bump when candidate filters / certificate-transfer rules change: cached
# choices are only as sound as the rules that admitted them.
_SCHEMA_VERSION = 1

_MEM: dict | None = None  # lazy-loaded {key: record} view of the disk cache
_SWEEPS_RUN = 0  # observability for the zero-sweep-cost-on-warm-cache gate


def enabled() -> bool:
    """Whether wrappers may consult the cache (``HYDRAGNN_OPS_AUTOTUNE``)."""
    from ..utils import flags

    return bool(flags.get(flags.OPS_AUTOTUNE))


def cache_path() -> str | None:
    """The on-disk cache file, next to the persistent XLA compile cache;
    None when the compile cache is disabled (in-memory only)."""
    from ..utils import flags

    setting = flags.get(flags.COMPILE_CACHE)
    if setting in ("0", "false", "False", "", None):
        return None
    return os.path.join(str(setting), "ops_autotune.json")


def _load() -> dict:
    global _MEM
    if _MEM is not None:
        return _MEM
    _MEM = {}
    path = cache_path()
    if path is not None and os.path.exists(path):
        try:
            with open(path) as f:
                blob = json.load(f)
            if blob.get("version") == _SCHEMA_VERSION:
                _MEM = dict(blob.get("choices", {}))
        except (OSError, ValueError):
            pass  # unreadable cache = cold cache, never a failure
    return _MEM


def _persist() -> None:
    path = cache_path()
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": _SCHEMA_VERSION, "choices": _load()}, f,
                      indent=1)
        os.replace(tmp, path)
    except OSError:
        pass  # a read-only cache dir degrades to in-memory tuning


def reset_cache(forget_disk: bool = False) -> None:
    """Drop the in-memory view (tests; cross-process invalidation). With
    ``forget_disk`` also remove the persisted file."""
    global _MEM
    _MEM = None
    if forget_disk:
        path = cache_path()
        if path is not None and os.path.exists(path):
            try:
                os.remove(path)
            except OSError:
                pass


def shape_signature(**dims) -> str:
    """Canonical shape signature: sorted ``k=v`` pairs."""
    return ",".join(f"{k}={v}" for k, v in sorted(dims.items()))


def _key(kernel: str, sig: str) -> str:
    return f"{kernel}|{jax.default_backend()}|{sig}"


def lookup(kernel: str, sig: str) -> dict | None:
    """Cached choice for (kernel, this backend, sig), or None."""
    return _load().get(_key(kernel, sig))


def record(kernel: str, sig: str, geometry, evidence: dict | None = None) -> dict:
    """Persist a chosen geometry (+ the sweep evidence that earned it)."""
    rec = {"geometry": list(geometry) if isinstance(geometry, (tuple, list))
           else geometry, "evidence": evidence or {}}
    _load()[_key(kernel, sig)] = rec
    _persist()
    return rec


# ---------------------------------------------------------------------------
# Timing: ABBA paired windows, shared verdict discipline
# ---------------------------------------------------------------------------


def _time_window(fn, args, reps: int) -> float:
    out = fn(*args)
    jax.block_until_ready(out)  # compile outside the window
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / max(reps, 1) * 1e3


def _abba_pairs(build_a: Callable, build_b: Callable, reps: int, pairs: int):
    """Interleaved A/B windows (untimed burn-in pair first): the autotuner's
    timing loop. Both callables are built ONCE and reused — the jitted
    candidates compile before their first timed window, never inside one."""
    fa, aa = build_a()
    fb, ab = build_b()
    _time_window(fa, aa, reps)  # burn-in: post-compile allocator settle
    _time_window(fb, ab, reps)
    a_ms, b_ms = [], []
    for w in range(max(pairs, 1)):
        if w % 2 == 0:
            a_ms.append(_time_window(fa, aa, reps))
            b_ms.append(_time_window(fb, ab, reps))
        else:
            b_ms.append(_time_window(fb, ab, reps))
            a_ms.append(_time_window(fa, aa, reps))
    return a_ms, b_ms


def sweep(kernel: str, sig: str, builds: dict, default, *,
          reps: int = 8, pairs: int = 4, force: bool = False) -> dict:
    """The generic sweep: ``builds`` maps geometry -> ``() -> (fn, args)``
    for every candidate that survived the kernel's fit/cert filters
    (``default`` must be among them). Returns the cache record augmented
    with ``cache``/``swept`` bookkeeping; a warm cache returns instantly
    (``swept=False``) unless ``force``.

    Adoption is deliberately conservative: candidate B replaces the
    incumbent A only when the paired-window verdict says B is faster even
    pessimistically (median paired diff + noise floor < 0). Anything the
    host cannot resolve keeps the incumbent."""
    global _SWEEPS_RUN
    cached = lookup(kernel, sig)
    if cached is not None and not force:
        return {**cached, "cache": "hit", "swept": False, "sweep_s": 0.0}
    from ..utils.abtest import abba_verdict

    t0 = time.perf_counter()
    _SWEEPS_RUN += 1
    if default not in builds:
        raise ValueError(f"default geometry {default!r} not in candidates "
                         f"{sorted(map(str, builds))}")
    incumbent = default
    trials = {}
    built: dict = {}

    def built_pair(geom):
        # one build (and one jit compile) per geometry for the WHOLE sweep:
        # without the memo the incumbent would re-jit on every trial, ~
        # doubling sweep compile cost (tens of seconds each on TPU)
        if geom not in built:
            built[geom] = builds[geom]()
        return built[geom]

    for geom in builds:
        if geom == default:
            continue
        a_ms, b_ms = _abba_pairs(
            lambda g=incumbent: built_pair(g), lambda g=geom: built_pair(g),
            reps, pairs,
        )
        overhead_pct, noise_pct, verdict = abba_verdict(a_ms, b_ms,
                                                        budget_pct=0.0)
        adopted = overhead_pct + noise_pct < 0  # faster even pessimistically
        trials[str(geom)] = {
            "vs": str(incumbent),
            "overhead_pct": round(overhead_pct, 2),
            "noise_pct": round(noise_pct, 2),
            "verdict": verdict,
            "adopted": bool(adopted),
        }
        if adopted:
            incumbent = geom
    evidence = {
        "default": str(default),
        "candidates": sorted(map(str, builds)),
        "trials": trials,
        "reps": reps,
        "pairs": pairs,
        "backend": jax.default_backend(),
    }
    rec = record(kernel, sig, incumbent, evidence)
    return {**rec, "cache": "miss", "swept": True,
            "sweep_s": round(time.perf_counter() - t0, 3)}


def sweeps_run() -> int:
    return _SWEEPS_RUN


# ---------------------------------------------------------------------------
# fused_scatter: the (window, block_edges) axis — the proven sweep
# ---------------------------------------------------------------------------

# the candidate grid bench_fused_autotune swept by hand, plus the hard-coded
# default; every entry still passes through fit + certificate filters below
GS_CANDIDATES = ((128, 128), (128, 256), (256, 256), (256, 512), (512, 256))


def gs_signature(num_nodes: int, num_edges: int, channels: int, dtype) -> str:
    return shape_signature(n=int(num_nodes), e=int(num_edges),
                           c=int(channels), dtype=str(dtype))


def gs_static_candidates(num_nodes: int, channels: int) -> list[tuple[int, int]]:
    """GS_CANDIDATES filtered by the wrapper's static-fit rules (mirrors
    ``fused_scatter._static_ok`` per geometry: window fits the node count,
    8-aligned nodes, resident h+out inside the VMEM budget)."""
    from .fused_scatter import _VMEM_RESIDENT_LIMIT

    out = []
    if num_nodes % 8:
        return out
    for window, block_edges in GS_CANDIDATES:
        if num_nodes < window:
            continue
        if 2 * num_nodes * channels * 4 > _VMEM_RESIDENT_LIMIT:
            continue
        out.append((window, block_edges))
    return out


def gs_cert_compatible(window: int, block_edges: int, num_nodes: int) -> bool:
    """Whether collate's DEFAULT-geometry certificate (``BatchMeta.gs_fits``,
    checked at ``(GS_CERT_WINDOW, GS_CERT_BLOCK)``) provably transfers to
    this geometry: same blocks (``block_edges == GS_CERT_BLOCK``) and a
    window at least as wide — a block whose span fits the 256 window from
    its 8-aligned clamped start also fits any wider window from the (≤)
    clamped start, provided the array is at least window wide so the clamp
    argument holds (the ``fused_softmax`` 128→256 implication, generalized
    upward). Narrower windows or different blockings need a fresh host
    certificate and are sweep-only."""
    from .fused_scatter import GS_CERT_BLOCK, GS_CERT_WINDOW

    return (
        block_edges == GS_CERT_BLOCK
        and window >= GS_CERT_WINDOW
        and num_nodes >= window
    )


def autotune_gather_scatter(
    h: Array, senders: Array, receivers: Array, num_nodes: int,
    weight: Array | None = None, *, reps: int = 8, pairs: int = 4,
    force: bool = False, interpret: bool | None = None,
) -> dict:
    """Sweep the fused gather-scatter geometries on a REAL staged batch
    (ids host-certified per candidate via ``window_fits_host``) and cache
    the per-shape winner. The hard-coded default ``(256, 256)`` is the
    incumbent; candidates whose layout certificate cannot be established
    on this batch are filtered out before timing."""
    import jax.numpy as jnp

    from .fused_scatter import (
        GS_CERT_BLOCK,
        GS_CERT_WINDOW,
        fused_gather_scatter,
        window_fits_host,
    )

    n = int(num_nodes)
    c = int(h.shape[1])
    sig = gs_signature(n, senders.shape[0], c, h.dtype)
    default = (GS_CERT_WINDOW, GS_CERT_BLOCK)
    cached = lookup("fused_scatter", sig)
    if cached is not None and not force:
        return {**cached, "cache": "hit", "swept": False, "sweep_s": 0.0}

    if weight is None:
        weight = jnp.ones(senders.shape[0], dtype=h.dtype)
    snd_np, rcv_np = np.asarray(senders), np.asarray(receivers)
    certified = []
    for window, block_edges in gs_static_candidates(n, c):
        if window_fits_host(snd_np, n, window, block_edges,
                            exempt_pad_id=True) and window_fits_host(
                rcv_np, n, window, block_edges, exempt_pad_id=True):
            certified.append((window, block_edges))
    if default not in certified:
        # the staged batch cannot certify even the default: nothing to tune
        rec = record("fused_scatter", sig, default,
                     {"default": str(default), "candidates": [],
                      "note": "default geometry not certifiable on the "
                              "staged batch; kept uncontested"})
        return {**rec, "cache": "miss", "swept": False, "sweep_s": 0.0}

    def build(geom):
        window, block_edges = geom

        def make():
            fn = jax.jit(
                lambda h_, s_, r_, w_, _win=window, _be=block_edges:
                fused_gather_scatter(
                    h_, s_, r_, n, w_, window=_win, block_edges=_be,
                    fits=True, cert_geometry=(_win, _be),
                    interpret=interpret,
                )
            )
            return fn, (h, senders, receivers, weight)

        return make

    builds = {geom: build(geom) for geom in certified}
    return sweep("fused_scatter", sig, builds, default,
                 reps=reps, pairs=pairs, force=force)


def tuned_gather_scatter_geometry(
    num_nodes: int, num_edges: int, channels: int, dtype
) -> tuple[int, int] | None:
    """Wrapper hook (``gather_scatter_sum``): the cached geometry for this
    shape, or None to keep the default. Only returned when the default-
    geometry collate certificate provably transfers (``gs_cert_compatible``)
    — the wrapper passes it straight through ``cert_geometry=`` and keeps
    its static, cond-free program."""
    if not enabled():
        return None
    rec = lookup("fused_scatter",
                 gs_signature(num_nodes, num_edges, channels, dtype))
    if rec is None:
        return None
    from .fused_scatter import GS_CERT_BLOCK, GS_CERT_WINDOW

    geom = rec.get("geometry")
    if not isinstance(geom, (list, tuple)) or len(geom) != 2:
        return None
    window, block_edges = int(geom[0]), int(geom[1])
    if (window, block_edges) == (GS_CERT_WINDOW, GS_CERT_BLOCK):
        return None  # the default; nothing to override
    if not gs_cert_compatible(window, block_edges, num_nodes):
        return None
    return window, block_edges


# ---------------------------------------------------------------------------
# quant_matmul: the row-block axis
# ---------------------------------------------------------------------------

QM_ROW_BLOCKS = (8, 16, 32)


def qm_signature(m: int, k: int, n: int) -> str:
    return shape_signature(m=int(m), k=int(k), n=int(n))


def qm_static_candidates(m: int, k: int, n: int) -> list[int]:
    """Row blocks the quant kernel's own VMEM/shape rules admit (mirrors
    ``quant_matmul.quant_dense``'s eligibility per row block)."""
    from .quant_matmul import _VMEM_LIMIT

    out = []
    for rb in QM_ROW_BLOCKS:
        if m < rb:
            continue
        if (k * n + rb * (k + 2 * n)) * 4 > _VMEM_LIMIT:
            continue
        out.append(rb)
    return out


def autotune_quant_dense(
    x: Array, w_q: Array, s_w: Array, s_x: float,
    bias: Array | None = None, *, reps: int = 8, pairs: int = 4,
    force: bool = False, interpret: bool | None = None,
) -> dict:
    """Sweep the int8 dense kernel's row block per activation shape. The
    quant kernel has no layout certificate (dense rows are layout-free), so
    every statically-admissible row block is timeable."""
    from .quant_matmul import _ROW_BLOCK, quant_dense

    m, k = int(x.shape[0]), int(x.shape[1])
    n = int(w_q.shape[1])
    sig = qm_signature(m, k, n)
    default = _ROW_BLOCK
    candidates = qm_static_candidates(m, k, n)
    if default not in candidates:
        rec = record("quant_matmul", sig, default,
                     {"default": str(default), "candidates": [],
                      "note": "kernel statically ineligible at this shape; "
                              "XLA route only"})
        return {**rec, "cache": "miss", "swept": False, "sweep_s": 0.0}

    def build(rb):
        def make():
            fn = jax.jit(
                lambda x_, _rb=rb: quant_dense(
                    x_, w_q, s_w, s_x, bias, kernel=True, interpret=interpret,
                    row_block=_rb,
                )
            )
            return fn, (x,)

        return make

    return sweep("quant_matmul", sig, {rb: build(rb) for rb in candidates},
                 default, reps=reps, pairs=pairs, force=force)


def tuned_quant_row_block(m: int, k: int, n: int) -> int | None:
    """Wrapper hook (``quant_dense``): cached row block for this activation
    shape, or None for the default. Dense rows carry no layout certificate,
    so the only refusals are stale/corrupt records (non-multiples of the
    base block, blocks the shape's own eligibility rules reject)."""
    if not enabled():
        return None
    from .quant_matmul import _ROW_BLOCK

    rec = lookup("quant_matmul", qm_signature(m, k, n))
    if rec is None:
        return None
    try:
        rb = int(rec.get("geometry"))
    except (TypeError, ValueError):
        return None
    if rb == _ROW_BLOCK or rb < _ROW_BLOCK or rb % _ROW_BLOCK:
        return None
    if rb not in qm_static_candidates(m, k, n):
        return None
    return rb


# ---------------------------------------------------------------------------
# fused_softmax / fused_cell_list: cert-pinned axes
# ---------------------------------------------------------------------------


def autotune_softmax(num_segments: int, heads: int) -> dict:
    """The segment-softmax geometry axis after its cert rules: pinned to the
    singleton ``(SM_CERT_WINDOW, SM_CERT_BLOCK)``. GAT's appended self-loop
    arange is block-aligned by ``self_loop_pad`` at exactly ``SM_CERT_BLOCK``
    and spans exactly one window per block, so any other blocking breaks the
    collate certificate, and the window must equal the block to cover the
    arange section — the filter leaves nothing to time, which the record
    states explicitly rather than timing an empty sweep."""
    from .fused_softmax import SM_CERT_BLOCK, SM_CERT_WINDOW

    sig = shape_signature(n=int(num_segments), h=int(heads))
    default = (SM_CERT_WINDOW, SM_CERT_BLOCK)
    cached = lookup("fused_softmax", sig)
    if cached is not None:
        return {**cached, "cache": "hit", "swept": False, "sweep_s": 0.0}
    rec = record("fused_softmax", sig, default, {
        "default": str(default), "candidates": [str(default)],
        "pinned_by": "cert rules: self_loop_pad aligns the GAT self-loop "
                     "arange to SM_CERT_BLOCK and the window must cover a "
                     "full arange block (window == block)",
    })
    return {**rec, "cache": "miss", "swept": False, "sweep_s": 0.0}


def cl_signature(n_atoms: int, n_cells: int, capacity: int) -> str:
    return shape_signature(n=int(n_atoms), cells=int(n_cells),
                           cap=int(capacity))


def cl_static_candidates(n_atoms: int, n_cells: int, capacity: int) -> list[int]:
    """Cell-list window candidates: the minimal 8-aligned capacity window
    plus wider alignments, filtered by the kernel's own static rules. The
    in-kernel exact membership check makes ANY window >= cell_window(cap)
    correct; wider windows trade VMEM/FLOPs for nothing, which the sweep is
    free to prove."""
    from .fused_cell_list import _static_ok, cell_window

    base = cell_window(capacity)
    return [w for w in (base, base + 8, base + 16)
            if _static_ok(n_atoms, n_cells, w)]


def autotune_cell_list(
    pos: Array, cutoff: float, max_edges: int, cell, pbc,
    grid: tuple[int, int, int], capacity: int, *, reps: int = 4,
    pairs: int = 2, force: bool = False, interpret: bool | None = None,
) -> dict:
    """Sweep the cell-list kernel's window width (alignment slack above the
    exact-membership minimum) per (atoms, cells, capacity) shape."""
    from .fused_cell_list import cell_window, fused_binned_radius_graph

    n = int(pos.shape[0])
    gx, gy, gz = (int(g) for g in grid)
    n_cells = gx * gy * gz
    sig = cl_signature(n, n_cells, capacity)
    default = cell_window(int(capacity))
    candidates = cl_static_candidates(n, n_cells, int(capacity))
    if default not in candidates:
        rec = record("fused_cell_list", sig, default,
                     {"default": str(default), "candidates": [],
                      "note": "kernel statically ineligible at this shape; "
                              "XLA route only"})
        return {**rec, "cache": "miss", "swept": False, "sweep_s": 0.0}

    def build(w):
        def make():
            # time the FULL build (mask kernel + decode epilogue): the
            # epilogue's nonzero/decode cost grows with the window, and a
            # truncated program that dead-code-eliminates it would bias
            # the sweep toward wide windows production then pays for
            fn = jax.jit(
                lambda p, _w=w: fused_binned_radius_graph(
                    p, cutoff, max_edges, cell, pbc, grid, capacity,
                    interpret=interpret, window=_w,
                )
            )
            return fn, (pos,)

        return make

    return sweep("fused_cell_list", sig, {w: build(w) for w in candidates},
                 default, reps=reps, pairs=pairs, force=force)


def tuned_cell_list_window(n_atoms: int, n_cells: int, capacity: int) -> int | None:
    """Wrapper hook (``fused_binned_radius_graph``): cached window for this
    shape, or None for the capacity-derived default. Any cached window below
    the exact-membership minimum is ignored (stale-cache guard)."""
    if not enabled():
        return None
    from .fused_cell_list import cell_window

    rec = lookup("fused_cell_list", cl_signature(n_atoms, n_cells, capacity))
    if rec is None:
        return None
    try:
        w = int(rec.get("geometry"))
    except (TypeError, ValueError):
        return None
    base = cell_window(int(capacity))
    if w < base or w % 8 or w == base:
        return None
    return w


__all__ = [
    "autotune_cell_list",
    "autotune_gather_scatter",
    "autotune_quant_dense",
    "autotune_softmax",
    "cache_path",
    "enabled",
    "gs_cert_compatible",
    "gs_static_candidates",
    "lookup",
    "record",
    "reset_cache",
    "shape_signature",
    "sweep",
    "sweeps_run",
    "tuned_cell_list_window",
    "tuned_gather_scatter_geometry",
    "tuned_quant_row_block",
]
