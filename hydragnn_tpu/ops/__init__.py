"""TPU Pallas kernel library for the message-passing / MD / serving hot
paths. One playbook per kernel (see ``fused_scatter``): receiver-sorted
windows + scalar prefetch, collate-certified geometry where a layout
contract exists, an in-program (or static) XLA fallback, and
``interpret=True`` CPU testability behind a ``HYDRAGNN_*`` A/B flag."""

from .autotune import (  # noqa: F401
    autotune_cell_list,
    autotune_gather_scatter,
    autotune_quant_dense,
    autotune_softmax,
)
from .fp8_matmul import certify_fp8_dense, fp8_dense  # noqa: F401
from .fused_cell_list import fused_binned_radius_graph  # noqa: F401
from .fused_scatter import fused_gather_scatter, gather_scatter_sum  # noqa: F401
from .fused_softmax import (  # noqa: F401
    fused_masked_softmax,
    fused_segment_softmax,
)
from .quant_matmul import quant_dense, quantize_weight  # noqa: F401

__all__ = [
    "autotune_cell_list",
    "autotune_gather_scatter",
    "autotune_quant_dense",
    "autotune_softmax",
    "certify_fp8_dense",
    "fp8_dense",
    "fused_binned_radius_graph",
    "fused_gather_scatter",
    "fused_masked_softmax",
    "fused_segment_softmax",
    "gather_scatter_sum",
    "quant_dense",
    "quantize_weight",
]
