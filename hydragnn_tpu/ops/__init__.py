"""TPU Pallas kernels for the message-passing hot path."""

from .fused_scatter import fused_gather_scatter, gather_scatter_sum

__all__ = ["fused_gather_scatter", "gather_scatter_sum"]
