"""Int8 dense kernel for serving-tier inference quantization.

The serving tier's quantized predict step (``serve.quant``) replaces every
calibrated ``nn.Dense`` with

    y = (q(x / s_x) · W_q) · (s_x ⊗ s_w) + b

where ``W_q`` is the weight matrix symmetric-quantized per OUTPUT channel at
registration time and ``s_x`` is the layer's per-(model, bucket) activation
scale collected from calibration traffic during ``warmup()``. The XLA
expression materializes the int8 activation tensor in HBM between the
quantize and the matmul; this kernel fuses quantize → int8×int8 MXU matmul
(int32 accumulate) → dequantize + bias into one pass, so the only HBM
traffic is fp32 activations in, int8 weights in (4× fewer weight bytes than
fp32 — the memory-bound serving win), fp32 activations out.

Both routes compute the same quantization arithmetic (same rounding, same
clip, same int32 accumulation — the int8 products are exact in either, so
they differ only by ~1-ulp dequant/bias FMA fusion); the kernel is an
execution strategy, not a numerics change, and the per-head error bounds
the serving tier certifies at calibration time hold for either route.
Static fallback (odd shapes, VMEM budget, no Pallas backend) takes the XLA
expression.

A/B: the serving quant path as a whole rides ``HYDRAGNN_SERVE_QUANT`` /
``Serving.quantize``; this module's ``kernel=`` argument (auto: TPU only,
``interpret=True`` testable anywhere) picks the execution route.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

Array = jax.Array

_ROW_BLOCK = 8
_VMEM_LIMIT = 8 * 1024 * 1024


def quantize_weight(w: Array) -> tuple[Array, Array]:
    """Symmetric per-output-channel int8 weight quantization:
    ``(w_q int8 [K, N], s_w fp32 [N])`` with ``w ≈ w_q · s_w``."""
    absmax = jnp.max(jnp.abs(w), axis=0)
    s_w = jnp.maximum(absmax, 1e-12) / 127.0
    w_q = jnp.clip(jnp.round(w / s_w[None, :]), -127, 127).astype(jnp.int8)
    return w_q, s_w.astype(jnp.float32)


def _quantize_acts(x: Array, s_x: float) -> Array:
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / s_x), -127, 127
    ).astype(jnp.int8)


def reference_quant_dense(
    x: Array, w_q: Array, s_w: Array, s_x: float, bias: Array | None
) -> Array:
    """The XLA route — the single definition of the quantization arithmetic
    (the kernel below must match it exactly; tests pin this)."""
    x_q = _quantize_acts(x, s_x)
    acc = jax.lax.dot_general(
        x_q, w_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * (s_x * s_w)[None, :]
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y


def _quant_kernel(x_ref, wq_ref, sw_ref, b_ref, o_ref, *, s_x: float):
    # the ONE quantization expression (shared with the XLA route): the
    # serving error certification relies on both routes rounding alike
    x_q = _quantize_acts(x_ref[...], s_x)
    acc = jax.lax.dot_general(
        x_q, wq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * (s_x * sw_ref[0, :])[None, :]
    o_ref[...] = y + b_ref[0, :][None, :]


def quant_dense(
    x: Array,
    w_q: Array,
    s_w: Array,
    s_x: float,
    bias: Array | None = None,
    kernel: bool | None = None,
    interpret: bool | None = None,
    row_block: int | None = None,
) -> Array:
    """Quantized dense layer ``[M, K] × int8 [K, N] → fp32 [M, N]`` with the
    activation scale ``s_x`` baked as a compile-time constant (one executable
    per (model, bucket) — exactly the serving tier's AOT table shape).

    ``row_block`` is the kernel's only free geometry (rows per grid step,
    multiple of 8; default 8) — the axis the shared autotuner
    (``ops/autotune.py``) sweeps. Dense rows carry no layout contract, so
    any admissible block is exact; eligibility (VMEM, row count) is checked
    at the REQUESTED block. When ``row_block`` is None and
    ``HYDRAGNN_OPS_AUTOTUNE`` is set, a cached per-shape choice from the
    shared autotuner replaces the default (one dict read at trace time)."""
    if kernel is None:
        kernel = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if row_block is None:
        from .autotune import tuned_quant_row_block

        row_block = tuned_quant_row_block(x.shape[0], x.shape[1], w_q.shape[1])
    rb = _ROW_BLOCK if row_block is None else int(row_block)
    if rb < _ROW_BLOCK or rb % _ROW_BLOCK:
        raise ValueError(f"row_block must be a positive multiple of "
                         f"{_ROW_BLOCK}, got {rb}")
    s_x = float(s_x)
    m, k = x.shape
    n = w_q.shape[1]
    eligible = (
        kernel
        and pltpu is not None
        and m >= rb
        and (k * n + rb * (k + 2 * n)) * 4 <= _VMEM_LIMIT
        and jnp.issubdtype(x.dtype, jnp.floating)
    )
    if not eligible:
        return reference_quant_dense(x, w_q, s_w, s_x, bias)
    b = (bias if bias is not None else jnp.zeros((n,), jnp.float32))
    m_pad = -m % rb
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
    g = x.shape[0] // rb
    out = pl.pallas_call(
        functools.partial(_quant_kernel, s_x=s_x),
        grid=(g,),
        in_specs=[
            pl.BlockSpec((rb, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),  # weights resident
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rb, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], n), jnp.float32),
        interpret=interpret,
    )(x, w_q, s_w.astype(jnp.float32).reshape(1, n),
      b.astype(jnp.float32).reshape(1, n))
    return out[:m] if m_pad else out


__all__ = ["quant_dense", "quantize_weight", "reference_quant_dense"]
