"""MFC conv stack (reference ``hydragnn/models/MFCStack.py:21-53``, PyG
``MFConv`` — the molecular fingerprint conv of Duvenaud et al.):
h_i' = W_root^{(deg_i)} x_i + W_nbr^{(deg_i)} sum_j x_j
with a separate weight pair per node degree, clamped at ``max_neighbours``.

TPU design: weight banks [max_deg+1, in, out] gathered by clamped degree and
applied as one batched einsum instead of PyG's per-degree index_select loop.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv


@register_conv("MFC")
class MFCConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        hidden = self.out_dim or self.spec.hidden_dim
        max_deg = int(self.spec.max_neighbours or 10)
        N = batch.num_nodes
        in_dim = inv.shape[-1]

        from ..ops import gather_scatter_sum

        agg = gather_scatter_sum(
            inv, batch.senders, batch.receivers, N,
            weight=batch.edge_mask.astype(inv.dtype), hints=batch,
        )
        deg = segment.segment_sum(batch.edge_mask, batch.receivers, N)
        deg_idx = jnp.clip(deg.astype(jnp.int32), 0, max_deg)

        w_root = self.param(
            "w_root", nn.initializers.lecun_normal(), (max_deg + 1, in_dim, hidden)
        )
        w_nbr = self.param(
            "w_nbr", nn.initializers.lecun_normal(), (max_deg + 1, in_dim, hidden)
        )
        b = self.param("bias", nn.initializers.zeros, (max_deg + 1, hidden))

        out = (
            jnp.einsum("ni,nio->no", inv, w_root[deg_idx])
            + jnp.einsum("ni,nio->no", agg, w_nbr[deg_idx])
            + b[deg_idx]
        )
        return out, equiv
