"""MLIP: energy-conserving interatomic potentials — forces via ``jax.grad``.

Reference: the ``EnhancedModelWrapper`` composition (``hydragnn/models/
create.py:590-758``). There, forces require ``data.pos.requires_grad``, an
inner ``torch.autograd.grad(energy, pos, create_graph=True)`` and an FSDP2
double-backward workaround (``train_validate_test.py:150-169, 722-754``).

Here the model's energy is a *pure function* of positions, so forces are one
``jax.grad`` and the outer parameter gradient is grad-of-grad — no workaround,
no mutable flags; the whole energy+force loss compiles into the same XLA
program as everything else. This is the architectural win of the functional
design.

Loss composition (``energy_force_loss``, reference ``create.py:626-738``):
    L = w_E * loss(E, E_true) + w_Ea * loss(E/n_atoms, E_true/n_atoms)
        + w_F * loss(F, F_true),   F = -dE/dpos
with per-task losses reported as [energy, energy_per_atom, force].

Constraints kept from the reference: exactly one output head (``:646-648``);
graph-type heads require sum pooling (``:659-662``); node-type heads are
summed into a graph energy (``:654-658``).
"""

from __future__ import annotations

import functools

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import HydraModel
from .common import get_loss


def validate_mlip_spec(spec: ModelSpec) -> None:
    if spec.num_heads != 1:
        raise ValueError("Force predictions require exactly one head (create.py:646-648)")
    if spec.activation in ("relu", "lrelu_01", "lrelu_025", "lrelu_05"):
        import warnings

        warnings.warn(
            "Force training with piecewise-linear activations (relu/leaky-relu) "
            "learns poorly: forces are energy gradients, and dE/dr is "
            "piecewise-constant under relu. Use 'silu', 'tanh', or 'gelu' "
            "(set NeuralNetwork.Architecture.activation_function)."
        )
    if spec.output_type[0] == "graph" and spec.graph_pooling not in ("add", "sum"):
        raise ValueError(
            "Graph head force loss requires sum pooling (graph_pooling='add')"
        )
    if (
        spec.energy_weight <= 0
        and spec.energy_peratom_weight <= 0
        and spec.force_weight <= 0
    ):
        raise ValueError(
            "All interatomic potential loss weights are zero; set at least one of "
            "energy_weight, energy_peratom_weight, or force_weight"
        )


def make_graph_energy_fn(model: HydraModel):
    """(variables, pos, batch) -> per-graph energies [G] (padding graphs 0)."""
    spec = model.spec

    def energy_fn(variables, pos, batch: GraphBatch, train: bool = False):
        b = batch.replace(pos=pos)
        pred = model.apply(variables, b, train=train)
        if spec.var_output:
            pred = pred[0]
        if spec.output_type[0] == "node":
            node_e = pred[0] * b.node_mask[:, None]
            graph_e = segment.segment_sum(node_e[:, 0], b.batch, b.num_graphs)
        else:
            graph_e = pred[0][:, 0]
        return graph_e * batch.graph_mask

    return energy_fn


def make_energy_and_forces(model: HydraModel):
    """(variables, batch) -> (graph_energy [G], forces [N, 3]).

    forces = -dE/dpos with E = sum of per-graph energies; every atom belongs
    to exactly one graph so the summed gradient is the per-atom force.
    """
    energy_fn = make_graph_energy_fn(model)

    def energy_and_forces(variables, batch: GraphBatch, train: bool = False):
        def total_energy(pos):
            e = energy_fn(variables, pos, batch, train)
            return e.sum(), e

        (_, graph_e), grad_pos = jax.value_and_grad(total_energy, has_aux=True)(
            batch.pos
        )
        forces = -grad_pos * batch.node_mask[:, None]
        return graph_e, forces

    return energy_and_forces


def energy_force_loss(spec: ModelSpec, graph_e, forces, batch: GraphBatch):
    """Returns (total loss, [energy, energy_per_atom, force] task losses)."""
    loss_fn = get_loss(spec.loss_type)
    gmask = batch.graph_mask
    e_true = batch.energy_y[:, 0]

    e_loss = loss_fn(graph_e[:, None], e_true[:, None], gmask)
    natoms = jnp.maximum(batch.n_node.astype(graph_e.dtype), 1.0)
    ea_loss = loss_fn(
        (graph_e / natoms)[:, None], (e_true / natoms)[:, None], gmask
    )
    f_loss = loss_fn(forces, batch.forces_y, batch.node_mask)

    tot = (
        spec.energy_weight * e_loss
        + spec.energy_peratom_weight * ea_loss
        + spec.force_weight * f_loss
    )
    return tot, [e_loss, ea_loss, f_loss]


def make_mlip_train_step(model: HydraModel, optimizer, compute_dtype=jnp.float32,
                         loss_scale=None):
    """Jitted MLIP train step: outer grad over (inner force grad + losses).

    ``loss_scale`` as in ``train.step._make_step_impl`` (static fp16-class
    scaling; None/1 keeps the historical program byte-for-byte). Only the
    OUTER (param) objective is scaled — the inner position grad must stay in
    physical units because the forces it produces feed the loss itself."""
    from ..train.step import TrainState, _cast_floats

    spec = model.spec
    validate_mlip_spec(spec)
    energy_fn = make_graph_energy_fn(model)
    loss_scale = None if not loss_scale or float(loss_scale) == 1.0 else float(loss_scale)

    def loss_fn(params, batch_stats, batch: GraphBatch, dropout_rng):
        c_params = _cast_floats(params, compute_dtype)

        def compute(c_batch, b_raw, rng):
            def total_energy(pos):
                # train-mode forward (dropout + batch-stat updates, matching
                # the reference's autocast train forward); the SAME dropout
                # mask is shared by the energy and its position-gradient
                b = c_batch.replace(pos=pos)
                pred, updates = model.apply(
                    {"params": c_params, "batch_stats": batch_stats},
                    b,
                    train=True,
                    mutable=["batch_stats"],
                    rngs={"dropout": rng},
                )
                if spec.var_output:
                    pred = pred[0]
                if spec.output_type[0] == "node":
                    node_e = pred[0] * b.node_mask[:, None]
                    graph_e = segment.segment_sum(node_e[:, 0], b.batch, b.num_graphs)
                else:
                    graph_e = pred[0][:, 0]
                graph_e = (graph_e * b_raw.graph_mask).astype(jnp.float32)
                return graph_e.sum(), (graph_e, updates["batch_stats"])

            (_, (graph_e, new_stats)), grad_pos = jax.value_and_grad(
                total_energy, has_aux=True
            )(c_batch.pos)
            forces = (-grad_pos * b_raw.node_mask[:, None]).astype(jnp.float32)
            tot, tasks = energy_force_loss(spec, graph_e, forces, b_raw)
            return tot, jnp.stack(tasks), new_stats

        if spec.sync_batch_norm:
            # size-1 vmap binds the sync axis (pmean = identity) so
            # SyncBatchNorm configs run unchanged on one device
            from .common import SYNC_BN_AXIS

            tot, tasks, new_stats = jax.vmap(compute, axis_name=SYNC_BN_AXIS)(
                jax.tree.map(lambda x: x[None], _cast_floats(batch, compute_dtype)),
                jax.tree.map(lambda x: x[None], batch),
                dropout_rng[None],
            )
            tot = tot[0]
            tasks = tasks[0]
            new_stats = jax.tree.map(lambda x: x[0], new_stats)
        else:
            tot, tasks, new_stats = compute(
                _cast_floats(batch, compute_dtype), batch, dropout_rng
            )
        if loss_scale is not None:
            # differentiate the scaled loss; the unscaled one rides out via
            # aux so metrics never see the scale
            return tot * loss_scale, (tot, tasks, new_stats)
        return tot, (tasks, new_stats)

    from ..train.step import donate_state_argnums

    @functools.partial(jax.jit, donate_argnums=donate_state_argnums())
    def train_step(state: TrainState, batch: GraphBatch):
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0), state.step)
        (tot, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.batch_stats, batch, dropout_rng
        )
        from ..train.step import freeze_conv_grads

        grads = _cast_floats(grads, jnp.float32)
        if loss_scale is not None:
            # un-scale AFTER the fp32 cast (2^k scales divide back exactly)
            tot, tasks, new_stats = aux
            grads = jax.tree.map(lambda g: g / loss_scale, grads)
        else:
            tasks, new_stats = aux
        grads = freeze_conv_grads(grads, spec)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        return new_state, {
            "loss": tot,
            "tasks_loss": jnp.asarray(tasks),
            "num_graphs": batch.graph_mask.sum(),
        }

    return train_step


def make_mlip_eval_step(model: HydraModel, compute_dtype=jnp.float32):
    from ..train.step import TrainState, _cast_floats

    spec = model.spec
    energy_and_forces = make_energy_and_forces(model)

    @jax.jit
    def eval_step(state: TrainState, batch: GraphBatch):
        c_params = _cast_floats(state.params, compute_dtype)
        c_batch = _cast_floats(batch, compute_dtype)
        variables = {"params": c_params, "batch_stats": state.batch_stats}
        graph_e, forces = energy_and_forces(variables, c_batch, False)
        graph_e = graph_e.astype(jnp.float32)
        forces = forces.astype(jnp.float32)
        tot, tasks = energy_force_loss(spec, graph_e, forces, batch)

        # RMSE accumulators: [energy, force]
        gm = batch.graph_mask
        e_sse = (((graph_e - batch.energy_y[:, 0]) ** 2) * gm).sum()
        e_cnt = gm.sum()
        f_sse = (((forces - batch.forces_y) ** 2) * batch.node_mask[:, None]).sum()
        f_cnt = batch.node_mask.sum() * 3
        return {
            "loss": tot,
            "tasks_loss": jnp.stack(tasks),
            "head_sse": jnp.stack([e_sse, f_sse]),
            "head_count": jnp.stack([e_cnt, f_cnt]),
            "num_graphs": batch.graph_mask.sum(),
        }

    return eval_step
