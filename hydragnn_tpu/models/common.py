"""Shared model components: activations, losses, MLPs, masked batch norm.

Mirrors reference ``hydragnn/utils/model/model.py:30-61`` (activation / loss
selection) with jax-native implementations, plus the padding-aware BatchNorm
that the TPU build needs (the reference uses plain ``BatchNorm1d`` because its
batches are ragged-but-exact; ours carry padded node slots that must not
contaminate the statistics).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array

_ACTIVATIONS: dict[str, Callable[[Array], Array]] = {
    "relu": nn.relu,
    "selu": nn.selu,
    "prelu": lambda x: jnp.where(x >= 0, x, 0.25 * x),  # torch PReLU init slope
    "elu": nn.elu,
    "lrelu_01": lambda x: nn.leaky_relu(x, negative_slope=0.1),
    "lrelu_025": lambda x: nn.leaky_relu(x, negative_slope=0.25),
    "lrelu_05": lambda x: nn.leaky_relu(x, negative_slope=0.5),
    "sigmoid": nn.sigmoid,
    "gelu": nn.gelu,
    "tanh": nn.tanh,
    "silu": nn.silu,
}


def get_activation(name: str) -> Callable[[Array], Array]:
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation '{name}'; supported: {sorted(_ACTIVATIONS)}"
        )


def _masked_mean(terms: Array, mask: Array, per_row: int,
                 axis_name: str | None = None) -> Array:
    """sum(terms) / (real rows x row width), with numerator AND denominator
    optionally psum'd over a mapped mesh axis first. That makes every masked
    loss exact over a row set PARTITIONED across devices (the halo-exchange
    route: each device holds only its owned nodes) — a mean of per-device
    means would weight devices, not rows. Rows replicated on every device
    (graph-level targets) scale numerator and denominator by the same device
    count, so the psum'd ratio is unchanged there too."""
    s = terms.sum()
    n = mask.sum() * per_row
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
        n = jax.lax.psum(n, axis_name)
    return s / jnp.maximum(n, 1.0)


def masked_mse(pred: Array, target: Array, mask: Array,
               axis_name: str | None = None) -> Array:
    """Mean squared error over real (mask=1) rows only."""
    mask = mask.reshape(mask.shape[0], *([1] * (pred.ndim - 1)))
    se = (pred - target) ** 2 * mask
    return _masked_mean(se, mask, pred.shape[-1], axis_name)


def masked_mae(pred: Array, target: Array, mask: Array,
               axis_name: str | None = None) -> Array:
    mask = mask.reshape(mask.shape[0], *([1] * (pred.ndim - 1)))
    ae = jnp.abs(pred - target) * mask
    return _masked_mean(ae, mask, pred.shape[-1], axis_name)


def masked_rmse(pred: Array, target: Array, mask: Array,
                axis_name: str | None = None) -> Array:
    # sqrt OUTSIDE the (cross-device) mean: the global mse then one sqrt —
    # a psum of per-device rmse values would not be any rmse
    return jnp.sqrt(masked_mse(pred, target, mask, axis_name) + 1e-16)


def masked_smooth_l1(pred: Array, target: Array, mask: Array,
                     axis_name: str | None = None) -> Array:
    """torch SmoothL1Loss (beta=1): 0.5 d^2 for |d|<1 else |d|-0.5, mean over
    real rows (reference loss_function_selection, model.py:54-55)."""
    mask = mask.reshape(mask.shape[0], *([1] * (pred.ndim - 1)))
    d = jnp.abs(pred - target)
    huber = jnp.where(d < 1.0, 0.5 * d**2, d - 0.5) * mask
    return _masked_mean(huber, mask, pred.shape[-1], axis_name)


def masked_gaussian_nll(pred: Array, target: Array, mask: Array, var: Array,
                        axis_name: str | None = None) -> Array:
    """torch.nn.GaussianNLLLoss semantics: 0.5*(log(var) + (x-mu)^2/var),
    var clamped below at eps, mean reduction over real rows."""
    eps = 1e-6
    var = jnp.maximum(var, eps)
    mask = mask.reshape(mask.shape[0], *([1] * (pred.ndim - 1)))
    nll = 0.5 * (jnp.log(var) + (pred - target) ** 2 / var) * mask
    return _masked_mean(nll, mask, pred.shape[-1], axis_name)


_LOSSES = {
    "mse": masked_mse,
    "mae": masked_mae,
    "rmse": masked_rmse,
    "smooth_l1": masked_smooth_l1,
}


def get_loss(name: str):
    if name == "GaussianNLLLoss":
        return masked_gaussian_nll
    try:
        return _LOSSES[name]
    except KeyError:
        raise ValueError(f"Unknown loss '{name}'; supported: {sorted(_LOSSES)} or GaussianNLLLoss")


class MLP(nn.Module):
    """Dense stack with activation between layers (last layer linear unless
    ``act_last``)."""

    features: Sequence[int]
    activation: str = "relu"
    act_last: bool = False

    @nn.compact
    def __call__(self, x: Array) -> Array:
        act = get_activation(self.activation)
        n = len(self.features)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, name=f"dense_{i}")(x)
            if i < n - 1 or self.act_last:
                x = act(x)
        return x


# the vmap/shard axis SPMD steps bind for cross-device stat syncing
SYNC_BN_AXIS = "sync_bn"


class MaskedBatchNorm(nn.Module):
    """BatchNorm over valid rows only (padding excluded from statistics).

    Functional equivalent of the per-layer ``BatchNorm(hidden_dim)`` feature
    layers in reference ``Base.py:446-463``; running stats live in the
    ``batch_stats`` collection like flax's own BatchNorm. On multi-device
    meshes, stats are synced across the ``axis_name`` axis when provided —
    the analog of the reference's optional SyncBatchNorm
    (``distributed.py:414-416``).
    """

    momentum: float = 0.9  # torch BatchNorm1d default (1 - torch's 0.1)
    epsilon: float = 1e-5
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x: Array, mask: Array, train: bool = False) -> Array:
        features = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (features,))
        bias = self.param("bias", nn.initializers.zeros, (features,))

        if train:
            m = mask.reshape(-1, 1).astype(x.dtype)
            # count-weighted sums (not per-replica means): SyncBN then psums
            # raw sums, giving the EXACT union-batch statistics regardless of
            # per-replica counts — and an ALL-masked replica (a fill batch
            # padding a partial device group) contributes zero weight
            # instead of dragging the stats toward 0
            msum = m.sum()
            s1 = (x * m).sum(axis=0)
            if self.axis_name is not None:
                msum = jax.lax.psum(msum, self.axis_name)
                s1 = jax.lax.psum(s1, self.axis_name)
            count = jnp.maximum(msum, 1.0)
            mean = s1 / count
            # second pass centered on the (global) mean: two-pass numerics,
            # and under SyncBN the psum'd centered sums give the EXACT
            # union-batch variance (not the mean of per-replica variances)
            cv = (((x - mean) ** 2) * m).sum(axis=0)
            if self.axis_name is not None:
                cv = jax.lax.psum(cv, self.axis_name)
            var = cv / count
            if not self.is_initializing():
                # EMA gated on real rows: a zero-count batch keeps the old
                # running stats bit-identical (no decay toward 0)
                alpha = (1.0 - self.momentum) * (msum > 0)
                ra_mean.value = ra_mean.value + alpha * (mean - ra_mean.value)
                ra_var.value = ra_var.value + alpha * (var - ra_var.value)
            # FORWARD for a zero-count batch (an all-masked fill replica
            # without SyncBN) uses the running stats: normalizing by
            # mean=0/var=0 would amplify donor features ~1/sqrt(eps) per
            # layer, overflowing deep stacks to inf — and inf * mask(0) is
            # NaN in the loss, poisoning the whole device group's gradients
            mean = jnp.where(msum > 0, mean, ra_mean.value)
            var = jnp.where(msum > 0, var, ra_var.value)
        else:
            mean, var = ra_mean.value, ra_var.value

        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * scale + bias


def local_node_index(batch_ids: Array, n_node: Array, num_nodes: int) -> Array:
    """Position of each node within its own graph (0-based) — needed by the
    ``mlp_per_node`` head type (reference ``MLPNode``, ``Base.py:912-982``).

    Works because collate packs each graph's nodes contiguously.
    """
    offsets = jnp.concatenate([jnp.zeros((1,), n_node.dtype), jnp.cumsum(n_node)[:-1]])
    return jnp.arange(num_nodes, dtype=batch_ids.dtype) - offsets[batch_ids]


def equivariant_coordinate_update(
    edge_feat: Array,
    coord_diff: Array,
    senders: Array,
    edge_mask: Array,
    num_nodes: int,
    hidden: int,
    tanh_bound: bool,
    name_prefix: str = "coord",
    hints=None,
) -> Array:
    """Shared E(3) coordinate-update block used by EGNN and SchNet
    (reference ``E_GCL.coord_model`` / ``CFConv.coord_model``): per-edge scalar
    gate MLP (final layer xavier_uniform gain=0.001 == variance_scaling 1e-6),
    optional tanh bound, +/-100 clip, padding mask, sender-mean aggregation.
    Returns the per-node position delta [N, 3].
    """
    from ..graphs import segment

    # must be called from inside a @nn.compact __call__ — the Dense layers
    # attach to the calling module's scope
    gate = nn.Dense(hidden, name=f"{name_prefix}_mlp_0")(edge_feat)
    gate = nn.relu(gate)
    gate = nn.Dense(
        1,
        use_bias=False,
        kernel_init=nn.initializers.variance_scaling(1e-6, "fan_avg", "uniform"),
        name=f"{name_prefix}_mlp_out",
    )(gate)
    if tanh_bound:
        gate = jnp.tanh(gate)
    trans = jnp.clip(coord_diff * gate, -100.0, 100.0) * edge_mask[:, None]
    agg = segment.segment_sum(trans, senders, num_nodes, hints)
    cnt = segment.segment_sum(edge_mask, senders, num_nodes)
    return agg / jnp.maximum(cnt, 1.0)[:, None]
