"""Radial basis functions + cutoffs — shared by SchNet/PNAPlus/DimeNet/PaiNN/
PNAEq/MACE.

Reference counterparts: PyG ``GaussianSmearing``/``BesselBasisLayer`` (used by
``SCFStack``/``PNAPlusStack``/``DIMEStack``) and
``hydragnn/utils/model/mace_utils/modules/radial.py`` (Bessel / Chebyshev
bases, ``PolynomialCutoff``). All are pure elementwise functions of the edge
length — XLA fuses them into the surrounding message computation.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

Array = jax.Array


class GaussianSmearing(nn.Module):
    """Distances -> Gaussian RBF grid on [start, stop] (SchNet's expansion)."""

    start: float = 0.0
    stop: float = 5.0
    num_gaussians: int = 50

    @nn.compact
    def __call__(self, dist: Array) -> Array:
        offset = jnp.linspace(self.start, self.stop, self.num_gaussians)
        coeff = -0.5 / (offset[1] - offset[0]) ** 2 if self.num_gaussians > 1 else -0.5
        d = dist.reshape(-1, 1) - offset.reshape(1, -1)
        return jnp.exp(coeff * d**2)


def polynomial_envelope(x: Array, exponent: int) -> Array:
    """DimeNet smooth envelope u(x) on x = d/cutoff in [0, 1]:
    1/x + a x^p + b x^(p+1) + c x^(p+2) with u(1)=u'(1)=u''(1)=0
    (multiplied by x here so callers get the d-space form sin-basis needs)."""
    p = exponent + 1
    a = -(p + 1) * (p + 2) / 2.0
    b = p * (p + 2)
    c = -p * (p + 1) / 2.0
    env = 1.0 / jnp.where(x == 0, 1.0, x) + a * x ** (p - 1) + b * x**p + c * x ** (p + 1)
    return jnp.where(x < 1.0, env, jnp.zeros_like(env))


class BesselBasis(nn.Module):
    """DimeNet Bessel radial basis with polynomial envelope (PyG
    ``BesselBasisLayer``; also MACE's ``BesselBasis``). Frequencies are
    trainable, initialized at n*pi."""

    num_radial: int = 6
    cutoff: float = 5.0
    envelope_exponent: int = 5

    @nn.compact
    def __call__(self, dist: Array) -> Array:
        freq = self.param(
            "freq",
            lambda key: jnp.arange(1, self.num_radial + 1, dtype=jnp.float32) * math.pi,
        )
        d = dist.reshape(-1) / self.cutoff
        env = polynomial_envelope(d, self.envelope_exponent)
        return env[:, None] * jnp.sin(freq[None, :] * d[:, None])


def cosine_cutoff(dist: Array, cutoff: float) -> Array:
    """SchNet/PaiNN cosine cutoff window C(d) in [0, 1]."""
    c = 0.5 * (jnp.cos(dist * math.pi / cutoff) + 1.0)
    return jnp.where(dist <= cutoff, c, jnp.zeros_like(c))


def polynomial_cutoff(dist: Array, cutoff: float, p: int = 6) -> Array:
    """MACE ``PolynomialCutoff`` (radial.py:118): smooth f(d) with p-th order
    continuity, f(0)=1, f(cutoff)=0."""
    x = dist / cutoff
    out = (
        1.0
        - ((p + 1.0) * (p + 2.0) / 2.0) * x**p
        + p * (p + 2.0) * x ** (p + 1)
        - (p * (p + 1.0) / 2.0) * x ** (p + 2)
    )
    return jnp.where(x < 1.0, out, jnp.zeros_like(out))


def sinc_expansion(dist: Array, num_basis: int, cutoff: float) -> Array:
    """PaiNN's sin(n pi d / r_cut)/d expansion (reference ``PainnMessage``,
    ``PAINNStack.py:331-349``)."""
    n = jnp.arange(1, num_basis + 1, dtype=jnp.float32)
    d = dist.reshape(-1, 1)
    safe = jnp.where(d == 0, 1.0, d)
    return jnp.where(d == 0, n * math.pi / cutoff, jnp.sin(n * math.pi * d / cutoff) / safe)


class ChebyshevBasis(nn.Module):
    """Chebyshev polynomial radial basis on rescaled distances (MACE option,
    ``mace_utils/modules/radial.py``)."""

    num_basis: int = 8
    cutoff: float = 5.0

    @nn.compact
    def __call__(self, dist: Array) -> Array:
        x = jnp.clip(2.0 * dist.reshape(-1) / self.cutoff - 1.0, -1.0, 1.0)
        out = [jnp.ones_like(x), x]
        for _ in range(2, self.num_basis):
            out.append(2.0 * x * out[-1] - out[-2])
        return jnp.stack(out[: self.num_basis], axis=-1)


def shifted_softplus(x: Array) -> Array:
    """SchNet's activation: softplus(x) - log(2)."""
    return jax.nn.softplus(x) - math.log(2.0)
