"""MACE conv stack — higher-order equivariant message passing.

Reference: ``hydragnn/models/MACEStack.py:74-577`` +
``hydragnn/utils/model/mace_utils/modules/blocks.py`` (RadialEmbeddingBlock,
RealAgnosticAttResidualInteractionBlock, EquivariantProductBasisBlock) and the
Clebsch-Gordan symmetric contraction
(``mace_utils/modules/symmetric_contraction.py:29-242``, ``tools/cg.py:94``).

TPU-native redesign (capability parity, not a weight-for-weight port):

* irreps features are dicts {l: [N, 2l+1, C]} flowing between layers packed
  into one flat array (the CombineBlock/SplitBlock analog);
* spherical-harmonic edge attributes and all CG couplings come from
  ``harmonics.py`` — Gaunt coefficients by exact quadrature, channel-wise
  tensor products (validated equivariant to float32 precision);
* the interaction block gathers sender features, applies per-edge
  radial-MLP-weighted TP paths with the edge harmonics, aggregates at the
  receiver / avg_num_neighbors, with an element-gated residual (the
  "agnostic residual" skip);
* the product basis builds correlation-order nu features by iterated
  channel-wise Gaunt products (B_1 = A, B_nu = TP(B_{nu-1}, A)) with learned
  per-path weights and element gates — spanning the same symmetric n-body
  space as the reference's U-matrix contraction with a mildly overcomplete
  parameterization;
* node attributes are one-hot atomic numbers over the full periodic table
  (Z in 1..118, ``MACEStack :510-541``), read from ``batch.z`` — the raw
  pre-normalization atomic numbers;
* per-layer readouts: the stack exposes every layer's scalars to the heads
  (``collect_layer_outputs``) instead of summing per-layer decoders.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .harmonics import coupling_paths, spherical_harmonics, tensor_product
from .radial import BesselBasis, ChebyshevBasis, GaussianSmearing, polynomial_cutoff

NUM_ELEMENTS = 119  # Z in 0..118; index 0 absorbs non-integer/unknown types


def _pack_equiv(feats: dict, l_max: int) -> jax.Array:
    """{l: [N, 2l+1, C]} for l=1..l_max -> [N, sum(2l+1), C] (3-D on purpose:
    MACE detects the first layer by equiv.ndim == 2 == raw positions)."""
    return jnp.concatenate([feats[l] for l in range(1, l_max + 1)], axis=1)


def _unpack_equiv(equiv: jax.Array, l_max: int) -> dict:
    feats = {}
    off = 0
    for l in range(1, l_max + 1):
        feats[l] = equiv[:, off : off + 2 * l + 1, :]
        off += 2 * l + 1
    return feats


class IrrepsLinear(nn.Module):
    """Per-l channel-mixing linear (e3nn o3.Linear equivalent): each l block
    gets its own [C_in, C_out] matrix; only l=0 may carry a bias."""

    channels: int
    l_max: int
    bias: bool = False

    @nn.compact
    def __call__(self, feats: dict) -> dict:
        out = {}
        for l in range(self.l_max + 1):
            if l not in feats:
                continue
            w = self.param(
                f"w{l}",
                nn.initializers.lecun_normal(),
                (feats[l].shape[-1], self.channels),
            )
            y = jnp.einsum("nmc,cd->nmd", feats[l], w)
            if l == 0 and self.bias:
                y = y + self.param(f"b{l}", nn.initializers.zeros, (self.channels,))
            out[l] = y
        return out


@register_conv("MACE")
class MACEConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    feature_norm = False  # reference: no batch norm between MACE layers
    stack_activation = False  # reference forward applies no activation either
    collect_layer_outputs = True  # heads see all layers' scalars

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        C = max(spec.hidden_dim, 2)
        out_c = self.out_dim or spec.hidden_dim
        max_ell = 1 if spec.max_ell is None else spec.max_ell  # sh order
        node_ell = 1 if spec.node_max_ell is None else spec.node_max_ell
        last_layer = self.layer >= spec.num_conv_layers - 1
        # first layer receives raw positions [N, 3]; later layers receive the
        # 3-D packed irreps [N, sum(2l+1), C] from pack_irreps
        first_layer = equiv.ndim == 2
        out_ell = 0 if last_layer else node_ell
        correlation = spec.correlation
        if correlation is None:
            correlation = 2
        if isinstance(correlation, (list, tuple)):
            correlation = int(correlation[min(self.layer, len(correlation) - 1)])
        avg_nbr = float(spec.avg_num_neighbors or 1.0)

        # --- node features as irreps dict ---
        if first_layer:
            feats = {0: nn.Dense(C, name="node_embedding")(inv)[:, None, :]}
        else:
            feats = {0: inv[:, None, :]}
            feats.update(_unpack_equiv(equiv, node_ell))
        feats = IrrepsLinear(C, node_ell, bias=True, name="linear_up")(feats)

        # --- node attributes: one-hot Z + element embedding gate ---
        # batch.z carries RAW atomic numbers captured before feature
        # normalization (min-max scaling of x would collapse all elements
        # onto embedding rows 0/1)
        z = jnp.clip(batch.z.astype(jnp.int32), 0, NUM_ELEMENTS - 1)
        elem_gate = nn.Embed(NUM_ELEMENTS, C, name="element_embed")(z)  # [N, C]

        # --- edge attributes ---
        vec = batch.pos[batch.receivers] - batch.pos[batch.senders] + batch.edge_shifts
        dist = jnp.sqrt(jnp.sum(vec * vec, axis=-1) + 1e-18)
        Y = spherical_harmonics(vec, max_ell)  # list of [E, 2l+1]
        r_max = float(spec.radius or 5.0)
        num_radial = spec.num_radial or 8
        rt = (spec.radial_type or "bessel").lower()
        if rt == "bessel":
            rbf = BesselBasis(num_radial=num_radial, cutoff=r_max, name="rbf")(dist)
        elif rt == "chebyshev":
            rbf = ChebyshevBasis(num_basis=num_radial, cutoff=r_max, name="rbf")(dist)
        elif rt == "gaussian":
            rbf = GaussianSmearing(stop=r_max, num_gaussians=num_radial, name="rbf")(dist)
        else:
            raise ValueError(f"unknown radial_type '{rt}'")
        rbf = rbf * polynomial_cutoff(dist, r_max)[:, None]

        # --- interaction: radial-weighted TP with edge harmonics ---
        # messages keep l <= node_ell even on the last layer: the product
        # basis needs them before the sizing layer trims to scalars
        paths = coupling_paths(node_ell, max_ell, node_ell)
        rm = max(math.ceil(C / 3.0), 4)
        h = rbf
        for i in range(3):  # radial_MLP = [ceil(C/3)] * 3 (MACEStack :290-293)
            h = nn.silu(nn.Dense(rm, name=f"radial_mlp_{i}")(h))
        path_w = nn.Dense(len(paths) * C, use_bias=False, name="radial_out")(h)
        path_w = path_w.reshape(-1, len(paths), C)  # [E, P, C]

        sender_feats = {l: f[batch.senders] for l, f in feats.items()}
        sh = {l: Y[l][:, :, None] for l in range(max_ell + 1)}  # [E, 2l+1, 1]
        weights = {
            p: path_w[:, i, None, :] * batch.edge_mask[:, None, None]
            for i, p in enumerate(paths)
        }
        msgs = tensor_product(sender_feats, sh, node_ell, weights)
        agg = {
            l: segment.segment_sum(m, batch.receivers, batch.num_nodes, hints=batch) / avg_nbr
            for l, m in msgs.items()
        }
        agg = IrrepsLinear(C, node_ell, name="linear_post")(agg)

        # --- residual skip (element-gated, the "agnostic residual" TP) ---
        sc = IrrepsLinear(C, node_ell, name="skip_tp")(feats)
        sc = {l: f * elem_gate[:, None, :] for l, f in sc.items()}

        # --- product basis: iterated symmetric Gaunt products ---
        # `prod` accumulates over ALL l up to node_ell: correlation products
        # can reach l-blocks the first-order messages don't have (e.g.
        # max_ell=1 messages coupling to l=2 at nu=2)
        prod: dict[int, jax.Array] = {}
        B = agg
        for nu in range(1, correlation + 1):
            if nu > 1:
                wts = {
                    p: self.param(
                        f"prod_w{nu}_{p[0]}{p[1]}{p[2]}",
                        nn.initializers.normal(1.0 / math.sqrt(nu)),
                        (C,),
                    )
                    for p in coupling_paths(node_ell, node_ell, node_ell)
                }
                B = tensor_product(B, agg, node_ell, wts)
            contrib = IrrepsLinear(C, node_ell, name=f"prod_linear_{nu}")(B)
            for l, c in contrib.items():
                if l <= node_ell:
                    term = c * elem_gate[:, None, :]
                    prod[l] = prod[l] + term if l in prod else term

        # first layer has scalar-only inputs, so the skip lacks l>0 blocks
        out = {l: prod[l] + sc[l] if l in sc else prod[l] for l in prod}

        # --- sizing to output channels + split ---
        # zero-fill any l blocks unreachable this layer (e.g. scalar-only
        # first-layer inputs with max_ell < node_ell) so the packed layout
        # stays static across layers
        dtype = out[0].dtype
        for l in range(out_ell + 1):
            if l not in out:
                out[l] = jnp.zeros((batch.num_nodes, 2 * l + 1, C), dtype)
        out = IrrepsLinear(out_c, out_ell, name="sizing")(out)
        inv_out = out[0][:, 0, :]
        if last_layer or out_ell == 0:
            return inv_out, batch.pos  # scalars only (reference last layer)
        return inv_out, _pack_equiv(out, out_ell)
