"""Real spherical harmonics + Gaunt (real-CG) tensor products — the
hand-rolled replacement for e3nn that MACE needs.

Reference: ``hydragnn/models/MACEStack.py`` uses ``e3nn.o3.SphericalHarmonics``
and tensor products whose Clebsch-Gordan contractions come from
``utils/model/mace_utils/tools/cg.py:94`` (``U_matrix_real``). Here:

* ``spherical_harmonics(vec, l_max)`` — explicit Cartesian polynomial
  formulas up to l=3 (differentiable jnp, component normalization: the l=0
  value is 1 and each block has ||Y_l||^2 = 2l+1 on the unit sphere);
* Gaunt coefficients G^{l3}_{l1 l2}[m1, m2, m3] = ∫ Y_{l1 m1} Y_{l2 m2}
  Y_{l3 m3} dΩ computed ONCE on host by *exact* Gauss-Legendre x uniform-phi
  quadrature (the integrand is a polynomial on the sphere) — this makes the
  coupling self-consistent with our harmonics convention by construction, no
  sympy table matching needed;
* ``tensor_product`` — channel-wise equivariant product of two irreps
  dictionaries ``{l: [N, 2l+1, C]}`` through the Gaunt coupling.

Equivariance of the whole pipeline is asserted by rotation tests at the model
level (MACE scalar outputs invariant, forces equivariant).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Real spherical harmonics (component normalization), m ordered -l..l
# ---------------------------------------------------------------------------


def _sh_blocks(x, y, z, l_max: int, xp):
    """Shared implementation for jnp (device) and numpy (host quadrature)."""
    out = [xp.stack([xp.ones_like(x)], axis=-1)]  # l=0: [.., 1]
    if l_max >= 1:
        c1 = math.sqrt(3.0)
        out.append(xp.stack([c1 * y, c1 * z, c1 * x], axis=-1))
    if l_max >= 2:
        c = math.sqrt(15.0)
        c20 = math.sqrt(5.0)
        out.append(
            xp.stack(
                [
                    c * x * y,
                    c * y * z,
                    c20 * 0.5 * (3.0 * z * z - 1.0),
                    c * x * z,
                    c * 0.5 * (x * x - y * y),
                ],
                axis=-1,
            )
        )
    if l_max >= 3:
        out.append(
            xp.stack(
                [
                    math.sqrt(35.0 / 8.0) * y * (3.0 * x * x - y * y),
                    math.sqrt(105.0) * x * y * z,
                    math.sqrt(21.0 / 8.0) * y * (5.0 * z * z - 1.0),
                    math.sqrt(7.0) * 0.5 * z * (5.0 * z * z - 3.0),
                    math.sqrt(21.0 / 8.0) * x * (5.0 * z * z - 1.0),
                    math.sqrt(105.0) * 0.5 * z * (x * x - y * y),
                    math.sqrt(35.0 / 8.0) * x * (x * x - 3.0 * y * y),
                ],
                axis=-1,
            )
        )
    if l_max >= 4:
        out.extend(_sh_recurrence(x, y, z, 4, l_max, xp))
    return out


def _sh_recurrence(x, y, z, l_from: int, l_max: int, xp):
    """General real spherical harmonics for l >= 4 by recurrence, same
    convention as the explicit blocks (m ordered -l..l, e3nn axis roles,
    component normalization ||Y_l||^2 = 2l+1 on the unit sphere).

    Uses A_m = Re (x+iy)^m, B_m = Im (x+iy)^m and associated Legendre
    polynomials with the sin^m(theta) factor divided out (it lives in
    A_m/B_m), so everything is polynomial in (x, y, z) — differentiable and
    pole-safe."""
    one = xp.ones_like(x)
    A = [one, x]
    B = [xp.zeros_like(x), y]
    for m in range(2, l_max + 1):
        A.append(x * A[m - 1] - y * B[m - 1])
        B.append(x * B[m - 1] + y * A[m - 1])

    # Q[(l, m)]: P_l^m(z) / sin^m(theta), via the standard l-recurrence
    Q = {}
    for m in range(l_max + 1):
        Q[(m, m)] = float(math.prod(range(1, 2 * m, 2))) * one  # (2m-1)!!
        if m + 1 <= l_max:
            Q[(m + 1, m)] = (2 * m + 1) * z * Q[(m, m)]
        for l in range(m + 2, l_max + 1):
            Q[(l, m)] = (
                (2 * l - 1) * z * Q[(l - 1, m)] - (l - 1 + m) * Q[(l - 2, m)]
            ) / (l - m)

    blocks = []
    for l in range(l_from, l_max + 1):
        comps = []
        for m in range(-l, l + 1):
            am = abs(m)
            c = math.sqrt(
                (2 * l + 1)
                * (2.0 if m != 0 else 1.0)
                * math.factorial(l - am)
                / math.factorial(l + am)
            )
            base = c * Q[(l, am)]
            if m < 0:
                comps.append(base * B[am])
            elif m > 0:
                comps.append(base * A[am])
            else:
                comps.append(base)
        blocks.append(xp.stack(comps, axis=-1))
    return blocks


def spherical_harmonics(vec: jax.Array, l_max: int, eps: float = 1e-6) -> list:
    """Unit-normalize ``vec`` [E, 3] and return [Y_0, ..., Y_lmax], each
    [E, 2l+1]. Zero vectors (padding) are substituted with the +z pole BEFORE
    the norm so gradients stay finite (sqrt at 0 has a NaN derivative and
    0 * NaN defeats downstream masking)."""
    n2 = jnp.sum(vec * vec, axis=-1, keepdims=True)
    is_zero = n2 < eps * eps
    safe_vec = jnp.where(is_zero, jnp.array([0.0, 0.0, 1.0]), vec)
    n = jnp.sqrt(jnp.sum(safe_vec * safe_vec, axis=-1, keepdims=True))
    unit = safe_vec / n
    return _sh_blocks(unit[..., 0], unit[..., 1], unit[..., 2], l_max, jnp)


# ---------------------------------------------------------------------------
# Gaunt coefficients by exact quadrature (host, cached)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _quadrature(l_max_total: int):
    """Gauss-Legendre in cos(theta) x uniform phi — exact for spherical
    polynomials up to the triple-product degree."""
    n_theta = 2 * l_max_total + 4
    n_phi = 4 * l_max_total + 5
    ct, wt = np.polynomial.legendre.leggauss(n_theta)
    phi = np.arange(n_phi) * (2.0 * np.pi / n_phi)
    st = np.sqrt(1.0 - ct**2)
    x = st[:, None] * np.cos(phi)[None, :]
    y = st[:, None] * np.sin(phi)[None, :]
    z = np.broadcast_to(ct[:, None], x.shape)
    w = np.broadcast_to(wt[:, None], x.shape) * (2.0 * np.pi / n_phi)
    return x.ravel(), y.ravel(), z.ravel(), w.ravel()


@functools.lru_cache(maxsize=None)
def gaunt(l1: int, l2: int, l3: int) -> tuple:
    """G[m1, m2, m3] = (1/4pi) ∫ Y_{l1 m1} Y_{l2 m2} Y_{l3 m3} dΩ in the
    component-normalized basis above. Zero unless |l1-l2| <= l3 <= l1+l2 and
    l1+l2+l3 even. Returned as a nested tuple (hashable, cached)."""
    x, y, z, w = _quadrature(l1 + l2 + l3)
    blocks = _sh_blocks(x, y, z, max(l1, l2, l3), np)
    Y1, Y2, Y3 = blocks[l1], blocks[l2], blocks[l3]  # [Q, 2l+1]
    G = np.einsum("q,qa,qb,qc->abc", w / (4.0 * np.pi), Y1, Y2, Y3)
    G[np.abs(G) < 1e-12] = 0.0
    return tuple(map(lambda m: tuple(map(tuple, m)), G))


def gaunt_array(l1: int, l2: int, l3: int) -> np.ndarray:
    return np.asarray(gaunt(l1, l2, l3))


def coupling_paths(l_in1: int, l_in2: int, l_out_max: int) -> list:
    """All (l1, l2, l3) with nonzero Gaunt coupling within the given maxima."""
    paths = []
    for l1 in range(l_in1 + 1):
        for l2 in range(l_in2 + 1):
            for l3 in range(abs(l1 - l2), min(l1 + l2, l_out_max) + 1):
                if (l1 + l2 + l3) % 2 == 0:
                    paths.append((l1, l2, l3))
    return paths


def tensor_product(
    u: dict, v: dict, l_out_max: int, weights: dict | None = None
) -> dict:
    """Channel-wise equivariant product of irreps dicts {l: [..., 2l+1, C]}.

    out[l3][..., m3, c] = sum_{l1 l2 m1 m2} w[(l1,l2,l3)][..., c] *
                          G[m1,m2,m3] u[l1][..., m1, c] v[l2][..., m2, c]

    ``weights`` maps path -> per-channel (broadcastable) weights; None = 1.
    Channel-wise (depthwise) like MACE's symmetric contraction — channel mixing
    happens in the surrounding linear layers.
    """
    out: dict[int, jax.Array] = {}
    for l1, ul in u.items():
        for l2, vl in v.items():
            for l3 in range(abs(l1 - l2), min(l1 + l2, l_out_max) + 1):
                if (l1 + l2 + l3) % 2 != 0:
                    continue
                G = jnp.asarray(gaunt_array(l1, l2, l3), ul.dtype)
                term = jnp.einsum("abc,...ax,...bx->...cx", G, ul, vl)
                if weights is not None:
                    term = term * weights[(l1, l2, l3)]
                out[l3] = out.get(l3, 0) + term
    return out


# ---------------------------------------------------------------------------
# Misc irreps helpers
# ---------------------------------------------------------------------------


def irreps_dim(l_max: int) -> int:
    return (l_max + 1) ** 2
