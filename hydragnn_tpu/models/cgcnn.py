"""CGCNN conv stack (reference ``hydragnn/models/CGCNNStack.py:19-113``, PyG
``CGConv``): crystal graph conv with gated residual update
x_i' = x_i + sum_j sigmoid(W_f z_ij) * softplus(W_s z_ij),
z_ij = [x_i, x_j, e_ij].

Dimensional quirk kept from the reference: hidden_dim is forced equal to
input_dim when GPS is off (``config_utils.py:76-83``) because the update is
residual (output dim == input dim)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv


@register_conv("CGCNN")
class CGCNNConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        dim = inv.shape[-1]
        z = jnp.concatenate([inv[batch.receivers], inv[batch.senders]], axis=-1)
        if self.spec.edge_dim and batch.edge_attr.shape[1]:
            z = jnp.concatenate([z, batch.edge_attr], axis=-1)
        gate = nn.sigmoid(nn.Dense(dim, name="lin_f")(z))
        core = nn.softplus(nn.Dense(dim, name="lin_s")(z))
        msg = gate * core * batch.edge_mask[:, None]
        agg = segment.segment_sum(msg, batch.receivers, batch.num_nodes, hints=batch)
        out = inv + agg  # residual (aggr='add' in reference CGConv)
        if self.out_dim is not None and self.out_dim != dim:
            out = nn.Dense(self.out_dim, name="proj")(out)
        return out, equiv
