"""HydraModel — the multi-headed GNN skeleton (TPU-native Base).

Functional re-design of reference ``hydragnn/models/Base.py:36-909``:

* conv stack with per-layer masked BatchNorm + activation (``Base.py:446-463,
  697-728``), gradient checkpointing via ``nn.remat`` (``:714-721``);
* graph-level readout with mean/add/max pooling (``:147-170``);
* multi-head decoders: per-head graph MLPs with per-branch shared layers
  (``_multihead``, ``:590-691``), node heads of type mlp / mlp_per_node / conv
  (``:641-684`` + ``MLPNode :912-982``);
* multibranch (multidataset) routing by ``dataset_id``: the reference gathers
  rows per branch with boolean masks (``forward :747-841``) — data-dependent
  shapes that XLA cannot compile. Here every branch computes on the full batch
  and a ``where`` select keeps the right rows: branch count is small (<=14) and
  head MLPs are tiny, so redundant FLOPs are noise on the MXU while shapes stay
  static;
* weighted multi-task loss (``loss_hpweighted``, ``:879-906``) and GaussianNLL
  variance outputs (``var_output``, ``:108-112``) — masked for padding;
* targets are columnar (``graph_y``/``node_y`` column slices per head) instead
  of the reference's concatenated ``data.y`` + ``y_loc`` offsets
  (``get_head_indices``, ``train_validate_test.py:494-557``) — a static-shape
  redesign, not a port.

Conv layers follow one uniform contract (no PyG string signatures):
``conv(inv_node_feat, equiv_node_feat, batch) -> (inv_node_feat,
equiv_node_feat)`` where ``batch`` is the full ``GraphBatch``.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import HeadBranchSpec, ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .common import (
    MLP,
    SYNC_BN_AXIS,
    MaskedBatchNorm,
    get_activation,
    get_loss,
    local_node_index,
)

Array = jax.Array

# Registered by each architecture module at import time (create.py imports them).
CONV_REGISTRY: dict[str, Callable[..., nn.Module]] = {}


def register_conv(name: str):
    def deco(cls):
        CONV_REGISTRY[name] = cls
        return cls

    return deco


def head_columns(spec: ModelSpec) -> list[tuple[str, int, int]]:
    """Per-head (kind, column_start, dim) into the columnar target arrays."""
    cols = []
    g_off = n_off = 0
    for dim, kind in zip(spec.output_dim, spec.output_type):
        if kind == "graph":
            cols.append(("graph", g_off, dim))
            g_off += dim
        else:
            cols.append(("node", n_off, dim))
            n_off += dim
    return cols


class PerNodeMLP(nn.Module):
    """``mlp_per_node`` head: a separate MLP per node *position* (fixed-size
    graphs only — reference ``MLPNode`` with ``num_mlp=num_nodes``).

    TPU design: one weight bank ``[num_nodes, in, out]`` per layer, gathered by
    each node's local index and applied as a batched matmul — one einsum instead
    of ``num_nodes`` tiny MLP calls.
    """

    num_nodes: int
    features: tuple[int, ...]
    activation: str = "relu"

    @nn.compact
    def __call__(self, x: Array, local_idx: Array) -> Array:
        act = get_activation(self.activation)
        n_layers = len(self.features)
        in_dim = x.shape[-1]
        for i, out_dim in enumerate(self.features):
            w = self.param(
                f"w_{i}",
                nn.initializers.lecun_normal(),
                (self.num_nodes, in_dim, out_dim),
            )
            b = self.param(f"b_{i}", nn.initializers.zeros, (self.num_nodes, out_dim))
            wn = w[local_idx]  # [N, in, out]
            bn = b[local_idx]  # [N, out]
            x = jnp.einsum("ni,nio->no", x, wn) + bn
            if i < n_layers - 1:
                x = act(x)
            in_dim = out_dim
        return x


class HydraModel(nn.Module):
    """Multi-headed GNN over padded graph batches."""

    spec: ModelSpec

    def setup(self):
        spec = self.spec
        conv_cls = CONV_REGISTRY[spec.mpnn_type]
        # stack flags always come from the architecture's own conv class,
        # even when GPS wraps it (the reference keeps Identity feature layers
        # for SchNet/MACE/etc. with or without GPS)
        use_feature_norm = getattr(conv_cls, "feature_norm", True)
        if spec.global_attn_engine == "GPS":
            # wrap every conv layer in local-MPNN + global attention
            # (reference Base._apply_global_attn, Base.py:234-247)
            from .gps import GPSConv as conv_cls  # noqa: F811

            self.pos_emb = nn.Dense(spec.hidden_dim, use_bias=False, name="pos_emb")
            if spec.input_dim:
                self.node_emb = nn.Dense(
                    spec.hidden_dim, use_bias=False, name="node_emb"
                )
                self.node_lin = nn.Dense(
                    spec.hidden_dim, use_bias=False, name="node_lin"
                )
        if spec.conv_checkpointing:
            # trade recompute for HBM: rematerialize each conv block on backward
            # (reference uses torch checkpointing at Base.py:714-721).
            # `train` (argnum 4 counting the module receiver) must stay static:
            # convs branch on it in Python (dropout determinism).
            conv_cls = nn.remat(conv_cls, static_argnums=(4,))
        self.graph_convs = [
            conv_cls(spec=spec, layer=i) for i in range(spec.num_conv_layers)
        ]
        # some stacks (SchNet) use identity feature layers in the reference
        # SyncBatchNorm (reference distributed.py:415-416, config key
        # Architecture.SyncBatchNorm): stats pmean'd over the axis the SPMD
        # steps bind; requires running under a parallel step's vmap.
        # ``bn_sync_axis`` overrides with a MESH axis name instead: the
        # halo-partitioned step runs under shard_map where the node set is
        # split across devices, so feature-norm statistics are only correct
        # when the masked sums are psum'd over the data axis.
        bn_axis = spec.bn_sync_axis or (
            SYNC_BN_AXIS if spec.sync_batch_norm else None
        )
        self.feature_layers = [
            (
                MaskedBatchNorm(name=f"feature_norm_{i}", axis_name=bn_axis)
                if use_feature_norm
                else None
            )
            for i in range(spec.num_conv_layers)
        ]

        # graph-head shared layers + per-head MLPs, per branch
        # (num_sharedlayers == 0 -> no shared stack, heads read pooled features)
        self.graph_shared = {
            b.branch: (
                MLP(
                    features=(b.dim_sharedlayers,) * b.num_sharedlayers,
                    activation=spec.activation,
                    act_last=True,
                    name=f"graph_shared_{b.branch}",
                )
                if b.num_sharedlayers > 0 and b.dim_sharedlayers > 0
                else None
            )
            for b in spec.graph_heads
        }
        var_mult = 2 if spec.var_output else 1
        heads = []
        cols = head_columns(spec)
        node_local_needed = False
        for ihead, (kind, _, dim) in enumerate(cols):
            if kind == "graph":
                per_branch = {}
                for b in spec.graph_heads:
                    feats = tuple(b.dim_headlayers[: b.num_headlayers]) + (dim * var_mult,)
                    per_branch[b.branch] = MLP(
                        features=feats,
                        activation=spec.activation,
                        name=f"head{ihead}_{b.branch}",
                    )
                heads.append(per_branch)
            else:
                per_branch = {}
                for b in spec.node_heads:
                    node_type = b.node_type or "mlp"
                    feats = tuple(b.dim_headlayers[: b.num_headlayers]) + (dim * var_mult,)
                    if node_type == "mlp":
                        per_branch[b.branch] = MLP(
                            features=feats,
                            activation=spec.activation,
                            name=f"head{ihead}_{b.branch}",
                        )
                    elif node_type == "mlp_per_node":
                        if spec.num_nodes is None or spec.graph_size_variable:
                            raise ValueError(
                                "mlp_per_node requires fixed-size graphs (reference "
                                "config_utils.py:240-249)"
                            )
                        node_local_needed = True
                        per_branch[b.branch] = PerNodeMLP(
                            num_nodes=spec.num_nodes,
                            features=feats,
                            activation=spec.activation,
                            name=f"head{ihead}_{b.branch}",
                        )
                    elif node_type == "conv":
                        # conv-type node head: extra conv layers + output conv
                        # (reference _init_node_conv, Base.py:544-588)
                        conv_cls2 = CONV_REGISTRY[spec.mpnn_type]
                        layers = []
                        hidden = list(b.dim_headlayers[: b.num_headlayers])
                        for j, _h in enumerate(hidden):
                            layers.append(
                                conv_cls2(
                                    spec=spec,
                                    layer=spec.num_conv_layers + j,
                                    name=f"head{ihead}_{b.branch}_conv{j}",
                                )
                            )
                        layers.append(
                            conv_cls2(
                                spec=spec,
                                layer=spec.num_conv_layers + len(hidden),
                                out_dim=dim * var_mult,
                                name=f"head{ihead}_{b.branch}_convout",
                            )
                        )
                        per_branch[b.branch] = layers
                    else:
                        raise ValueError(
                            f"Unknown node head type '{node_type}'; support 'mlp', "
                            "'mlp_per_node', 'conv'"
                        )
                heads.append(per_branch)
        self.heads_NN = heads
        self._head_cols = cols
        self._node_local_needed = node_local_needed

        # graph-attribute conditioning (reference Base.py:249-444):
        # 'film'        — gamma/beta modulation of node features per layer
        # 'concat_node' — broadcast graph_attr to nodes, concat + project
        # 'fuse_pool'   — fuse into the pooled embedding before graph heads
        if spec.use_graph_attr_conditioning:
            mode = spec.graph_attr_conditioning_mode
            if mode not in ("film", "concat_node", "fuse_pool"):
                raise ValueError(
                    "graph_attr_conditioning_mode must be one of: "
                    "'film', 'concat_node', 'fuse_pool'"
                )
            if mode == "film":
                self.graph_conditioner = MLP(
                    features=(spec.hidden_dim, 2 * spec.hidden_dim),
                    activation=spec.activation,
                    name="graph_conditioner",
                )
            elif mode == "concat_node":
                self.graph_concat_projector = nn.Dense(
                    spec.hidden_dim, name="graph_concat_projector"
                )
            else:  # fuse_pool
                self.graph_pool_projector = MLP(
                    features=(spec.hidden_dim, spec.hidden_dim),
                    activation=spec.activation,
                    name="graph_pool_projector",
                )

    # -- encoder ------------------------------------------------------------
    def conv_block(self, i: int, inv: Array, equiv: Array, batch: GraphBatch,
                   train: bool = False):
        """One conv layer block: conv + graph-attr conditioning + feature
        norm + activation. Factored out so the pipeline-parallel runtime
        (``parallel/pipeline.py``) can scan it over per-layer params."""
        conv_cls = CONV_REGISTRY[self.spec.mpnn_type]
        stack_activation = getattr(conv_cls, "stack_activation", True)
        conv = self.graph_convs[i]
        norm = self.feature_layers[i]
        inv, equiv = conv(inv, equiv, batch, train)  # positional: remat statics
        inv = self._apply_graph_conditioning(inv, batch)
        if norm is not None:
            inv = norm(inv, batch.node_mask, train)
        if stack_activation:
            inv = get_activation(self.spec.activation)(inv)
        return inv, equiv

    def embed_block0(self, batch: GraphBatch, train: bool = False):
        """Input embedding + conv block 0 — the pipeline prologue (block 0
        lifts input_dim -> hidden_dim, so it is the one non-uniform layer)."""
        inv, equiv = self.embed(batch)
        return self.conv_block(0, inv, equiv, batch, train)

    def encode(self, batch: GraphBatch, train: bool = False, layer_hook=None):
        """Run the conv stack; returns (node_features, equiv_features).

        ``layer_hook(inv, equiv) -> (inv, equiv)`` runs BEFORE every conv
        layer after the first — the seam the halo-exchange route uses to
        refresh boundary-node features over the mesh (``parallel/halo.py``):
        layer 0 reads collate-time halo copies, every later layer reads rows
        re-fetched from their owner device. Single-device and replicated
        paths pass None and trace the exact historical program."""
        conv_cls = CONV_REGISTRY[self.spec.mpnn_type]
        # MACE: no inter-layer activation; heads read concatenated per-layer
        # scalars (our static-shape take on the reference's summed per-layer
        # readout decoders, MACEStack.forward :375-421)
        collect = getattr(conv_cls, "collect_layer_outputs", False)

        inv, equiv = self.embed(batch)
        layer_outs = []
        for i in range(len(self.graph_convs)):
            if layer_hook is not None and i > 0:
                inv, equiv = layer_hook(inv, equiv)
            inv, equiv = self.conv_block(i, inv, equiv, batch, train)
            if collect:
                layer_outs.append(inv)
        if collect:
            inv = jnp.concatenate(layer_outs, axis=-1)
        return inv, equiv

    def _apply_graph_conditioning(self, inv: Array, batch: GraphBatch) -> Array:
        """Per-layer node-feature conditioning on graph attributes
        (reference ``_apply_graph_conditioning``, Base.py:346-420)."""
        spec = self.spec
        if not spec.use_graph_attr_conditioning or batch.graph_attr.shape[1] == 0:
            return inv
        mode = spec.graph_attr_conditioning_mode
        if mode == "film":
            gb = self.graph_conditioner(batch.graph_attr)  # [G, 2H]
            gamma, beta = jnp.split(gb, 2, axis=-1)
            h = min(inv.shape[-1], gamma.shape[-1])
            scaled = inv[:, :h] * (1.0 + gamma[batch.batch][:, :h]) + beta[
                batch.batch
            ][:, :h]
            return jnp.concatenate([scaled, inv[:, h:]], axis=-1)
        if mode == "concat_node":
            ga = batch.graph_attr[batch.batch]  # broadcast to nodes
            return self.graph_concat_projector(jnp.concatenate([inv, ga], axis=-1))
        return inv  # fuse_pool conditions at the pooled level instead

    def embed(self, batch: GraphBatch):
        """Input embedding. With GPS, node features and Laplacian positional
        encodings are embedded to hidden_dim and fused (reference Base.py
        :203-215); otherwise raw features + positions pass through (each
        stack's first conv layer does its own lifting)."""
        if self.spec.global_attn_engine == "GPS":
            if batch.pe.shape[1] == 0:
                raise ValueError(
                    "GPS needs Laplacian positional encodings; set pe_dim > 0 "
                    "and attach them in preprocessing (attach_lap_pe)"
                )
            x = self.pos_emb(batch.pe)
            if self.spec.input_dim:
                x = jnp.concatenate([self.node_emb(batch.x), x], axis=1)
                x = self.node_lin(x)
            return x, batch.pos
        return batch.x, batch.pos

    def pool(self, x: Array, batch: GraphBatch, pool_reduce=None) -> Array:
        pooled = segment.global_pool(
            self.spec.graph_pooling,
            x * batch.node_mask[:, None],
            batch.batch,
            batch.num_graphs,
            hints=batch,
        )
        if pool_reduce is not None:
            # partitioned node sets (halo route): each device pooled only its
            # owned rows — the hook merges the per-device partials into the
            # union-graph readout (psum/weighted-mean/pmax per pooling kind)
            # BEFORE any nonlinear head consumes them
            pooled = pool_reduce(pooled)
        if (
            self.spec.use_graph_attr_conditioning
            and self.spec.graph_attr_conditioning_mode == "fuse_pool"
            and batch.graph_attr.shape[1] > 0
        ):
            pooled = self.graph_pool_projector(
                jnp.concatenate([pooled, batch.graph_attr], axis=-1)
            )
        return pooled

    # -- full forward --------------------------------------------------------
    def __call__(self, batch: GraphBatch, train: bool = False,
                 layer_hook=None, pool_reduce=None):
        inv, equiv = self.encode(batch, train, layer_hook=layer_hook)
        return self.decode(inv, equiv, batch, train, pool_reduce=pool_reduce)

    def decode(self, inv: Array, equiv: Array, batch: GraphBatch,
               train: bool = False, pool_reduce=None):
        """Pooling + multi-head decoders on encoded node features — the
        pipeline epilogue (everything after the conv stack)."""
        spec = self.spec
        x_graph = self.pool(inv, batch, pool_reduce=pool_reduce)

        outputs = []
        outputs_var = []
        local_idx = None
        if self._node_local_needed:
            local_idx = local_node_index(batch.batch, batch.n_node, batch.num_nodes)

        for ihead, (kind, _, dim) in enumerate(self._head_cols):
            per_branch = self.heads_NN[ihead]
            if kind == "graph":
                out = jnp.zeros((batch.num_graphs, dim), inv.dtype)
                out_var = jnp.zeros((batch.num_graphs, dim), inv.dtype)
                for b in spec.graph_heads:
                    shared_mlp = self.graph_shared[b.branch]
                    shared = shared_mlp(x_graph) if shared_mlp is not None else x_graph
                    o = per_branch[b.branch](shared)
                    mu = o[:, :dim]
                    var = o[:, dim:] ** 2 if spec.var_output else out_var
                    if len(spec.graph_heads) == 1:
                        out, out_var = mu, var
                    else:
                        sel = (batch.dataset_id == int(b.branch.split("-")[1]))[:, None]
                        out = jnp.where(sel, mu, out)
                        out_var = jnp.where(sel, var, out_var)
                outputs.append(out)
                outputs_var.append(out_var)
            else:
                out = jnp.zeros((batch.num_nodes, dim), inv.dtype)
                out_var = jnp.zeros((batch.num_nodes, dim), inv.dtype)
                for b in spec.node_heads:
                    node_type = b.node_type or "mlp"
                    if node_type == "conv":
                        h, e = inv, equiv
                        for conv in per_branch[b.branch]:
                            h, e = conv(h, e, batch, train=train)
                        o = h
                    elif node_type == "mlp_per_node":
                        o = per_branch[b.branch](inv, local_idx)
                    else:
                        o = per_branch[b.branch](inv)
                    mu = o[:, :dim]
                    var = o[:, dim:] ** 2 if spec.var_output else out_var
                    if len(spec.node_heads) == 1:
                        out, out_var = mu, var
                    else:
                        bid = int(b.branch.split("-")[1])
                        sel = (batch.dataset_id[batch.batch] == bid)[:, None]
                        out = jnp.where(sel, mu, out)
                        out_var = jnp.where(sel, var, out_var)
                outputs.append(out)
                outputs_var.append(out_var)

        if spec.var_output:
            return outputs, outputs_var
        return outputs

    # -- loss ----------------------------------------------------------------
    def loss(self, pred, batch: GraphBatch, loss_axis: str | None = None):
        """Weighted multi-task loss (reference ``loss_hpweighted``,
        ``Base.py:879-906``). Returns (total, [per-task losses]).

        ``loss_axis``: mesh axis name when the batch's NODE rows are
        partitioned across devices (halo route) — each masked mean then
        psums numerator and denominator over the axis so every device holds
        the exact union-batch loss (graph rows are replicated there, which
        the psum'd ratio absorbs unchanged)."""
        spec = self.spec
        var = None
        if spec.var_output:
            pred, var = pred
        loss_fn = get_loss(spec.loss_type)
        tot = 0.0
        tasks = []
        for ihead, (kind, col, dim) in enumerate(head_columns(spec)):
            if kind == "graph":
                target = batch.graph_y[:, col : col + dim]
                mask = batch.graph_mask
            else:
                target = batch.node_y[:, col : col + dim]
                mask = batch.node_mask
            if var is not None:
                task_loss = loss_fn(pred[ihead], target, mask, var[ihead],
                                    axis_name=loss_axis)
            else:
                task_loss = loss_fn(pred[ihead], target, mask,
                                    axis_name=loss_axis)
            tot = tot + task_loss * spec.task_weights[ihead]
            tasks.append(task_loss)
        return tot, tasks

    def head_sse(self, pred, batch: GraphBatch):
        """Per-head (sum of squared errors, element count) over real rows.

        Callers accumulate these across batches and take ONE sqrt at the end —
        the statistically correct split RMSE (the CI accuracy gate metric,
        reference ``test_graphs.py:144-170``); a mean of per-batch RMSEs is not.
        """
        spec = self.spec
        if spec.var_output:
            pred = pred[0]
        sses, counts = [], []
        for ihead, (kind, col, dim) in enumerate(head_columns(spec)):
            if kind == "graph":
                target = batch.graph_y[:, col : col + dim]
                mask = batch.graph_mask
            else:
                target = batch.node_y[:, col : col + dim]
                mask = batch.node_mask
            m = mask[:, None]
            sses.append((((pred[ihead] - target) ** 2) * m).sum())
            counts.append(mask.sum() * dim)
        return sses, counts
