"""EGNN conv stack (reference ``hydragnn/models/EGCLStack.py:22-300``,
``E_GCL`` layer): E(n)-equivariant message passing.

Per layer:
    m_ij   = edge_mlp([h_i, h_j, ||d_ij||, e_ij])
    pos_i +=  mean_j( d_hat_ij * tanh(coord_mlp(m_ij)) )  [if equivariant,
              skipped on the last layer — EGCLStack.get_conv :46-70]
    h_i    = node_mlp([h_i, sum_j m_ij])

Parity notes: edge vectors are normalized with eps=1.0 (reference calls
``get_edge_vectors_and_lengths(..., normalize=True, eps=1.0)``); messages are
aggregated at the edge *sender* (row) like the reference's
``unsorted_segment_sum(edge_feat, row)``; PBC ``edge_shifts`` flow through the
geometry (EGCLStack supports them, ``:111-131``); feature layers are Identity
(no batch norm). Coordinate updates honor padding via edge masks.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .common import MLP, equivariant_coordinate_update


@register_conv("EGNN")
class EGNNConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    feature_norm = False  # reference EGCLStack uses Identity feature layers

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        hidden = spec.hidden_dim
        out_dim = self.out_dim or hidden
        last_layer = self.layer >= spec.num_conv_layers - 1
        # reference default: equivariance toggles coordinate updates, off on
        # the last layer (EGCLStack._init_conv :46-70)
        equivariant = bool(spec.equivariance) and not last_layer

        vec = equiv[batch.receivers] - equiv[batch.senders] + batch.edge_shifts
        lengths = jnp.sqrt(jnp.sum(vec * vec, axis=-1, keepdims=True) + 1e-18)
        coord_diff = vec / (lengths + 1.0)  # normalize=True, eps=1.0

        feats = [inv[batch.senders], inv[batch.receivers], lengths]
        if spec.edge_dim and batch.edge_attr.shape[1]:
            feats.append(batch.edge_attr)
        edge_in = jnp.concatenate(feats, axis=-1)
        m = MLP(
            features=(hidden, hidden),
            activation=spec.activation,
            act_last=True,
            name="edge_mlp",
        )(edge_in)

        if equivariant:
            equiv = equiv + equivariant_coordinate_update(
                m, coord_diff, batch.senders, batch.edge_mask, batch.num_nodes,
                hidden, tanh_bound=True, name_prefix="coord_mlp", hints=batch,
            )

        m_masked = m * batch.edge_mask[:, None]
        agg = segment.segment_sum(m_masked, batch.senders, batch.num_nodes, hints=batch)
        h = MLP(
            features=(hidden, out_dim),
            activation=spec.activation,
            name="node_mlp",
        )(jnp.concatenate([inv, agg], axis=-1))
        return h, equiv
