"""SchNet conv stack (reference ``hydragnn/models/SCFStack.py:42-301``):
continuous-filter convolution — filters are an MLP of the Gaussian-smeared
edge length windowed by a cosine cutoff; messages are filter-gated sender
features, sum-aggregated:

    W_ij = filter_mlp(rbf(d_ij) [, e_ij]) * C(d_ij)
    x_i' = lin2( sum_j  lin1(x_j) * W_ij )

Optionally E(3)-equivariant (``equivariance`` config flag): every layer except
the last also nudges positions along normalized edge vectors scaled by a
coordinate MLP of the filters (``CFConv.coord_model``, ``SCFStack.py:243-250``)
— mean-aggregated over incident edges. SchNet layers use no batch norm
(feature layers are Identity in the reference, ``_init_conv :81-95``).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .common import equivariant_coordinate_update
from .radial import GaussianSmearing, cosine_cutoff, shifted_softplus


@register_conv("SchNet")
class SchNetConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    feature_norm = False  # reference uses Identity feature layers for SchNet

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        hidden = self.out_dim or spec.hidden_dim
        nf = spec.num_filters or 64
        cutoff = float(spec.radius or 5.0)
        last_layer = self.layer >= spec.num_conv_layers - 1
        equivariant = bool(spec.equivariance) and not last_layer

        vec = equiv[batch.receivers] - equiv[batch.senders] + batch.edge_shifts
        dist = jnp.sqrt(jnp.sum(vec * vec, axis=-1) + 1e-12)

        rbf = GaussianSmearing(
            start=0.0, stop=cutoff, num_gaussians=spec.num_gaussians or 50, name="smearing"
        )(dist)
        if spec.edge_dim and batch.edge_attr.shape[1]:
            rbf = jnp.concatenate([rbf, batch.edge_attr], axis=-1)

        w = nn.Dense(nf, name="filter1")(rbf)
        w = shifted_softplus(w)
        w = nn.Dense(nf, name="filter2")(w)
        w = w * cosine_cutoff(dist, cutoff)[:, None]

        x = nn.Dense(nf, use_bias=False, name="lin1")(inv)
        # fused gather+filter+scatter: the CFConv hot path in one kernel
        # (vector edge weight = learned filter x mask)
        from ..ops import gather_scatter_sum

        agg = gather_scatter_sum(
            x, batch.senders, batch.receivers, batch.num_nodes,
            weight=(w * batch.edge_mask[:, None]).astype(x.dtype), hints=batch,
        )
        out = nn.Dense(hidden, name="lin2")(agg)

        if equivariant:
            # reference CFConv.coord_model: normalized diff (eps=1.0), sender-
            # mean aggregation (edge_index[0] convention), no tanh bound
            coord_diff = vec / (dist[:, None] + 1.0)
            equiv = equiv + equivariant_coordinate_update(
                w, coord_diff, batch.senders, batch.edge_mask, batch.num_nodes,
                nf, tanh_bound=False, name_prefix="coord", hints=batch,
            )

        return out, equiv
