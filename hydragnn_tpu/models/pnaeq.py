"""PNAEq conv stack (reference ``hydragnn/models/PNAEqStack.py:41-538``):
PaiNN-style scalar+vector channels where scalar messages are aggregated with
the PNA degree-scaled multi-aggregator (mean/min/max/std x identity/
amplification/attenuation/linear/inverse_linear) instead of a plain sum.

Per layer: message (Bessel rbf embed -> pre-MLP on [x_i, x_j, rbf(+edge)] ->
tanh/silu scalar MLP -> rbf-gated split into vector/edge gates + scalar
message; scalar degree-aggregated at the sender, vector sum-aggregated;
residual) then the shared PainnUpdate, then the output-size embeddings.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .painn import PainnUpdate
from .pna import avg_degree_linear, degree_scaled_aggregate, log_degree_mean
from .radial import BesselBasis

PNAEQ_AGGREGATORS = ("mean", "min", "max", "std")
PNAEQ_SCALERS = ("identity", "amplification", "attenuation", "linear", "inverse_linear")


@register_conv("PNAEq")
class PNAEqConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    feature_norm = False  # reference PNAEqStack uses Identity feature layers

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        out_dim = self.out_dim or spec.hidden_dim
        ns = inv.shape[-1]
        last_layer = self.layer >= spec.num_conv_layers - 1
        delta = log_degree_mean(spec.pna_deg or [0, 1])
        avg_lin = max(avg_degree_linear(spec.pna_deg or [0, 1]), 1.0)

        if equiv.ndim == 2:
            v = jnp.zeros((batch.num_nodes, 3, ns), inv.dtype)
        else:
            v = equiv

        vec = batch.pos[batch.receivers] - batch.pos[batch.senders] + batch.edge_shifts
        dist = jnp.sqrt(jnp.sum(vec * vec, axis=-1) + 1e-18)
        unit_vec = vec / (dist[:, None] + 1e-9)

        rbf = BesselBasis(
            num_radial=spec.num_radial or 6,
            cutoff=float(spec.radius or 5.0),
            envelope_exponent=spec.envelope_exponent or 5,
            name="rbf",
        )(dist)

        # pre-MLP on concatenated endpoint scalars + rbf embed (+ edge attr)
        rbf_attr = jnp.tanh(nn.Dense(ns, name="rbf_emb")(rbf))
        feats = [inv[batch.senders], inv[batch.receivers], rbf_attr]
        if spec.edge_dim and batch.edge_attr.shape[1]:
            feats.append(nn.Dense(ns, name="edge_encoder")(batch.edge_attr))
        h = jnp.concatenate(feats, axis=-1)
        h = nn.Dense(ns, name="pre_nn")(h)

        # scalar message MLP (tanh stabilized) and rbf gating
        m = nn.Dense(ns, name="scalar_mlp_0")(h)
        m = jnp.tanh(m)
        m = nn.Dense(ns, name="scalar_mlp_1")(m)
        m = nn.silu(m)
        m = nn.Dense(ns * 3, name="scalar_mlp_2")(m)
        m = m * nn.Dense(ns * 3, use_bias=False, name="rbf_lin")(rbf)

        gate_v, gate_edge, msg_s = jnp.split(m, 3, axis=-1)
        v_msg = (
            v[batch.receivers] * gate_v[:, None, :]
            + gate_edge[:, None, :] * unit_vec[:, :, None]
        )

        # scalar: degree-scaled aggregation at the sender + post MLP
        agg = degree_scaled_aggregate(
            msg_s * batch.edge_mask[:, None],
            batch.senders,
            batch.edge_mask,
            batch.num_nodes,
            delta,
            aggregators=PNAEQ_AGGREGATORS,
            scalers=PNAEQ_SCALERS,
            avg_deg_lin=avg_lin,
        )
        delta_x = nn.Dense(ns, name="post_nn")(jnp.concatenate([inv, agg], axis=-1))
        dv = segment.segment_sum(
            v_msg * batch.edge_mask[:, None, None], batch.senders, batch.num_nodes
        )
        s = inv + delta_x
        v = v + dv

        s, v = PainnUpdate(node_size=ns, last_layer=last_layer, name="update")(s, v)

        s = nn.Dense(out_dim, name="node_embed_0")(s)
        s = jnp.tanh(s)
        s = nn.Dense(out_dim, name="node_embed_1")(s)
        if not last_layer:
            v = nn.Dense(out_dim, use_bias=False, name="vec_embed")(v)
        return s, v
