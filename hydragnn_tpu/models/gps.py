"""GPS global attention (reference ``hydragnn/globalAtt/gps.py:32-159``):
every conv layer becomes  local MPNN + per-graph multi-head self-attention,
each with residual + norm, combined and passed through an MLP block.

TPU redesign: the reference densifies each batch with ``to_dense_batch`` and
runs ``nn.MultiheadAttention`` over [G, N_max, C] padded blocks — a
ragged->dense conversion per step. Here attention runs directly on the flat
padded node array with a same-graph mask (``batch[i] == batch[j]``): one
[H, N, N] masked softmax, no data movement, static shapes. O(N^2) over the
whole padded batch — within a graph it matches the reference's per-graph
O(n^2); a Pallas block-sparse kernel is the scale-up path for giant graphs.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

import dataclasses

from ..config.schema import EDGE_MODELS, ModelSpec
from ..graphs.graph import GraphBatch
from .base import CONV_REGISTRY
from .common import MaskedBatchNorm, get_activation


class GraphMultiheadAttention(nn.Module):
    """Self-attention restricted to nodes of the same graph."""

    channels: int
    heads: int

    @nn.compact
    def __call__(self, h: jax.Array, batch: GraphBatch, train: bool = False):
        N = h.shape[0]
        H = self.heads
        Dh = self.channels // H
        assert self.channels % H == 0, "hidden_dim must divide global_attn_heads"
        q = nn.Dense(self.channels, name="q")(h).reshape(N, H, Dh)
        k = nn.Dense(self.channels, name="k")(h).reshape(N, H, Dh)
        v = nn.Dense(self.channels, name="v")(h).reshape(N, H, Dh)
        logits = jnp.einsum("nhd,mhd->hnm", q, k) / jnp.sqrt(float(Dh))
        same_graph = batch.batch[:, None] == batch.batch[None, :]
        valid = same_graph & (batch.node_mask[None, :] > 0)
        logits = jnp.where(valid[None, :, :], logits, -1e9)
        attn = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("hnm,mhd->nhd", attn, v).reshape(N, self.channels)
        return nn.Dense(self.channels, name="out")(out)


class GPSConv(nn.Module):
    """One GPS layer wrapping the architecture's local MPNN conv."""

    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        C = spec.hidden_dim
        drop = nn.Dropout(rate=spec.dropout)
        act = get_activation(spec.activation)

        inner_cls = CONV_REGISTRY[spec.mpnn_type]
        inner_spec = spec
        if spec.mpnn_type in EDGE_MODELS and batch.rel_pe.shape[1] > 0:
            # relative-PE edge encodings for edge-capable convs (reference
            # Base.py:210-215: rel_pos_emb fused with any edge features)
            e = nn.Dense(C, use_bias=False, name="rel_pos_emb")(batch.rel_pe)
            if spec.edge_dim and batch.edge_attr.shape[1]:
                ea = nn.Dense(C, use_bias=False, name="edge_emb")(batch.edge_attr)
                e = nn.Dense(C, use_bias=False, name="edge_lin")(
                    jnp.concatenate([ea, e], axis=-1)
                )
            batch = batch.replace(edge_attr=e)
            inner_spec = dataclasses.replace(spec, edge_dim=C)
        h_local, equiv = inner_cls(spec=inner_spec, layer=self.layer, name="local")(
            inv, equiv, batch, train
        )
        h_local = drop(h_local, deterministic=not train)
        if h_local.shape[-1] == inv.shape[-1]:
            h_local = h_local + inv  # residual
        h_local = MaskedBatchNorm(name="norm1")(h_local, batch.node_mask, train)

        h_attn = GraphMultiheadAttention(
            channels=inv.shape[-1], heads=max(spec.global_attn_heads, 1), name="attn"
        )(inv, batch, train)
        h_attn = drop(h_attn, deterministic=not train)
        h_attn = h_attn + inv  # residual
        h_attn = MaskedBatchNorm(name="norm2")(h_attn, batch.node_mask, train)

        if h_local.shape[-1] != h_attn.shape[-1]:
            h_local = nn.Dense(h_attn.shape[-1], name="local_proj")(h_local)
        out = h_local + h_attn
        mlp = nn.Dense(out.shape[-1] * 2, name="mlp_0")(out)
        mlp = act(mlp)
        mlp = drop(mlp, deterministic=not train)
        mlp = nn.Dense(out.shape[-1], name="mlp_1")(mlp)
        mlp = drop(mlp, deterministic=not train)
        out = out + mlp
        out = MaskedBatchNorm(name="norm3")(out, batch.node_mask, train)
        return out, equiv
