"""GPS global attention (reference ``hydragnn/globalAtt/gps.py:32-159``):
every conv layer becomes  local MPNN + per-graph multi-head self-attention,
each with residual + norm, combined and passed through an MLP block.

TPU redesign of the reference's ``to_dense_batch`` + ``nn.MultiheadAttention``
/ ``PerformerAttention`` pair (``gps.py:55-67,126-133``):

* ``multihead``: nodes scatter into static dense blocks ``[G, N_max, C]``
  (``N_max`` = ``spec.max_graph_nodes``, derived from the dataset at config
  time), attention runs per graph — O(Σ nᵢ²) like the reference, not O((ΣN)²)
  over the padded batch. Graphs that outgrow ``N_max`` at inference flip the
  whole batch, in-program, to an exact flat masked-attention fallback.
* ``performer``: FAVOR+ linear attention computed directly on the flat node
  array — the per-graph softmax-kernel statistics are two ``segment_sum``s,
  so cost is O(N · m · d) with zero densification. This is the option for
  graphs where even per-graph dense attention is too big.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

import dataclasses

from ..config.schema import EDGE_MODELS, ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import CONV_REGISTRY
from .common import SYNC_BN_AXIS, MaskedBatchNorm, get_activation


def _positions_in_graph(batch: GraphBatch, n_max: int):
    """Per-node (graph_id, slot) coordinates for dense-block scatter/gather.
    Real nodes of a graph are contiguous, so slot = node_id − graph_start."""
    starts = jnp.cumsum(batch.n_node) - batch.n_node  # [G]
    slot = jnp.arange(batch.num_nodes) - starts[batch.batch]
    return jnp.clip(slot, 0, n_max - 1)


class GraphMultiheadAttention(nn.Module):
    """Self-attention restricted to nodes of the same graph.

    ``n_max > 0`` enables the dense-block path; otherwise (or when a graph
    exceeds ``n_max`` at runtime) the exact flat masked path runs.
    """

    channels: int
    heads: int
    n_max: int = 0
    ring: bool = False  # rotate K/V shards over the mesh (giant graphs)

    def _flat_attention(self, q, k, v, batch: GraphBatch):
        Dh = q.shape[-1]
        logits = jnp.einsum("nhd,mhd->hnm", q, k) / jnp.sqrt(float(Dh))
        same_graph = batch.batch[:, None] == batch.batch[None, :]
        valid = same_graph & (batch.node_mask[None, :] > 0)
        logits = jnp.where(valid[None, :, :], logits, -1e9)
        attn = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("hnm,mhd->nhd", attn, v)

    def _dense_attention(self, q, k, v, batch: GraphBatch):
        """Scatter to [G, n_max, H, Dh] blocks, per-graph softmax attention,
        gather back. Padded/overflow slots carry zero and are masked."""
        G = batch.num_graphs
        n_max = self.n_max
        Dh = q.shape[-1]
        slot = _positions_in_graph(batch, n_max)
        gid = batch.batch

        def to_dense(x):
            buf = jnp.zeros((G, n_max) + x.shape[1:], x.dtype)
            return buf.at[gid, slot].set(x * batch.node_mask[:, None, None])

        qd, kd, vd = to_dense(q), to_dense(k), to_dense(v)
        valid = jnp.arange(n_max)[None, :] < batch.n_node[:, None]  # [G, n_max]
        logits = jnp.einsum("gnhd,gmhd->ghnm", qd, kd) / jnp.sqrt(float(Dh))
        # the dense-block path itself is chosen at trace time off the
        # collate-certified bound (batch.meta.max_n_node below); the fused
        # kernel collapses its mask→max→exp→sum→divide per-row chain into
        # one Pallas pass (A/B: HYDRAGNN_FUSED_SOFTMAX, exact — rows are
        # independent, so no layout contract / fallback cond is needed)
        from ..ops import fused_softmax

        if fused_softmax._auto_enabled():
            attn = fused_softmax.fused_masked_softmax(
                logits, valid[:, None, None, :]
            )
        else:
            logits = jnp.where(valid[:, None, None, :], logits, -1e9)
            attn = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("ghnm,gmhd->gnhd", attn, vd)
        return out[gid, slot] * batch.node_mask[:, None, None]

    @nn.compact
    def __call__(self, h: jax.Array, batch: GraphBatch, train: bool = False):
        N = h.shape[0]
        H = self.heads
        Dh = self.channels // H
        assert self.channels % H == 0, "hidden_dim must divide global_attn_heads"
        q = nn.Dense(self.channels, name="q")(h).reshape(N, H, Dh)
        k = nn.Dense(self.channels, name="k")(h).reshape(N, H, Dh)
        v = nn.Dense(self.channels, name="v")(h).reshape(N, H, Dh)
        if self.ring:
            # giant-graph path: K/V shards rotate around the mesh ring with
            # an online softmax — O(N/D) peak memory, exact results. The user
            # asked for ring explicitly, so never silently hand them the
            # O(N²) flat path that defeats the point: an indivisible N is an
            # error (pad the bucket node count to a mesh multiple), and a
            # missing mesh warns loudly before degrading.
            from ..parallel.ring_attention import get_global_mesh, ring_attention

            mesh = get_global_mesh()
            if mesh is not None:
                ring_dev = mesh.shape["data"]
                if N % ring_dev:
                    raise ValueError(
                        f"global_attn_type 'ring' needs the padded node count "
                        f"({N}) divisible by the mesh data axis ({ring_dev}); "
                        f"pad the bucket n_node to a multiple of {ring_dev}"
                    )
                out = ring_attention(
                    q, k, v, batch.batch, batch.node_mask, mesh
                )
                return nn.Dense(self.channels, name="out")(
                    out.reshape(N, self.channels)
                )
            import warnings

            warnings.warn(
                "global_attn_type 'ring' requested but no global mesh is "
                "published (parallel.ring_attention.set_global_mesh); falling "
                "back to flat O(N^2) masked attention",
                stacklevel=2,
            )
        # dense-block vs exact flat attention: decided AT TRACE TIME whenever
        # collate certified a per-graph size bound (BatchMeta.max_n_node) — a
        # data-dependent lax.cond here lowers to select under vmap (the SPMD
        # per-device step), which would compute BOTH attentions every step.
        bound = batch.meta.max_n_node if batch.meta is not None else None
        if self.n_max and self.n_max < N:
            if bound is not None:
                if bound <= self.n_max:
                    out = self._dense_attention(q, k, v, batch)
                else:
                    out = self._flat_attention(q, k, v, batch)
            else:
                fits = jnp.all(batch.n_node <= self.n_max)
                out = jax.lax.cond(
                    fits,
                    lambda: self._dense_attention(q, k, v, batch),
                    lambda: self._flat_attention(q, k, v, batch),
                )
        else:
            out = self._flat_attention(q, k, v, batch)
        return nn.Dense(self.channels, name="out")(out.reshape(N, self.channels))


class PerformerAttention(nn.Module):
    """FAVOR+ softmax-kernel linear attention per graph (the reference's
    ``PerformerAttention`` option, ``gps.py:62-67``), on flat node arrays:

        out_i = φ(q_i) · Σ_{j∈g(i)} φ(k_j) v_jᵀ  /  φ(q_i) · Σ_{j∈g(i)} φ(k_j)

    with φ the positive random-feature map exp(w·x − ‖x‖²/2). The per-graph
    sums are ``segment_sum``s over nodes — O(N·m·d), no densification.
    """

    channels: int
    heads: int
    num_features: int = 0  # default: Dh rounded up to 8

    @nn.compact
    def __call__(self, h: jax.Array, batch: GraphBatch, train: bool = False):
        N = h.shape[0]
        H = self.heads
        Dh = self.channels // H
        m = self.num_features or max(8, (Dh + 7) // 8 * 8)
        q = nn.Dense(self.channels, name="q")(h).reshape(N, H, Dh)
        k = nn.Dense(self.channels, name="k")(h).reshape(N, H, Dh)
        v = nn.Dense(self.channels, name="v")(h).reshape(N, H, Dh)

        # Fixed (non-trainable) projection, seeded per layer from the module
        # path: independent draws across depth keep the per-layer FAVOR+
        # estimates unbiased instead of compounding one shared error.
        import zlib

        seed = zlib.crc32("/".join(self.path).encode()) & 0x7FFFFFFF
        w = jax.random.normal(jax.random.PRNGKey(seed), (H, Dh, m), h.dtype)
        scale = float(Dh) ** -0.25

        def phi(x, stab):
            proj = jnp.einsum("nhd,hdm->nhm", x * scale, w)
            norm = 0.5 * jnp.sum((x * scale) ** 2, axis=-1, keepdims=True)
            return jnp.exp(proj - norm - stab) / jnp.sqrt(float(m))

        # stabilizers: per-row max for q (cancels in the ratio) and per-GRAPH
        # max for k — uniform within a graph so it cancels exactly in num/den,
        # and graph-local so no numerical coupling between graphs exists
        G = batch.num_graphs
        kproj = jnp.einsum("nhd,hdm->nhm", k * scale, w)
        per_node = jax.lax.stop_gradient(kproj.max(axis=-1))  # [N, H]
        per_graph = segment.segment_max(per_node, batch.batch, G)  # [G, H]
        k_stab = per_graph[batch.batch][:, :, None]
        qproj = jnp.einsum("nhd,hdm->nhm", q * scale, w)
        q_stab = jax.lax.stop_gradient(qproj.max(axis=-1, keepdims=True))

        qp = phi(q, q_stab)  # [N, H, m]
        kp = phi(k, k_stab) * batch.node_mask[:, None, None]

        kv = segment.segment_sum(
            (kp[:, :, :, None] * v[:, :, None, :]).reshape(N, H * m * Dh),
            batch.batch, G, hints=batch,
        ).reshape(G, H, m, Dh)
        z = segment.segment_sum(kp.reshape(N, H * m), batch.batch, G, hints=batch).reshape(G, H, m)

        num = jnp.einsum("nhm,nhmd->nhd", qp, kv[batch.batch])
        den = jnp.einsum("nhm,nhm->nh", qp, z[batch.batch])
        out = num / jnp.maximum(den, 1e-9)[..., None]
        out = out * batch.node_mask[:, None, None]
        return nn.Dense(self.channels, name="out")(out.reshape(N, self.channels))


class GPSConv(nn.Module):
    """One GPS layer wrapping the architecture's local MPNN conv."""

    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        C = spec.hidden_dim
        drop = nn.Dropout(rate=spec.dropout)
        act = get_activation(spec.activation)

        inner_cls = CONV_REGISTRY[spec.mpnn_type]
        inner_spec = spec
        if spec.mpnn_type in EDGE_MODELS and batch.rel_pe.shape[1] > 0:
            # relative-PE edge encodings for edge-capable convs (reference
            # Base.py:210-215: rel_pos_emb fused with any edge features)
            e = nn.Dense(C, use_bias=False, name="rel_pos_emb")(batch.rel_pe)
            if spec.edge_dim and batch.edge_attr.shape[1]:
                ea = nn.Dense(C, use_bias=False, name="edge_emb")(batch.edge_attr)
                e = nn.Dense(C, use_bias=False, name="edge_lin")(
                    jnp.concatenate([ea, e], axis=-1)
                )
            batch = batch.replace(edge_attr=e)
            inner_spec = dataclasses.replace(spec, edge_dim=C)
        h_local, equiv = inner_cls(spec=inner_spec, layer=self.layer, name="local")(
            inv, equiv, batch, train
        )
        h_local = drop(h_local, deterministic=not train)
        if h_local.shape[-1] == inv.shape[-1]:
            h_local = h_local + inv  # residual
        h_local = MaskedBatchNorm(name="norm1", axis_name=(SYNC_BN_AXIS if spec.sync_batch_norm else None))(h_local, batch.node_mask, train)

        attn_type = spec.global_attn_type or "multihead"
        if attn_type == "performer":
            attn_mod = PerformerAttention(
                channels=inv.shape[-1], heads=max(spec.global_attn_heads, 1),
                name="attn",
            )
        else:
            attn_mod = GraphMultiheadAttention(
                channels=inv.shape[-1], heads=max(spec.global_attn_heads, 1),
                n_max=spec.max_graph_nodes or 0, ring=(attn_type == "ring"),
                name="attn",
            )
        h_attn = attn_mod(inv, batch, train)
        h_attn = drop(h_attn, deterministic=not train)
        h_attn = h_attn + inv  # residual
        h_attn = MaskedBatchNorm(name="norm2", axis_name=(SYNC_BN_AXIS if spec.sync_batch_norm else None))(h_attn, batch.node_mask, train)

        if h_local.shape[-1] != h_attn.shape[-1]:
            h_local = nn.Dense(h_attn.shape[-1], name="local_proj")(h_local)
        out = h_local + h_attn
        mlp = nn.Dense(out.shape[-1] * 2, name="mlp_0")(out)
        mlp = act(mlp)
        mlp = drop(mlp, deterministic=not train)
        mlp = nn.Dense(out.shape[-1], name="mlp_1")(mlp)
        mlp = drop(mlp, deterministic=not train)
        out = out + mlp
        out = MaskedBatchNorm(name="norm3", axis_name=(SYNC_BN_AXIS if spec.sync_batch_norm else None))(out, batch.node_mask, train)
        return out, equiv
