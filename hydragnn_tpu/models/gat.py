"""GATv2 conv stack (reference ``hydragnn/models/GATStack.py``, PyG
``GATv2Conv`` with heads=6, add_self_loops=True).

Reference head layout (``GATStack._init_conv``): layers 0..L-2 concatenate
heads (features = hidden*heads), the last layer averages them (features =
hidden). Attention logits use the GATv2 form a^T LeakyReLU(W_l x_i + W_r x_j
[+ W_e e_ij]) with softmax over each receiver's in-edges *including* a self
loop. Self loops are materialized as N extra static edge slots (senders =
receivers = arange(N)) so shapes stay jit-constant.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv

HEADS = 6  # reference create.py:263 hardcodes 6 attention heads
NEGATIVE_SLOPE = 0.05  # reference create.py:264


@register_conv("GAT")
class GATConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None
    concat_override: bool | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        hidden = self.out_dim or spec.hidden_dim
        # last conv layer averages heads instead of concatenating
        concat = (
            self.concat_override
            if self.concat_override is not None
            else self.layer < spec.num_conv_layers - 1
        )
        N = batch.num_nodes
        H, F = HEADS, hidden

        x_l = nn.Dense(H * F, name="lin_l")(inv).reshape(N, H, F)
        x_r = nn.Dense(H * F, name="lin_r")(inv).reshape(N, H, F)
        att = self.param("att", nn.initializers.lecun_normal(), (H, F))

        # real edges + one self-loop slot per node (static shapes), with
        # `self_loop_pad` masked alignment slots between the sections so the
        # arange section starts on a fused-softmax block boundary — the
        # layout BatchMeta.attn_fits certifies (ops/fused_softmax.py). The
        # pad slots are dummy-wired (node N-1, mask 0): their logits are
        # masked to -1e9 below and their messages are zeroed, so the XLA
        # path's results are bit-unchanged by the extra slots.
        from ..ops.fused_softmax import self_loop_pad

        sl_pad = self_loop_pad(batch.num_edges)
        pad_ids = jnp.full((sl_pad,), N - 1, batch.senders.dtype)
        loop = jnp.arange(N, dtype=batch.senders.dtype)
        senders = jnp.concatenate([batch.senders, pad_ids, loop])
        receivers = jnp.concatenate([batch.receivers, pad_ids, loop])
        e_mask = jnp.concatenate([
            batch.edge_mask,
            jnp.zeros((sl_pad,), batch.edge_mask.dtype),
            jnp.ones((N,), batch.edge_mask.dtype),
        ])

        z = x_l[senders] + x_r[receivers]  # [E+N, H, F]
        if spec.edge_dim:
            # self-loop edge features use the mean of each node's incident
            # real edge features (PyG add_self_loops fill_value='mean')
            masked_ea = batch.edge_attr * batch.edge_mask[:, None]
            ea_sum = segment.segment_sum(masked_ea, batch.receivers, N)
            deg = segment.segment_sum(batch.edge_mask, batch.receivers, N)
            self_ea = ea_sum / jnp.maximum(deg, 1.0)[:, None]
            ea = jnp.concatenate([
                batch.edge_attr,
                jnp.zeros((sl_pad,) + batch.edge_attr.shape[1:],
                          batch.edge_attr.dtype),
                self_ea,
            ], axis=0)
            z = z + nn.Dense(H * F, name="lin_edge")(ea).reshape(-1, H, F)
        z = nn.leaky_relu(z, negative_slope=NEGATIVE_SLOPE)
        logits = jnp.einsum("ehf,hf->eh", z, att)
        # mask padded edges out of the softmax
        logits = jnp.where(e_mask[:, None] > 0, logits, -1e9)
        # collate certifies this exact extended-receivers layout for the
        # fused segment-softmax kernel (BatchMeta.attn_fits); seg_hint can't
        # resolve it (new array), so the certificate rides explicitly
        attn_fits = batch.meta.attn_fits if batch.meta is not None else None
        alpha = segment.segment_softmax(
            logits, receivers, N, hints=batch, fits=attn_fits
        )  # [E+pad+N, H]
        alpha = alpha * e_mask[:, None]
        # attention-coefficient dropout (reference GATv2Conv dropout=0.25)
        alpha = nn.Dropout(rate=self.spec.dropout, name="attn_drop")(
            alpha, deterministic=not train
        )

        msg = x_l[senders] * alpha[:, :, None]  # [E+N, H, F]
        out = segment.segment_sum(msg, receivers, N)  # [N, H, F]
        out = out.reshape(N, H * F) if concat else out.mean(axis=1)
        return out, equiv
