"""GraphSAGE conv stack (reference ``hydragnn/models/SAGEStack.py:21-47``,
PyG ``SAGEConv`` with mean aggregation):
h_i' = W_root x_i + W_nbr mean_j x_j."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv


@register_conv("SAGE")
class SAGEConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        hidden = self.out_dim or self.spec.hidden_dim
        # fused gather+mask+scatter (ops.fused_scatter), then the neighbor
        # mean; padded edges route to the dummy node so the masked count is
        # already the real in-degree
        from ..ops import gather_scatter_sum

        N = batch.num_nodes
        total = gather_scatter_sum(
            inv, batch.senders, batch.receivers, N,
            weight=batch.edge_mask.astype(inv.dtype), hints=batch,
        )
        count = segment.segment_count(batch.receivers, N, weights=batch.edge_mask)
        agg = total / jnp.maximum(count, 1e-12).astype(total.dtype)[:, None]
        out = nn.Dense(hidden, name="lin_root")(inv) + nn.Dense(hidden, name="lin_nbr")(agg)
        return out, equiv
