"""GraphSAGE conv stack (reference ``hydragnn/models/SAGEStack.py:21-47``,
PyG ``SAGEConv`` with mean aggregation):
h_i' = W_root x_i + W_nbr mean_j x_j."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv


@register_conv("SAGE")
class SAGEConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        hidden = self.out_dim or self.spec.hidden_dim
        # padded edges route to the dummy node, so segment_mean over receivers
        # is already the masked neighbor mean for real nodes
        msg = inv[batch.senders] * batch.edge_mask[:, None]
        agg = segment.segment_mean(msg, batch.receivers, batch.num_nodes)
        out = nn.Dense(hidden, name="lin_root")(inv) + nn.Dense(hidden, name="lin_nbr")(agg)
        return out, equiv
