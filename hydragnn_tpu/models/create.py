"""Model factory: config dict -> HydraModel (reference ``models/create.py``).

The reference dispatches on ``mpnn_type`` across 13 stack classes, passing
string signatures of conv inputs for PyG Sequential (``create.py:112-766``).
Here each architecture registers a conv module in ``CONV_REGISTRY`` with one
uniform call contract, and the factory just builds the typed ``ModelSpec`` and
instantiates ``HydraModel`` (plus the MLIP wrapper when
``enable_interatomic_potential`` — reference ``create.py:590-758``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from .base import CONV_REGISTRY, HydraModel

# Importing architecture modules populates CONV_REGISTRY.
from . import gin  # noqa: F401

_IMPORT_ERRORS: dict[str, Exception] = {}
for _mod in (
    "sage", "gat", "mfc", "cgcnn", "pna", "pnaplus", "schnet",
    "dimenet", "egnn", "painn", "pnaeq", "mace",
):
    try:
        __import__(f"{__name__.rsplit('.', 1)[0]}.{_mod}")
    except ImportError as e:  # arch not built yet; factory errors on use
        _IMPORT_ERRORS[_mod] = e


def create_model_config(config: dict) -> HydraModel:
    """Build the model from an *augmented* config dict (after
    ``hydragnn_tpu.config.update_config``)."""
    return create_model(ModelSpec.from_config(config))


def create_model(spec: ModelSpec) -> HydraModel:
    if spec.mpnn_type not in CONV_REGISTRY:
        known = sorted(CONV_REGISTRY)
        hint = ""
        failed = _IMPORT_ERRORS.get(spec.mpnn_type.lower())
        if failed is not None:
            hint = (
                f" The '{spec.mpnn_type.lower()}' module exists but failed to "
                f"import: {failed!r}."
            )
        raise ValueError(
            f"Unknown or not-yet-registered mpnn_type '{spec.mpnn_type}'. "
            f"Registered: {known}.{hint}"
        )
    return HydraModel(spec=spec)


def init_model(model: HydraModel, example_batch, rng=None):
    """Initialize parameters + batch stats on an example batch."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    example_batch = jax.tree.map(jnp.asarray, example_batch)
    variables = model.init(rng, example_batch, train=False)
    return variables
