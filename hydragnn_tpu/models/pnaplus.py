"""PNAPlus conv stack (reference ``hydragnn/models/PNAPlusStack.py:40-304``):
PNA with Bessel radial embeddings of edge lengths — messages are
pre_nn([x_i, x_j, rbf_emb(rbf)]) Hadamard-gated by a linear projection of the
rbf, aggregated with the same degree-scaled multi-aggregator as PNA
(identity/amplification/attenuation/linear scalers).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from .base import register_conv
from .pna import AGGREGATORS, SCALERS, avg_degree_linear, degree_scaled_aggregate, log_degree_mean
from .radial import BesselBasis


@register_conv("PNAPlus")
class PNAPlusConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        hidden = self.out_dim or spec.hidden_dim
        F = inv.shape[-1]
        delta = log_degree_mean(spec.pna_deg or [0, 1])
        avg_lin = avg_degree_linear(spec.pna_deg or [0, 1])

        dist = batch.edge_lengths().reshape(-1)
        rbf = BesselBasis(
            num_radial=spec.num_radial or 6,
            cutoff=spec.radius or 5.0,
            envelope_exponent=spec.envelope_exponent or 5,
            name="rbf",
        )(dist)

        rbf_feat = nn.relu(nn.Dense(F, name="rbf_emb")(rbf))
        if spec.edge_dim and batch.edge_attr.shape[1]:
            ea = jnp.concatenate([batch.edge_attr, rbf_feat], axis=-1)
            ea = nn.Dense(F, name="edge_encoder")(ea)
        else:
            ea = rbf_feat
        h = jnp.concatenate([inv[batch.receivers], inv[batch.senders], ea], axis=-1)
        msg = nn.Dense(F, name="pre_nn")(h)
        # Hadamard gate by projected rbf (PNAPlusStack message :253-280)
        msg = msg * nn.Dense(F, use_bias=False, name="rbf_lin")(rbf)

        agg = degree_scaled_aggregate(
            msg,
            batch.receivers,
            batch.edge_mask,
            batch.num_nodes,
            delta,
            aggregators=AGGREGATORS,
            scalers=SCALERS,
            avg_deg_lin=avg_lin,
        )
        out = jnp.concatenate([inv, agg], axis=-1)
        out = nn.Dense(hidden, name="post_nn")(out)
        out = nn.Dense(hidden, name="lin")(out)
        return out, equiv
