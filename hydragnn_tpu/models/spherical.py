"""Spherical Bessel / harmonic basis for DimeNet's directional messages.

Reference: PyG ``SphericalBasisLayer`` (used by ``DIMEStack.py:70-73``), which
sympy-generates j_l and Y_l^0 formulas. Here: spherical Bessel functions via
the standard upward recurrence, their roots precomputed with scipy at module
*build* time (host numpy, cached), and m=0 real spherical harmonics as
Legendre polynomials — all plain jnp elementwise math that XLA fuses.

    sbf[t, l*num_radial + n] = envelope(d/c) * j_l(z_{l,n} d/c) * P_l(cos(angle))

matching DimeNet's normalization (each radial slice scaled by
1/|j_{l+1}(z_{l,n})|, angular part sqrt((2l+1)/4pi) folded into learned
weights downstream — we keep plain P_l like PyG's generated code does for l=0
normalization consistency).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def spherical_bessel_roots(num_spherical: int, num_radial: int) -> tuple:
    """First ``num_radial`` positive roots of j_l for l < num_spherical."""
    from scipy import optimize, special

    roots = np.zeros((num_spherical, num_radial))
    # j_0 roots are n*pi; use them as brackets that shift with l
    for l in range(num_spherical):
        found = []
        x = 1e-6
        step = 0.1
        prev = special.spherical_jn(l, x)
        while len(found) < num_radial:
            x2 = x + step
            cur = special.spherical_jn(l, x2)
            if prev == 0.0:
                prev = cur
                x = x2
                continue
            if np.sign(prev) != np.sign(cur):
                r = optimize.brentq(lambda t: special.spherical_jn(l, t), x, x2)
                if r > 1e-4:
                    found.append(r)
            prev = cur
            x = x2
        roots[l] = found[:num_radial]
    return tuple(map(tuple, roots))


@functools.lru_cache(maxsize=None)
def _normalizers(num_spherical: int, num_radial: int) -> tuple:
    from scipy import special

    roots = np.asarray(spherical_bessel_roots(num_spherical, num_radial))
    norm = np.zeros_like(roots)
    for l in range(num_spherical):
        norm[l] = 1.0 / np.abs(special.spherical_jn(l + 1, roots[l]))
    return tuple(map(tuple, norm))


import functools as _functools


@_functools.partial(jax.custom_jvp, nondiff_argnums=(0,))
def _sph_jn_stack(l_max: int, x: jnp.ndarray) -> jnp.ndarray:
    """Stacked [l_max+1, ...] spherical Bessel values with an *analytic*
    derivative (``j_l' = j_{l-1} - (l+1)/x j_l``).

    The custom JVP is load-bearing: the primal blends upward and Miller
    recurrences whose intermediate values overflow float32 outside their
    stability regions; autodiff through the unselected ``where`` branch then
    produces 0 * inf = NaN cotangents (this killed DimeNet force training).
    The analytic derivative only touches the final, finite values.
    """
    return jnp.stack(_spherical_jn_primal(l_max, x))


@_sph_jn_stack.defjvp
def _sph_jn_jvp(l_max, primals, tangents):
    (x,), (dx,) = primals, tangents
    safe = jnp.maximum(x, 0.05)
    j_full = jnp.stack(_spherical_jn_primal(l_max + 1, x))
    out = j_full[: l_max + 1]
    derivs = [-j_full[1]]  # j_0' = -j_1
    for l in range(1, l_max + 1):
        derivs.append(j_full[l - 1] - (l + 1) / safe * j_full[l])
    # clamp region (x < 0.05): zero derivative, matching jnp.maximum's choice
    grad = jnp.stack(derivs) * jnp.where(x >= 0.05, 1.0, 0.0)
    return out, grad * dx


def _spherical_jn(l_max: int, x: jnp.ndarray) -> list:
    stacked = _sph_jn_stack(l_max, x)
    return [stacked[l] for l in range(l_max + 1)]


def _spherical_jn_primal(l_max: int, x: jnp.ndarray) -> list:
    """j_0..j_{l_max}, stable over the full argument range.

    Upward recurrence from the analytic j_0/j_1 is stable only for x > l (it
    amplifies the irregular solution y_l below that; padded edges with x ~ 0
    overflow it to inf). Miller's downward recurrence is stable for x < l but
    its truncated start loses accuracy for x >> l. So: compute both and select
    per (l, x). Downward is normalized against whichever of j_0/j_1 is larger
    in magnitude at each x (normalizing only by j_0 breaks at its zeros).
    x is clamped to >= 0.05; callers mask padded (x ~ 0) entries.
    """
    safe = jnp.maximum(x, 0.05)
    j0 = jnp.sin(safe) / safe
    j1 = jnp.sin(safe) / safe**2 - jnp.cos(safe) / safe

    # upward recurrence (stable region x > l)
    up = [j0, j1]
    for l in range(2, l_max + 1):
        up.append((2 * l - 1) / safe * up[l - 1] - up[l - 2])

    # Miller downward recurrence
    L = l_max + 8
    jp1 = jnp.zeros_like(safe)
    j = jnp.full_like(safe, 1e-18)
    store: dict[int, jnp.ndarray] = {}
    for l in range(L, 0, -1):
        jm1 = (2 * l + 1) / safe * j - jp1
        jp1 = j
        j = jm1
        if l - 1 <= max(l_max, 1):
            store[l - 1] = j
    use_j0 = jnp.abs(j0) >= jnp.abs(j1)
    num = jnp.where(use_j0, j0, j1)
    den = jnp.where(use_j0, store[0], store[1])
    scale = num / jnp.where(den == 0, 1.0, den)
    down = [store[l] * scale if l in store else up[l] for l in range(l_max + 1)]

    out = [j0]
    for l in range(1, l_max + 1):
        out.append(jnp.where(safe > l, up[l], down[l]))
    return out


def _legendre(l_max: int, x: jnp.ndarray) -> list:
    p = [jnp.ones_like(x)]
    if l_max >= 1:
        p.append(x)
    for l in range(2, l_max + 1):
        p.append(((2 * l - 1) * x * p[l - 1] - (l - 1) * p[l - 2]) / l)
    return p


def spherical_basis(
    dist: jnp.ndarray,
    angle: jnp.ndarray,
    idx_kj: jnp.ndarray,
    num_spherical: int,
    num_radial: int,
    cutoff: float,
    envelope_exponent: int = 5,
) -> jnp.ndarray:
    """[T] distances (of edge kj, gathered via idx_kj), [T] angles ->
    [T, num_spherical * num_radial] basis values."""
    from .radial import polynomial_envelope

    roots = jnp.asarray(spherical_bessel_roots(num_spherical, num_radial))
    norms = jnp.asarray(_normalizers(num_spherical, num_radial))
    d = dist[idx_kj] / cutoff  # [T]
    env = polynomial_envelope(d, envelope_exponent)  # [T]
    real = (d > 1e-6).astype(env.dtype)  # padded triplets -> exact zeros
    cos_angle = jnp.cos(angle)

    legendre = _legendre(num_spherical - 1, cos_angle)  # list of [T]
    out = []
    for l in range(num_spherical):
        arg = roots[l][None, :] * d[:, None]  # [T, num_radial]
        jl = _spherical_jn(l, arg)[l]  # [T, num_radial]
        radial = (env * real)[:, None] * jl * norms[l][None, :]
        out.append(radial * legendre[l][:, None])
    return jnp.concatenate(out, axis=-1)  # [T, S*R]
