from .base import HydraModel, CONV_REGISTRY, register_conv, head_columns
from .create import create_model, create_model_config, init_model
from .common import MLP, MaskedBatchNorm, get_activation, get_loss

__all__ = [
    "HydraModel",
    "CONV_REGISTRY",
    "register_conv",
    "head_columns",
    "create_model",
    "create_model_config",
    "init_model",
    "MLP",
    "MaskedBatchNorm",
    "get_activation",
    "get_loss",
]
