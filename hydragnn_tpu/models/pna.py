"""PNA conv stack (reference ``hydragnn/models/PNAStack.py:19-70``, PyG
``PNAConv``): Principal Neighbourhood Aggregation — multi-aggregator
(mean/min/max/std) message passing with degree-dependent scalers
(identity/amplification/attenuation/linear, reference ``PNAStack.py:31-36``)
calibrated on the training-set degree histogram (``pna_deg`` derived in
config augmentation).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv

AGGREGATORS = ("mean", "min", "max", "std")
SCALERS = ("identity", "amplification", "attenuation", "linear")


def avg_degree_linear(deg_hist) -> float:
    """Plain mean degree — normalizer for the 'linear' scaler."""
    hist = np.asarray(deg_hist, np.float64)
    d = np.arange(len(hist))
    total = hist.sum()
    return float((d * hist).sum() / total) if total else 1.0


def log_degree_mean(deg_hist) -> float:
    """delta = E_hist[log(d+1)] — the scaler normalization constant (PyG
    ``DegreeScalerAggregation``)."""
    hist = np.asarray(deg_hist, np.float64)
    d = np.arange(len(hist))
    total = hist.sum()
    if total == 0:
        return 1.0
    return float((np.log(d + 1) * hist).sum() / total)


def degree_scaled_aggregate(
    msg: jax.Array,
    receivers: jax.Array,
    edge_mask: jax.Array,
    num_nodes: int,
    delta: float,
    aggregators=AGGREGATORS,
    scalers=SCALERS,
    avg_deg_lin: float | None = None,
    hints=None,
) -> jax.Array:
    """[E, F] messages -> [N, len(aggr)*len(scalers)*F] aggregated features.

    Masking: padded edges carry zeroed messages for sum/mean; for min/max/std
    they are routed to the dummy node slot already (receivers point at the
    padded node), so real-node statistics are unaffected.
    """
    # padded edges already route to the dummy node slot, so the plain segment
    # reductions see only real edges at real receivers (segment.py contract)
    msg_sum = msg * edge_mask[:, None]
    deg = segment.segment_sum(edge_mask, receivers, num_nodes)
    outs = []
    for a in aggregators:
        if a == "mean":
            outs.append(segment.segment_mean(msg_sum, receivers, num_nodes, hints=hints))
        elif a == "min":
            outs.append(segment.segment_min(msg, receivers, num_nodes, hints=hints))
        elif a == "max":
            outs.append(segment.segment_max(msg, receivers, num_nodes, hints=hints))
        elif a == "std":
            outs.append(segment.segment_std(msg, receivers, num_nodes, hints=hints))
        elif a == "sum":
            outs.append(segment.segment_sum(msg_sum, receivers, num_nodes, hints))
        else:
            raise ValueError(f"unknown aggregator {a}")
    agg = jnp.concatenate(outs, axis=-1)  # [N, A*F]

    # PyG DegreeScalerAggregation clamps deg to >=1 before the scalers —
    # without it a degree-0 node (padded dummy rows, isolated atoms) gets
    # attenuation scale delta/log(1) -> ~1e6, which compounds per layer into
    # inf/NaN on deep stacks
    deg_c = jnp.maximum(deg, 1.0)
    log_deg = jnp.log(deg_c + 1.0)
    scaled = []
    for s in scalers:
        if s == "identity":
            scaled.append(agg)
        elif s == "amplification":
            scaled.append(agg * (log_deg / delta)[:, None])
        elif s == "attenuation":
            scaled.append(agg * (delta / log_deg)[:, None])
        elif s == "linear":
            scaled.append(agg * (deg_c / max(avg_deg_lin or 1.0, 1e-6))[:, None])
        elif s == "inverse_linear":
            scaled.append(agg * ((avg_deg_lin or 1.0) / deg_c)[:, None])
        else:
            raise ValueError(f"unknown scaler {s}")
    return jnp.concatenate(scaled, axis=-1)  # [N, A*S*F]


@register_conv("PNA")
class PNAConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        hidden = self.out_dim or spec.hidden_dim
        F = inv.shape[-1]
        delta = log_degree_mean(spec.pna_deg or [0, 1])

        h = jnp.concatenate([inv[batch.receivers], inv[batch.senders]], axis=-1)
        if spec.edge_dim and batch.edge_attr.shape[1]:
            h = jnp.concatenate([h, batch.edge_attr], axis=-1)
        msg = nn.Dense(F, name="pre_nn")(h)  # pre_layers=1 (reference)

        agg = degree_scaled_aggregate(
            msg,
            batch.receivers,
            batch.edge_mask,
            batch.num_nodes,
            delta,
            avg_deg_lin=avg_degree_linear(spec.pna_deg or [0, 1]),
        )
        out = jnp.concatenate([inv, agg], axis=-1)
        out = nn.Dense(hidden, name="post_nn")(out)  # post_layers=1
        out = nn.Dense(hidden, name="lin")(out)
        return out, equiv
