"""PaiNN conv stack (reference ``hydragnn/models/PAINNStack.py:27-352``):
polarizable atom interaction network with scalar [N, F] + vector [N, 3, F]
channels.

Per layer (PainnMessage + PainnUpdate + output embeds, ``get_conv :76-120``):
  message: filter = W(sinc_rbf(d)) * cos_cutoff(d) (optionally * edge filter);
           (gate_v | gate_edge | msg_s) = split(filter * MLP(s)[other end]);
           v_msg = v[other] * gate_v + gate_edge * d_hat;  residual sum-agg.
  update:  Uv, Vv = channel linears on v; (a_vv | a_sv | a_ss) =
           MLP([||Vv||, s]); dv = a_vv * Uv; ds = a_sv * <Uv, Vv> + a_ss.
  embed:   s -> Linear-Tanh-Linear to output dim; v -> channel Linear
           (skipped on the last layer, which drops the vector update too).

Vector-channel linears are bias-free: the reference uses ``nn.Linear`` with
bias on [N, 3, F] tensors, which adds the same offset to every spatial
component and silently breaks rotation equivariance — a reference bug we do
not reproduce. Aggregation is at the edge *sender* (reference ``index_add_(0,
edge[:, 0], ...)``); v initializes to zeros at the first layer
(``_embedding :190``).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .radial import cosine_cutoff, sinc_expansion


class PainnMessage(nn.Module):
    node_size: int
    num_radial: int
    cutoff: float
    use_edge_attr: bool

    @nn.compact
    def __call__(self, s, v, batch: GraphBatch, dist, unit_vec):
        ns = self.node_size
        filter_w = nn.Dense(ns * 3, name="filter_layer")(
            sinc_expansion(dist, self.num_radial, self.cutoff)
        )
        filter_w = filter_w * cosine_cutoff(dist, self.cutoff)[:, None]
        if self.use_edge_attr and batch.edge_attr.shape[1]:
            ef = nn.Dense(ns, name="edge_filter_0")(batch.edge_attr)
            ef = nn.silu(ef)
            ef = nn.Dense(ns * 3, name="edge_filter_1")(ef)
            filter_w = filter_w * ef

        scalar_out = nn.Dense(ns, name="scalar_mlp_0")(s)
        scalar_out = nn.silu(scalar_out)
        scalar_out = nn.Dense(ns * 3, name="scalar_mlp_1")(scalar_out)
        filter_out = filter_w * scalar_out[batch.receivers]  # "other" end features

        gate_v, gate_edge, msg_s = jnp.split(filter_out, 3, axis=-1)
        v_msg = v[batch.receivers] * gate_v[:, None, :] + gate_edge[:, None, :] * unit_vec[:, :, None]

        em = batch.edge_mask
        ds = segment.segment_sum(msg_s * em[:, None], batch.senders, batch.num_nodes, hints=batch)
        dv = segment.segment_sum(
            v_msg * em[:, None, None], batch.senders, batch.num_nodes
        )
        return s + ds, v + dv


class PainnUpdate(nn.Module):
    node_size: int
    last_layer: bool

    @nn.compact
    def __call__(self, s, v):
        ns = self.node_size
        # bias-free channel mixes keep rotation equivariance exact
        Uv = nn.Dense(ns, use_bias=False, name="update_U")(v)
        Vv = nn.Dense(ns, use_bias=False, name="update_V")(v)
        Vv_norm = jnp.sqrt(jnp.sum(Vv * Vv, axis=1) + 1e-16)
        h = jnp.concatenate([Vv_norm, s], axis=-1)
        h = nn.Dense(ns, name="update_mlp_0")(h)
        h = nn.silu(h)
        out_mult = 2 if self.last_layer else 3
        h = nn.Dense(ns * out_mult, name="update_mlp_1")(h)
        inner = jnp.sum(Uv * Vv, axis=1)  # [N, ns]
        if self.last_layer:
            a_sv, a_ss = jnp.split(h, 2, axis=-1)
            return s + a_sv * inner + a_ss, v
        a_vv, a_sv, a_ss = jnp.split(h, 3, axis=-1)
        return s + a_sv * inner + a_ss, v + a_vv[:, None, :] * Uv


@register_conv("PAINN")
class PaiNNConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    feature_norm = False  # reference PAINNStack uses Identity feature layers

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        out_dim = self.out_dim or spec.hidden_dim
        ns = inv.shape[-1]
        last_layer = self.layer >= spec.num_conv_layers - 1

        # first layer receives positions as `equiv`; vector channel starts 0
        if equiv.ndim == 2:
            v = jnp.zeros((batch.num_nodes, 3, ns), inv.dtype)
        else:
            v = equiv

        vec = batch.pos[batch.receivers] - batch.pos[batch.senders] + batch.edge_shifts
        dist = jnp.sqrt(jnp.sum(vec * vec, axis=-1) + 1e-18)
        unit_vec = vec / dist[:, None]

        s, v = PainnMessage(
            node_size=ns,
            num_radial=spec.num_radial or 6,
            cutoff=float(spec.radius or 5.0),
            use_edge_attr=bool(spec.edge_dim),
            name="message",
        )(inv, v, batch, dist, unit_vec)
        s, v = PainnUpdate(node_size=ns, last_layer=last_layer, name="update")(s, v)

        # size embeddings (reference node_embed_out / vec_embed_out)
        s = nn.Dense(out_dim, name="node_embed_0")(s)
        s = jnp.tanh(s)
        s = nn.Dense(out_dim, name="node_embed_1")(s)
        if not last_layer:
            v = nn.Dense(out_dim, use_bias=False, name="vec_embed")(v)
        return s, v
