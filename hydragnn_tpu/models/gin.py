"""GIN — Graph Isomorphism Network conv stack.

Capability parity with reference ``hydragnn/models/GINStack.py:21-49`` (PyG
``GINConv`` with ``train_eps=True``): message = neighbor sum, update =
MLP((1+eps) * h_i + sum_j h_j). Invariant-only; positions pass through
untouched (reference returns ``equiv_node_feat`` unchanged).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .common import MLP


@register_conv("GIN")
class GINConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        hidden = self.out_dim or self.spec.hidden_dim
        eps = self.param("eps", nn.initializers.zeros, ())
        # fully-fused gather→mask→scatter (ops.fused_scatter); falls back to
        # take + segment_sum when the kernel is disabled or shapes don't fit
        from ..ops import gather_scatter_sum

        agg = gather_scatter_sum(
            inv, batch.senders, batch.receivers, batch.num_nodes,
            weight=batch.edge_mask.astype(inv.dtype), hints=batch,
        )
        out = MLP(
            features=(hidden, hidden),
            activation=self.spec.activation,
            name="nn",
        )((1.0 + eps) * inv + agg)
        return out, equiv
