"""DimeNet++ conv stack (reference ``hydragnn/models/DIMEStack.py:34-328``,
blocks adapted from PyG):
directional message passing over edge embeddings, with angular (triplet)
interactions weighted by a spherical Bessel/harmonic basis.

Per conv layer (``get_conv :97-160``): node Linear -> EmbeddingBlock (node
pairs + rbf -> edge embedding) -> InteractionPPBlock (triplet mixing with
sbf, residual blocks) -> OutputPPBlock (rbf-gated scatter back to nodes).

Triplet indices (idx_kj, idx_ji) are host-precomputed and padded
(``graphs/triplets.py``); angles are computed on-device from padded edge
vectors — vectors first, then sum, to stay correct under PBC (reference
``_embedding :176-183``).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config.schema import ModelSpec
from ..graphs.graph import GraphBatch
from ..graphs import segment
from .base import register_conv
from .radial import BesselBasis
from .spherical import spherical_basis


class ResidualLayer(nn.Module):
    hidden: int

    @nn.compact
    def __call__(self, x):
        h = nn.silu(nn.Dense(self.hidden, name="lin1")(x))
        h = nn.silu(nn.Dense(self.hidden, name="lin2")(h))
        return x + h


class InteractionPPBlock(nn.Module):
    hidden: int
    int_emb_size: int
    basis_emb_size: int
    num_before_skip: int
    num_after_skip: int

    @nn.compact
    def __call__(self, x, rbf, sbf, idx_kj, idx_ji, triplet_mask):
        E = x.shape[0]
        # basis transforms (bias-free, PyG InteractionPPBlock)
        rbf_e = nn.Dense(self.basis_emb_size, use_bias=False, name="lin_rbf1")(rbf)
        rbf_e = nn.Dense(self.hidden, use_bias=False, name="lin_rbf2")(rbf_e)
        sbf_e = nn.Dense(self.basis_emb_size, use_bias=False, name="lin_sbf1")(sbf)
        sbf_e = nn.Dense(self.int_emb_size, use_bias=False, name="lin_sbf2")(sbf_e)

        x_ji = nn.silu(nn.Dense(self.hidden, name="lin_ji")(x))
        x_kj = nn.silu(nn.Dense(self.hidden, name="lin_kj")(x))
        x_kj = x_kj * rbf_e
        x_kj = nn.silu(nn.Dense(self.int_emb_size, name="lin_down")(x_kj))
        # triplet mixing: messages from edge kj weighted by the angular basis,
        # accumulated onto edge ji
        t = x_kj[idx_kj] * sbf_e * triplet_mask[:, None]
        x_kj = segment.segment_sum(t, idx_ji, E)
        x_kj = nn.silu(nn.Dense(self.hidden, name="lin_up")(x_kj))

        h = x_ji + x_kj
        for i in range(self.num_before_skip):
            h = ResidualLayer(self.hidden, name=f"res_before_{i}")(h)
        h = nn.silu(nn.Dense(self.hidden, name="lin")(h)) + x
        for i in range(self.num_after_skip):
            h = ResidualLayer(self.hidden, name=f"res_after_{i}")(h)
        return h


@register_conv("DimeNet")
class DimeNetConv(nn.Module):
    spec: ModelSpec
    layer: int
    out_dim: int | None = None

    feature_norm = False  # reference DIMEStack uses Identity feature layers

    @nn.compact
    def __call__(
        self, inv: jax.Array, equiv: jax.Array, batch: GraphBatch, train: bool = False
    ):
        spec = self.spec
        hidden = max(spec.hidden_dim, 2)
        out_dim = self.out_dim or spec.hidden_dim
        cutoff = float(spec.radius or 5.0)
        num_radial = spec.num_radial or 6
        num_spherical = spec.num_spherical or 7
        if batch.idx_kj.shape[0] == 0:
            raise ValueError(
                "DimeNet needs triplet indices; attach them in preprocessing "
                "(hydragnn_tpu.graphs.triplets.attach_triplets)"
            )

        vec = batch.pos[batch.receivers] - batch.pos[batch.senders] + batch.edge_shifts
        dist = jnp.sqrt(jnp.sum(vec * vec, axis=-1) + 1e-18)

        # angles at the shared vertex (vectors first, then sum — PBC-safe).
        # Gradient safety: arctan2(0, 0) and |cross| at 0 have NaN gradients,
        # and 0 * NaN = NaN defeats post-hoc masking — so (a, b) are replaced
        # with constants for padded triplets BEFORE the math (jnp.where routes
        # cotangents only to the selected branch), and the cross norm is
        # max-guarded so exactly-collinear real triplets get a zero
        # subgradient instead of NaN.
        tm = batch.triplet_mask > 0
        pos_ji = vec[batch.idx_ji]
        pos_kj = vec[batch.idx_kj]
        pos_ki = pos_kj + pos_ji
        a = jnp.sum(pos_ji * pos_ki, axis=-1)
        a = jnp.where(tm, a, 1.0)
        cr = jnp.cross(pos_ji, pos_ki)
        b2 = jnp.sum(cr * cr, axis=-1)
        b = jnp.sqrt(jnp.maximum(b2, 1e-18))
        b = jnp.where(tm, b, 0.0)
        angle = jnp.arctan2(b, a)

        rbf = BesselBasis(
            num_radial=num_radial,
            cutoff=cutoff,
            envelope_exponent=spec.envelope_exponent or 5,
            name="rbf",
        )(dist)
        sbf = spherical_basis(
            dist, angle, batch.idx_kj, num_spherical, num_radial, cutoff,
            spec.envelope_exponent or 5,
        )

        # node Linear + EmbeddingBlock (HydraEmbeddingBlock: features not
        # atomic-number embeddings)
        h = nn.Dense(hidden, name="lin_node")(inv)
        rbf_emb = nn.silu(nn.Dense(hidden, name="emb_lin_rbf")(rbf))
        feats = [h[batch.senders], h[batch.receivers], rbf_emb]
        if spec.edge_dim and batch.edge_attr.shape[1]:
            feats.append(batch.edge_attr)
        x_edge = nn.silu(
            nn.Dense(hidden, name="emb_lin")(jnp.concatenate(feats, axis=-1))
        )

        x_edge = InteractionPPBlock(
            hidden=hidden,
            int_emb_size=spec.int_emb_size or 64,
            basis_emb_size=spec.basis_emb_size or 8,
            num_before_skip=spec.num_before_skip or 1,
            num_after_skip=spec.num_after_skip or 2,
            name="interaction",
        )(x_edge, rbf, sbf, batch.idx_kj, batch.idx_ji, batch.triplet_mask)

        # OutputPPBlock: rbf-gated edge -> node scatter
        g = nn.Dense(hidden, use_bias=False, name="out_lin_rbf")(rbf)
        x_gated = g * x_edge * batch.edge_mask[:, None]
        node_x = segment.segment_sum(x_gated, batch.receivers, batch.num_nodes, hints=batch)
        node_x = nn.Dense(spec.out_emb_size or 128, use_bias=False, name="out_lin_up")(
            node_x
        )
        node_x = nn.silu(nn.Dense(spec.out_emb_size or 128, name="out_lin_0")(node_x))
        node_x = nn.Dense(out_dim, use_bias=False, name="out_lin")(node_x)
        return node_x, equiv
