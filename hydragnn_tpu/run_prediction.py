"""``run_prediction`` — inference entry point (reference
``hydragnn/run_prediction.py:34-114``): same data prologue, then runs the test
split and returns ``(error, per-task losses, true values, predictions)`` with
optional min-max denormalization (reference ``postprocess/postprocess.py:13``).

The predict path itself (step construction, per-head gather, denormalize)
lives in ``serve.predictor.Predictor`` — shared with the always-hot serving
tier so the batch evaluator and the server execute identical code; this
module is the thin batch driver around it (data prologue, epoch loop,
cross-rank gather, loss reduction).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import load_config, update_config
from .models.create import create_model_config
from .preprocess.load_data import dataset_loading_and_splitting
from .serve.predictor import Predictor
from .train.step import TrainState


def _allgather_ragged(arr: np.ndarray) -> np.ndarray:
    """Concatenate per-process arrays of differing lengths (the reference's
    cross-rank sample gather, train_validate_test.py:989-1080): exchange
    lengths, pad to the max, allgather, strip, concatenate in rank order."""
    from jax.experimental import multihost_utils

    lengths = multihost_utils.process_allgather(
        np.array([arr.shape[0]], np.int32)
    ).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros((max_len,) + arr.shape[1:], arr.dtype)
    padded[: arr.shape[0]] = arr
    gathered = multihost_utils.process_allgather(padded)
    return np.concatenate(
        [gathered[r, : int(lengths[r])] for r in range(len(lengths))], axis=0
    )


def run_prediction(config_source, state: TrainState, model=None, samples: Sequence | None = None):
    config = load_config(config_source)
    world, rank = 1, 0
    try:
        if jax.process_count() > 1:
            world, rank = jax.process_count(), jax.process_index()
    except Exception:
        pass
    train_loader, val_loader, test_loader = dataset_loading_and_splitting(
        config, samples=samples, rank=rank, world=world
    )
    config = update_config(config, train_loader.samples, val_loader.samples, test_loader.samples)
    if model is None:
        model = create_model_config(config)

    predictor = Predictor(model, state, config)

    # ONE pass over the test split: gather per-head true/pred arrays
    # (reference ``test()`` collection + gather,
    # train_validate_test.py:989-1080); loss/RMSE are computed from the
    # gathered arrays below instead of a second forward pass.
    trues = [[] for _ in predictor.cols]
    preds = [[] for _ in predictor.cols]
    for batch in test_loader:
        batch = jax.tree.map(jnp.asarray, batch)
        bt, bp = predictor.gather(batch)
        for ihead in range(len(predictor.cols)):
            trues[ihead].append(bt[ihead])
            preds[ihead].append(bp[ihead])
    true_values = [np.concatenate(t) for t in trues]
    predicted_values = [np.concatenate(p) for p in preds]
    if world > 1:
        # merge every process's test-shard predictions (reference's gather)
        true_values = [_allgather_ragged(t) for t in true_values]
        predicted_values = [_allgather_ragged(p) for p in predicted_values]

    from .utils import flags

    if flags.get(flags.DUMP_TESTDATA):
        # reference dumps per-rank test pickles (train_validate_test.py:908)
        import pickle

        with open(f"testdata_rank{rank}.pickle", "wb") as f:
            pickle.dump({"true": true_values, "pred": predicted_values}, f)

    # per-task losses + weighted total from the gathered arrays
    spec = model.spec
    tasks_loss = [
        float(np.mean((t - p) ** 2)) for t, p in zip(true_values, predicted_values)
    ]
    error = float(sum(w * l for w, l in zip(spec.task_weights, tasks_loss)))

    true_values, predicted_values = predictor.denormalize(
        true_values, predicted_values
    )

    return error, tasks_loss, true_values, predicted_values


__all__ = ["run_prediction"]
