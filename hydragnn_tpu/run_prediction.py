"""``run_prediction`` — inference entry point (reference
``hydragnn/run_prediction.py:34-114``): same data prologue, then runs the test
split and returns ``(error, per-task losses, true values, predictions)`` with
optional min-max denormalization (reference ``postprocess/postprocess.py:13``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import load_config, update_config
from .models.base import head_columns
from .models.create import create_model_config
from .preprocess.load_data import dataset_loading_and_splitting
from .train.step import TrainState, make_predict_step, resolve_precision


def _allgather_ragged(arr: np.ndarray) -> np.ndarray:
    """Concatenate per-process arrays of differing lengths (the reference's
    cross-rank sample gather, train_validate_test.py:989-1080): exchange
    lengths, pad to the max, allgather, strip, concatenate in rank order."""
    from jax.experimental import multihost_utils

    lengths = multihost_utils.process_allgather(
        np.array([arr.shape[0]], np.int32)
    ).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros((max_len,) + arr.shape[1:], arr.dtype)
    padded[: arr.shape[0]] = arr
    gathered = multihost_utils.process_allgather(padded)
    return np.concatenate(
        [gathered[r, : int(lengths[r])] for r in range(len(lengths))], axis=0
    )


def run_prediction(config_source, state: TrainState, model=None, samples: Sequence | None = None):
    config = load_config(config_source)
    world, rank = 1, 0
    try:
        if jax.process_count() > 1:
            world, rank = jax.process_count(), jax.process_index()
    except Exception:
        pass
    train_loader, val_loader, test_loader = dataset_loading_and_splitting(
        config, samples=samples, rank=rank, world=world
    )
    config = update_config(config, train_loader.samples, val_loader.samples, test_loader.samples)
    if model is None:
        model = create_model_config(config)

    precision = resolve_precision(
        config["NeuralNetwork"]["Training"].get("precision", "fp32")
    )
    predict_step = make_predict_step(model, compute_dtype=precision)

    # ONE pass over the test split: gather per-head true/pred arrays
    # (reference ``test()`` collection + gather,
    # train_validate_test.py:989-1080); loss/RMSE are computed from the
    # gathered arrays below instead of a second forward pass.
    cols = head_columns(model.spec)
    trues = [[] for _ in cols]
    preds = [[] for _ in cols]
    for batch in test_loader:
        batch = jax.tree.map(jnp.asarray, batch)
        out = predict_step(state, batch)
        if model.spec.var_output:
            out = out[0]
        for ihead, (kind, col, dim) in enumerate(cols):
            if kind == "graph":
                mask = np.asarray(batch.graph_mask) > 0
                trues[ihead].append(np.asarray(batch.graph_y[:, col : col + dim])[mask])
                preds[ihead].append(np.asarray(out[ihead])[mask])
            else:
                mask = np.asarray(batch.node_mask) > 0
                trues[ihead].append(np.asarray(batch.node_y[:, col : col + dim])[mask])
                preds[ihead].append(np.asarray(out[ihead])[mask])
    true_values = [np.concatenate(t) for t in trues]
    predicted_values = [np.concatenate(p) for p in preds]
    if world > 1:
        # merge every process's test-shard predictions (reference's gather)
        true_values = [_allgather_ragged(t) for t in true_values]
        predicted_values = [_allgather_ragged(p) for p in predicted_values]

    from .utils import flags

    if flags.get(flags.DUMP_TESTDATA):
        # reference dumps per-rank test pickles (train_validate_test.py:908)
        import pickle

        with open(f"testdata_rank{rank}.pickle", "wb") as f:
            pickle.dump({"true": true_values, "pred": predicted_values}, f)

    # per-task losses + weighted total from the gathered arrays
    spec = model.spec
    tasks_loss = [
        float(np.mean((t - p) ** 2)) for t, p in zip(true_values, predicted_values)
    ]
    error = float(sum(w * l for w, l in zip(spec.task_weights, tasks_loss)))

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    if voi.get("denormalize_output"):
        from .postprocess.postprocess import output_denormalize

        true_values, predicted_values = output_denormalize(
            voi, true_values, predicted_values, model.spec
        )

    return error, tasks_loss, true_values, predicted_values


__all__ = ["run_prediction"]
