"""``run_prediction`` — inference entry point (reference
``hydragnn/run_prediction.py:34-114``): same data prologue, then runs the test
split and returns ``(error, per-task losses, true values, predictions)`` with
optional min-max denormalization (reference ``postprocess/postprocess.py:13``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import load_config, update_config
from .models.base import head_columns
from .models.create import create_model_config
from .preprocess.load_data import dataset_loading_and_splitting
from .train.step import TrainState, make_predict_step, resolve_precision


def run_prediction(config_source, state: TrainState, model=None, samples: Sequence | None = None):
    config = load_config(config_source)
    train_loader, val_loader, test_loader = dataset_loading_and_splitting(
        config, samples=samples
    )
    config = update_config(config, train_loader.samples, val_loader.samples, test_loader.samples)
    if model is None:
        model = create_model_config(config)

    precision = resolve_precision(
        config["NeuralNetwork"]["Training"].get("precision", "fp32")
    )
    predict_step = make_predict_step(model, compute_dtype=precision)

    # ONE pass over the test split: gather per-head true/pred arrays
    # (reference ``test()`` collection + gather,
    # train_validate_test.py:989-1080); loss/RMSE are computed from the
    # gathered arrays below instead of a second forward pass.
    cols = head_columns(model.spec)
    trues = [[] for _ in cols]
    preds = [[] for _ in cols]
    for batch in test_loader:
        batch = jax.tree.map(jnp.asarray, batch)
        out = predict_step(state, batch)
        if model.spec.var_output:
            out = out[0]
        for ihead, (kind, col, dim) in enumerate(cols):
            if kind == "graph":
                mask = np.asarray(batch.graph_mask) > 0
                trues[ihead].append(np.asarray(batch.graph_y[:, col : col + dim])[mask])
                preds[ihead].append(np.asarray(out[ihead])[mask])
            else:
                mask = np.asarray(batch.node_mask) > 0
                trues[ihead].append(np.asarray(batch.node_y[:, col : col + dim])[mask])
                preds[ihead].append(np.asarray(out[ihead])[mask])
    true_values = [np.concatenate(t) for t in trues]
    predicted_values = [np.concatenate(p) for p in preds]

    # per-task losses + weighted total from the gathered arrays
    spec = model.spec
    tasks_loss = [
        float(np.mean((t - p) ** 2)) for t, p in zip(true_values, predicted_values)
    ]
    error = float(sum(w * l for w, l in zip(spec.task_weights, tasks_loss)))

    voi = config["NeuralNetwork"]["Variables_of_interest"]
    if voi.get("denormalize_output"):
        from .postprocess.postprocess import output_denormalize

        true_values, predicted_values = output_denormalize(
            voi, true_values, predicted_values, model.spec
        )

    return error, tasks_loss, true_values, predicted_values


__all__ = ["run_prediction"]
