"""Hung-dispatch watchdog: a timer around host-blocking device syncs.

A wedged interconnect or a deadlocked collective doesn't crash a JAX run —
it parks the host forever inside ``block_until_ready`` with zero log output,
which on a scheduler means burning the full walltime allocation in silence.
The watchdog arms a deadline around each blocking sync (the loop's
backpressure wait and the end-of-epoch drain); if the sync outlives the
timeout a warning (and an optional callback) fires from a monitor thread,
so the operator/log gets a "dispatch N has been stuck for T seconds"
breadcrumb while the main thread is still blocked. It deliberately does NOT
try to kill the sync — interrupting XLA mid-collective corrupts the runtime;
detection + diagnosis is the job, the scheduler owns the kill.

The elastic data plane (``datasets/sharded.py``) reuses the same timers
around replica round-trips, with two extensions this module grew for it:

* **concurrent guards** — N prefetch workers each bracket their own fetch,
  so the armed deadlines are a table keyed by a per-guard token, not a
  single slot (which concurrent regions would silently clobber — only the
  last-armed region would ever be watched);
* **per-guard ``on_expire``** — a guard can carry its own escalation
  callback (the store severs the wedged socket, turning a byte-dribbling
  peer into an ordinary connection error that quarantines + fails over).
  Unlike a device sync, a TCP round-trip CAN be interrupted safely.

ONE long-lived daemon monitor thread serves every guarded region (lazily
started, parked on a condition variable while nothing is armed): the loop
enters a guard 2+ times per dispatch, and spawning/cancelling a fresh
``threading.Timer`` thread each time would put hundreds of OS thread
creations per second on exactly the dispatch-latency-bound path the
superstep work exists to shrink.

The chaos harness (``chaos.py`` ``hang``/``slow_peer`` events) injects a
deterministic stall inside a guarded region to prove the timer fires.
"""

from __future__ import annotations

import itertools
import threading
import time
import warnings
from contextlib import contextmanager


class Watchdog:
    """``with watchdog.guard("step sync"): jax.block_until_ready(...)`` —
    fires ``on_hang(what)`` (and a warning) if the region runs longer than
    ``timeout_s``. A zero/negative timeout disables the guard entirely
    (zero overhead: the context manager short-circuits). Guards may nest
    and run concurrently from many threads; each armed region has its own
    deadline and fires independently, at most once. A per-guard
    ``on_expire`` callback (no arguments) runs on expiry in addition to
    the shared ``on_hang(what)``."""

    def __init__(self, timeout_s: float, on_hang=None):
        self.timeout_s = float(timeout_s)
        self.on_hang = on_hang
        self.fired = 0  # guarded-by: _cond
        self.events: list[str] = []  # guarded-by: _cond
        self._cond = threading.Condition()
        self._token = itertools.count()
        # token -> (deadline, what, on_expire)
        self._armed: dict[int, tuple[float, str, object]] = {}  # guarded-by: _cond
        self._thread: threading.Thread | None = None  # guarded-by: _cond

    @contextmanager
    def guard(self, what: str = "device sync", on_expire=None):
        if self.timeout_s <= 0:
            yield
            return
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._monitor, name="hydragnn-watchdog", daemon=True
                )
                self._thread.start()
            tok = next(self._token)
            self._armed[tok] = (
                time.monotonic() + self.timeout_s, what, on_expire
            )
            self._cond.notify()
        try:
            yield
        finally:
            with self._cond:
                self._armed.pop(tok, None)
                self._cond.notify()

    def _monitor(self) -> None:  # daemon thread: dies with the process
        while True:
            with self._cond:
                if not self._armed:
                    self._cond.wait()  # parked: nothing armed, zero cost
                    continue
                now = time.monotonic()
                expired = [
                    (tok, what, on_expire)
                    for tok, (t, what, on_expire) in self._armed.items()
                    if t <= now
                ]
                if not expired:
                    soonest = min(t for t, _, _ in self._armed.values())
                    self._cond.wait(soonest - now)
                    continue
                # deadlines passed with regions still armed: fire each ONCE
                # (dropping the entry keeps a still-hung region from
                # re-firing every wakeup; the next guard re-arms)
                for tok, _, _ in expired:
                    self._armed.pop(tok, None)
                self.fired += len(expired)
                self.events.extend(what for _, what, _ in expired)
            for _, what, on_expire in expired:
                warnings.warn(
                    f"watchdog: {what} exceeded {self.timeout_s:.1f}s — a "
                    "dispatch appears hung (wedged interconnect / deadlocked "
                    "collective?); the run continues but needs attention",
                    stacklevel=2,
                )
                for cb in (on_expire, self.on_hang):
                    if cb is None:
                        continue
                    try:
                        cb(what) if cb is self.on_hang else cb()
                    except Exception:
                        pass  # a broken callback must not kill the monitor


__all__ = ["Watchdog"]
