"""Hung-dispatch watchdog: a timer around host-blocking device syncs.

A wedged interconnect or a deadlocked collective doesn't crash a JAX run —
it parks the host forever inside ``block_until_ready`` with zero log output,
which on a scheduler means burning the full walltime allocation in silence.
The watchdog arms a deadline around each blocking sync (the loop's
backpressure wait and the end-of-epoch drain); if the sync outlives the
timeout a warning (and an optional callback) fires from a monitor thread,
so the operator/log gets a "dispatch N has been stuck for T seconds"
breadcrumb while the main thread is still blocked. It deliberately does NOT
try to kill the sync — interrupting XLA mid-collective corrupts the runtime;
detection + diagnosis is the job, the scheduler owns the kill.

ONE long-lived daemon monitor thread serves every guarded region (lazily
started, parked on a condition variable while nothing is armed): the loop
enters a guard 2+ times per dispatch, and spawning/cancelling a fresh
``threading.Timer`` thread each time would put hundreds of OS thread
creations per second on exactly the dispatch-latency-bound path the
superstep work exists to shrink.

The chaos harness (``chaos.py`` ``hang`` events) injects a deterministic
sleep inside a guarded region to prove the timer actually fires.
"""

from __future__ import annotations

import threading
import time
import warnings
from contextlib import contextmanager


class Watchdog:
    """``with watchdog.guard("step sync"): jax.block_until_ready(...)`` —
    fires ``on_hang(what)`` (and a warning) if the region runs longer than
    ``timeout_s``. A zero/negative timeout disables the guard entirely
    (zero overhead: the context manager short-circuits)."""

    def __init__(self, timeout_s: float, on_hang=None):
        self.timeout_s = float(timeout_s)
        self.on_hang = on_hang
        self.fired = 0
        self.events: list[str] = []
        self._cond = threading.Condition()
        self._deadline: tuple[float, str] | None = None  # guarded by _cond
        self._thread: threading.Thread | None = None

    @contextmanager
    def guard(self, what: str = "device sync"):
        if self.timeout_s <= 0:
            yield
            return
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._monitor, name="hydragnn-watchdog", daemon=True
                )
                self._thread.start()
            self._deadline = (time.monotonic() + self.timeout_s, what)
            self._cond.notify()
        try:
            yield
        finally:
            with self._cond:
                self._deadline = None
                self._cond.notify()

    def _monitor(self) -> None:  # daemon thread: dies with the process
        while True:
            with self._cond:
                if self._deadline is None:
                    self._cond.wait()  # parked: nothing armed, zero cost
                    continue
                t, what = self._deadline
                remaining = t - time.monotonic()
                if remaining > 0:
                    self._cond.wait(remaining)
                    continue
                # deadline passed with the region still armed: fire ONCE
                # (clearing the deadline keeps a still-hung region from
                # re-firing every wakeup; the next guard re-arms)
                self._deadline = None
                self.fired += 1
                self.events.append(what)
            warnings.warn(
                f"watchdog: {what} exceeded {self.timeout_s:.1f}s — a "
                "dispatch appears hung (wedged interconnect / deadlocked "
                "collective?); the run continues but needs attention",
                stacklevel=2,
            )
            if self.on_hang is not None:
                try:
                    self.on_hang(what)
                except Exception:
                    pass  # a broken callback must not kill the monitor


__all__ = ["Watchdog"]
