"""Fault-injection harness (``HYDRAGNN_FAULT_PLAN``): deterministic chaos.

A recovery path that has never run is a recovery path that does not work.
This module injects the faults the resilience layer claims to survive, at
exact (epoch, dispatch) coordinates, so ``tests/test_resilience.py`` can
prove each path end-to-end — and so an operator can rehearse a preemption
drill on a real cluster with one env var instead of ssh-ing kill signals.

Plan format — a JSON list of events (inline, or ``@/path/to/plan.json``)::

    HYDRAGNN_FAULT_PLAN='[
      {"fault": "nan_batch", "epoch": 0, "dispatch": 3},
      {"fault": "sigterm",   "epoch": 1, "dispatch": 5},
      {"fault": "hang",      "epoch": 0, "dispatch": 2, "seconds": 1.5},
      {"fault": "corrupt_latest", "epoch": 0},
      {"fault": "dead_shard", "epoch": 0, "dispatch": 4, "peer": 1},
      {"fault": "slow_peer",  "epoch": 0, "dispatch": 2, "peer": 0, "seconds": 5},
      {"fault": "device_loss", "epoch": 1, "dispatch": 0, "device": 3},
      {"fault": "mesh_shrink", "epoch": 1, "dispatch": 1, "to": 2},
      {"fault": "double_fault", "inner": {"fault": "device_loss"}},
      {"fault": "replica_kill", "dispatch": 40, "peer": 1},
      {"fault": "replica_slow", "dispatch": 10, "peer": 0, "seconds": 0.4},
      {"fault": "rollout_during_load", "dispatch": 60}
    ]'

* ``nan_batch`` — multiply the batch's node features by NaN *after* device
  placement (an elementwise op, so shardings are preserved and nothing
  retraces): the NaN flows through the real forward/loss/grad path exactly
  like a genuine fp16/bf16 blow-up would.
* ``sigterm`` — the process signals itself; the installed
  ``PreemptionHandler`` turns it into a checkpoint-and-stop at the next
  dispatch boundary (a faithful SLURM preemption rehearsal).
* ``hang`` — sleep ``seconds`` inside the watchdog-guarded dispatch region,
  proving the hung-dispatch timer fires.
* ``corrupt_latest`` — at the end of the matching epoch, truncate the
  largest leaf file of the checkpoint "latest" points to, so the next
  restore must take the manifest-verified fallback path.
* ``dead_shard`` — close the ``peer``-th live ``ShardServer`` in this
  process (creation order) mid-epoch: the host-loss drill for the elastic
  data plane. With ``replication_factor`` > 1 the epoch must complete with
  every sample fetched from a replica; with R=1 it proves the
  retry/diagnosis path.
* ``slow_peer`` — delay every response of the ``peer``-th live server by
  ``seconds``: the gray-failure drill. A delay past the client's
  ``peer_timeout`` must escalate to quarantine + failover, not a stuck
  epoch.
* ``device_loss`` — mark ``count`` devices (starting at ORIGINAL index
  ``device``; default the last still-alive one) dead on the active
  ``resilience.elastic`` controller: the COMPUTE-plane host-loss drill. The
  loop drains to the dispatch boundary, checkpoints, and resumes on a mesh
  rebuilt from the survivors — in process.
* ``mesh_shrink`` — shrink the survivor list to ``to`` devices (the
  multi-host-partition drill; same recovery path as ``device_loss``).
* ``double_fault`` — fire the ``inner`` fault payload (``device_loss``,
  ``mesh_shrink``, or ``sigterm``) while a recovery is ALREADY in flight:
  proves recovery is re-entrant — a topology fault folds into the re-mesh
  underway, a nested sigterm re-drains the resumed segment, and the
  checkpoint sidecar records the logical grid exactly once either way.

The SERVING-fleet vocabulary fires at request coordinates instead of
training dispatches — the traffic driver calls :meth:`FaultPlan.on_request`
before admitting request ``i``, which matches events at ``(epoch=0,
dispatch=i)`` (fleet plans leave ``epoch`` at its default). The harness
stays mechanism-free here: the driver binds each fault name to an action
callable (kill THAT replica process, ``set_delay`` on THAT host, run the
mid-load blue/green rollout), because only the driver owns the topology.

* ``replica_kill`` — SIGKILL the ``peer``-th replica mid-traffic: the
  router must quarantine it and fail its in-flight requests over with zero
  lost requests.
* ``replica_slow`` — delay the ``peer``-th replica's replies by
  ``seconds``: the gray-failure drill at the serving tier (watchdog severs
  the dribble, quarantine + failover take over).
* ``rollout_during_load`` — run a full blue/green cutover while the
  request stream is in flight: the compound drill proving upgrade and
  fault-recovery compose.

``dispatch`` omitted/null matches every dispatch of the epoch; ``times``
caps how often an event fires (default 1; -1 = unlimited).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import time
from pathlib import Path

# serving-fleet faults: fired by FaultPlan.on_request at request
# coordinates (epoch 0), bound to actions by the traffic driver
FLEET_FAULTS = ("replica_kill", "replica_slow", "rollout_during_load")

_FAULTS = (
    "nan_batch", "sigterm", "hang", "corrupt_latest", "dead_shard",
    "slow_peer", "device_loss", "mesh_shrink", "double_fault",
) + FLEET_FAULTS

# double_fault payloads fire while a recovery is ALREADY in flight, so the
# nested fault must itself be something the controller can absorb mid-flight
_INNER_FAULTS = ("device_loss", "mesh_shrink", "sigterm")


@dataclasses.dataclass
class FaultEvent:
    fault: str
    epoch: int = 0
    dispatch: int | None = None  # None = every dispatch of the epoch
    seconds: float = 1.0  # hang / slow_peer
    times: int = 1  # -1 = unlimited
    peer: int = 0  # dead_shard / slow_peer: index into live_servers()
    device: int | None = None  # device_loss: ORIGINAL device index (None = last alive)
    count: int = 1  # device_loss: how many devices die at once
    to: int | None = None  # mesh_shrink: survivor-count target
    inner: dict | None = None  # double_fault: the nested fault payload

    def matches(self, epoch: int, dispatch: int | None) -> bool:
        if self.times == 0 or self.epoch != epoch:
            return False
        return self.dispatch is None or self.dispatch == dispatch

    def consume(self) -> None:
        if self.times > 0:
            self.times -= 1


class FaultPlan:
    """Ordered fault events + a fired-event log (what/where, for tests and
    post-mortems)."""

    def __init__(self, events):
        self.events = list(events)
        self.log: list[tuple[str, int, int | None]] = []

    @staticmethod
    def parse(text: str) -> "FaultPlan":
        if text.startswith("@"):
            with open(text[1:]) as f:
                raw = json.load(f)
        else:
            raw = json.loads(text)
        if isinstance(raw, dict):
            raw = [raw]
        events = []
        for i, e in enumerate(raw):
            fault = e.get("fault")
            if fault not in _FAULTS:
                raise ValueError(
                    f"HYDRAGNN_FAULT_PLAN event {i}: fault {fault!r} not one "
                    f"of {_FAULTS}"
                )
            inner = e.get("inner")
            if fault == "double_fault":
                inner = dict(inner or {"fault": "device_loss"})
                if inner.get("fault") not in _INNER_FAULTS:
                    raise ValueError(
                        f"HYDRAGNN_FAULT_PLAN event {i}: double_fault inner "
                        f"fault {inner.get('fault')!r} not one of "
                        f"{_INNER_FAULTS}"
                    )
            events.append(
                FaultEvent(
                    fault=fault,
                    epoch=int(e.get("epoch", 0)),
                    dispatch=(
                        None if e.get("dispatch") is None else int(e["dispatch"])
                    ),
                    seconds=float(e.get("seconds", 1.0)),
                    times=int(e.get("times", 1)),
                    peer=int(e.get("peer", 0)),
                    device=(
                        None if e.get("device") is None else int(e["device"])
                    ),
                    count=int(e.get("count", 1)),
                    to=None if e.get("to") is None else int(e["to"]),
                    inner=inner,
                )
            )
        return FaultPlan(events)

    @staticmethod
    def from_env() -> "FaultPlan | None":
        from ..utils import flags

        text = flags.get(flags.FAULT_PLAN)
        if not text:
            return None
        return FaultPlan.parse(str(text))

    def _take(self, fault: str, epoch: int, dispatch: int | None):
        for ev in self.events:
            if ev.fault == fault and ev.matches(epoch, dispatch):
                ev.consume()
                self.log.append((fault, epoch, dispatch))
                return ev
        return None

    # -- loop hooks ----------------------------------------------------------
    def on_dispatch(self, epoch: int, dispatch: int, batch):
        """Apply dispatch-scoped faults; returns the (possibly poisoned)
        batch. Called inside the loop's watchdog-guarded dispatch region so
        an injected hang exercises the real timer."""
        ev = self._take("hang", epoch, dispatch)
        if ev is not None:
            time.sleep(ev.seconds)
        if self._take("sigterm", epoch, dispatch) is not None:
            os.kill(os.getpid(), signal.SIGTERM)
        ev = self._take("dead_shard", epoch, dispatch)
        if ev is not None:
            _kill_live_server(ev.peer)
        ev = self._take("slow_peer", epoch, dispatch)
        if ev is not None:
            _slow_live_server(ev.peer, ev.seconds)
        ev = self._take("device_loss", epoch, dispatch)
        if ev is not None:
            # host-loss drill for the COMPUTE plane: mark devices dead on
            # the active elastic controller, which drains the loop to the
            # dispatch boundary and re-meshes from the survivors
            from .elastic import deliver_fault

            deliver_fault("device_loss", device=ev.device, count=ev.count)
        ev = self._take("mesh_shrink", epoch, dispatch)
        if ev is not None:
            from .elastic import deliver_fault

            deliver_fault("mesh_shrink", to=ev.to)
        if self._take("nan_batch", epoch, dispatch) is not None:
            batch = poison_batch(batch)
        return batch

    def on_recovery(self, recovery_no: int) -> list[dict]:
        """Fault-during-recovery drill (``double_fault``): called by the
        elastic controller's driver while a recovery is in flight, BEFORE it
        re-meshes. Each pending double_fault event fires (consuming
        ``times``) and contributes its nested fault payload — a topology
        fault folds into the re-mesh already underway; a nested ``sigterm``
        makes the resumed segment drain again immediately."""
        out: list[dict] = []
        for ev in self.events:
            if ev.fault != "double_fault" or ev.times == 0:
                continue
            ev.consume()
            self.log.append(("double_fault", -1, recovery_no))
            out.append(dict(ev.inner or {"fault": "device_loss"}))
        return out

    def on_request(self, request_no: int, actions: dict) -> list:
        """Apply serving-fleet faults before request ``request_no`` is
        admitted. Fleet plans address requests as ``(epoch=0, dispatch=
        request_no)`` — the request stream is one "epoch" of dispatches.

        ``actions`` binds fault names to callables taking the fired
        :class:`FaultEvent` — the traffic driver owns the topology (which
        subprocess to SIGKILL, which host to ``set_delay``, how to run the
        mid-load rollout), so the plan stays pure schedule. A fault with no
        bound action is an inert stderr note, mirroring
        :func:`_live_server`'s out-of-range behavior. Returns the fired
        events."""
        fired = []
        for fault in FLEET_FAULTS:
            ev = self._take(fault, 0, request_no)
            if ev is None:
                continue
            fn = actions.get(fault)
            if fn is None:
                print(
                    f"[chaos] no action bound for {fault!r} at request "
                    f"{request_no}; fault skipped",
                    file=sys.stderr,
                )
                continue
            fn(ev)
            fired.append(ev)
        return fired

    def on_epoch_end(self, epoch: int, log_name: str, path: str = "./logs/"):
        """Apply epoch-scoped faults (checkpoint corruption) after the
        epoch's checkpoints are written. Each matching event fires at most
        ONCE per epoch end (``times: -1`` means "at every matching epoch",
        not "loop forever re-corrupting within one epoch")."""
        for ev in self.events:
            if ev.fault != "corrupt_latest" or not ev.matches(epoch, None):
                continue
            ev.consume()
            self.log.append(("corrupt_latest", epoch, None))
            from ..train.checkpoint import _ckpt_dir

            latest = os.path.join(_ckpt_dir(log_name, path), "latest")
            target = os.path.realpath(latest)
            if os.path.isdir(target):
                corrupt_checkpoint(target)


def _live_server(peer: int):
    """The ``peer``-th live ShardServer in this process (creation order),
    or None (with a stderr note) when the index is out of range — a chaos
    plan naming a server that never existed is an inert event, not a crash
    in the middle of the run being drilled."""
    from ..datasets.sharded import live_servers

    servers = live_servers()
    if 0 <= peer < len(servers):
        return servers[peer]
    print(
        f"[chaos] no live ShardServer at index {peer} "
        f"({len(servers)} registered); fault skipped",
        file=sys.stderr,
    )
    return None


def _kill_live_server(peer: int) -> None:
    srv = _live_server(peer)
    if srv is not None:
        srv.close()  # connections refuse from here on: the host-loss drill


def _slow_live_server(peer: int, seconds: float) -> None:
    srv = _live_server(peer)
    if srv is not None:
        srv.set_delay(seconds)  # gray failure: alive but past any deadline


def poison_batch(batch):
    """NaN the node features through an elementwise multiply — preserves
    shape, dtype, AND sharding (no retrace under jit), and the NaN reaches
    the loss through the genuine forward path."""
    return batch.replace(x=batch.x * float("nan"))


def corrupt_checkpoint(ckpt_path: str) -> str:
    """Truncate the largest file under an orbax checkpoint dir to half its
    size — the deterministic stand-in for a node dying mid-write or a
    filesystem tearing a block. Returns the mangled file's path."""
    files = sorted(
        (p for p in Path(ckpt_path).rglob("*") if p.is_file()),
        key=lambda p: (p.stat().st_size, str(p)),
    )
    if not files:
        raise FileNotFoundError(f"no files to corrupt under {ckpt_path}")
    target = files[-1]
    size = target.stat().st_size
    with open(target, "r+b") as f:
        f.truncate(max(size // 2, 1))
    return str(target)


__all__ = [
    "FLEET_FAULTS",
    "FaultEvent",
    "FaultPlan",
    "corrupt_checkpoint",
    "poison_batch",
]
