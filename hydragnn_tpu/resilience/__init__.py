"""Fault-tolerant training: the resilience layer.

HydraGNN's reference deployments are multi-day MLIP trainings on DOE
schedulers where preemption, node loss, and diverging reduced-precision runs
are routine — the reference ships a SLURM walltime guard and per-epoch
best-checkpoint logic (both already ported: ``utils/walltime.py``,
``train/checkpoint.py``). This package adds the rest of the story, threaded
through the train loop, superstep, checkpoint, and data layers:

* **Non-finite step guard** (``guard.py``): inside the jitted step, a NaN/Inf
  loss (or exploded parameters from an Inf gradient) skips the optimizer
  update via one ``lax.cond`` that forwards either the new or the incoming
  state — the same skip-don't-branch discipline as the superstep's
  fill-batch select, with zero extra dispatches and zero retraces. Default policy (``nonfinite_guard: "auto"``): armed
  for reduced-precision training (bf16/fp16-class), where non-finite steps
  are routine; fp32/fp64 opt in via config or ``HYDRAGNN_NONFINITE_GUARD=1``
  (the guard costs one extra XLA compile of the step program). The host reads a ``skipped`` counter from the metrics
  *after* dispatch (deferred by the in-flight window, so the async pipeline
  keeps running ahead) and escalates: N consecutive skips → roll back to the
  last good checkpoint with an LR cut; M rollbacks → abort with a diagnosis
  (``TrainingDivergedError``).
* **Preemption-safe checkpointing** (``preempt.py`` + ``train/checkpoint.py``):
  SIGTERM/SIGUSR1 requests a checkpoint at the next dispatch boundary;
  checkpoints are written atomically (temp + ``os.replace``) with a JSON
  manifest (pytree structure hash + per-leaf checksums) and ``load_checkpoint``
  falls back to the previous epoch when "latest" is dangling or corrupt.
* **Exact mid-epoch resume**: the preemption checkpoint's sidecar records the
  loader position (epoch, raw batches consumed, shuffle seed, superstep K,
  device-group width); a resumed run consumes exactly the not-yet-seen
  batches, so kill-at-step-k + resume bit-matches an uninterrupted fp32 run.
* **Fault injection** (``chaos.py``, ``HYDRAGNN_FAULT_PLAN``): deterministic
  NaN batches, mid-epoch SIGTERM, hung dispatches (watched by ``watchdog.py``
  timers around the device syncs), checkpoint corruption, and — for the
  elastic data plane — ``dead_shard`` (kill a live ``ShardServer`` mid-epoch,
  the host-loss drill) and ``slow_peer`` (delay a server past the fetch
  timeout, the gray-failure drill) — so ``tests/test_resilience.py`` and
  ``tests/test_elastic.py`` prove every recovery path end-to-end instead
  of trusting it.
* **Elastic data plane + layout-aware resume** (``datasets/sharded.py``,
  ``train/checkpoint.py``): with ``replication_factor`` > 1 a dead shard
  owner fails over to a replica (quarantine + background re-probe, watchdog
  deadlines around each replica round-trip), and a mid-epoch checkpoint
  resumes EXACTLY onto a different device count — the interrupted epoch
  finishes on the saved logical update grid resharded over the new mesh.
* **In-process elastic recovery** (``elastic.py``, ``Training.resilience.
  elastic`` / ``HYDRAGNN_ELASTIC``): close the loop the above pieces permit
  — on a recoverable fault (chaos ``device_loss``/``mesh_shrink``, SIGTERM,
  a hung-dispatch ``watchdog_dispatch_s`` expiry) the run drains to the
  dispatch boundary, snapshots, rebuilds the mesh from survivors, re-places
  the state, and continues the SAME epoch without a process restart; K>1
  supersteps finish their interrupted scan blocks on the saved logical grid
  bit-exactly. The randomized chaos campaign (``campaign.py``) composes
  multi-fault schedules and asserts zero lost samples / state agreement /
  no leaked threads / bounded recovery after every one.

Mode coverage: the guard wraps any ``(state, batch) -> (state, metrics)``
step, so data-parallel, FSDP, edge-sharded, and pipeline steps all pass
through it unchanged (edge-sharded/pipeline keep their K=1 pin; the guard
composes with K>1 supersteps by wrapping the step *before* the scan fold).
"""

from __future__ import annotations

import dataclasses
from contextlib import nullcontext

from .chaos import FaultPlan
from .guard import (
    DivergenceDetected,
    SkipTracker,
    TrainingDivergedError,
    wrap_step_with_guard,
)
from .preempt import PreemptionHandler
from .watchdog import Watchdog


@dataclasses.dataclass
class Resilience:
    """Per-run resilience context: configuration + the live fault machinery,
    built once (``from_config``) and threaded through ``train_validate_test``
    and ``train_epoch``. Also the back-channel the loop uses to report
    preemption progress to ``run_training`` (which must then *not* overwrite
    the mid-epoch "latest" pointer with a final save)."""

    guard_enabled: bool = True
    max_consecutive_skips: int = 25
    max_rollbacks: int = 2
    rollback_lr_factor: float = 0.5
    checkpoint_on_preempt: bool = True
    checkpoint_every_epoch: bool = False
    watchdog_timeout: float = 0.0
    # in-process elastic recovery (resilience/elastic.py): route preemption/
    # host-loss/hung-dispatch faults through the ElasticController and
    # resume on a re-built mesh instead of stopping the process
    elastic: bool = False
    max_recoveries: int = 4
    # a DISPATCH taking longer than this (staging + step dispatch + the
    # backpressure sync) fires the hung-dispatch watchdog; with a controller
    # attached the expiry becomes a recoverable fault (drain + resume)
    watchdog_dispatch_s: float = 0.0

    preempt: PreemptionHandler | None = None
    chaos: FaultPlan | None = None
    watchdog: Watchdog | None = None
    dispatch_watchdog: Watchdog | None = None
    tracker: SkipTracker | None = None  # persistent skip-streak state
    controller: object | None = None  # attached ElasticController

    # the Training.resilience config keys whose defaults ARE these dataclass
    # field defaults — the single source config.update_config and
    # from_config both read, so a tuned default can't silently diverge
    # between config-routed runs and direct train_validate_test callers
    CONFIG_KEYS = (
        "max_consecutive_skips",
        "max_rollbacks",
        "rollback_lr_factor",
        "checkpoint_on_preempt",
        "checkpoint_every_epoch",
        "watchdog_timeout",
        "elastic",
        "max_recoveries",
        "watchdog_dispatch_s",
    )

    # live state, written by the loop / train_epoch
    current_epoch: int = 0
    interrupted: bool = False  # last train_epoch stopped on a preempt request
    epoch_raw_done: int = 0  # raw batches consumed by the last train_epoch
    preempted: bool = False  # loop saved a mid-epoch checkpoint and stopped
    skipped_total: int = 0  # guard-skipped steps, summed over the run
    rollbacks: int = 0
    hung_dispatches: int = 0  # dispatch-watchdog expiries this run
    # how the loop entered the current segment's first epoch, recorded for
    # the elastic driver / tests: None (fresh), "exact", "elastic"
    # (logical-grid reshard), "restart" (epoch-restart fallback),
    # "next_epoch" (boundary sidecar), "epoch_start"
    resume_mode: str | None = None
    resume_reason: str | None = None

    @staticmethod
    def from_config(training_cfg: dict) -> "Resilience":
        """Build from the ``Training.resilience`` config block (defaults
        filled by ``config.update_config``; absent keys get the same
        defaults here so direct ``train_validate_test`` callers behave
        identically). ``nonfinite_guard`` accepts ``True``/``False`` or
        ``"auto"`` (the default): guard reduced-precision training, where
        non-finite steps are routine, and leave fp32 — which practically
        never produces them — opt-in, so fp32 runs don't pay the guard's
        extra XLA compile of the step program. ``HYDRAGNN_NONFINITE_GUARD``
        overrides the guard switch; ``HYDRAGNN_FAULT_PLAN`` arms the chaos
        harness."""
        import jax.numpy as jnp

        from ..train.step import resolve_training_precision
        from ..utils import flags

        cfg = dict(training_cfg.get("resilience") or {})
        guard = cfg.get("nonfinite_guard", "auto")
        if guard == "auto" or guard is None:
            # the RESOLVED dtype (HYDRAGNN_PRECISION wins over the config,
            # "auto" resolves per backend), so flipping a run to bf16/fp16
            # via the env arms the guard exactly as a config edit would
            precision = resolve_training_precision(training_cfg)
            guard = jnp.dtype(precision).itemsize < 4  # bf16/fp16-class only
        guard = bool(guard)
        env_guard = flags.get(flags.NONFINITE_GUARD)
        if env_guard is not None:
            guard = bool(env_guard)
        d = config_defaults()  # dataclass field defaults, the single source
        timeout = float(cfg.get("watchdog_timeout", d["watchdog_timeout"]) or 0.0)
        elastic = bool(cfg.get("elastic", d["elastic"]))
        env_elastic = flags.get(flags.ELASTIC)
        if env_elastic is not None:
            elastic = bool(env_elastic)
        dispatch_s = flags.get(
            flags.WATCHDOG_DISPATCH_S,
            default=float(
                cfg.get("watchdog_dispatch_s", d["watchdog_dispatch_s"]) or 0.0
            ),
        )
        dispatch_s = float(dispatch_s or 0.0)
        res = Resilience(
            guard_enabled=guard,
            max_consecutive_skips=int(
                cfg.get("max_consecutive_skips", d["max_consecutive_skips"])
            ),
            max_rollbacks=int(cfg.get("max_rollbacks", d["max_rollbacks"])),
            rollback_lr_factor=float(
                cfg.get("rollback_lr_factor", d["rollback_lr_factor"])
            ),
            checkpoint_on_preempt=bool(
                cfg.get("checkpoint_on_preempt", d["checkpoint_on_preempt"])
            ),
            checkpoint_every_epoch=bool(
                cfg.get("checkpoint_every_epoch", d["checkpoint_every_epoch"])
            ),
            watchdog_timeout=timeout,
            elastic=elastic,
            max_recoveries=int(cfg.get("max_recoveries", d["max_recoveries"])),
            watchdog_dispatch_s=dispatch_s,
            chaos=FaultPlan.from_env(),
            watchdog=Watchdog(timeout) if timeout > 0 else None,
            dispatch_watchdog=Watchdog(dispatch_s) if dispatch_s > 0 else None,
        )
        if res.checkpoint_on_preempt:
            res.preempt = PreemptionHandler()
        return res

    # -- loop hooks ----------------------------------------------------------
    def install(self) -> None:
        if self.preempt is not None:
            self.preempt.install()

    def uninstall(self) -> None:
        if self.preempt is not None:
            self.preempt.uninstall()

    def preempt_requested(self) -> bool:
        return self.preempt is not None and self.preempt.requested

    def request_checkpoint(self) -> None:
        """Programmatic drain request (the elastic controller's channel):
        identical effect to receiving SIGTERM — the loop stops at the next
        dispatch boundary and saves a mid-epoch checkpoint."""
        if self.preempt is None:
            self.preempt = PreemptionHandler()  # event-only; never installed
        self.preempt.request()

    def note_hung_dispatch(self) -> None:
        """Dispatch-watchdog expiry (``watchdog_dispatch_s``): count it and,
        with an elastic controller attached, escalate to a recoverable fault
        — the run drains at the boundary (once the wedged dispatch finally
        returns) and resumes in process instead of burning walltime in
        silence. Called from the watchdog's monitor thread."""
        self.hung_dispatches += 1
        if self.controller is not None:
            from .elastic import Fault

            self.controller.signal(
                Fault(kind="hung_dispatch", detail="dispatch watchdog expiry")
            )

    def reset_for_resume(self) -> None:
        """Clear the drain/preempt state before the elastic driver re-enters
        the loop — without this the resumed segment would immediately see
        the old request and drain again forever."""
        if self.preempt is not None:
            self.preempt.clear()
        self.preempted = False
        self.interrupted = False
        self.resume_mode = None
        self.resume_reason = None

    def new_tracker(self, lag: int) -> SkipTracker | None:
        """The run's skip-streak tracker, or None when the guard (or its
        escalation) is off. ONE tracker persists across epochs: a divergence
        skipping every step of short epochs (fewer dispatches than
        ``max_consecutive_skips``) must still accumulate a streak and
        escalate — a per-epoch tracker would reset the count each epoch and
        never fire. ``lag`` must be the loop's in-flight window so the
        deferred metric reads never block on an unfinished dispatch."""
        if not self.guard_enabled or self.max_consecutive_skips <= 0:
            return None
        if self.tracker is None:
            self.tracker = SkipTracker(self.max_consecutive_skips, lag=lag)
        else:
            self.tracker.lag = max(0, int(lag))
        return self.tracker

    def reset_streak(self) -> None:
        """Forget the consecutive-skip streak (rollback restored a good
        state; the retry starts clean). Run totals stay for diagnosis."""
        if self.tracker is not None:
            self.tracker.consecutive = 0

    def watchdog_guard(self, what: str):
        if self.watchdog is None:
            return nullcontext()
        return self.watchdog.guard(what)


def config_defaults() -> dict:
    """``{config key: default}`` for the ``Training.resilience`` block, read
    off the ``Resilience`` dataclass fields — ``config.update_config`` fills
    the block from this, so the two can't drift."""
    fields = {f.name: f.default for f in dataclasses.fields(Resilience)}
    return {k: fields[k] for k in Resilience.CONFIG_KEYS}


from .elastic import (  # noqa: E402 (needs Resilience defined for the driver)
    ElasticController,
    ElasticRecoveryError,
    Fault,
    train_elastic,
)

__all__ = [
    "DivergenceDetected",
    "ElasticController",
    "ElasticRecoveryError",
    "Fault",
    "FaultPlan",
    "PreemptionHandler",
    "Resilience",
    "SkipTracker",
    "TrainingDivergedError",
    "Watchdog",
    "config_defaults",
    "train_elastic",
    "wrap_step_with_guard",
]
