"""Randomized chaos campaign: seeded multi-fault schedules + invariants.

One injected fault proves one recovery path; production failure is
*compositions* — a NaN blow-up two epochs before a preemption, a device loss
while a peer is already quarantined, a second fault landing mid-recovery.
This module turns the deterministic chaos harness (``chaos.py``) into a
campaign: a seeded scheduler composes the fault vocabulary into random
multi-fault ``HYDRAGNN_FAULT_PLAN`` schedules, and an invariant suite checks
what graceful degradation actually MEANS after every schedule:

1. **zero lost samples** — the faulted run performs exactly the reference
   run's optimizer updates (exact resume never re-trains or drops a batch;
   the logical-grid resume preserves the update count through a re-mesh);
2. **state agreement** — bit-exact against the reference when the topology
   never changed, allclose at the documented lr-scale tolerance after a
   shrink (re-associated gradient reductions on fewer devices perturb
   near-zero elements, and one Adam update turns any perturbation into an
   O(lr) parameter move — see ``tests/test_elastic.py``'s derivation);
3. **no leaked threads** — the run must not leave non-daemon threads behind
   (the campaign's test module additionally runs under the
   ``threadsan_module`` lock-order sanitizer, so the drills double as a
   deadlock hunt);
4. **bounded recovery** — every in-process recovery completes inside the
   budget (drain -> snapshot -> re-mesh -> restore, measured to the point
   the resumed segment re-enters the loop).

Comparability discipline (why the scheduler constrains placement): the
REFERENCE run replays the *training-perturbing* events (``nan_batch`` — both
runs guard-skip the same poisoned update) but none of the recovery events.
Fault coordinates are (epoch, dispatch-within-epoch), and a mid-epoch
recovery restarts dispatch numbering for the resumed tail — so perturbing
events must land in epochs strictly BEFORE the first recovery event, and
mesh-changing events pin to the FINAL epoch (after a shrink, later epochs
would regroup to the survivor-native grid: genuinely different update math,
not noise). ``hang``/``dead_shard``/``slow_peer`` perturb nothing and may
land anywhere.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# events both the reference and the faulted run must replay (they change the
# training math itself, deterministically, via the non-finite guard skip)
PERTURBING_FAULTS = ("nan_batch",)
# events only the faulted run sees (they exercise recovery, not math)
RECOVERY_FAULTS = ("sigterm", "device_loss", "mesh_shrink", "double_fault")
# events that perturb neither math nor topology (timing / data-plane drills)
BENIGN_FAULTS = ("hang", "dead_shard", "slow_peer")

# the default draw set: everything except double_fault (a rider, drawn
# separately) — topology faults included, since re-mesh recovery is the
# headline path this campaign exists to prove; the scheduler prunes them
# automatically when n_devices <= 1 (and the peer faults when n_peers == 0)
DEFAULT_VOCAB = PERTURBING_FAULTS + BENIGN_FAULTS + (
    "sigterm", "device_loss", "mesh_shrink",
)


def split_plan(events: list[dict]) -> tuple[list[dict], list[dict]]:
    """``(reference_events, all_events)``: the reference run replays only the
    training-perturbing subset."""
    ref = [e for e in events if e.get("fault") in PERTURBING_FAULTS]
    return ref, list(events)


def random_fault_schedule(
    seed: int,
    *,
    epochs: int,
    dispatches: int,
    n_devices: int = 1,
    kinds=DEFAULT_VOCAB,
    max_faults: int = 3,
    n_peers: int = 0,
) -> list[dict]:
    """One seeded multi-fault schedule (a ``HYDRAGNN_FAULT_PLAN``-shaped
    event list). Placement constraints keep the reference comparable (module
    docstring): perturbing faults land in epochs before the final one;
    recovery faults land in the final epoch; at most ``n_devices - 1``
    devices ever die; ``double_fault`` only rides along with a recovery
    fault. Deterministic per ``(seed, kwargs)``."""
    rng = np.random.default_rng(seed)
    kinds = [k for k in kinds]
    if n_devices <= 1:
        kinds = [k for k in kinds if k not in ("device_loss", "mesh_shrink")]
    if n_peers <= 0:
        kinds = [k for k in kinds if k not in ("dead_shard", "slow_peer")]
    if epochs < 2:
        # no pre-final epoch to put perturbing faults in
        kinds = [k for k in kinds if k not in PERTURBING_FAULTS]
    kinds = [k for k in kinds if k != "double_fault"]  # rider, drawn below
    if not kinds:
        raise ValueError("fault vocabulary is empty under the constraints")
    n_faults = int(rng.integers(1, max(2, max_faults + 1)))
    final = epochs - 1
    loss_budget = max(0, n_devices - 1)  # devices that may still die
    events: list[dict] = []
    for _ in range(n_faults):
        kind = kinds[int(rng.integers(len(kinds)))]
        if kind in ("device_loss", "mesh_shrink") and loss_budget <= 0:
            kind = "sigterm"
        ev: dict = {"fault": kind}
        if kind in PERTURBING_FAULTS:
            ev["epoch"] = int(rng.integers(0, max(1, final)))
            ev["dispatch"] = int(rng.integers(0, dispatches))
        elif kind == "device_loss":
            ev["epoch"] = final
            ev["dispatch"] = int(rng.integers(0, dispatches))
            ev["device"] = int(rng.integers(0, n_devices))
            loss_budget -= 1
        elif kind == "mesh_shrink":
            # shrink no further than the remaining loss budget allows
            lo = n_devices - loss_budget
            target = int(rng.integers(lo, n_devices))
            ev["epoch"] = final
            ev["dispatch"] = int(rng.integers(0, dispatches))
            ev["to"] = max(1, target)
            loss_budget = max(0, target - 1)
        elif kind == "sigterm":
            ev["epoch"] = final
            ev["dispatch"] = int(rng.integers(0, dispatches))
        elif kind == "hang":
            ev["epoch"] = int(rng.integers(0, epochs))
            ev["dispatch"] = int(rng.integers(0, dispatches))
            ev["seconds"] = round(float(rng.uniform(0.05, 0.2)), 3)
        elif kind in ("dead_shard", "slow_peer"):
            ev["epoch"] = int(rng.integers(0, epochs))
            ev["dispatch"] = int(rng.integers(0, dispatches))
            ev["peer"] = int(rng.integers(0, n_peers))
            if kind == "slow_peer":
                ev["seconds"] = round(float(rng.uniform(0.3, 0.8)), 3)
        events.append(ev)
    has_recovery = any(e["fault"] in RECOVERY_FAULTS for e in events)
    if (
        has_recovery and n_devices > 1 and loss_budget > 0
        and "device_loss" in kinds and rng.random() < 0.5
    ):
        # ~half the recovery schedules add a fault DURING recovery
        events.append(
            {"fault": "double_fault", "inner": {"fault": "device_loss"}}
        )
    # deterministic order: epoch-major, then dispatch (the plan is taken in
    # event order by the harness; sorting makes the schedule readable)
    events.sort(
        key=lambda e: (e.get("epoch", epochs), e.get("dispatch") or 0)
    )
    return events


@dataclasses.dataclass
class ScheduleOutcome:
    """Everything the invariant suite needs from one executed schedule.
    ``ref_state``/``state`` are final pytrees; ``lr`` scales the shrink
    tolerance; ``approx_updates`` bounds how many optimizer updates ran
    after the first topology change (each compounds the lr-scale drift);
    ``threads_before``/``threads_after`` are non-daemon thread counts."""

    seed: int
    events: list
    ref_state: object
    state: object
    controller: object
    lr: float
    mesh_changed: bool
    approx_updates: int = 1
    threads_before: int = 0
    threads_after: int = 0
    recovery_budget_ms: float = 60_000.0


def nondaemon_thread_count() -> int:
    import threading

    return sum(1 for t in threading.enumerate() if not t.daemon)


def _tree_leaves_host(tree):
    import jax

    from ..parallel.mesh import host_gather

    return [np.asarray(x) for x in jax.tree.leaves(host_gather(tree))]


def check_invariants(out: ScheduleOutcome) -> list[str]:
    """The campaign's acceptance gate: returns human-readable violations
    (empty = the schedule degraded gracefully)."""
    violations: list[str] = []
    ra, rb = _tree_leaves_host(out.ref_state), _tree_leaves_host(out.state)
    if len(ra) != len(rb):
        return [f"seed {out.seed}: state structure diverged"]
    # zero lost samples: identical update counts (the step counter is a
    # leaf, so the comparisons below cover it — but report it by name)
    step_ref = _find_step(out.ref_state)
    step_out = _find_step(out.state)
    if step_ref is not None and step_out is not None and step_ref != step_out:
        violations.append(
            f"seed {out.seed}: lost/duplicated updates — step {step_out} "
            f"vs reference {step_ref}"
        )
    atol = out.lr * max(1, int(out.approx_updates))
    for i, (x, y) in enumerate(zip(ra, rb)):
        if x.shape != y.shape or x.dtype != y.dtype:
            violations.append(f"seed {out.seed}: leaf {i} shape/dtype diverged")
            break
        if not out.mesh_changed:
            if not np.array_equal(x, y):
                violations.append(
                    f"seed {out.seed}: leaf {i} not BIT-exact though the "
                    "topology never changed"
                )
                break
        elif np.issubdtype(x.dtype, np.floating):
            if not np.allclose(x, y, rtol=2e-2, atol=atol):
                err = float(np.max(np.abs(x - y)))
                violations.append(
                    f"seed {out.seed}: leaf {i} off by {err:.2e} "
                    f"(> lr-scale tolerance {atol:.2e} after shrink)"
                )
                break
        elif not np.array_equal(x, y):
            violations.append(f"seed {out.seed}: non-float leaf {i} diverged")
            break
    ctl = out.controller
    if ctl is not None:
        for rec in getattr(ctl, "recovery_log", ()):
            if rec["recovery_ms"] > out.recovery_budget_ms:
                violations.append(
                    f"seed {out.seed}: recovery took {rec['recovery_ms']:.0f} "
                    f"ms (> {out.recovery_budget_ms:.0f} ms budget)"
                )
        if getattr(ctl, "state", None) not in ("done", "running"):
            violations.append(
                f"seed {out.seed}: controller ended in state "
                f"{getattr(ctl, 'state', None)!r}, not 'done'"
            )
    if out.threads_after > out.threads_before:
        violations.append(
            f"seed {out.seed}: {out.threads_after - out.threads_before} "
            "non-daemon thread(s) leaked"
        )
    return violations


def _find_step(state):
    step = getattr(state, "step", None)
    if step is None:
        inner = getattr(state, "state", None)
        step = getattr(inner, "step", None)
    try:
        return None if step is None else int(np.asarray(step).max())
    except TypeError:
        return None


def run_campaign(seeds, run_schedule, **schedule_kw) -> dict:
    """Execute one schedule per seed and collect the invariant verdicts.
    ``run_schedule(seed, events) -> ScheduleOutcome`` is supplied by the
    caller (it owns the model/loaders/driver); this function owns the
    scheduling and the gate. Returns a report dict; ``report["violations"]``
    empty means the whole campaign passed."""
    report: dict = {"schedules": [], "violations": []}
    for seed in seeds:
        events = random_fault_schedule(int(seed), **schedule_kw)
        outcome = run_schedule(int(seed), [dict(e) for e in events])
        violations = check_invariants(outcome)
        report["schedules"].append(
            {
                "seed": int(seed),
                "events": events,
                "recoveries": getattr(outcome.controller, "recoveries", 0),
                "mesh_changed": outcome.mesh_changed,
                "violations": violations,
            }
        )
        report["violations"].extend(violations)
    report["n_schedules"] = len(report["schedules"])
    report["passed"] = not report["violations"]
    return report


# -- serving-fleet campaign ---------------------------------------------------
#
# The training campaign above proves the COMPUTE plane degrades gracefully;
# the fleet campaign proves the SERVING plane does: seeded schedules over the
# chaos fleet vocabulary (replica_kill / replica_slow / rollout_during_load)
# are fired at request coordinates into a live Zipf + mixed-priority replay
# (serve.traffic), and the gate checks what self-healing actually MEANS:
# zero lost requests, bounded service gaps (SLO recovery), bit-identical
# answers for every duplicate graph across kills AND the blue/green cutover,
# and no leaked threads or replica subprocesses. Serve imports stay lazy —
# this module must stay importable from training-only contexts.

#: the serving-fleet fault draw set (chaos.FLEET_FAULTS, re-exported here as
#: the campaign vocabulary so schedule call sites read uniformly)
FLEET_VOCAB = ("replica_kill", "replica_slow", "rollout_during_load")


def random_fleet_schedule(
    seed: int,
    *,
    n_requests: int,
    n_replicas: int,
    kinds=FLEET_VOCAB,
    max_faults: int = 2,
) -> list[dict]:
    """One seeded fleet-fault schedule at request coordinates (``epoch`` 0,
    ``dispatch`` = request index — see ``FaultPlan.on_request``). Placement
    constraints keep every schedule survivable and meaningful: at most
    ``n_replicas - 1`` kills (a survivor must exist to drain the queue),
    kills land mid-stream (a kill at request 0 is just a smaller fleet, at
    the last request it drills nothing), and at most one rollout per
    schedule, landing in the middle third so requests genuinely straddle
    the cutover. Deterministic per ``(seed, kwargs)``."""
    if n_requests < 3:
        raise ValueError(f"n_requests must be >= 3, got {n_requests}")
    rng = np.random.default_rng(seed)
    kinds = [k for k in kinds]
    if n_replicas <= 1:
        kinds = [k for k in kinds if k != "replica_kill"]
    if not kinds:
        raise ValueError("fleet fault vocabulary is empty under the constraints")
    n_faults = int(rng.integers(1, max(2, max_faults + 1)))
    kill_budget = max(0, n_replicas - 1)
    rollout_used = False
    events: list[dict] = []
    for _ in range(n_faults):
        kind = kinds[int(rng.integers(len(kinds)))]
        if kind == "replica_kill" and kill_budget <= 0:
            kind = "replica_slow"
        if kind == "rollout_during_load" and rollout_used:
            kind = "replica_slow"
        ev: dict = {"fault": kind}
        if kind == "replica_kill":
            ev["dispatch"] = int(
                rng.integers(n_requests // 4, max(n_requests // 4 + 1,
                                                  3 * n_requests // 4))
            )
            ev["peer"] = int(rng.integers(0, n_replicas))
            kill_budget -= 1
        elif kind == "replica_slow":
            ev["dispatch"] = int(rng.integers(0, n_requests))
            ev["peer"] = int(rng.integers(0, n_replicas))
            ev["seconds"] = round(float(rng.uniform(0.2, 0.6)), 3)
        else:  # rollout_during_load: mid-stream, so traffic straddles it
            ev["dispatch"] = int(
                rng.integers(n_requests // 3, max(n_requests // 3 + 1,
                                                  2 * n_requests // 3))
            )
            rollout_used = True
        events.append(ev)
    events.sort(key=lambda e: (e.get("dispatch") or 0, e["fault"]))
    return events


@dataclasses.dataclass
class FleetOutcome:
    """Everything the fleet invariant gate needs from one executed schedule.
    ``answers`` maps sample index -> set of served-answer digests (one entry
    per UNIQUE bit pattern: len > 1 means the same graph got different
    answers somewhere — across a failover, or across the cutover);
    ``lost`` counts requests that neither served nor shed typed;
    ``max_service_gap_ms`` is the longest stretch with zero completions
    (the observable SLO-recovery bound); ``leaked_procs`` counts replica
    subprocesses still alive after teardown."""

    seed: int
    events: list
    n_requests: int
    served: int
    shed: int
    lost: int
    answers: dict
    max_service_gap_ms: float
    lost_detail: list = dataclasses.field(default_factory=list)
    recovery_budget_ms: float = 30_000.0
    threads_before: int = 0
    threads_after: int = 0
    leaked_procs: int = 0


def check_fleet_invariants(out: FleetOutcome) -> list[str]:
    """The fleet campaign's acceptance gate: returns human-readable
    violations (empty = the fleet self-healed through the schedule)."""
    violations: list[str] = []
    accounted = out.served + out.shed + out.lost
    if accounted != out.n_requests:
        violations.append(
            f"seed {out.seed}: accounting hole — {accounted} outcomes for "
            f"{out.n_requests} requests"
        )
    if out.lost:
        detail = "; ".join(str(d) for d in out.lost_detail[:3])
        violations.append(
            f"seed {out.seed}: {out.lost} request(s) LOST (neither served "
            f"nor shed typed): {detail or 'no detail'}"
        )
    split = {k: v for k, v in out.answers.items() if len(v) > 1}
    if split:
        violations.append(
            f"seed {out.seed}: bit-identity broken — sample(s) "
            f"{sorted(split)[:5]} served {max(len(v) for v in split.values())}"
            " distinct answers across the run"
        )
    if out.max_service_gap_ms > out.recovery_budget_ms:
        violations.append(
            f"seed {out.seed}: {out.max_service_gap_ms:.0f} ms with zero "
            f"completions (> {out.recovery_budget_ms:.0f} ms SLO-recovery "
            "budget)"
        )
    if out.threads_after > out.threads_before:
        violations.append(
            f"seed {out.seed}: {out.threads_after - out.threads_before} "
            "non-daemon thread(s) leaked"
        )
    if out.leaked_procs:
        violations.append(
            f"seed {out.seed}: {out.leaked_procs} replica subprocess(es) "
            "still alive after teardown"
        )
    return violations


def replay_traffic_with_faults(
    router,
    model: str,
    samples,
    n_requests: int,
    *,
    seed: int = 0,
    plan=None,
    actions: dict | None = None,
    order=None,
    priorities=None,
    timeout_s: float = 120.0,
) -> dict:
    """Drive a Zipf-duplicate, mixed-priority request replay at ``router``,
    firing ``plan``'s fleet faults at request coordinates via the bound
    ``actions`` (see ``FaultPlan.on_request``). Run it against a router
    with ``cache_bytes=0`` when the point is bit-identity: with the answer
    cache on, a duplicate after the cutover could be served from a
    pre-cutover answer and the cross-generation comparison proves nothing.

    Returns the raw material for :class:`FleetOutcome`: ``served`` /
    ``shed`` / ``lost`` counts, ``lost_detail``, ``answers`` (sample index
    -> digest set over served heads), and ``max_service_gap_ms``."""
    import hashlib
    import time

    from ..serve.admission import AdmissionError, QueueFullError
    from ..serve.traffic import mixed_priority_plan, zipf_duplicate_order

    if order is None:
        order = zipf_duplicate_order(n_requests, len(samples), seed=seed)
    if priorities is None:
        priorities = mixed_priority_plan(n_requests, seed=seed)
    done_times: list[float] = []  # appended from done-callbacks
    futures: list[tuple[int, object]] = []
    served = shed = 0
    lost_detail: list[str] = []
    answers: dict[int, set] = {}
    t0 = time.monotonic()
    for i in range(n_requests):
        if plan is not None:
            plan.on_request(i, actions or {})
        sample = samples[int(order[i])]

        def _submit():
            fut = router.submit(model, sample, priority=priorities[i])
            fut.add_done_callback(
                lambda f: done_times.append(time.monotonic())
            )
            futures.append((int(order[i]), fut))

        try:
            _submit()
        except QueueFullError:
            time.sleep(0.002)  # run_traffic's retry-once-then-shed idiom
            try:
                _submit()
            except QueueFullError:
                shed += 1
    for sample_idx, fut in futures:
        try:
            heads = [np.asarray(h) for h in fut.result(timeout_s)["heads"]]
        except AdmissionError:
            shed += 1  # typed shed (failover exhausted / deadline): counted
            continue
        except Exception as e:  # anything untyped or hung is a LOST request
            lost_detail.append(f"sample {sample_idx}: {type(e).__name__}: {e}")
            continue
        served += 1
        digest = hashlib.sha1()
        for h in heads:
            digest.update(repr((h.shape, str(h.dtype))).encode())
            digest.update(np.ascontiguousarray(h).tobytes())
        answers.setdefault(sample_idx, set()).add(digest.hexdigest())
    gaps_ms = 0.0
    marks = [t0] + sorted(done_times)
    for a, b in zip(marks, marks[1:]):
        gaps_ms = max(gaps_ms, (b - a) * 1e3)
    return {
        "served": served,
        "shed": shed,
        "lost": len(lost_detail),
        "lost_detail": lost_detail,
        "answers": answers,
        "max_service_gap_ms": round(gaps_ms, 3),
    }


def run_fleet_campaign(seeds, run_schedule, **schedule_kw) -> dict:
    """The fleet mirror of :func:`run_campaign`: one seeded fleet schedule
    per seed, executed by the caller-supplied ``run_schedule(seed, events)
    -> FleetOutcome`` (it owns the topology: replicas, router, fault
    actions), gated by :func:`check_fleet_invariants`."""
    report: dict = {"schedules": [], "violations": []}
    for seed in seeds:
        events = random_fleet_schedule(int(seed), **schedule_kw)
        outcome = run_schedule(int(seed), [dict(e) for e in events])
        violations = check_fleet_invariants(outcome)
        report["schedules"].append(
            {
                "seed": int(seed),
                "events": events,
                "served": outcome.served,
                "shed": outcome.shed,
                "violations": violations,
            }
        )
        report["violations"].extend(violations)
    report["n_schedules"] = len(report["schedules"])
    report["passed"] = not report["violations"]
    return report


__all__ = [
    "BENIGN_FAULTS",
    "DEFAULT_VOCAB",
    "FLEET_VOCAB",
    "FleetOutcome",
    "PERTURBING_FAULTS",
    "RECOVERY_FAULTS",
    "ScheduleOutcome",
    "check_fleet_invariants",
    "check_invariants",
    "nondaemon_thread_count",
    "random_fault_schedule",
    "random_fleet_schedule",
    "replay_traffic_with_faults",
    "run_campaign",
    "run_fleet_campaign",
    "split_plan",
]
